"""Continuous-batching decode server (slot scheduler over the KV cache).

Beyond-reference serving: the reference serves with one AnalysisPredictor
per thread (inference/api/analysis_predictor.cc — fixed batch, no shared
state); modern LLM serving instead keeps ONE resident batched KV cache and
lets requests join and leave mid-flight (continuous batching).  TPU-first
shape: the whole tick is one jitted ``decode_step`` vmapped over slots
with PER-SLOT positions — fixed shapes (XLA compiles once per
(max_batch, max_len)), no re-running prefixes, no cache re-allocation; a
freed slot is reused without clearing (the causal mask ``t <= pos`` hides
stale rows until they are overwritten).

    srv = DecodeServer(params, cfg, max_batch=8, max_len=256, eos_id=2)
    rid = srv.submit([5, 3, 9], max_new_tokens=32)
    while srv.pending():
        srv.tick()
    tokens = srv.result(rid)

Weight-only quantized params (text/woq.py) work unchanged — the vmapped
step routes through the same woq accessors.
"""
from __future__ import annotations

import os as _os
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from . import admission as _admission
from . import engine as _engine
from . import generate, gpt
from .engine import StepSpec as _Spec
from .. import faults as _faults
from .. import flags as _flags
from .. import resilience as _resilience
from .. import telemetry as _telemetry

__all__ = ["decode_step_batched", "DecodeServer", "validate_request"]


def decode_step_batched(params, cache, token, pos, cfg: gpt.GPTConfig):
    """decode_step with PER-SLOT positions: token [B] int32, pos [B] int32.

    Implemented as vmap of the scalar-pos ``decode_step`` over the batch
    axis (params broadcast, every cache leaf's batch axis 1 — int8 scale
    planes included) — identical math, batched cache scatter.

    A pooled cache (text/kv_pool — a ``tables`` leaf marks the paged
    layout) routes to the block-table twin instead; the branch is on
    pytree STRUCTURE at trace time, so every step getter (sample/block/
    async) serves both layouts without new plumbing."""
    if "tables" in cache:
        from . import kv_pool

        return kv_pool.paged_decode_step_batched(params, cache, token,
                                                 pos, cfg)

    def one(tok, csl, p):
        sl = {name: v[:, None] for name, v in csl.items()}
        logits, new = generate.decode_step(params, sl, tok[None], p, cfg)
        return logits[0], {name: v[:, 0] for name, v in new.items()}

    logits, new = jax.vmap(one, in_axes=(0, 1, 0), out_axes=(0, 1))(
        token, cache, pos)
    return logits, new


def _sample_batched(logits, key, temp, topk, topp, mask=None):
    """Per-slot sampling over batched logits [B, V]: temperature scale,
    then top-k, then nucleus — the same pipeline (and order) as
    ``generate``'s sampler, vectorized with PER-SLOT parameters so one
    compiled step serves a batch mixing greedy and sampled requests.
    temp/topp are float32 [B], topk int32 [B] (0 = off); slots with
    temp == 0 take the argmax of the raw logits (bit-identical to the
    greedy path).  The filter math lives in generate._filter_logits —
    the single shared implementation.

    ``mask``: optional additive constraint mask [B, V] float32
    (0 = allowed, ``adapters.NEG_INF`` = banned — see
    text/adapters.mask_logits), applied BEFORE both branches so greedy
    (temp == 0) slots take the argmax of the MASKED logits: one
    executable serves constrained-greedy and constrained-sampled.  An
    all-zero row is exactly the unconstrained math."""
    if mask is not None:
        logits = logits + mask
    scaled = generate._filter_logits(logits, temp, topk, topp)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jnp.where(temp > 0.0, sampled, greedy)


def sample_step_batched(params, cache, tok, pos, key, temp, topk, topp,
                        cfg: gpt.GPTConfig, mask=None):
    """One batched decode step that returns sampled TOKENS [B] (greedy
    where temp == 0) instead of logits — the sampling-serving twin of
    decode_step_batched.  ``mask`` (optional [B, V] additive constraint
    mask, see _sample_batched) rides through to the sampler."""
    logits, cache = decode_step_batched(params, cache, tok, pos, cfg)
    return _sample_batched(logits, key, temp, topk, topp, mask=mask), cache


def sample_block_batched(params, cache, tok, pos, base_key, off, temp, topk,
                         topp, k: int, cfg: gpt.GPTConfig):
    """``k`` sampled decode steps on device, one host fetch — the
    sampling twin of decode_block_batched.  Step j draws with
    fold_in(base_key, off + j): the SAME key schedule the per-tick path
    uses, so tick and tick_block produce identical tokens for identical
    step counters (tests rely on this parity)."""
    def body(carry, j):
        cache, tok, pos = carry
        logits, cache = decode_step_batched(params, cache, tok, pos, cfg)
        nxt = _sample_batched(logits, jax.random.fold_in(base_key, off + j),
                              temp, topk, topp)
        return (cache, nxt, pos + 1), nxt

    (cache, tok, pos), toks = jax.lax.scan(body, (cache, tok, pos),
                                           jnp.arange(k))
    return toks.T, cache


def decode_block_batched(params, cache, tok, pos, k: int, cfg: gpt.GPTConfig):
    """``k`` greedy decode steps entirely ON DEVICE (round-4 verdict Weak
    #3: fetching the argmax to numpy every tick makes tunnel decode
    latency host-round-trip-bound).  Each step's argmax feeds the next
    step inside one jitted ``lax.scan`` — the host sees one [B, k] token
    block per call instead of k scalar fetches.

    tok/pos [B] int32 are the NEXT token to feed / its position per slot.
    Returns (tokens [B, k], cache, next_tok [B], next_pos [B]).  Slots
    whose request finishes mid-block keep decoding (their surplus tokens
    are discarded by the caller) — the standard chunked-serving overrun
    tradeoff; their cache rows stay hidden by the slot-reuse invariant."""
    def body(carry, _):
        cache, tok, pos = carry
        logits, cache = decode_step_batched(params, cache, tok, pos, cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (cache, nxt, pos + 1), nxt

    (cache, tok, pos), toks = jax.lax.scan(body, (cache, tok, pos), None,
                                           length=k)
    return toks.T, cache, tok, pos


def _hits_stop(st: dict) -> bool:
    gen = st["generated"]
    return any(len(gen) >= len(seq) and gen[-len(seq):] == seq
               for seq in st.get("stop", []))


# round 15: the Engine (text/engine.py) owns the step cache, the shard
# context, and every builder — the names below stay as aliases (tests and
# the fleet address them here), and each retired getter survives as a
# one-line shim over ``ENGINE.get(kind, StepSpec(...))`` with
# byte-compatible keys, watch names, jit bodies, and donation.
_STEP_CACHE = _engine.ENGINE._steps
_ShardCtx = _engine._ShardCtx
_shard_kw = _engine._shard_kw
_shard_key = _engine._shard_key

# cold prefix-cache entries evicted per OOM-chain engagement (LRU-first
# batches — repeated engagements drain more; never the whole index)
_EVICT_BATCH = 4


def _get_prefill_fn(cfg: gpt.GPTConfig, bucket: int, shard=None):
    """Engine shim: whole-prompt admission at one power-of-two bucket.
    MoE configs route to the ``moe_prefill`` kind (same dropless body,
    named/keyed apart) — call sites never branch."""
    kind = "moe_prefill" if cfg.moe is not None else "prefill"
    return _engine.ENGINE.get(kind, _Spec(
        cfg=cfg, bucket=int(bucket), shard=shard))


def _get_prefill_chunk_fn(cfg: gpt.GPTConfig, shard=None,
                          width: int | None = None):
    """Engine shim: contiguous fixed-chunk / budgeted admission step
    (``moe_prefill_chunk`` for MoE configs)."""
    kind = "moe_prefill_chunk" if cfg.moe is not None else "prefill_chunk"
    return _engine.ENGINE.get(kind, _Spec(
        cfg=cfg, shard=shard, width=width))


def _get_paged_prefill_fn(cfg: gpt.GPTConfig, bucket: int, shard=None):
    """Engine shim: paged offset-aware admission chunk
    (``moe_paged_prefill`` for MoE configs)."""
    kind = "moe_paged_prefill" if cfg.moe is not None else "paged_prefill"
    return _engine.ENGINE.get(kind, _Spec(
        cfg=cfg, bucket=int(bucket), shard=shard))


def _get_copy_fn(cfg: gpt.GPTConfig, n_pairs: int, shard=None):
    """Engine shim: copy-on-write block gather/scatter (n_pairs wide)."""
    return _engine.ENGINE.get("kv_copy", _Spec(
        cfg=cfg, k=int(n_pairs), shard=shard))


def _get_inject_fn(cfg: gpt.GPTConfig, bucket: int, paged: bool,
                   shard=None):
    """Engine shim: prefill-handoff row injector (the fleet's decode
    half)."""
    return _engine.ENGINE.get("inject", _Spec(
        cfg=cfg, bucket=int(bucket), paged=paged, shard=shard))


def _get_block_fn(cfg: gpt.GPTConfig, k: int, paged: bool = False,
                  shard=None):
    """Engine shim: k greedy decode steps on device per host fetch."""
    return _engine.ENGINE.get("block", _Spec(
        cfg=cfg, k=k, paged=paged, shard=shard))


def _get_sample_step_fn(cfg: gpt.GPTConfig, paged: bool = False,
                        shard=None):
    """Engine shim: the batched sampled tick step."""
    return _engine.ENGINE.get("sample", _Spec(
        cfg=cfg, paged=paged, shard=shard))


def _get_sample_block_fn(cfg: gpt.GPTConfig, k: int, paged: bool = False,
                         shard=None):
    """Engine shim: k sampled decode steps on device per host fetch."""
    return _engine.ENGINE.get("sample_block", _Spec(
        cfg=cfg, k=k, paged=paged, shard=shard))


def _get_step_fn(cfg: gpt.GPTConfig, paged: bool = False, shard=None):
    """Engine shim: THE batched greedy tick step (cache donated — the
    caller reassigns from the return; DecodeServer always does)."""
    return _engine.ENGINE.get("step", _Spec(
        cfg=cfg, paged=paged, shard=shard))


def _get_async_step_fn(cfg: gpt.GPTConfig, paged: bool = False,
                       shard=None):
    """Engine shim: the async-dispatch tick step (device-side feed
    select between host token and the in-flight step's output)."""
    return _engine.ENGINE.get("async", _Spec(
        cfg=cfg, paged=paged, shard=shard))


def _get_async_block_fn(cfg: gpt.GPTConfig, k: int, paged: bool = False,
                        shard=None):
    """Engine shim: async greedy block."""
    return _engine.ENGINE.get("async_block", _Spec(
        cfg=cfg, k=k, paged=paged, shard=shard))


def _get_async_sample_block_fn(cfg: gpt.GPTConfig, k: int,
                               paged: bool = False, shard=None):
    """Engine shim: async sampled block."""
    return _engine.ENGINE.get("async_sample_block", _Spec(
        cfg=cfg, k=k, paged=paged, shard=shard))


def _get_moe_step_fn(cfg: gpt.GPTConfig, paged: bool = False, shard=None):
    """Engine shim: the joint-routing greedy MoE tick step (round 19) —
    (p, cache, tok, pos, act, stats) -> (logits, cache, stats')."""
    return _engine.ENGINE.get("moe_step", _Spec(
        cfg=cfg, paged=paged, shard=shard))


def _get_moe_sample_step_fn(cfg: gpt.GPTConfig, paged: bool = False,
                            shard=None):
    """Engine shim: the sampled joint-routing MoE tick step."""
    return _engine.ENGINE.get("moe_sample", _Spec(
        cfg=cfg, paged=paged, shard=shard))


def _get_moe_block_fn(cfg: gpt.GPTConfig, k: int, paged: bool = False,
                      shard=None):
    """Engine shim: k greedy joint-routing MoE steps per host fetch."""
    return _engine.ENGINE.get("moe_block", _Spec(
        cfg=cfg, k=k, paged=paged, shard=shard))


def _get_moe_async_step_fn(cfg: gpt.GPTConfig, paged: bool = False,
                           shard=None):
    """Engine shim: the async-dispatch joint-routing MoE tick step."""
    return _engine.ENGINE.get("moe_async", _Spec(
        cfg=cfg, paged=paged, shard=shard))


def spec_verify_batched(params, cache, tokens, pos, cfg: gpt.GPTConfig):
    """Batched draft-then-verify scoring: tokens [B, K] int32 fed at
    PER-SLOT positions [pos_b, pos_b + K) -> (logits [B, K, V] fp32,
    cache).  Column 0 is each slot's normal feed token, columns 1..K-1
    its draft proposals; row j scores position pos_b + j, so row 0
    equals the plain decode step's logits (greedy parity) and rows
    1.. are the target's verdicts on the proposals.

    Contiguous: vmap of ``generate.verify_chunk`` per slot — the
    offline speculative path's exact math at decode_step_batched's
    batching shapes — or, when the flash-decode flag + shape gate allow
    it, ``generate.verify_chunk_batched`` (layer loop at top level, one
    Tq=K kernel launch per block — the ROADMAP "flash-verify" item).
    Paged (a ``tables`` leaf): the block-table twin
    ``kv_pool.paged_verify_chunk_batched`` (which routes to its own
    kernel form under the same gate).  Either way the chunk's K
    cache rows are written unconditionally: rejected rows sit at/past
    the slot's position pointer where the causal mask hides them and
    the next round overwrites them (the stale-row invariant the whole
    server rests on), so no masked write is needed."""
    if "tables" in cache:
        from . import kv_pool

        return kv_pool.paged_verify_chunk_batched(params, cache, tokens,
                                                  pos, cfg)
    B, K = tokens.shape
    if generate._use_decode_kernel(
            cfg, (B, K, cfg.num_heads, cfg.head_dim),
            cache["k"].shape[1:]):
        return generate.verify_chunk_batched(params, cache, tokens, pos,
                                             cfg)

    def one(tok, csl, p):
        sl = {name: v[:, None] for name, v in csl.items()}
        logits, new = generate.verify_chunk(params, sl, tok[None], p, cfg)
        return logits[0], {name: v[:, 0] for name, v in new.items()}

    logits, new = jax.vmap(one, in_axes=(0, 1, 0), out_axes=(0, 1))(
        tokens, cache, pos)
    return logits, new


def _get_spec_verify_fn(cfg: gpt.GPTConfig, k: int, paged: bool = False,
                        shard=None):
    """Engine shim: the speculative serving verify step — one
    executable per (cfg, K, layout, placement); under a ``mesh=`` shard
    context it composes with TP exactly like the plain step (round 15's
    registry unlock)."""
    return _engine.ENGINE.get("spec_verify", _Spec(
        cfg=cfg, k=int(k), paged=paged, shard=shard))


def spec_tree_verify_batched(params, cache, tokens, amask, depth, pos,
                             cfg: gpt.GPTConfig):
    """Batched TREE verify: tokens [B, N] int32 (column 0 = each slot's
    feed token = the tree root, columns 1.. its proposed tree nodes in
    topological order), ancestor-or-self mask ``amask`` [B, N, N] bool
    and ``depth`` [B, N] int32 describing each slot's topology as
    RUNTIME arguments, per-slot positions ``pos`` [B] ->
    (logits [B, N, V] fp32, cache).  Node j's row scores the
    continuation of j's root path — row 0 still equals the plain decode
    step's logits (a chain tree reduces to ``spec_verify_batched``'s
    fallback bit-for-bit), which is what greedy tree parity rests on.

    Contiguous routes to ``generate.tree_verify_chunk_batched``, paged
    (a ``tables`` leaf) to ``kv_pool.paged_tree_verify_chunk_batched``
    — both share ``generate._tree_attend_block`` so the layouts cannot
    drift.  No kernel route: the flash kernels assume causal masks, so
    tree verify is einsum-only everywhere (ROADMAP follow-up).  All N
    rows are written unconditionally; rejected/unused nodes sit at or
    past the slot's pointer as stale rows (the PR 11 invariant)."""
    if "tables" in cache:
        from . import kv_pool

        return kv_pool.paged_tree_verify_chunk_batched(
            params, cache, tokens, amask, depth, pos, cfg)
    return generate.tree_verify_chunk_batched(params, cache, tokens,
                                              amask, depth, pos, cfg)


def spec_tree_commit_batched(cache, src, pos):
    """Post-acceptance KV permute: move each slot's accepted-path rows
    (``src`` [B, N-1] node indices, identity where nothing moved) to
    the contiguous rows [pos+1, pos+N) their committed positions
    require.  Layout-routed like the verify; cache-only (the Engine
    donates it like ``kv_copy``)."""
    if "tables" in cache:
        from . import kv_pool

        return kv_pool.paged_tree_commit(cache, src, pos)
    return generate.tree_commit_rows(cache, src, pos)


def _get_spec_tree_verify_fn(cfg: gpt.GPTConfig, nodes: int,
                             paged: bool = False, shard=None):
    """Engine shim: the tree-speculation verify — one executable per
    (cfg, node count, layout, placement); topology never keys."""
    return _engine.ENGINE.get("spec_tree_verify", _Spec(
        cfg=cfg, k=int(nodes), paged=paged, shard=shard))


def _get_spec_tree_commit_fn(cfg: gpt.GPTConfig, nodes: int,
                             paged: bool = False, shard=None):
    """Engine shim: the tree acceptance KV permute (cache-only)."""
    return _engine.ENGINE.get("spec_tree_commit", _Spec(
        cfg=cfg, k=int(nodes), paged=paged, shard=shard))


# -- adapter-aware shims (multi-tenant serving: text/adapters.py) ----------
#
# Every kind keys on ``pkey`` (AdapterPool.pool_key() — the pool GEOMETRY:
# capacity/rank/targets) next to the usual cfg/layout/placement fragments,
# so two servers sharing one pool share executables while a differently-
# shaped pool compiles its own; see the registry entries in engine.py for
# the stacked-leaf sharding and donation story.


def _get_adapter_step_fn(cfg: gpt.GPTConfig, pkey, paged: bool = False,
                         shard=None):
    """Engine shim: greedy adapter-gathered batched step."""
    return _engine.ENGINE.get("adapter_step", _Spec(
        cfg=cfg, pkey=pkey, paged=paged, shard=shard))


def _get_adapter_sample_step_fn(cfg: gpt.GPTConfig, pkey,
                                paged: bool = False, shard=None):
    """Engine shim: adapter-gathered sampled/masked step."""
    return _engine.ENGINE.get("adapter_sample", _Spec(
        cfg=cfg, pkey=pkey, paged=paged, shard=shard))


def _get_adapter_block_fn(cfg: gpt.GPTConfig, k: int, pkey,
                          paged: bool = False, shard=None):
    """Engine shim: adapter-gathered greedy block."""
    return _engine.ENGINE.get("adapter_block", _Spec(
        cfg=cfg, k=k, pkey=pkey, paged=paged, shard=shard))


def _get_adapter_async_step_fn(cfg: gpt.GPTConfig, pkey,
                               paged: bool = False, shard=None):
    """Engine shim: adapter-gathered async step."""
    return _engine.ENGINE.get("adapter_async", _Spec(
        cfg=cfg, pkey=pkey, paged=paged, shard=shard))


def _get_adapter_spec_verify_fn(cfg: gpt.GPTConfig, k: int, pkey,
                                paged: bool = False, shard=None):
    """Engine shim: adapter-gathered speculative verify."""
    return _engine.ENGINE.get("adapter_spec_verify", _Spec(
        cfg=cfg, k=int(k), pkey=pkey, paged=paged, shard=shard))


def _get_adapter_prefill_fn(cfg: gpt.GPTConfig, bucket: int, pkey,
                            shard=None):
    """Engine shim: whole-prompt admission under one slot's adapter."""
    return _engine.ENGINE.get("adapter_prefill", _Spec(
        cfg=cfg, bucket=int(bucket), pkey=pkey, shard=shard))


def _get_adapter_prefill_chunk_fn(cfg: gpt.GPTConfig, pkey, shard=None,
                                  width: int | None = None):
    """Engine shim: fixed-chunk / budgeted admission under one slot's
    adapter."""
    return _engine.ENGINE.get("adapter_prefill_chunk", _Spec(
        cfg=cfg, pkey=pkey, shard=shard, width=width))


def _get_adapter_paged_prefill_fn(cfg: gpt.GPTConfig, bucket: int, pkey,
                                  shard=None):
    """Engine shim: paged admission chunk under one slot's adapter."""
    return _engine.ENGINE.get("adapter_paged_prefill", _Spec(
        cfg=cfg, bucket=int(bucket), pkey=pkey, shard=shard))


def _get_masked_step_fn(cfg: gpt.GPTConfig, paged: bool = False,
                        shard=None):
    """Engine shim: constrained (masked) step for pool-less servers."""
    return _engine.ENGINE.get("masked_step", _Spec(
        cfg=cfg, paged=paged, shard=shard))


def _pow2_bucket(n: int, *bounds) -> int:
    """Smallest power of two >= ``n``, clamped to the given upper
    bounds — THE prompt-bucket rule.  The bucket is a jit-cache key, so
    every admission surface (local prefill, the paged suffix walk,
    prefill workers, row injection) must compute it HERE or executables
    silently split between surfaces."""
    b = 1
    while b < n:
        b *= 2
    return min(b, *bounds) if bounds else b


def validate_request(prompt, max_new_tokens, stop, temperature, top_k,
                     top_p, ttl_s, *, window, vocab_size, default_ttl):
    """THE request-validation rules, shared by ``DecodeServer`` and the
    fleet ``Router`` (one level up, with the fleet-wide window) so the
    two admission surfaces can never drift.  Returns the normalized
    ``(prompt, stop, ttl, top_k)``."""
    prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
    if not prompt:
        raise ValueError("empty prompt")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, "
                         f"got {max_new_tokens}")
    total = len(prompt) + max_new_tokens
    if total > window:
        raise ValueError(
            f"prompt+max_new_tokens {total} exceeds serving window "
            f"{window}")
    stop = [[int(t) for t in seq] for seq in (stop or [])]
    if any(not seq for seq in stop):
        raise ValueError("empty stop sequence")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    ttl = default_ttl if ttl_s is None else float(ttl_s)
    if ttl is not None and ttl <= 0:
        raise ValueError(f"ttl_s must be > 0, got {ttl}")
    return prompt, stop, ttl, min(int(top_k), vocab_size)


class DecodeServer:
    """Host-side slot scheduler around one jitted batched decode step.

    Greedy by default; per-request ``temperature``/``top_k``/``top_p``
    (round-5) sample on device with per-slot parameters, so one batch
    mixes greedy and sampled requests in the same compiled step.  With
    the default ``prefill=True``, submit/_admit
    runs the whole (bucket-padded) prompt through ONE jitted
    ``generate.prefill_slot`` step — device work at admission, one XLA
    compile per power-of-two bucket — and ticks only generate; with
    ``prefill=False`` prompts are consumed token-by-token through the
    tick step (each prompt token's logits discarded until the prompt
    ends)."""

    def __init__(self, params, cfg: gpt.GPTConfig, max_batch: int,
                 max_len: int, eos_id: int | None = None,
                 prefill: bool = True, seed: int = 0,
                 prefill_chunk: int | None = None,
                 async_dispatch: bool = False,
                 metrics_port: int | None = None,
                 layout: str | None = None,
                 block_size: int | None = None,
                 num_blocks: int | None = None,
                 mesh=None, mp_axis: str = "mp",
                 ep_axis: str | None = None,
                 device=None,
                 draft_cfg: gpt.GPTConfig | None = None,
                 draft_params=None, spec_k: int | None = None,
                 spec_tree: int | None = None,
                 prefill_budget: int | None = None,
                 adapter_pool=None):
        self.params = params
        # telemetry (request tracing + latency histograms + gauges):
        # decided once at construction — per-tick records are lock-cheap
        # host counters off the already-fetched host values, and with
        # PADDLE_TPU_TELEMETRY=0 every sample site is one bool check.
        # ``metrics_port`` opts into the /metrics HTTP endpoint
        # (telemetry.serve_metrics; port 0 = ephemeral, see
        # ``self.metrics_server.port``).
        self._tel = _telemetry.enabled()
        self.metrics_server = (_telemetry.serve_metrics(metrics_port)
                               if metrics_port is not None else None)
        # fleet observability plane (round 20): completed trace spans
        # for requests carrying a router-minted trace context, plus
        # per-SERVER histogram twins — loopback fleets co-host many
        # replicas in one process, so the fleet metrics merge needs
        # per-server distributions the process-global registry can't
        # give.  Both empty and untouched when no trace/telemetry.
        self._span_ring = _telemetry.SpanRing()
        self._hist_local: dict = {}
        self._counts_local: dict = {}
        self.cfg = cfg
        self.max_batch = max_batch
        self.max_len = max_len
        self.eos_id = eos_id
        # KV-cache layout (round 8): 'contiguous' (the default slab —
        # every slot provisioned for max_len rows) or 'paged'
        # (text/kv_pool: a shared block pool addressed through per-slot
        # block tables, blocks allocated as ``pos`` crosses block
        # boundaries, refcounted prefix reuse + copy-on-write).
        # ``PADDLE_TPU_KV_LAYOUT`` flips the default; ``num_blocks``
        # defaults to full provisioning (slab-equivalent capacity) and
        # is the knob operators shrink to actual-traffic budgets.
        lay = layout if layout is not None else _flags.kv_layout()
        if lay not in ("contiguous", "paged"):
            raise ValueError(
                f"layout {lay!r}: expected 'contiguous' or 'paged'")
        self._paged = lay == "paged"
        if self._paged:
            from . import kv_pool as _kv

            # init_cache -> kv_pool.init_paged_cache is the ONE
            # validator of block_size/num_blocks (and the default pool
            # sizing); the allocator mirrors the built cache's geometry
            self.cache = generate.init_cache(
                cfg, max_batch, max_len, layout="paged",
                block_size=block_size, num_blocks=num_blocks)
            self._pool = _kv.PagedAllocator(
                self.cache["k"].shape[1], self.cache["k"].shape[2],
                self.cache["tables"].shape[1], max_batch)
        else:
            self._pool = None
            self.cache = generate.init_cache(cfg, max_batch, max_len)
        self._rss_tick = 0          # host-RSS watchdog cadence counter
        # speculative decoding (draft-then-verify in the serving tick):
        # spec_k > 0 turns speculation on — with (draft_cfg,
        # draft_params) a small draft model proposes K-1 tokens per
        # round (its KV state rides a twin cache pytree; under the
        # paged layout the draft pool shares THE SAME allocator/table,
        # so eviction/rollback frees both coherently), without a draft
        # the server self-drafts via host n-gram lookup
        # (generate.ngram_propose — zero extra model FLOPs).  Greedy
        # output stays bit-identical to the non-speculative server;
        # per-request rolling acceptance below PADDLE_TPU_SPEC_MIN_ACCEPT
        # falls the slot back to plain decode.
        # tree speculation (Medusa/SpecInfer shape, round 17): a token
        # TREE of `spec_tree` node slots per round — n-gram trie or the
        # draft's top-b fanout — verified in ONE tree-masked pass with
        # host-side best-path acceptance.  Mutually exclusive with the
        # linear spec_k round shape; constrained slots SPECULATE in
        # tree mode (branches the grammar forbids are pruned before the
        # verify) instead of falling back to plain stepping.
        if spec_tree is not None:
            n_tree = int(spec_tree)
            if n_tree < 0 or n_tree == 1:
                raise ValueError(
                    f"spec_tree must be 0 (off) or >= 2 node slots "
                    f"(node 0 carries the feed token), got {n_tree}")
        else:
            n_tree = _flags.spec_tree()
        self._spec_tree_n = n_tree
        self._spec_branch = _flags.spec_branch()
        if spec_k is not None:
            k_spec = int(spec_k)
            if n_tree and k_spec:
                raise ValueError(
                    f"spec_k={k_spec} and spec_tree={n_tree} are "
                    f"mutually exclusive — a round is either a linear "
                    f"verify or a tree verify")
        else:
            # an explicit/env tree budget overrides the env spec_k (one
            # env flip turns tree mode on without unsetting the other)
            k_spec = 0 if n_tree else _flags.spec_k()
            if k_spec == 0 and draft_cfg is not None and not n_tree:
                k_spec = 4          # passing a draft model IS opting in
        if k_spec < 0:
            raise ValueError(f"spec_k must be >= 0, got {k_spec}")
        if k_spec == 0 and draft_cfg is not None and not n_tree:
            raise ValueError("draft_cfg given but spec_k=0 disables "
                             "speculation — drop one or the other")
        self._spec_k = k_spec
        self._spec_on = k_spec > 0 or n_tree > 0
        self.draft_cfg = draft_cfg
        self._draft_params = draft_params
        self._draft_cache = None
        self._self_draft = self._spec_on and draft_cfg is None
        self._min_accept = _flags.spec_min_accept()
        # server-level speculation accounting (load_stats / the
        # acceptance-rate gauge / bench's target-passes-per-token)
        self._spec_prop = 0         # proposals scored by the target
        self._spec_acc = 0          # ... of those, accepted
        self._spec_rounds = 0       # batched verify dispatches
        self._spec_plain_steps = 0  # plain target steps while spec on
        self._tree_path_sum = 0     # accepted path tokens (tree rounds)
        self._tree_path_cnt = 0     # ... over this many slot-rounds
        if self._spec_on:
            window = min(max_len, cfg.max_seq_len)
            if cfg.moe is not None or (draft_cfg is not None
                                       and draft_cfg.moe is not None):
                # speculative_generate's rule, enforced at BUILD (not
                # first tick): chunked verify routes a chunk's tokens
                # jointly through MoE capacity, stepwise decode routes
                # them one at a time — the two are not bit-equal
                raise NotImplementedError(
                    "speculative serving requires dense models (MoE "
                    "capacity routing differs between chunked verify "
                    "and stepwise decode — speculative_generate's "
                    "rule)")
            if n_tree:
                if not 2 <= n_tree < window:
                    raise ValueError(
                        f"spec_tree {n_tree} must be in [2, {window}) — "
                        f"the tree chunk must fit the serving window")
                if adapter_pool is not None:
                    raise NotImplementedError(
                        "spec_tree with an adapter_pool is not "
                        "supported yet (the tree verify kind has no "
                        "adapter-gathered twin); linear spec_k composes "
                        "with pools")
            elif not 1 <= k_spec < window:
                raise ValueError(
                    f"spec_k {k_spec} must be in [1, {window}) — the "
                    f"verify chunk must fit the serving window")
            if draft_cfg is not None:
                if draft_params is None:
                    raise ValueError("draft_cfg requires draft_params")
                if draft_cfg.vocab_size != cfg.vocab_size:
                    raise ValueError(
                        f"draft vocab {draft_cfg.vocab_size} != target "
                        f"vocab {cfg.vocab_size}")
                if draft_cfg.max_seq_len < window:
                    raise ValueError(
                        f"draft max_seq_len {draft_cfg.max_seq_len} < "
                        f"serving window {window}")
        # tensor-parallel decode INSIDE the server (round 9): with a
        # ``mesh``, params take the Megatron specs and every cache leaf
        # shards its Hkv axis over ``mp_axis`` (paged pool included, the
        # slab rule) — the batched tick then runs pjit'd with XLA's
        # collectives over ICI, donation/jit-key/recompile-watch
        # composing unchanged (_ShardCtx).  ``device`` instead pins a
        # single-chip server to one device (the fleet's per-replica
        # placement knob); the two are mutually exclusive.
        self._device = None
        self._shard = None
        if ep_axis is not None and mesh is None:
            raise ValueError("ep_axis requires mesh= (expert parallelism "
                             "is a mesh placement)")
        if mesh is not None:
            if device is not None:
                raise ValueError("mesh= and device= are mutually "
                                 "exclusive (TP server vs pinned "
                                 "single-chip replica)")
            # round 19: MoE configs shard through the regex rule table
            # (moe_serving.moe_decode_param_specs) — _ShardCtx routes
            # there itself, placing experts over ``ep_axis`` when given
            # (replicated experts under pure TP otherwise)
            self._shard = _ShardCtx(mesh, cfg, params, self.cache,
                                    mp_axis, pool=adapter_pool,
                                    ep=ep_axis)
            self.params = jax.tree_util.tree_map(
                jax.device_put, params, self._shard.params)
            self.cache = {n: jax.device_put(a, self._shard.cache[n])
                          for n, a in self.cache.items()}
        elif device is not None:
            self._device = device
            self.params = jax.device_put(params, device)
            self.cache = jax.device_put(self.cache, device)
            # placement joins every step-cache key (see _shard_key)
            self._shard = ("device", int(getattr(device, "id", 0)))
        # MoE serving (round 19): the tick runs the JOINT-routing step —
        # all occupied slots' tokens route through expert capacity in
        # one call, with the device-side drop/load accumulator threaded
        # through like the cache.  ``_moe_wrap`` adapts the moe kinds to
        # the dense calling convention (appends act+stats, peels the
        # stats output), so every dispatch site — and warmup — stays
        # shared with the dense server.
        if cfg.moe is not None:
            from . import moe_serving as _moe_serving

            self._moe_stats = _moe_serving.moe_stats_init(
                cfg.moe.num_experts)
            self._moe_counted = 0       # drained high-water mark
            self._step = self._moe_wrap(
                _get_moe_step_fn(cfg, self._paged, self._shard))
        else:
            self._moe_stats = None
            self._step = _get_step_fn(cfg, self._paged, self._shard)
        # the draft model's placement context: identical to the target's
        # for pinned/un-placed servers; under mesh= it gets its OWN
        # _ShardCtx (the draft cfg's Megatron/cache specs differ from the
        # target's), built below once the twin cache exists
        self._draft_shard = self._shard
        if self._spec_on and draft_cfg is not None:
            if self._paged:
                from . import kv_pool as _kv

                # the draft pool mirrors the target pool's geometry
                # (same block size, same block count, same nmax), so the
                # ONE allocator + the one table leaf address both —
                # target and draft positions advance in lockstep, and
                # free_slot/eviction releases both pools' rows together
                self._draft_cache = _kv.init_paged_cache(
                    draft_cfg, max_batch, max_len,
                    block_size=int(self.cache["k"].shape[2]),
                    num_blocks=int(self.cache["k"].shape[1]))
            else:
                self._draft_cache = generate.init_cache(
                    draft_cfg, max_batch, max_len)
            if self._device is not None:
                self._draft_params = jax.device_put(draft_params,
                                                    self._device)
                self._draft_cache = jax.device_put(self._draft_cache,
                                                   self._device)
            elif mesh is not None:
                # spec × TP (the registry unlock): the draft twin shards
                # by the SAME sharded_cache_specs rule as the target —
                # its Hkv axis over mp_axis, params Megatron-style
                self._draft_shard = _ShardCtx(mesh, draft_cfg,
                                              draft_params,
                                              self._draft_cache, mp_axis)
                self._draft_params = jax.tree_util.tree_map(
                    jax.device_put, draft_params,
                    self._draft_shard.params)
                self._draft_cache = {
                    n: jax.device_put(a, self._draft_shard.cache[n])
                    for n, a in self._draft_cache.items()}
        # async_dispatch: keep ONE step/block in flight — tick() first
        # dispatches step N+1 (feeding the previous step's tokens from
        # the DEVICE array, never fetched) and only then blocks on step
        # N's tokens for host bookkeeping, overlapping host scheduling
        # with device compute.  Per-request tokens are identical to the
        # sync path; the one observable schedule shift is that a QUEUED
        # request admits one tick later after a retire (for sampled
        # requests that shifts WHICH global steps the slot occupies —
        # the documented batched-serving dependence above).
        self._async = bool(async_dispatch)
        self._inflight: dict | None = None
        # per-request sampling (round-5): one base key; device step n
        # draws with fold_in(base, n) — the same schedule for tick and
        # tick_block, so the two paths produce identical samples.  A
        # slot's draws depend on its batch-mates only through WHICH
        # global steps it occupies (standard for batched serving).
        self._base_key = jax.random.PRNGKey(seed)
        self._step_no = 0
        # chunked prefill: a whole prompt becomes ONE admission-time step
        # (generate.prefill_slot) instead of len(prompt) ticks; prompts pad
        # to power-of-two buckets so XLA compiles one prefill per bucket.
        # MoE models prefill too (round-5): the pad mask reaches the
        # router, padding claims no expert capacity, and the chunk uses
        # the dropless capacity bound — admission routes exactly like
        # token-by-token feeding.
        # prefill_chunk=N (round-5, vLLM-style): admission instead walks
        # the prompt in FIXED N-token chunks (generate.prefill_slot_chunk,
        # each attending the rows earlier chunks filled) — bounded
        # activation memory and ONE executable for ANY prompt length
        if prefill_chunk is not None:
            if not prefill:
                # the combination would silently degrade to token-by-token
                # feeding — neither the bounded-memory chunks the caller
                # asked for nor whole-prompt prefill
                raise ValueError(
                    "prefill_chunk requires prefill=True (chunked "
                    "admission IS a prefill mode)")
            window = min(max_len, cfg.max_seq_len)
            if not 1 <= int(prefill_chunk) <= window:
                raise ValueError(
                    f"prefill_chunk must be in [1, {window}] "
                    f"(the serving window), got {prefill_chunk}")
        # whole-prompt prefill executables resolve PER BUCKET at
        # admission (_get_prefill_fn(cfg, bucket)); this marker is the
        # factory, kept callable-shaped so `is not None` mode checks read
        # the same as before
        # the paged layout routes ALL prefill admission through the
        # offset-aware kv_pool.paged_prefill_chunk executables (a shared
        # prefix moves the chunk's start past the adopted blocks, which
        # the contiguous bucket/chunk programs cannot express)
        self._prefill_on = bool(prefill)
        self._prefill = ((lambda bucket: _get_prefill_fn(
                             cfg, bucket, self._shard))
                         if prefill and prefill_chunk is None
                         and not self._paged else None)
        self._chunk = (int(prefill_chunk) if prefill_chunk is not None
                       else None)
        self._prefill_chunk = (_get_prefill_chunk_fn(cfg, self._shard)
                               if prefill and self._chunk
                               and not self._paged else None)
        # budgeted admission (stall-free continuous batching,
        # Sarathi-style chunked-prefill co-scheduling): prefill_budget=N
        # (or PADDLE_TPU_PREFILL_BUDGET) caps the prefill tokens any ONE
        # scheduler round may run.  Admission then only CLAIMS a slot
        # (state "admitting") and each round advances the oldest
        # admitting slot by one budget-wide chunk, interleaved with the
        # decode step — a 4k-token prompt no longer freezes every
        # decoding request for its whole prefill.  The budget is the
        # chunk width of the admission executables (contiguous:
        # prefill_slot_chunk at width N; paged: paged_prefill_chunk at
        # width N — the offset-aware resume-at-pos0 machinery), so it
        # rides decode_jit_key.  Greedy tokens are bit-identical to
        # monolithic admission: chunked prefill is exact math (the paged
        # layout ALWAYS admits chunked), only the host schedule changes.
        # When > 0 it supersedes the prefill/prefill_chunk admission
        # modes above; prefilled handoffs (submit_prefilled) stay
        # monolithic — injection is one cheap row-write, not a prefill.
        if prefill_budget is not None:
            b = int(prefill_budget)
            if b < 0:
                raise ValueError(
                    f"prefill_budget must be >= 0, got {b}")
            if b > 0 and not prefill:
                raise ValueError(
                    "prefill_budget requires prefill=True (budgeted "
                    "admission IS a prefill mode)")
        else:
            b = _flags.prefill_budget() if prefill else 0
        self._budget = min(b, min(max_len, cfg.max_seq_len)) if b else 0
        # per-slot host state
        self._free = list(range(max_batch))
        self._slots: dict[int, dict] = {}        # slot -> request state
        self._queue: list[dict] = []             # waiting requests
        self._results: dict[int, list] = {}
        self._dropped: set[int] = set()          # rids abandoned by close()
        self._streams: dict[int, dict] = {}      # rid -> open handoff stream
        self._next_rid = 0
        # decode-gap probe (the stall the budget exists to kill): host
        # timestamp of the last tick that appended decode tokens; the
        # next appending tick observes the gap as serving.decode_gap_ms.
        # None while idle — an empty server's first tick is not a stall.
        self._gap_anchor: float | None = None
        # resilience layer (PADDLE_TPU_RESILIENCE=0 restores fail-fast):
        # per-request deadlines shed expired queued work, an OOM on a
        # tick engages the degradation chain (drop to sync dispatch ->
        # halve the admitted batch -> evict lowest-priority slots ->
        # re-tick — the reference's retry-on-OOM allocator chain at
        # scheduler granularity), and a wall-budget watchdog recovers a
        # wedged async step with slot state intact.
        self._resil = _resilience.enabled()
        self._default_ttl = _flags.request_ttl_s()
        self._step_budget = _flags.step_budget_s()
        self._admit_cap = max_batch     # halved by the OOM chain
        self._status: dict[int, str] = {}   # rid -> "timeout" | "error"
        #                                   #      | "rejected"
        self._err_reason: dict[int, str] = {}   # rid -> why "error"
        self._wedged = False            # a wedge was detected, not yet
        self._wedge_event = False       # ... recovered by a clean tick
        self._in_tick = False           # guard re-entrancy (block fallback)
        # admission control (text/admission.py): per-tenant token
        # buckets + bounded per-class queues at submit, and the SLO
        # degradation ladder consulted by _admit/_claim_admitting (admit
        # cap, pre-warmed budget rung, spec-off, shed).  Decided once at
        # construction like _tel/_resil: PADDLE_TPU_ADMISSION=0 builds
        # NO controller and every hot-path consult is `is None` —
        # greedy FIFO admission, bit-identical to the pre-admission
        # server.  The budget rungs are ladder_widths(self._budget);
        # warmup() pre-compiles every rung so a ladder move never
        # retraces mid-serving.
        self._adm = (_admission.AdmissionController(
                         scope="serving",
                         budget_rungs=_admission.ladder_widths(
                             self._budget))
                     if _flags.admission_enabled() else None)
        # multi-tenant adapter pool (text/adapters.py): N LoRA products
        # served from ONE base server.  The pool's stacked [A, ...]
        # leaves join every step call as a replicated extra input and the
        # jitted step gathers each slot's (a, b) pair by its int32
        # adapter id — id 0 is the all-zero base row, so a pool-attached
        # server with only base traffic produces the SAME TOKENS as a
        # pool-less one (the delta is + 0.0).  pool=None keeps every
        # code path byte-identical to the pre-adapter server.
        self._adapters = adapter_pool
        if adapter_pool is not None:
            if cfg.moe is not None:
                raise NotImplementedError(
                    "adapter_pool with an MoE config is not supported "
                    "yet — the adapter step kinds have no joint-routing "
                    "twin (the gathered LoRA delta composes with dense "
                    "FFNs only)")
            if (generate._cfg_key(adapter_pool.cfg)
                    != generate._cfg_key(cfg)):
                raise ValueError(
                    "adapter_pool was built for a different GPTConfig "
                    "than this server — pool and server must share the "
                    "base model geometry")
            if any(k.endswith(("_lora_a", "_lora_b"))
                   for k in params["blocks"]):
                raise ValueError(
                    "params already carry lora leaves — merge or strip "
                    "them before attaching an adapter_pool (the pool's "
                    "gathered delta would stack on top of them)")

    # -- request lifecycle --------------------------------------------------

    def submit(self, prompt, max_new_tokens: int = 32,
               stop: list | None = None, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0,
               ttl_s: float | None = None, priority: int = 0,
               tenant: str | None = None,
               adapter: str | None = None, constraint=None) -> int:
        """``stop``: optional list of token SEQUENCES; generation ends
        (sequence included) as soon as the generated tail matches one.

        ``temperature``/``top_k``/``top_p`` (round-5): PER-REQUEST
        sampling — greedy at temperature 0 (the default, bit-identical
        to before); otherwise the same scale→top-k→nucleus pipeline as
        ``generate``, applied per slot so one batch can mix greedy and
        sampled requests.

        ``ttl_s``: per-request deadline (default from
        ``PADDLE_TPU_REQUEST_TTL_S``; None = none) — a request still
        QUEUED past its TTL is shed with the ``timeout`` status
        (``result`` raises ``resilience.DeadlineExceeded``) instead of
        occupying a slot.  ``priority`` (higher = keep longer): the OOM
        degradation chain evicts the lowest-priority slots first, and
        admission control buckets it into three classes (<=0 low, 1
        normal, >=2 high) for queue bounds and shed ordering.

        ``tenant``: admission-control identity — with
        ``PADDLE_TPU_TENANT_RATE`` set, each tenant's admitted tokens
        (prompt + max_new) draw from its own token bucket; an empty
        bucket REJECTS the request at the door (status ``rejected``,
        ``result`` raises ``resilience.Overloaded`` — distinct from the
        TTL ``timeout``: a reject is the back-off signal, the request
        never queued).  ``tenant=None`` shares one default bucket.

        ``adapter``: serve this request under a named LoRA from the
        attached ``adapter_pool`` (None = the tenant's default adapter
        if one was set via ``AdapterPool.set_tenant_default``, else the
        base model).  ``constraint``: constrained decoding — a
        :class:`~paddle_tpu.text.adapters.Constraint` spec (TokenSet /
        Regex / JsonSchema, or a bare iterable of allowed token ids)
        compiled host-side to a per-slot automaton; each step bans
        disallowed tokens with an additive mask inside the jitted
        sampler, so greedy AND sampled slots only ever emit tokens the
        automaton accepts."""
        req = self._build_request(prompt, max_new_tokens, stop,
                                  temperature, top_k, top_p, ttl_s,
                                  priority, tenant=tenant,
                                  adapter=adapter, constraint=constraint)
        if self._tel:
            _telemetry.count("serving.requests_submitted")
        if self._adm is not None:
            self._adm.control_tick()
            ok, _reason = self._adm.admit(
                tenant, priority, len(req["prompt"]) + req["max_new"])
            if not ok:
                self._status[req["rid"]] = "rejected"
                if self._tel:
                    _telemetry.count("serving.requests_rejected")
                self._tel_gauges()
                return req["rid"]
        self._queue.append(req)
        if self._adm is not None:
            self._shed_queue_overflow()
        self._admit()
        self._tel_gauges()
        return req["rid"]

    def _shed_queue_overflow(self) -> None:
        """Enforce the bounded per-class queues: while any class is over
        ``PADDLE_TPU_ADMISSION_QUEUE_CAP``, retire the controller's
        victim (lowest over-cap class, newest entry) with the
        ``rejected`` status.  Runs after every enqueue, so the bound
        holds between submits, not just eventually."""
        while True:
            i = self._adm.overflow_victim(self._queue)
            if i is None:
                return
            req = self._queue.pop(i)
            self._status[req["rid"]] = "rejected"
            self._adm.count_shed(req.get("priority", 0), "queue_full")
            if self._tel:
                _telemetry.count("serving.requests_rejected")

    def _build_request(self, prompt, max_new_tokens, stop, temperature,
                       top_k, top_p, ttl_s, priority,
                       tenant=None, adapter=None, constraint=None) -> dict:
        """Validate one request and mint its queue entry (the shared
        half of :meth:`submit` and :meth:`submit_prefilled`)."""
        prompt, stop, ttl, top_k = validate_request(
            prompt, max_new_tokens, stop, temperature, top_k, top_p,
            ttl_s, window=min(self.max_len, self.cfg.max_seq_len),
            vocab_size=self.cfg.vocab_size,
            default_ttl=self._default_ttl)
        aid = 0
        if adapter is not None and self._adapters is None:
            raise ValueError(
                f"adapter={adapter!r} but no adapter_pool attached to "
                f"this server")
        if self._adapters is not None:
            if adapter is None:
                adapter = self._adapters.default_for(tenant)
            aid = self._adapters.resolve(adapter)
        if constraint is not None:
            if self.cfg.moe is not None:
                # the masked step kinds have no joint-routing twin yet
                # (ROADMAP follow-up) — reject at the door, not ticks
                # later with a silent unconstrained fallback
                raise NotImplementedError(
                    "constrained decoding on an MoE server is not "
                    "supported yet (no joint-routing masked step kind)")
            from . import adapters as _ad

            # compile at the door (and discard): a malformed spec raises
            # HERE in the caller's frame, not ticks later at admission
            _ad.compile_constraint(constraint, self.cfg.vocab_size)
        if self._paged:
            # a request needing more blocks than the whole pool can
            # NEVER be admitted (eviction frees other tenants' blocks,
            # not capacity) — rejecting here prevents it parking at the
            # queue front forever and livelocking the serve loop
            need = -(-(len(prompt) + max_new_tokens) // self._pool.bs)
            if need > self._pool.N:
                raise ValueError(
                    f"request needs {need} KV blocks but the pool has "
                    f"{self._pool.N} (raise num_blocks or shrink the "
                    f"request)")
        rid = self._next_rid
        self._next_rid += 1
        return {"rid": rid, "prompt": prompt,
                "max_new": max_new_tokens, "stop": stop,
                "temperature": float(temperature),
                "top_k": top_k, "top_p": float(top_p),
                "ttl": ttl, "priority": int(priority),
                "tenant": tenant,
                "adapter": aid,
                "adapter_name": adapter if aid else None,
                "constraint": constraint,
                "t_submit": time.perf_counter(),
                "t_enqueue": time.perf_counter()}

    def submit_prefilled(self, prompt, rows, logits,
                         max_new_tokens: int = 32, stop: list | None = None,
                         temperature: float = 0.0, top_k: int = 0,
                         top_p: float = 1.0, ttl_s: float | None = None,
                         priority: int = 0, trace=None) -> int:
        """Admit a request whose prompt a PREFILL WORKER already ran
        (round 9, the fleet's prefill/decode handoff): ``rows`` are the
        worker's finished cache rows — leaves ``[L, 1, n, Hkv(, hd)]``
        in this server's storage dtype (int8 scale planes included) —
        and ``logits`` its admission logits ``[V]``.  Admission writes
        the rows into a slot (one donated injector executable per
        power-of-two bucket; paged: through the slot's block table) and
        seeds the first token from ``logits`` with the exact sampling/
        telemetry/NaN-guard semantics of local prefill — decode then
        proceeds bit-identically to a locally prefilled request."""
        req = self._build_request(prompt, max_new_tokens, stop,
                                  temperature, top_k, top_p, ttl_s,
                                  priority)
        n = len(req["prompt"])
        rows = {name: np.asarray(v) for name, v in rows.items()}
        want = {name for name in self.cache if name != "tables"}
        if set(rows) != want:
            raise ValueError(
                f"prefilled rows leaves {sorted(rows)} do not match the "
                f"cache leaves {sorted(want)} (KV dtype mismatch between "
                f"prefill worker and decode server?)")
        for name, v in rows.items():
            have = self.cache[name].dtype
            if v.dtype != have:
                # bf16 worker rows into an fp32 server would otherwise
                # CAST silently in the injector and break the
                # bit-parity-with-local-admission contract
                raise ValueError(
                    f"prefilled rows leaf {name!r} is {v.dtype}, this "
                    f"server stores {have} (PADDLE_TPU_KV_DTYPE drift "
                    f"between prefill worker and decode server?)")
        if rows["k"].shape[2] != n:
            raise ValueError(
                f"prefilled rows cover {rows['k'].shape[2]} positions "
                f"for a {n}-token prompt")
        req["prefilled"] = (rows, np.asarray(logits, np.float32))
        if trace:
            req["trace"] = trace
        self._queue.append(req)
        if self._tel:
            _telemetry.count("serving.requests_submitted")
            _telemetry.count("serving.prefilled_submissions")
        self._admit()
        self._tel_gauges()
        return req["rid"]

    def adopt_request(self, req: dict) -> int:
        """Enqueue a request dict drained from ANOTHER server (the fleet
        router's re-route path): a fresh local rid and queue-entry clock
        (TTL stays a queue-wait bound), with progress carry and any
        prefilled payload preserved.  The dict must come from
        :meth:`drain_queue` / ``_build_request`` — it is trusted, not
        re-validated (but the window is re-checked: replicas may be
        heterogeneous)."""
        total = len(req["prompt"]) + req["max_new"] \
            - len(req.get("carry", ()))
        if total > min(self.max_len, self.cfg.max_seq_len):
            raise ValueError(
                f"adopted request needs a {total}-row window; this "
                f"replica serves {min(self.max_len, self.cfg.max_seq_len)}")
        if self._paged:
            # the submit-side whole-pool check, re-applied per replica
            # (pools may be heterogeneous): a request no eviction can
            # ever fit would park at the queue front and livelock the
            # serve loop
            need = -(-total // self._pool.bs)
            if need > self._pool.N:
                raise ValueError(
                    f"adopted request needs {need} KV blocks; this "
                    f"replica's pool has {self._pool.N}")
        rid = self._next_rid
        self._next_rid += 1
        r = dict(req, rid=rid, t_enqueue=time.perf_counter())
        r.setdefault("t_submit", time.perf_counter())
        self._queue.append(r)
        if self._tel:
            _telemetry.count("serving.requests_adopted")
        self._admit()
        self._tel_gauges()
        return rid

    def _shed_expired(self):
        """Deadline shedding: drop queued requests past their TTL with
        the ``timeout`` status — they never occupy a slot, and
        ``result()`` raises ``resilience.DeadlineExceeded`` for them.
        Host-clock arithmetic only; active slots are never shed (their
        device work is already paid for)."""
        if not self._resil or not self._queue:
            return
        now = time.perf_counter()
        kept = []
        for req in self._queue:
            ttl = req.get("ttl")
            # the deadline bounds QUEUE WAIT (time in this queue entry),
            # not total request age: an OOM-evicted request re-enqueues
            # with a fresh t_enqueue so server-side eviction can never
            # turn its TTL into a total-age limit and discard paid-for
            # progress
            if ttl is not None \
                    and now - req.get("t_enqueue", req["t_submit"]) > ttl:
                rid = req["rid"]
                self._status[rid] = "timeout"
                if self._tel:
                    _telemetry.count("serving.requests_shed")
                    _telemetry.count("resilience.deadline_sheds")
                    _telemetry.event("serving.shed", req["t_submit"], now,
                                     rid=rid, ttl_s=ttl)
            else:
                kept.append(req)
        self._queue[:] = kept

    def _fail_request(self, st, slot, reason: str):
        """Retire one request with the ``error`` status (NaN guard):
        the slot frees for the next tenant, the server lives."""
        rid = st["rid"]
        self._status[rid] = "error"
        self._err_reason[rid] = reason
        if self._paged:
            self._pool.free_slot(slot)
        self._free.append(slot)
        if self._tel:
            _telemetry.count("serving.requests_failed")
            _telemetry.count("resilience.nan_requests")
            _telemetry.event("serving.request_failed",
                             st.get("t_submit", time.perf_counter()),
                             time.perf_counter(), tid=slot, rid=rid,
                             reason=reason)

    def _admit(self):
        self._shed_expired()
        # the OOM-chain cap binds every class (it is a memory bound);
        # the controller's ladder cap is SHED pressure and binds class-0
        # admissions only — throttling the high-priority traffic the
        # ladder protects would make degradation self-defeating
        cap = self._admit_cap
        adm_cap = None
        if self._adm is not None:
            adm_cap = min(cap,
                          self._adm.effective_admit_cap(self.max_batch))
            if self._adm.engaged and len(self._queue) > 1:
                # a CONFIGURED controller spends free slots on the
                # highest priority class first (stable sort — FIFO
                # within a class); the unconfigured default keeps
                # strict FIFO so plain ADMISSION=1 matches the
                # ADMISSION=0 admit order exactly
                self._queue.sort(key=lambda r: (
                    -_admission.priority_class(r.get("priority", 0)),
                    r.get("t_enqueue", 0.0)))
        while self._queue and self._free \
                and len(self._slots) < cap:
            if (adm_cap is not None and len(self._slots) >= adm_cap
                    and _admission.priority_class(
                        self._queue[0].get("priority", 0)) == 0):
                # queue is class-sorted, so a class-0 head means no
                # higher-priority request is waiting either
                break
            slot = self._free.pop()
            req = self._queue.pop(0)
            t_admit = time.perf_counter()
            st = {
                "rid": req["rid"], "prompt": req["prompt"],
                "max_new": req["max_new"], "stop": req.get("stop", []),
                "temperature": req.get("temperature", 0.0),
                "top_k": req.get("top_k", 0),
                "top_p": req.get("top_p", 1.0),
                # an OOM-evicted request re-admits with its progress
                # carried: prompt = original + generated-so-far, and
                # ``carry`` seeds the generated list so result() returns
                # the FULL generation.  ``base`` is the ORIGINAL prompt
                # length — carried tokens appear in BOTH the extended
                # prompt and ``generated``, so the feed index is
                # sequence[i] = prompt[i] while i < len(prompt), else
                # generated[i - base] (i - len(prompt) would skip the
                # carry and re-feed from the wrong offset)
                "generated": list(req.get("carry", ())),
                "base": len(req["prompt"]) - len(req.get("carry", ())),
                "ttl": req.get("ttl"),
                "priority": req.get("priority", 0),
                "tenant": req.get("tenant"),
                # OOM-evict requeue aging (satellite: starvation bound):
                # how many times this request has been evicted and
                # re-queued; past PADDLE_TPU_EVICT_REQUEUE_MAX it fails
                # honestly instead of thrashing forever
                "evictions": req.get("evictions", 0),
                "pos": 0,   # next position == index of the token to feed
                # span timestamps (host clock only; never a device sync)
                "t_submit": req.get("t_submit", t_admit),
                "t_admit": t_admit,
                # fleet trace context (router-minted; absent on direct
                # submits and whenever telemetry is off) — every span
                # this slot records lands under it
                "trace": req.get("trace"),
                # multi-tenant serving: which pool row this slot gathers
                # (0 = base model) and the original spec — the spec (not
                # the live automaton) survives OOM-evict requeues
                "adapter": req.get("adapter", 0),
                "adapter_name": req.get("adapter_name"),
                "constraint_spec": req.get("constraint"),
            }
            if req.get("constraint") is not None:
                from . import adapters as _ad

                cst = _ad.compile_constraint(req["constraint"],
                                             self.cfg.vocab_size)
                # an OOM-evicted request re-admits mid-output: replay
                # the carried tokens so the automaton resumes where the
                # evicted slot's state machine stood
                for tt in req.get("carry", ()):
                    cst.advance(int(tt))
                st["constraint"] = cst
            if self._spec_on and self._adm is not None \
                    and self._adm.spec_forced():
                # ladder rung >= RUNG_SPEC_OFF: this admission decodes
                # plain, via the SAME per-slot flag the acceptance-rate
                # fallback sets — verify passes stop competing with
                # decode while the server is degraded
                st["spec_off"] = True
                if self._tel:
                    _telemetry.count("admission.spec_forced")
            if self._tel:
                self._observe(
                    "serving.queue_wait_ms",
                    (t_admit - st["t_submit"]) * 1e3)
            if req.get("stream"):
                # streamed fleet handoff: claim the slot now with zero
                # rows present — chunks inject as they arrive
                # (stream_prefilled_rows), decode ticks riding the
                # frontier exactly like budgeted admission
                if not self._claim_stream(req, slot, st):
                    break
                continue
            if self._budget and "prefilled" not in req \
                    and len(req["prompt"]) > self._budget:
                # budgeted admission: claim the slot NOW (plan the chunk
                # starts, paged: adopt + allocate) but run ZERO prefill —
                # each scheduler round advances the oldest admitting slot
                # by one budget-width chunk (_advance_admitting),
                # interleaved with decode steps, so a long prompt never
                # stalls the decode loop.  Prompts that fit one chunk
                # take the monolithic path below: one executable call
                # either way, and admission-tick latency stays minimal.
                # Handoff-admitted requests ("prefilled") stay monolithic
                # too — their rows arrive computed; injection is a copy
                if not self._claim_admitting(req, slot, st):
                    break
                continue
            if "prefilled" in req or self._prefill is not None \
                    or self._prefill_chunk is not None \
                    or (self._paged and self._prefill_on):
                n = len(req["prompt"])
                prefill_calls = 1
                try:
                    if "prefilled" in req:
                        from . import kv_pool as _kv

                        try:
                            prefill_name, logits = \
                                self._inject_prefilled(req, slot)
                        except _kv.PoolExhausted:
                            # same parking rule as local paged
                            # admission below: wait for blocks, never
                            # fail the submit
                            self._pool.free_slot(slot)
                            self._free.append(slot)
                            self._queue.insert(0, req)
                            if self._tel:
                                _telemetry.count("kv_pool.admit_blocked")
                            break
                    elif self._paged:
                        from . import kv_pool as _kv

                        try:
                            prefill_name, prefill_calls, logits = \
                                self._paged_prefill_slot(req, slot)
                        except _kv.PoolExhausted:
                            # no free blocks even after evicting the cold
                            # prefix cache: the request WAITS (active
                            # slots will retire and free blocks) instead
                            # of failing the submit — park it back at
                            # the queue front and stop admitting
                            self._pool.free_slot(slot)
                            self._free.append(slot)
                            self._queue.insert(0, req)
                            if self._tel:
                                _telemetry.count("kv_pool.admit_blocked")
                            break
                    elif self._prefill is not None:
                        # the padded chunk must fit both the wpe table
                        # and the cache window; both bounds >= n (submit
                        # checked)
                        bucket = _pow2_bucket(n, self.max_len,
                                              self.cfg.max_seq_len)
                        padded = np.zeros((1, bucket), np.int32)
                        padded[0, :n] = req["prompt"]
                        if self._adapters is not None:
                            # pool attached: ALL admissions run the
                            # adapter prefill (aid 0 merges the zero
                            # row — token-parity with the plain path),
                            # so one executable set serves the mixed
                            # batch and base-only warmup covers it
                            prefill_name = f"adapter_prefill@{bucket}"
                            fn = _get_adapter_prefill_fn(
                                self.cfg, bucket,
                                self._adapters.pool_key(), self._shard)
                            logits, self.cache = fn(
                                self.params, self.cache,
                                self._adapters.stacks(),
                                jnp.asarray(st["adapter"]),
                                jnp.asarray(padded), jnp.asarray(n),
                                jnp.asarray(slot))
                        else:
                            prefill_name = f"prefill@{bucket}"
                            logits, self.cache = self._prefill(bucket)(
                                self.params, self.cache,
                                jnp.asarray(padded),
                                jnp.asarray(n), jnp.asarray(slot))
                    else:
                        # fixed-chunk walk: every chunk reuses ONE
                        # executable.  The LAST window starts at n - C
                        # (overlapping the previous chunk) instead of
                        # overrunning the cache/wpe bounds — overlapped
                        # rows recompute to identical values
                        # (deterministic function of the same tokens +
                        # already-correct prefix), and
                        # dynamic_update_slice would otherwise CLAMP an
                        # overrunning start and silently shift the
                        # written rows (_chunk_attend_block's
                        # precondition)
                        C = self._chunk
                        if n <= C:
                            starts = [0]
                        else:
                            starts = list(range(0, n - C, C)) + [n - C]
                        prefill_calls = len(starts)
                        if self._adapters is not None:
                            prefill_name = "adapter_prefill_chunk"
                            afn = _get_adapter_prefill_chunk_fn(
                                self.cfg, self._adapters.pool_key(),
                                self._shard)
                            _ad_st = self._adapters.stacks()
                            _aid = jnp.asarray(st["adapter"])
                            pf = lambda p, c, t, p0, ln, sl: afn(
                                p, c, _ad_st, _aid, t, p0, ln, sl)
                        else:
                            prefill_name = "prefill_chunk"
                            pf = self._prefill_chunk
                        logits = None
                        for i in starts:
                            chunk = req["prompt"][i:i + C]
                            padded = np.zeros((1, C), np.int32)
                            padded[0, :len(chunk)] = chunk
                            logits, self.cache = pf(
                                self.params, self.cache,
                                jnp.asarray(padded),
                                jnp.asarray(i), jnp.asarray(len(chunk)),
                                jnp.asarray(slot))
                    if self._spec_on and self.draft_cfg is not None:
                        st["spec_dpos"] = self._spec_draft_admit(req,
                                                                 slot, n)
                    # one host fetch of the admission logits; the
                    # timestamp right after it bounds the DEVICE window
                    # (the sampling below is pure host math and must not
                    # be charged to the prefill executable's step wall)
                    logits_np = np.asarray(logits)
                except Exception:
                    # a failed admission prefill (e.g. a real OOM the
                    # guard will degrade around) must neither lose the
                    # request nor leak the slot: both go back where they
                    # came from before the error propagates (paged: the
                    # slot's partially mapped blocks return to the pool)
                    if self._paged:
                        self._pool.free_slot(slot)
                    self._free.append(slot)
                    self._queue.insert(0, req)
                    raise
                t_prefill_done = time.perf_counter()
                if _faults.active():
                    logits_np = _faults.corrupt_nan("logits", logits_np)
                if self._resil and not np.isfinite(logits_np).all():
                    # NaN guard at admission: the logits are ALREADY on
                    # the host, so the finite check costs no extra sync.
                    # A poisoned request fails cleanly (status "error",
                    # slot freed) instead of feeding garbage tokens —
                    # with resilience off the garbage argmax proceeds,
                    # exactly the pre-guard behavior.
                    self._fail_request(st, slot,
                                       "non-finite prefill logits")
                    continue
                cst = st.get("constraint")
                if cst is not None:
                    # first token: the logits are already host-side, so
                    # the constraint masks HERE (same -inf law the jitted
                    # steps apply) — the automaton then advances below
                    from . import adapters as _ad

                    logits_np = _ad.apply_constraint_host(logits_np, cst)
                if st["temperature"] > 0.0:
                    # admission draws host-side from the filtered law,
                    # seeded per rid off the server key — deterministic
                    # regardless of admission order or batch-mates
                    p = generate._filtered_probs(
                        logits_np, st["temperature"],
                        st["top_k"], st["top_p"])
                    rng = np.random.default_rng(generate._key_seed(
                        jax.random.fold_in(self._base_key,
                                           (1 << 20) + st["rid"])))
                    t = int(rng.choice(len(p), p=p))
                else:
                    t = int(logits_np.argmax())
                st["generated"].append(t)
                st["pos"] = n  # cache rows [0, n) are filled
                if self._tel:
                    # the argmax/choice above already fetched the host
                    # value, so "now" IS the first-token time — TTFT and
                    # the prefill span cost zero extra syncs
                    now = time.perf_counter()
                    st["t_first"] = st["t_last"] = now
                    self._observe(
                        "serving.ttft_ms", (now - st["t_submit"]) * 1e3)
                    _telemetry.event("serving.prefill", t_admit, now,
                                     tid=slot, rid=st["rid"],
                                     prompt_len=n)
                    self._span_ring.record(
                        st.get("trace"), "prefill", t_admit, now,
                        rid=st["rid"], prompt_len=n)
                    # per-EXECUTION wall bounded at the logits fetch
                    # (host sampling excluded): chunked admission ran
                    # the one chunk executable len(starts) times — the
                    # device feed joins this with ONE execution's FLOPs
                    _telemetry.note_step_time(
                        f"serving.{prefill_name}",
                        (t_prefill_done - t_admit) / prefill_calls)
                    _telemetry.count("serving.tokens_generated")
                    self._count_local("serving.tokens_generated")
                # _finished (not the old max_new <= 1 test): a carried
                # (OOM-evicted, re-admitted) request may hit its budget
                # on the admission token
                fin = self._constraint_push(st, t)
                if self._finished(st, t) or fin:
                    self._results[st["rid"]] = st["generated"]
                    if self._paged:
                        self._pool.free_slot(slot)
                    self._free.append(slot)
                    self._tel_retire(st, slot)
                    continue
            if self._spec_on and self.draft_cfg is not None:
                # prefill=False admission: the draft saw nothing yet —
                # the first spec round's catch-up feeds it the sequence
                st.setdefault("spec_dpos", 0)
            self._slots[slot] = st

    # -- budgeted admission: chunked-prefill co-scheduling ------------------

    def _effective_budget(self) -> int:
        """The prefill chunk width NEW budgeted admissions claim at: the
        base budget, or — under SLO degradation — the controller's
        current pre-warmed ladder rung (admission.ladder_widths; every
        rung is compiled by warmup(), so a ladder move is a host-side
        executable pick, never a retrace).  With no controller this is
        exactly ``self._budget``."""
        if self._adm is None:
            return self._budget
        return self._adm.effective_budget(self._budget)

    def _claim_admitting(self, req, slot, st) -> bool:
        """Budgeted admission, part 1 (claim): reserve the slot and plan
        the prompt's chunk starts WITHOUT running any prefill.  The
        starts follow the monolithic walks exactly — contiguous: the
        fixed-chunk rule at width=budget; paged: adopt the longest
        indexed prefix first, then the suffix rule of
        ``_paged_prefill_slot`` — so a budgeted admission writes the
        same rows through the same offset-aware executables, just
        spread over scheduler rounds.  Paged block allocation happens
        here in full (rows [min(starts), n)): the decode steps the slot
        rides during admission write its frontier row, which must
        already be mapped.  A PoolExhausted parks the request back at
        the queue front exactly like monolithic admission.

        Returns False when admission must stop (request parked)."""
        prompt = req["prompt"]
        n = len(prompt)
        window = min(self.max_len, self.cfg.max_seq_len)
        W = min(self._effective_budget(), window)
        if self._paged:
            from . import kv_pool as _kv

            alloc = self._pool
            try:
                # adapter≠0 prompts never share prefix-cache rows: the
                # cached rows were computed under a DIFFERENT weight
                # delta (or the base), so adoption would serve wrong
                # attention state.  Base (adapter 0) traffic shares as
                # before.
                shared = alloc.adopt_prefix(slot, prompt) \
                    if self._prefill_on and not req.get("adapter") else 0
                self._drain_restores()
                if n - shared <= W:
                    starts = [shared if shared + W <= window
                              else max(0, n - W)]
                else:
                    starts = list(range(shared, n - W, W)) + [n - W]
                while True:
                    try:
                        alloc.ensure_rows(slot, min(starts), n)
                        break
                    except _kv.PoolExhausted:
                        # the OOM chain's first rung at admission (see
                        # _paged_prefill_slot)
                        if self._evict_or_spill(_EVICT_BATCH) == 0:
                            raise
            except _kv.PoolExhausted:
                self._pool.free_slot(slot)
                self._free.append(slot)
                self._queue.insert(0, req)
                if self._tel:
                    _telemetry.count("kv_pool.admit_blocked")
                return False
            self._apply_pool_ops()
        else:
            starts = ([0] if n <= W
                      else list(range(0, n - W, W)) + [n - W])
        st["admitting"] = True
        st["admit_starts"] = starts
        st["admit_i"] = 0
        # the chunk width the starts were planned at: _advance_admitting
        # runs THIS width for the slot's whole admission even if the
        # ladder moves the effective budget mid-flight (the starts and
        # the executable must agree; new claims pick up the new rung)
        st["admit_w"] = W
        # pos doubles as the prefill frontier: rows [starts[0], pos)
        # are written.  While admitting, decode dispatches feed
        # prompt[pos] at pos — the frontier row they write is rewritten
        # (bit-identically, by chunk contiguity) by the next chunk, the
        # same stale-row argument as spec catch-up rides
        st["pos"] = starts[0]
        self._slots[slot] = st
        if self._tel:
            _telemetry.count("serving.admitting_claims")
        return True

    def _advance_admitting(self) -> bool:
        """Budgeted admission, part 2 (advance): run ONE budget-width
        prefill chunk for the OLDEST admitting slot (dict order =
        claim order), then return — at most ``budget`` prefill tokens
        per scheduler round, the decode ticks interleaving in between.
        The last chunk graduates the slot to decoding.

        Host state (admit_i, pos) advances only AFTER the executable
        returned, so a failed call (real or injected OOM) leaves the
        slot exactly as before and the guard's retry re-runs the same
        chunk bit-exactly.  Returns True when a chunk ran."""
        slot = st = None
        for s_, st_ in self._slots.items():
            # stream slots are "admitting" for the ride/skip machinery
            # but have no chunk plan — their rows arrive off-tick
            if st_.get("admitting") and not st_.get("stream"):
                slot, st = s_, st_
                break
        if st is None:
            return False
        t0 = time.perf_counter()
        prompt = st["prompt"]
        n = len(prompt)
        window = min(self.max_len, self.cfg.max_seq_len)
        # the width the slot's starts were planned at (see
        # _claim_admitting); absent only for pre-upgrade state — then
        # the base budget is what the starts were built from
        W = st.get("admit_w") or min(self._budget, window)
        i = st["admit_i"]
        s = st["admit_starts"][i]
        chunk = prompt[s:s + W]
        padded = np.zeros((1, W), np.int32)
        padded[0, :len(chunk)] = chunk
        if self._adapters is not None:
            pk = self._adapters.pool_key()
            if self._paged:
                kind = f"adapter_paged_prefill@{W}"
                afn = _get_adapter_paged_prefill_fn(self.cfg, W, pk,
                                                    self._shard)
            else:
                kind = f"adapter_prefill_chunk@{W}"
                afn = _get_adapter_prefill_chunk_fn(self.cfg, pk,
                                                    self._shard, width=W)
            _ad_st = self._adapters.stacks()
            _aid = jnp.asarray(st.get("adapter", 0))
            fn = lambda p, c, t, p0, ln, sl: afn(p, c, _ad_st, _aid,
                                                 t, p0, ln, sl)
        elif self._paged:
            kind = f"paged_prefill@{W}"
            fn = _get_paged_prefill_fn(self.cfg, W, self._shard)
        else:
            kind = f"prefill_chunk@{W}"
            fn = _get_prefill_chunk_fn(self.cfg, self._shard, width=W)
        logits, self.cache = fn(
            self.params, self.cache, jnp.asarray(padded),
            jnp.asarray(s), jnp.asarray(len(chunk)), jnp.asarray(slot))
        if self._draft_cache is not None:
            # the draft twin walks the SAME chunk (the budgeted version
            # of _spec_draft_admit / _paged_prefill_slot's draft walk),
            # so graduation can set spec_dpos = n directly
            dfn = (_get_paged_prefill_fn(self.draft_cfg, W,
                                         self._draft_shard)
                   if self._paged else
                   _get_prefill_chunk_fn(self.draft_cfg,
                                         self._draft_shard, width=W))
            _, self._draft_cache = dfn(
                self._draft_params, self._draft_cache,
                jnp.asarray(padded), jnp.asarray(s),
                jnp.asarray(len(chunk)), jnp.asarray(slot))
        st["admit_i"] = i + 1
        st["pos"] = min(s + len(chunk), n)
        if self._tel:
            _telemetry.count("serving.prefill_chunks_interleaved")
            if self._paged:
                _telemetry.count("kv_pool.prefill_rows", len(chunk))
        if st["admit_i"] == len(st["admit_starts"]):
            self._graduate_admitting(slot, st, logits, t0, kind)
        return True

    def _graduate_admitting(self, slot, st, logits, t0, kind):
        """The last chunk landed: fetch the admission logits, draw the
        first token (the SAME per-rid host sampling as monolithic
        admission — bit-identical by construction), and flip the slot
        to decoding.  Paged: the completed prompt's blocks index for
        future prefix sharing, exactly where monolithic admission
        registers them."""
        prompt = st["prompt"]
        n = len(prompt)
        logits_np = np.asarray(logits)
        t_fetch = time.perf_counter()
        if _faults.active():
            logits_np = _faults.corrupt_nan("logits", logits_np)
        if self._resil and not np.isfinite(logits_np).all():
            # NaN guard at graduation — the budgeted twin of the
            # monolithic admission guard (same fetch, same cost)
            del self._slots[slot]
            self._fail_request(st, slot, "non-finite prefill logits")
            return
        cst = st.get("constraint")
        if cst is not None:
            # same host-side first-token masking as monolithic admission
            from . import adapters as _ad

            logits_np = _ad.apply_constraint_host(logits_np, cst)
        if st["temperature"] > 0.0:
            p = generate._filtered_probs(
                logits_np, st["temperature"], st["top_k"], st["top_p"])
            rng = np.random.default_rng(generate._key_seed(
                jax.random.fold_in(self._base_key,
                                   (1 << 20) + st["rid"])))
            t = int(rng.choice(len(p), p=p))
        else:
            t = int(logits_np.argmax())
        st["generated"].append(t)
        st["pos"] = n
        st.pop("admitting", None)
        st.pop("admit_starts", None)
        st.pop("admit_i", None)
        if self._paged and self._prefill_on and not st.get("adapter"):
            # adapter rows never index for sharing (see _claim_admitting)
            self._pool.register_prefix(slot, prompt)
        if self._spec_on and self.draft_cfg is not None:
            # draft chunks advanced in lockstep (see _advance_admitting);
            # without a draft cache the catch-up feeds from 0
            st["spec_dpos"] = n if self._draft_cache is not None else 0
        if self._tel:
            now = time.perf_counter()
            st["t_first"] = st["t_last"] = now
            self._observe("serving.ttft_ms",
                          (now - st["t_submit"]) * 1e3)
            _telemetry.event("serving.prefill",
                             st.get("t_admit", t0), now, tid=slot,
                             rid=st["rid"], prompt_len=n)
            self._span_ring.record(
                st.get("trace"), "prefill", st.get("t_admit", t0), now,
                rid=st["rid"], prompt_len=n)
            # only the FINAL chunk's wall is fetch-bounded (earlier
            # chunks dispatch without a sync), so per-execution timing
            # covers exactly this one execution
            _telemetry.note_step_time(f"serving.{kind}", t_fetch - t0)
            _telemetry.count("serving.tokens_generated")
            self._count_local("serving.tokens_generated")
        fin = self._constraint_push(st, t)
        if self._finished(st, t) or fin:
            # carried (OOM-evicted) requests may hit their budget on
            # the admission token, exactly like monolithic admission
            del self._slots[slot]
            self._results[st["rid"]] = st["generated"]
            if self._paged:
                self._pool.free_slot(slot)
            self._free.append(slot)
            self._tel_retire(st, slot)

    # -- streamed fleet handoff: per-chunk row injection --------------------

    def stream_prefilled_begin(self, prompt, max_new_tokens: int = 32,
                               stop: list | None = None,
                               temperature: float = 0.0, top_k: int = 0,
                               top_p: float = 1.0,
                               ttl_s: float | None = None,
                               priority: int = 0, trace=None) -> int:
        """Open a STREAMED prefill handoff — the chunked twin of
        :meth:`submit_prefilled`.  The caller (the fleet router, as a
        worker's chunks land) follows with one
        :meth:`stream_prefilled_rows` call per finished prefill chunk;
        the final chunk carries the admission logits and graduates the
        request to plain decoding in the same call.  The slot is
        claimed at admission with ZERO rows present and decode ticks
        ride it at the injected frontier exactly like budgeted
        admission (the frontier row a ride writes is rewritten
        bit-identically by the next chunk's injection), so the
        transfer overlaps this server's decode steps instead of
        stalling them.  Chunks that arrive while the request is still
        QUEUED buffer host-side and replay at claim — admission order
        is unchanged.  Decoded output is bit-identical to
        :meth:`submit_prefilled` with the same rows and logits."""
        req = self._build_request(prompt, max_new_tokens, stop,
                                  temperature, top_k, top_p, ttl_s,
                                  priority)
        req["stream"] = True
        if trace:
            req["trace"] = trace
        self._streams[req["rid"]] = {
            "req": req, "pending": [], "expect": 0,
            "slot": None, "st": None}
        self._queue.append(req)
        if self._tel:
            _telemetry.count("serving.requests_submitted")
            _telemetry.count("serving.stream_begins")
        self._admit()
        self._tel_gauges()
        return req["rid"]

    def _claim_stream(self, req, slot, st) -> bool:
        """Streamed-handoff admission (claim): reserve the slot and —
        paged — adopt the longest indexed prefix + allocate the FULL
        row range before any chunk lands, mirroring
        :meth:`_inject_prefilled`'s allocation exactly (worker rows
        for adopted blocks are bit-identical to what the index already
        holds, so those blocks are attended, never rewritten).  No
        prefill runs here; rows arrive via
        :meth:`stream_prefilled_rows`.  Returns False when admission
        must stop (request parked on pool pressure, the monolithic
        parking rule)."""
        prompt = req["prompt"]
        n = len(prompt)
        shared = 0
        if self._paged:
            from . import kv_pool as _kv

            try:
                if self._prefill_on:
                    shared = self._pool.adopt_prefix(slot, prompt)
                    self._drain_restores()
                while True:
                    try:
                        self._pool.ensure_rows(slot, shared, n)
                        break
                    except _kv.PoolExhausted:
                        # the OOM chain's first rung at admission (see
                        # _paged_prefill_slot)
                        if self._evict_or_spill(_EVICT_BATCH) == 0:
                            raise
            except _kv.PoolExhausted:
                self._pool.free_slot(slot)
                self._free.append(slot)
                self._queue.insert(0, req)
                if self._tel:
                    _telemetry.count("kv_pool.admit_blocked")
                return False
            self._apply_pool_ops()
        st["admitting"] = True      # decode ticks ride the frontier
        st["stream"] = True
        st["stream_shared"] = shared
        # pos doubles as the injected frontier: rows [0, pos) are
        # valid (adopted prefix now, injected chunks as they land)
        st["pos"] = shared
        self._slots[slot] = st
        sr = self._streams[st["rid"]]
        sr["slot"], sr["st"] = slot, st
        if self._tel:
            _telemetry.count("serving.stream_claims")
        # chunks that arrived while the request was queued replay now
        self._stream_drain(st["rid"])
        return True

    def stream_prefilled_rows(self, rid: int, start: int, stop: int,
                              rows, logits=None) -> None:
        """Fold one streamed chunk — worker cache rows for prompt
        positions ``[start, stop)``, leaves ``[L, 1, stop-start,
        Hkv(, hd)]`` in this server's storage dtype — into the
        request's slot through the pow2 injector bucket.  ``logits``
        ([V], float32) rides the FINAL chunk (``stop == n``):
        graduation happens in the same call, so the slot never sits
        complete awaiting a separate done frame (a window a decode
        ride could corrupt).  Chunks landing before the claim buffer
        host-side.  Raises on leaf/dtype/range mismatch — the
        transport is ordered, so a gap is a protocol bug, not a
        retry."""
        sr = self._streams.get(rid)
        if sr is None:
            raise KeyError(f"no open handoff stream for rid {rid}")
        if self._status.get(rid) is not None:
            # shed or failed while the chunks were in flight: late
            # rows drop, the record closes
            self._streams.pop(rid, None)
            return
        start, stop = int(start), int(stop)
        n = len(sr["req"]["prompt"])
        if start != sr["expect"] or stop <= start or stop > n:
            raise ValueError(
                f"stream chunk [{start}, {stop}) for rid {rid}: "
                f"expected start {sr['expect']} in a {n}-token prompt")
        if logits is None and stop == n:
            raise ValueError(
                f"final stream chunk for rid {rid} carries no "
                f"admission logits")
        if logits is not None and stop != n:
            raise ValueError(
                f"stream chunk [{start}, {stop}) for rid {rid} "
                f"carries logits before the final row {n}")
        rows = {name: np.asarray(v) for name, v in rows.items()}
        want = {name for name in self.cache if name != "tables"}
        if set(rows) != want:
            raise ValueError(
                f"stream chunk leaves {sorted(rows)} do not match the "
                f"cache leaves {sorted(want)}")
        for name, v in rows.items():
            have = self.cache[name].dtype
            if v.dtype != have:
                raise ValueError(
                    f"stream chunk leaf {name!r} is {v.dtype}, this "
                    f"server stores {have} (PADDLE_TPU_KV_DTYPE drift "
                    f"between prefill worker and decode server?)")
            if v.shape[2] != stop - start:
                raise ValueError(
                    f"stream chunk leaf {name!r} covers {v.shape[2]} "
                    f"positions for range [{start}, {stop})")
        sr["expect"] = stop
        sr["pending"].append(
            (start, stop, rows,
             None if logits is None else np.asarray(logits,
                                                    np.float32)))
        if sr["st"] is not None:
            self._stream_drain(rid)

    def _stream_drain(self, rid: int) -> None:
        """Inject every buffered chunk for a CLAIMED stream, in order;
        the chunk carrying logits graduates the slot (and may retire
        the request — single-token budgets finish on the admission
        token, like every admission path)."""
        sr = self._streams.get(rid)
        if sr is None or sr["st"] is None:
            return
        while sr["pending"]:
            start, stop, rows, logits = sr["pending"].pop(0)
            self._stream_inject(sr["slot"], sr["st"], start, stop,
                                rows)
            if logits is not None:
                self._graduate_stream(sr["slot"], sr["st"], logits)
                break

    def _stream_inject(self, slot, st, start, stop, rows) -> None:
        """One chunk through the handoff injector: the rows pad into
        the request's pow2(n) bucket at their ABSOLUTE offsets and the
        range-gated executable writes ``[max(shared, start), stop)`` —
        the SAME ``inject@bucket`` program monolithic handoff
        admission runs, with per-chunk range arguments (zero new
        executable families, so bit-parity with
        :meth:`submit_prefilled` is by construction).  Rows under the
        adopted prefix are attended, never rewritten."""
        n = len(st["prompt"])
        lo = max(st.get("stream_shared", 0), start)
        if stop > lo:
            t_inj = time.perf_counter()
            bucket = _pow2_bucket(n, self.max_len,
                                  self.cfg.max_seq_len)
            padded = {}
            for name, v in rows.items():
                buf = np.zeros(v.shape[:2] + (bucket,) + v.shape[3:],
                               v.dtype)
                buf[:, :, lo:stop] = v[:, :, lo - start:stop - start]
                padded[name] = jnp.asarray(buf)
            fn = _get_inject_fn(self.cfg, bucket, self._paged,
                                self._shard)
            self.cache = fn(self.cache, padded, jnp.asarray(lo),
                            jnp.asarray(stop), jnp.asarray(slot))
            if self._tel:
                _telemetry.count("serving.prefilled_rows", stop - lo)
                self._span_ring.record(
                    st.get("trace"), "inject", t_inj,
                    time.perf_counter(), rid=st["rid"], start=lo,
                    stop=stop)
        # frontier advance: the row a decode ride wrote at the old pos
        # was just rewritten bit-identically by this inject
        st["pos"] = max(st["pos"], stop)

    def _graduate_stream(self, slot, st, logits) -> None:
        """The final chunk landed (logits in the same frame): draw the
        first token with the exact per-rid host sampling of monolithic
        handoff admission and flip the slot to plain decoding."""
        prompt = st["prompt"]
        n = len(prompt)
        rid = st["rid"]
        self._streams.pop(rid, None)
        logits_np = np.asarray(logits, np.float32)
        if _faults.active():
            logits_np = _faults.corrupt_nan("logits", logits_np)
        if self._resil and not np.isfinite(logits_np).all():
            # the admission NaN guard, streamed edition
            del self._slots[slot]
            self._fail_request(st, slot, "non-finite prefill logits")
            return
        if st["temperature"] > 0.0:
            p = generate._filtered_probs(
                logits_np, st["temperature"], st["top_k"], st["top_p"])
            rng = np.random.default_rng(generate._key_seed(
                jax.random.fold_in(self._base_key, (1 << 20) + rid)))
            t = int(rng.choice(len(p), p=p))
        else:
            t = int(logits_np.argmax())
        st["generated"].append(t)
        st["pos"] = n
        st.pop("admitting", None)
        st.pop("stream", None)
        st.pop("stream_shared", None)
        if self._paged and self._prefill_on:
            # streamed rows equal local prefill's bit-for-bit: the
            # prompt's full blocks index for future sharing
            self._pool.register_prefix(slot, prompt)
        if self._spec_on and self.draft_cfg is not None:
            # the draft cache saw none of these rows: the first spec
            # round's catch-up feeds it the sequence from 0
            st["spec_dpos"] = 0
        if self._tel:
            now = time.perf_counter()
            st["t_first"] = st["t_last"] = now
            self._observe("serving.ttft_ms",
                          (now - st["t_submit"]) * 1e3)
            _telemetry.event("serving.prefill",
                             st.get("t_admit", now), now, tid=slot,
                             rid=rid, prompt_len=n)
            _telemetry.count("serving.tokens_generated")
            self._count_local("serving.tokens_generated")
        fin = self._constraint_push(st, t)
        if self._finished(st, t) or fin:
            # single-token budgets finish on the admission token
            del self._slots[slot]
            self._results[rid] = st["generated"]
            if self._paged:
                self._pool.free_slot(slot)
            self._free.append(slot)
            self._tel_retire(st, slot)

    def stream_prefilled_abort(self, rid: int, reason: str) -> None:
        """Tear down a half-streamed handoff (worker death, transport
        loss, TTL, replica removal): the request retires with the
        ``error`` status and — if the stream had claimed a slot — the
        slot and its pool blocks free for the next tenant.  Raises
        ``KeyError`` when no stream is open for ``rid`` (already
        graduated, aborted, or never begun)."""
        sr = self._streams.pop(rid)
        st = sr["st"]
        if st is None:
            self._queue[:] = [r for r in self._queue
                              if r["rid"] != rid]
        else:
            self._slots.pop(sr["slot"], None)
            if self._paged:
                self._pool.free_slot(sr["slot"])
            self._free.append(sr["slot"])
        if self._status.get(rid) is None:
            self._status[rid] = "error"
            self._err_reason[rid] = reason
        if self._tel:
            _telemetry.count("serving.requests_failed")
            _telemetry.count("serving.stream_aborts")

    # -- paged layout: allocator plumbing (text/kv_pool) --------------------

    def _apply_pool_ops(self):
        """Execute the allocator's pending device work: COW block copies
        (one donated gather/scatter) and the host->device table push.
        Called right before any jitted step that depends on them."""
        pairs = self._pool.take_copies()
        if pairs:
            # pad to a power-of-two width by REPEATING the first real
            # pair (duplicate writes of identical rows — scatter-safe):
            # one kv_copy executable per log2 bucket instead of one per
            # distinct pair count, so a COW storm can't compile mid-tick
            # per count or flood the step LRU.  A constant (0, 0) filler
            # would collide when block 0 is itself a COW destination in
            # the same drain (dst=0 twice with DIFFERENT sources — XLA
            # scatter order is undefined), violating copy_blocks'
            # no-dst-in-src precondition
            width = 1
            while width < len(pairs):
                width *= 2
            pad = [pairs[0]] * (width - len(pairs))
            src = jnp.asarray([p[0] for p in pairs + pad], jnp.int32)
            dst = jnp.asarray([p[1] for p in pairs + pad], jnp.int32)
            self.cache = _get_copy_fn(self.cfg, width, self._shard)(
                self.cache, src, dst)
            if self._draft_cache is not None:
                # a COW'd block holds both pools' rows for its logical
                # positions — the draft pool copies the same pairs so
                # the shared table stays valid for both
                self._draft_cache = _get_copy_fn(
                    self.draft_cfg, width, self._draft_shard)(
                    self._draft_cache, src, dst)
        if self._pool.dirty:
            tables = jnp.asarray(self._pool.tables)
            if isinstance(self._shard, _ShardCtx):
                # committed to the replicated tables sharding so the
                # explicit in_shardings see a matching placement
                tables = jax.device_put(tables,
                                        self._shard.cache["tables"])
            elif self._device is not None:
                tables = jax.device_put(tables, self._device)
            self.cache = dict(self.cache, tables=tables)
            if self._draft_cache is not None:
                # the draft pytree gets its OWN device buffer of the
                # same host table: the two caches donate independently,
                # and a shared array would be deleted out from under
                # the draft the first time a target step donates it
                dtables = jnp.asarray(self._pool.tables)
                if isinstance(self._draft_shard, _ShardCtx):
                    dtables = jax.device_put(
                        dtables, self._draft_shard.cache["tables"])
                elif self._device is not None:
                    dtables = jax.device_put(dtables, self._device)
                self._draft_cache = dict(self._draft_cache,
                                         tables=dtables)
            self._pool.dirty = False

    def _evict_or_spill(self, max_entries: int) -> int:
        """The OOM chain's evict-cold rung, spill-aware: with
        ``PADDLE_TPU_KV_SPILL_MB`` set, cold prefix chains demote to
        host RAM (one batched ``device_get`` per round) instead of
        dropping, so the next admission restores them with one batched
        ``device_put`` instead of a recompute walk.  Delegates to
        ``evict_cold`` when spill is off or a draft cache shares the
        allocator (spilled target rows alone would leave the draft
        pool's rows for those blocks stale on restore)."""
        pool = self._pool
        if pool.spill_limit_bytes and self._draft_cache is None:
            return pool.spill_cold(max_entries, fetch=self._spill_fetch)
        return pool.evict_cold(max_entries=max_entries)

    def _spill_fetch(self, blocks):
        """The ONE batched device->host read a spill round pays: gather
        the demoted blocks' rows across every pool leaf."""
        from . import kv_pool as _kv

        idx = jnp.asarray(blocks, jnp.int32)
        return {name: np.asarray(jax.device_get(self.cache[name][:, idx]))
                for name in _kv.POOL_LEAVES if name in self.cache}

    def _drain_restores(self):
        """Promote spilled chains the last ``adopt_prefix`` matched back
        to the device: ONE batched host->device transfer + ONE
        ``inject_rows`` table scatter per slot, through the same
        executable buckets the fleet handoff already warms (zero new
        executable families).  Runs right after adoption — before
        ``ensure_rows`` can park the request — so a restored index entry
        never outlives this call with stale device rows."""
        if not self._paged:
            return
        recs = self._pool.take_restores()
        if not recs:
            return
        # the restored blocks' table entries must be live on device
        # before the scatter resolves through them
        self._apply_pool_ops()
        bs = self._pool.bs
        by_slot: dict = {}
        for slot, start, rows, _b in recs:
            by_slot.setdefault(slot, []).append((start, rows))
        for slot, items in by_slot.items():
            items.sort(key=lambda it: it[0])
            # one adopt walk restores a CONTIGUOUS run of blocks, but
            # inject writes every row in [start, length) — split on gaps
            # so a hole never zero-fills rows it doesn't own
            runs, run = [], [items[0]]
            for it in items[1:]:
                if it[0] == run[-1][0] + bs:
                    run.append(it)
                else:
                    runs.append(run)
                    run = [it]
            runs.append(run)
            for run in runs:
                lo, hi = run[0][0], run[-1][0] + bs
                bucket = _pow2_bucket(hi, self.max_len,
                                      self.cfg.max_seq_len)
                padded = {}
                for name, v0 in run[0][1].items():
                    buf = np.zeros(
                        (v0.shape[0], 1, bucket) + v0.shape[2:],
                        v0.dtype)
                    for s, rows in run:
                        buf[:, 0, s:s + bs] = rows[name]
                    padded[name] = jnp.asarray(buf)
                fn = _get_inject_fn(self.cfg, bucket, True, self._shard)
                self.cache = fn(self.cache, padded, jnp.asarray(lo),
                                jnp.asarray(hi), jnp.asarray(slot))

    def _ensure_decode_blocks(self, steps: int):
        """Incremental allocation: before a dispatch of ``steps`` decode
        steps, map (or copy-on-write) every active slot's blocks
        covering rows [pos, pos+steps) — admission no longer reserves
        ``max_len`` rows up front, THE memory point of the paged layout.
        Rows past the window clamp (block-decode overrun writes drop).
        A PoolExhausted here surfaces inside the guarded tick, where the
        OOM chain's first rung evicts cold prefix-cache entries and
        retries."""
        if not self._paged or not self._slots:
            return
        cap = self._pool.nmax * self._pool.bs
        for slot, st in self._slots.items():
            self._pool.ensure_rows(slot, st["pos"],
                                   min(st["pos"] + steps, cap))
        self._apply_pool_ops()

    def _paged_prefill_slot(self, req, slot):
        """Paged admission: adopt the longest indexed prompt prefix into
        the slot's block table (refcounted sharing — those rows are
        never recomputed), allocate/COW the blocks the suffix will
        write, run the suffix through the offset-aware paged prefill
        chunk executable(s), and register this prompt's full blocks for
        future sharing.  Returns (telemetry name, executable calls,
        admission logits)."""
        from . import kv_pool as _kv

        prompt = req["prompt"]
        n = len(prompt)
        alloc = self._pool
        # adapter≠0 prompts bypass the prefix cache entirely: adopted
        # rows carry a different (or no) weight delta, and registering
        # adapter rows would poison future base/other-adapter admissions
        shared = alloc.adopt_prefix(slot, prompt) \
            if self._prefill_on and not req.get("adapter") else 0
        self._drain_restores()
        window = min(self.max_len, self.cfg.max_seq_len)
        if self._chunk:
            C = min(self._chunk, window)
            if n - shared <= C:
                # one chunk covers the suffix: start AT the adopted
                # prefix (recomputing shared rows would COW every
                # adopted block and forfeit the reuse), backing off only
                # when the window bound forces an overlap
                starts = [shared if shared + C <= window
                          else max(0, n - C)]
            else:
                starts = list(range(shared, n - C, C)) + [n - C]
        else:
            # bucketed suffix: one power-of-two chunk per admission,
            # floored at the block size — suffixes after a prefix hit
            # are typically < block_size, and the floor keeps the
            # executable-width set small enough for warmup to cover.
            # pos0 backs off from ``shared`` only when the bucket would
            # overrun the wpe/window bound — overlapped rows recompute
            # to identical values (the contiguous walk's rule) after a
            # COW makes them writable
            C = min(max(_pow2_bucket(n - shared), self._pool.bs), window)
            starts = [shared if shared + C <= window else max(0, n - C)]
        while True:
            try:
                alloc.ensure_rows(slot, min(starts), n)
                break
            except _kv.PoolExhausted:
                # out of blocks: evict cold prefix-cache entries (the
                # OOM chain's first rung, applied at admission) in small
                # LRU batches until the suffix fits — NOT the whole
                # index at once: one pressure blip must not zero the
                # fleet's prefix hit rate.  Cold entries are ref==1, so
                # this request's freshly adopted blocks (ref>=2) are
                # never its own victims
                if self._evict_or_spill(_EVICT_BATCH) == 0:
                    raise
        self._apply_pool_ops()
        if self._adapters is not None:
            name = f"adapter_paged_prefill@{C}"
            afn = _get_adapter_paged_prefill_fn(
                self.cfg, C, self._adapters.pool_key(), self._shard)
            _ad_st = self._adapters.stacks()
            _aid = jnp.asarray(req.get("adapter", 0))
            fn = lambda p, c, t, p0, ln, sl: afn(p, c, _ad_st, _aid,
                                                 t, p0, ln, sl)
        else:
            name = f"paged_prefill@{C}"
            fn = _get_paged_prefill_fn(self.cfg, C, self._shard)
        logits = None
        rows_done = 0
        for s in starts:
            chunk = prompt[s:s + C]
            padded = np.zeros((1, C), np.int32)
            padded[0, :len(chunk)] = chunk
            logits, self.cache = fn(
                self.params, self.cache, jnp.asarray(padded),
                jnp.asarray(s), jnp.asarray(len(chunk)),
                jnp.asarray(slot))
            rows_done += len(chunk)
        if self._draft_cache is not None:
            # the draft cache walks the SAME starts through its own
            # chunk executable: the shared table maps both pools, so an
            # adopted prefix's draft rows are already valid (every
            # admission on this server writes both caches before
            # register_prefix) and the suffix fills here
            dfn = _get_paged_prefill_fn(self.draft_cfg, C,
                                        self._draft_shard)
            for s in starts:
                chunk = prompt[s:s + C]
                padded = np.zeros((1, C), np.int32)
                padded[0, :len(chunk)] = chunk
                _, self._draft_cache = dfn(
                    self._draft_params, self._draft_cache,
                    jnp.asarray(padded), jnp.asarray(s),
                    jnp.asarray(len(chunk)), jnp.asarray(slot))
        if self._tel:
            # rows actually prefilled — the repeated-prefix FLOPs saving
            # is (prompt length - this) per request
            _telemetry.count("kv_pool.prefill_rows", rows_done)
        if not req.get("adapter"):
            alloc.register_prefix(slot, prompt)
        return name, len(starts), logits

    def _inject_prefilled(self, req, slot):
        """Admission half of the prefill/decode handoff: write the
        worker-computed rows into ``slot`` — paged servers first adopt
        the longest indexed prefix (the injected rows for shared blocks
        are bit-identical to what the index already holds, so those
        blocks are attended, never rewritten or duplicated), then
        allocate/COW the remaining write range, evicting cold prefix
        entries under pressure exactly like local admission — and
        return (telemetry name, the worker's admission logits)."""
        rows, logits = req["prefilled"]
        n = len(req["prompt"])
        t_inj = time.perf_counter()
        bucket = _pow2_bucket(n, self.max_len, self.cfg.max_seq_len)
        padded = {}
        for name, v in rows.items():
            buf = np.zeros(v.shape[:2] + (bucket,) + v.shape[3:],
                           v.dtype)
            buf[:, :, :n] = v
            padded[name] = jnp.asarray(buf)
        shared = 0
        if self._paged:
            from . import kv_pool as _kv

            if self._prefill_on:
                # capped at n-1 like local admission: the final row is
                # always written (COW on a fully-shared prompt)
                shared = self._pool.adopt_prefix(slot, req["prompt"])
                self._drain_restores()
            while True:
                try:
                    self._pool.ensure_rows(slot, shared, n)
                    break
                except _kv.PoolExhausted:
                    # the OOM chain's first rung at admission (see
                    # _paged_prefill_slot)
                    if self._evict_or_spill(_EVICT_BATCH) == 0:
                        raise
            self._apply_pool_ops()
        fn = _get_inject_fn(self.cfg, bucket, self._paged, self._shard)
        self.cache = fn(self.cache, padded, jnp.asarray(shared),
                        jnp.asarray(n), jnp.asarray(slot))
        if self._paged and self._prefill_on:
            # the injected rows are exactly what local prefill would
            # have computed, so the prompt's full blocks index for
            # future local admissions to share
            self._pool.register_prefix(slot, req["prompt"])
        if self._tel:
            _telemetry.count("serving.prefilled_rows", n - shared)
            self._span_ring.record(
                req.get("trace"), "inject", t_inj, time.perf_counter(),
                rid=req["rid"], rows=n - shared)
        return f"inject@{bucket}", logits

    def pending(self) -> bool:
        return bool(self._slots or self._queue)

    # -- speculative decoding: batched draft-then-verify rounds -------------

    def _spec_limit(self) -> int:
        """Highest position a spec round may reach: ``pos + K`` must stay
        inside the cache rows, the target's wpe table, and (draft mode)
        the draft's twins — ``dynamic_update_slice``/``dynamic_slice``
        CLAMP out-of-range starts instead of failing, which would
        silently shift the verify chunk's rows.  Near the window the
        server just runs plain ticks (_spec_ready)."""
        if self._paged:
            rows = self._pool.nmax * self._pool.bs
        else:
            rows = int(self.cache["k"].shape[2])
        lim = min(rows, self.cfg.max_seq_len)
        if self._draft_cache is not None:
            drows = (rows if self._paged
                     else int(self._draft_cache["k"].shape[2]))
            lim = min(lim, drows, self.draft_cfg.max_seq_len)
        return lim

    def _spec_chunk(self) -> int:
        """Cache rows one speculative round writes per slot — the tree
        node budget in tree mode, the linear chunk K otherwise (both
        counts include the fed root/feed row)."""
        return self._spec_tree_n or self._spec_k

    def _spec_ready(self) -> bool:
        """Whether THIS tick can run as a speculative round: every slot
        past its prompt (the verify chunk consumes feedback positions
        only), every slot's ``pos + K`` inside :meth:`_spec_limit`, and
        at least one slot still speculating (all fallen back = the
        rounds are pure overhead)."""
        if not self._spec_on or not self._slots:
            return False
        if self._constrained_active() and not self._spec_tree_n:
            # LINEAR mode: constrained slots fall back to plain
            # stepping for the whole batch — draft tokens can't be
            # masked cheaply (each proposal would need the automaton
            # advanced host-side mid-chunk), and an unmasked draft's
            # acceptances could emit banned tokens.  Tree mode lifts
            # this: proposals are walked through a lookahead cursor
            # and grammar-banned branches pruned BEFORE the verify
            # pass (_prune_branches_constrained), so constrained
            # slots speculate and this counter stays at zero.
            if self._tel:
                _telemetry.count("constraint.spec_fallbacks")
            return False
        K = self._spec_chunk()
        lim = self._spec_limit()
        alive = False
        for st in self._slots.values():
            # a mid-admission slot counts as prompt-feeding: its pos is
            # the prefill frontier (possibly n-1), not a feedback
            # position — spec rounds wait for graduation
            if st.get("admitting") or st["pos"] < len(st["prompt"]) - 1:
                return False
            if st["pos"] + K > lim:
                return False
            if st.get("spec_off"):
                # re-earn: a fallen-back slot sits out a cooldown of
                # spec-eligible rounds, then rejoins with a FRESH
                # acceptance window (the old window's verdict was
                # about a different region of the sequence).  The
                # cooldown doubles per trip (16 → 256 cap), so a
                # persistently unpredictable request converges to
                # plain decode while a request that merely passed
                # through a hard patch re-earns its speculation.
                st["spec_cool"] = st.get("spec_cool", 1) - 1
                if st["spec_cool"] <= 0:
                    st["spec_off"] = False
                    st["spec_prop"] = st["spec_acc"] = 0
                    alive = True
                    if self._tel:
                        _telemetry.count("spec.reearns")
            else:
                alive = True
        return alive

    def _spec_rng(self, st):
        """Per-request host RNG for the sampled spec path (proposal
        draws + acceptance tests), seeded per rid off the server key —
        disjoint from the per-step device schedule (fold_in(base, n))
        and the admission draws (1 << 20 namespace)."""
        if "spec_rng" not in st:
            st["spec_rng"] = np.random.default_rng(generate._key_seed(
                jax.random.fold_in(self._base_key,
                                   (1 << 21) + st["rid"])))
        return st["spec_rng"]

    def _spec_draft_admit(self, req, slot, n) -> int:
        """Admission-time draft prefill: fill the draft cache's rows
        [0, n) for this slot so the first spec round drafts from
        position ``n`` directly.  Paged admission already walked the
        draft chunk executable inside ``_paged_prefill_slot`` (same
        starts, same shared table).  Handoff-admitted requests
        ("prefilled") carry TARGET rows only — the draft starts cold
        (returns 0) and the first spec round's catch-up feeds it the
        sequence with batched draft steps, still zero target passes."""
        if "prefilled" in req:
            return 0
        if self._paged:
            return n
        if self._prefill_chunk is not None:
            C = self._chunk
            starts = ([0] if n <= C
                      else list(range(0, n - C, C)) + [n - C])
            dfn = _get_prefill_chunk_fn(self.draft_cfg,
                                        self._draft_shard)
            for i in starts:
                chunk = req["prompt"][i:i + C]
                padded = np.zeros((1, C), np.int32)
                padded[0, :len(chunk)] = chunk
                _, self._draft_cache = dfn(
                    self._draft_params, self._draft_cache,
                    jnp.asarray(padded), jnp.asarray(i),
                    jnp.asarray(len(chunk)), jnp.asarray(slot))
            return n
        bucket = _pow2_bucket(n, self.max_len,
                              self.draft_cfg.max_seq_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = req["prompt"]
        _, self._draft_cache = _get_prefill_fn(
            self.draft_cfg, bucket, self._draft_shard)(
            self._draft_params, self._draft_cache, jnp.asarray(padded),
            jnp.asarray(n), jnp.asarray(slot))
        return n

    def _spec_draft_catchup(self):
        """Advance every lagging slot's draft cache to its target
        position with batched draft steps (``spec_dpos`` = rows the
        draft has consumed).  Lag comes from handoff admission (draft
        starts cold), prefill=False admission (the plain path fed the
        prompt to the target only), and post-rejection rounds capping
        dpos at the last drafted row.  Non-lagging slots ride along
        fed their own feed token at their own pos — that row is
        rewritten identically by the proposal steps, so the overwrite
        is benign (the same argument covers shared paged blocks:
        recomputed rows are a deterministic function of the same
        tokens, hence bit-identical)."""
        step = _get_step_fn(self.draft_cfg, self._paged,
                            self._draft_shard)
        while True:
            lag = [(slot, st) for slot, st in self._slots.items()
                   if not st.get("spec_off")
                   and st.get("spec_dpos", 0) < st["pos"]]
            if not lag:
                return
            tok, pos = self._feed_arrays()
            for slot, st in lag:
                d = st["spec_dpos"]
                np_ = len(st["prompt"])
                base = st.get("base", np_)
                tok[slot] = (st["prompt"][d] if d < np_
                             else st["generated"][d - base])
                pos[slot] = d
            _, self._draft_cache = step(
                self._draft_params, self._draft_cache,
                jnp.asarray(tok), jnp.asarray(pos))
            for slot, st in lag:
                st["spec_dpos"] += 1

    def _spec_propose_draft(self, K):
        """K-1 batched draft steps from each slot's feed position: the
        draft model's proposals for positions pos+1..pos+K-1 (the
        verify chunk's columns 1..K-1) plus — for sampled slots — the
        filtered proposal law q_j the acceptance test divides by.
        Draft logits are fetched per step (host argmax/sampling); the
        draft is the cheap model by construction and K is small.
        Fallen-back slots ride along fed their feed token (their draft
        rows go stale — benign, they never speculate again)."""
        self._spec_draft_catchup()
        step = _get_step_fn(self.draft_cfg, self._paged,
                            self._draft_shard)
        tok, pos = self._feed_arrays()
        temp, tk, tp = self._sampling_arrays()
        eligible = {slot: st for slot, st in self._slots.items()
                    if not st.get("spec_off")}
        props = {slot: ([], [] if temp[slot] > 0 else None)
                 for slot in eligible}
        for _ in range(K - 1):
            logits, self._draft_cache = step(
                self._draft_params, self._draft_cache,
                jnp.asarray(tok), jnp.asarray(pos))
            lnp = np.asarray(logits)
            for slot, st in eligible.items():
                toks, qs = props[slot]
                if qs is None:
                    d = int(lnp[slot].argmax())
                else:
                    q = generate._filtered_probs(
                        lnp[slot], float(temp[slot]), int(tk[slot]),
                        float(tp[slot]))
                    d = int(self._spec_rng(st).choice(len(q), p=q))
                    qs.append(q)
                toks.append(d)
                tok[slot] = d
            pos = pos + 1
        if self._tel and eligible and K > 1:
            _telemetry.count("spec.draft_steps", K - 1)
        return props

    def _spec_propose_ngram(self, K):
        """Model-free self-drafting: propose the continuation that
        followed the most recent earlier occurrence of the sequence's
        current suffix (generate.ngram_propose — longest-match lookup,
        pure host work, zero extra FLOPs).  Misses propose nothing:
        the slot still takes row 0 of the shared verify step, exactly
        one token — plain-decode behavior at plain-decode cost."""
        props = {}
        hits = miss = 0
        for slot, st in self._slots.items():
            if st.get("spec_off"):
                continue
            base = st.get("base", len(st["prompt"]))
            seq = st["prompt"][:base] + st["generated"]
            d = generate.ngram_propose(seq, K - 1) if K > 1 else None
            if d:
                props[slot] = (d, None)
                hits += 1
            else:
                miss += 1
        if self._tel:
            if hits:
                _telemetry.count("spec.ngram_hits", hits)
            if miss:
                _telemetry.count("spec.ngram_misses", miss)
        return props

    def _spec_accept(self, st, rows, prop):
        """Resolve one slot's verify logits [K, V] against its proposal
        -> the token list (1..K) this round appends.  Greedy: accept
        the longest prefix where the target's argmax agrees with the
        draft, append the target's own choice at the first disagreement
        (the correction IS the plain-decode token), and on full
        agreement keep the bonus row — every kept token equals what
        stepwise greedy decode would produce at that position given the
        same prefix, which is the bit-parity argument.  Sampled:
        delegated rejection sampling (_spec_sampled_tokens)."""
        draft, qs = prop if prop is not None else ([], None)
        kk = len(draft)
        if st.get("temperature", 0.0) > 0.0:
            toks, accepted = self._spec_sampled_tokens(st, rows, draft,
                                                       qs)
        else:
            tchoice = rows.argmax(axis=-1)
            toks, accepted = [], 0
            for j in range(kk):
                t = int(tchoice[j])
                toks.append(t)
                if t != draft[j]:
                    break
                accepted += 1
            else:
                toks.append(int(tchoice[kk]))
        if kk:
            self._spec_prop += kk
            self._spec_acc += accepted
            st["spec_prop"] = st.get("spec_prop", 0) + kk
            st["spec_acc"] = st.get("spec_acc", 0) + accepted
            if self._tel:
                _telemetry.count("spec.proposed", kk)
                if accepted:
                    _telemetry.count("spec.accepted", accepted)
        return toks

    def _spec_sampled_tokens(self, st, rows, draft, qs):
        """Leviathan rejection sampling on one slot's verify rows:
        accept draft x_j with prob min(1, p_j(x)/q_j(x)); the first
        rejection resamples the residual (p - q)+ — self-draft's q is
        the point mass at x, so the residual is p with p[x] zeroed —
        and full acceptance draws the bonus row.  Marginals equal
        plain sampled decode (speculative_generate's law;
        test_speculative.py's chi-square, re-checked at batch>1 by the
        serving tests)."""
        t, tk, tp = st["temperature"], st["top_k"], st["top_p"]
        rng = self._spec_rng(st)
        toks, accepted = [], 0
        for j, x in enumerate(draft):
            p = generate._filtered_probs(rows[j], t, tk, tp)
            qx = float(qs[j][x]) if qs is not None else 1.0
            if float(rng.uniform()) < min(
                    1.0, float(p[x]) / max(qx, 1e-300)):
                toks.append(int(x))
                accepted += 1
                continue
            if qs is not None:
                resid = np.maximum(p - qs[j], 0.0)
            else:
                resid = p.copy()
                resid[x] = 0.0
            mass = float(resid.sum())
            if mass > 0.0:
                toks.append(int(rng.choice(len(resid),
                                           p=resid / mass)))
            else:
                toks.append(int(rng.choice(len(p), p=p)))
            break
        else:
            p = generate._filtered_probs(rows[len(draft)], t, tk, tp)
            toks.append(int(rng.choice(len(p), p=p)))
        return toks, accepted

    def _spec_fallback_check(self, st):
        """Acceptance-driven fallback: a slot whose rolling accept rate
        sits below PADDLE_TPU_SPEC_MIN_ACCEPT after a fair trial stops
        speculating (row-0-only rounds — still bit-correct, no longer
        paying proposal work).  The window decays by halving so the
        rate tracks the request's RECENT regime, not its whole
        history.

        The window's unit is the ACCEPTED-PATH LENGTH a round could
        have delivered — K-1 drafted tokens in linear mode, the
        deepest live root-to-leaf path in tree mode — not the raw
        linear K, so tree-mode slots fall back (and later re-earn, see
        _spec_ready) on exactly the same accept-rate contract."""
        if st.get("spec_off") or not st.get("spec_prop"):
            return
        k = max(1, (self._spec_tree_n or self._spec_k) - 1)
        if st["spec_prop"] >= 16 * k:
            st["spec_prop"] //= 2
            st["spec_acc"] //= 2
        if st["spec_prop"] >= 4 * k \
                and st["spec_acc"] / st["spec_prop"] < self._min_accept:
            st["spec_off"] = True
            # next re-earn waits twice as long as the last one did
            st["spec_cool"] = cool = min(256,
                                         2 * st.get("spec_cool0", 8))
            st["spec_cool0"] = cool
            if self._tel:
                _telemetry.count("spec.fallbacks")

    def _tick_spec(self):
        """One speculative round: propose (host n-gram lookup or K-1
        batched draft steps), ONE batched target verify over every
        slot, host-side acceptance, retire.  The verify is the round's
        only target pass — up to K tokens per slot for one pass, the
        multiplier the spec bench arm measures.  Rejected verify rows
        land at/past each slot's new position pointer where the
        stale-row invariant already hides them (the same rule as
        warmup garbage and slot reuse), so acceptance needs no masked
        write and no rollback: after a rejection the next round's
        writes start exactly at the first stale row."""
        if self._spec_tree_n:
            return self._tick_spec_tree()
        if self._inflight is not None:
            # async servers run spec rounds synchronously: the pending
            # dispatch's tokens are real work — fetch them first
            self._drain_inflight()
            if not self._slots:
                return
        t0 = time.perf_counter()
        K = self._spec_k
        # rows [pos, pos+K) per slot, BEFORE any state mutates: a
        # PoolExhausted surfaces here and the OOM chain's retry re-runs
        # the round bit-exactly (greedy) / unbiasedly (sampled)
        self._ensure_decode_blocks(K)
        if self._self_draft:
            props = self._spec_propose_ngram(K)
        else:
            props = self._spec_propose_draft(K)
        tok, pos = self._feed_arrays()
        tok = np.repeat(tok[:, None], K, axis=1)
        for slot, (draft, _) in props.items():
            for j, d in enumerate(draft[:K - 1]):
                tok[slot, j + 1] = d
        if self._adapters is not None:
            # the verify pass gathers the SAME per-slot adapter the
            # decode step uses — acceptance compares draft tokens
            # against the ADAPTED target's argmax/law, so accepted
            # tokens are exactly what plain adapted stepping emits.
            # The (base-model) draft only moves the acceptance RATE.
            kind = f"adapter_spec_verify@{K}"
            self._fault_check(kind)
            fn = _get_adapter_spec_verify_fn(
                self.cfg, K, self._adapters.pool_key(), self._paged,
                self._shard)
            logits, self.cache = fn(
                self.params, self.cache, self._adapters.stacks(),
                jnp.asarray(self._gather_adapter_ids()),
                jnp.asarray(tok), jnp.asarray(pos))
        else:
            kind = f"spec_verify@{K}"
            self._fault_check(kind)
            fn = _get_spec_verify_fn(self.cfg, K, self._paged,
                                     self._shard)
            logits, self.cache = fn(self.params, self.cache,
                                    jnp.asarray(tok), jnp.asarray(pos))
        self._step_no += 1   # after the call: see _tick_impl
        self._spec_rounds += 1
        lnp = np.asarray(logits)   # the round's ONE device->host fetch
        failed = []
        if self._resil and (_faults.active()
                            or _os.environ.get(
                                "PADDLE_TPU_NAN_GUARD_SERVING",
                                "") == "1"):  # noqa: E129
            if _faults.active():
                lnp = _faults.corrupt_nan("logits", lnp)
            finite = np.isfinite(lnp).all(axis=(-2, -1))
            failed = [s for s in self._slots if not finite[s]]
        done = []
        appended = []
        for slot, st in self._slots.items():
            if slot in failed:
                continue
            toks = self._spec_accept(st, lnp[slot], props.get(slot))
            old = st["pos"]
            kept = 0
            for t in toks:
                st["generated"].append(t)
                st["pos"] += 1
                kept += 1
                if self._finished(st, t):
                    done.append(slot)
                    break
            appended.append((st, kept))
            if self._draft_cache is not None \
                    and not st.get("spec_off"):
                # draft rows [old, old+K-1) were fed this round; the
                # prefix fed ACCEPTED (real) tokens is valid through
                # the new position, capped at the last drafted row —
                # catch-up re-feeds anything past the cap next round
                st["spec_dpos"] = min(st["pos"], old + K - 1)
            self._spec_fallback_check(st)
        for slot in failed:
            st = self._slots.pop(slot)
            self._fail_request(st, slot, "non-finite spec-verify logits")
        steps = max([kept for _, kept in appended], default=1)
        self._tel_tokens(appended, t0, steps=max(steps, 1), kind=kind)
        self._retire(done)

    # -- draft-tree speculation: one verify pass over a token tree ----------

    def _spec_tree_propose(self):
        """Build each eligible slot's proposal tree.

        Returns {slot: tree}, where a tree is a dict with ``tokens``
        (index 0 is the ROOT — the feed token, already fed, so its
        entry is None), ``parent`` (parent[0] == -1, topological
        order), ``depth``, ``live`` (False == pruned, the node stays
        in the dispatched arrays but no acceptance path may use it),
        ``children`` ({node: [live kids, proposal order]}), and in
        draft mode ``trunk``/``dsteps``/``qs`` (the draft's base law
        per depth, for the sampled acceptance test).

        Self-draft: :func:`generate.ngram_propose_tree` merges up to
        ``branch`` DISTINCT n-gram continuations into one prefix trie.
        Draft mode: :meth:`_spec_tree_propose_draft` lays a trunk and
        fans siblings out at the draft's least-confident positions.
        Constrained slots then get grammar-forbidden subtrees pruned
        BEFORE the verify pass — the tree dispatched for them carries
        only tokens their automaton allows."""
        N = self._spec_tree_n
        b = max(1, min(self._spec_branch, N - 1))
        if self._self_draft:
            props = {}
            hits = miss = 0
            for slot, st in self._slots.items():
                if st.get("spec_off"):
                    continue
                base = st.get("base", len(st["prompt"]))
                seq = st["prompt"][:base] + st["generated"]
                t = generate.ngram_propose_tree(seq, N, branch=b)
                if t is not None:
                    props[slot] = {"tokens": list(t[0]),
                                   "parent": list(t[1])}
                    hits += 1
                else:
                    miss += 1
            if self._tel and hits:
                _telemetry.count("spec.ngram_hits", hits)
            if self._tel and miss:
                _telemetry.count("spec.ngram_misses", miss)
        else:
            props = self._spec_tree_propose_draft(N, b)
        total = 0
        for slot, tp in props.items():
            n = len(tp["tokens"])
            tp["depth"] = generate.tree_depths(tp["parent"])
            tp["live"] = [True] * n
            total += n - 1
            st = self._slots[slot]
            if st.get("constraint") is not None:
                self._prune_branches_constrained(st, tp)
            kids: dict = {}
            for j in range(1, n):
                if tp["live"][j]:
                    kids.setdefault(tp["parent"][j], []).append(j)
            tp["children"] = kids
        if self._tel and total:
            _telemetry.count("spec.tree_nodes_proposed", total)
        return props

    def _spec_tree_propose_draft(self, N, b):
        """Draft-model tree proposals: D = ceil((N-1)/b) batched draft
        steps lay a TRUNK (greedy: the draft's argmax chain; sampled:
        draws from its filtered law q, recorded for the acceptance
        test), then the remaining N-1-D node slots fan out as sibling
        leaves at the trunk positions where the draft was LEAST sure
        (smallest top-1/top-2 margin greedy, smallest chosen-token
        probability sampled) — branching exactly where linear
        speculation actually dies.  Greedy siblings take the draft's
        top-2..b tokens; sampled siblings are drawn from q WITHOUT
        replacement, so child i+1 at a node is distributed as the
        i-times-rejection-renormalized law the SpecInfer acceptance
        chain (_spec_tree_sampled) replays.  Counts spec.draft_steps
        once per batched draft dispatch, like the linear path."""
        self._spec_draft_catchup()
        step = _get_step_fn(self.draft_cfg, self._paged,
                            self._draft_shard)
        tok, pos = self._feed_arrays()
        temp, tk, tp_ = self._sampling_arrays()
        eligible = {slot: st for slot, st in self._slots.items()
                    if not st.get("spec_off")}
        D = max(1, -(-(N - 1) // b))
        rec = {slot: {"trunk": [], "alts": [], "margins": [],
                      "qs": [] if temp[slot] > 0 else None}
               for slot in eligible}
        for _ in range(D):
            logits, self._draft_cache = step(
                self._draft_params, self._draft_cache,
                jnp.asarray(tok), jnp.asarray(pos))
            if self._tel:
                _telemetry.count("spec.draft_steps")
            lnp = np.asarray(logits)
            for slot, st in eligible.items():
                r = rec[slot]
                row = lnp[slot]
                if r["qs"] is None:
                    order = np.argsort(row)[::-1][:max(b, 2)]
                    d = int(order[0])
                    alts = [int(x) for x in order[1:b]]
                    r["margins"].append(
                        float(row[order[0]] - row[order[1]]))
                else:
                    q = generate._filtered_probs(
                        row, float(temp[slot]), int(tk[slot]),
                        float(tp_[slot]))
                    rng = self._spec_rng(st)
                    d = int(rng.choice(len(q), p=q))
                    r["qs"].append(q)
                    alts = []
                    qq = q.copy()
                    last = d
                    for _a in range(b - 1):
                        qq[last] = 0.0
                        m = float(qq.sum())
                        if m <= 0.0:
                            break
                        last = int(rng.choice(len(qq), p=qq / m))
                        alts.append(last)
                    # low chosen-prob == much residual mass elsewhere
                    r["margins"].append(float(q[d]))
                r["trunk"].append(d)
                r["alts"].append(alts)
                tok[slot] = d
            pos = pos + 1
        props = {}
        for slot, r in rec.items():
            toks: list = [None]
            parent = [-1]
            for i, t in enumerate(r["trunk"]):
                toks.append(int(t))
                parent.append(i)      # trunk node i+1 sits at depth i+1
            budget = N - 1 - len(r["trunk"])
            order = np.argsort(np.asarray(r["margins"], np.float64),
                               kind="stable")
            for i in order:
                if budget <= 0:
                    break
                for a in r["alts"][int(i)]:
                    if budget <= 0:
                        break
                    if a == r["trunk"][int(i)]:
                        continue
                    toks.append(int(a))
                    parent.append(int(i))   # sibling of trunk node i+1
                    budget -= 1
            props[slot] = {"tokens": toks, "parent": parent,
                           "trunk": [int(t) for t in r["trunk"]],
                           "dsteps": len(r["trunk"]),
                           "qs": r["qs"]}
        return props

    def _prune_branches_constrained(self, st, tp):
        """Host DFA lookahead over one slot's proposed tree BEFORE the
        verify pass: walk :func:`adapters.constraint_lookahead` cursors
        down the trie from the request's CURRENT automaton state (never
        mutated — acceptance advances the real state through
        _constraint_push like every other path) and mark every node
        whose token the grammar forbids — plus its whole subtree —
        dead.  Pruned nodes still occupy rows in the compiled dispatch
        (shapes are trace keys), but they leave the host-side candidate
        set, so no acceptance path can emit a banned token and
        ``constraint.spec_fallbacks`` stays untouched in tree mode."""
        from . import adapters as _ad

        cst = st.get("constraint")
        tokens, parent, live = tp["tokens"], tp["parent"], tp["live"]
        cursors = {0: _ad.constraint_lookahead(cst)}
        pruned = 0
        for j in range(1, len(tokens)):
            pl = cursors.get(parent[j])
            if pl is None or not pl.allows(tokens[j]):
                live[j] = False       # parent dead, or token banned
                pruned += 1
                continue
            cursors[j] = pl.child(tokens[j])
        if pruned and self._tel:
            _telemetry.count("spec.tree_pruned_constrained", pruned)

    def _spec_tree_accept(self, st, rows, tp):
        """Resolve one slot's tree-verify logits [N, V] into the token
        list this round appends plus the accepted node-index path.

        Greedy: walk from the root; each visited node's target row
        (constraint-masked for constrained slots — np.where over the
        same fp32 values the masked plain step argmaxes, so every
        appended token equals stepwise masked greedy decode on the
        same prefix) yields an argmax; descend into the live child
        carrying that token.  The first miss appends the target's own
        choice — the "correction" IS the plain-decode token — and a
        leaf's choice is the bonus, so the walk always emits at least
        one token (the plain-decode floor).  Sampled: SpecInfer
        sequential multi-child rejection per node
        (:meth:`_spec_tree_sampled`) preserves the target law exactly.

        Constrained automata are NOT advanced here: the lookahead
        cursor only shapes masks; the tick loop pushes every appended
        token through _constraint_push exactly like the plain path.
        The rolling fallback window advances in PATH-LENGTH units —
        proposed = the deepest live root-to-leaf depth this round
        offered, accepted = edges actually taken."""
        from . import adapters as _ad

        if tp is None:
            tokens: list = [None]
            depth = [0]
            children: dict = {}
            live = [True]
            qs = None
        else:
            tokens, depth = tp["tokens"], tp["depth"]
            children, live = tp["children"], tp["live"]
            qs = tp.get("qs")
        cst = st.get("constraint")
        look = (_ad.constraint_lookahead(cst)
                if cst is not None else None)
        sampled = st.get("temperature", 0.0) > 0.0
        cur = 0
        toks: list = []
        sel: list = []
        while True:
            if look is not None and look.exhausted:
                break                 # automaton completed mid-path
            row = rows[cur]
            if look is not None:
                row = _ad.apply_constraint_host(row, look)
            kids = children.get(cur, [])
            if sampled:
                t, child = self._spec_tree_sampled(st, row, kids,
                                                   tokens, depth, qs,
                                                   look)
            else:
                t = int(row.argmax())
                child = next((j for j in kids if tokens[j] == t), None)
            toks.append(t)
            if look is not None:
                look = look.child(t)
            if child is None:
                break
            sel.append(child)
            cur = child
        maxd = max((int(depth[j]) for j in range(len(tokens))
                    if live[j]), default=0)
        if maxd:
            self._spec_prop += maxd
            self._spec_acc += len(sel)
            st["spec_prop"] = st.get("spec_prop", 0) + maxd
            st["spec_acc"] = st.get("spec_acc", 0) + len(sel)
            if self._tel:
                _telemetry.count("spec.proposed", maxd)
                if sel:
                    _telemetry.count("spec.accepted", len(sel))
        if self._tel and sel:
            _telemetry.count("spec.tree_nodes_accepted", len(sel))
        self._tree_path_sum += len(sel)
        self._tree_path_cnt += 1
        return toks, sel

    def _spec_tree_sampled(self, st, row, kids, tokens, depth, qs,
                           look):
        """SpecInfer-style sequential multi-candidate rejection at ONE
        tree node: children x_1..x_m (proposal order) are tested in
        turn against the target law p — accept x_i with probability
        min(1, p(x_i)/q_i(x_i)), where q_1 is the draft's base law at
        this depth and every rejection updates BOTH sides: p becomes
        norm((p - q_i)+) and q_{i+1} becomes norm(q_i with x_i zeroed),
        the very law the proposer drew x_{i+1} from (without-
        replacement draws).  All children rejected -> sample the final
        residual (the correction); no children -> sample p (the
        bonus).  Telescoping the per-child terms shows every emitted
        token is distributed exactly as p — the single-child case
        reduces to the linear path's Leviathan test bit-for-bit.

        Self-draft trees carry no qs: each child is a POINT MASS
        (q_i = 1 at x_i), so accept with probability p(x_i) and zero
        x_i out of the residual — exact for ANY proposal choice, which
        is what the constraint-pruned trie rides on.  Constrained
        draft-model slots condition q on the automaton mask (the
        proposal survived pruning, so its law GIVEN survival is q
        restricted to the allowed set, renormalized) while p is
        already the masked filtered law — the masked target law is
        preserved exactly.  Returns (token, accepted child or None)."""
        rng = self._spec_rng(st)
        p = generate._filtered_probs(row, float(st["temperature"]),
                                     int(st["top_k"]),
                                     float(st["top_p"]))
        p0 = p
        q = None
        if qs is not None and kids:
            q = np.asarray(qs[int(depth[kids[0]]) - 1], np.float64)
            if look is not None:
                q = q * look.allowed_mask()
                m = float(q.sum())
                q = q / m if m > 0.0 else None
        for x_node in kids:
            x = tokens[x_node]
            if qs is not None:
                if q is None:
                    break             # proposer's law exhausted
                qx = float(q[x])
                if qx <= 0.0:
                    continue          # proposer can't have drawn this
                if float(rng.uniform()) < min(1.0, float(p[x]) / qx):
                    return int(x), x_node
                p = np.maximum(p - q, 0.0)
                pm = float(p.sum())
                q = q.copy()
                q[x] = 0.0
                qm = float(q.sum())
                q = q / qm if qm > 0.0 else None
                if pm <= 0.0:
                    p = None
                    break
                p = p / pm
            else:
                if float(rng.uniform()) < float(p[x]):
                    return int(x), x_node
                p = p.copy()
                p[x] = 0.0
                pm = float(p.sum())
                if pm <= 0.0:
                    p = None
                    break
                p = p / pm
        if p is None:
            # numerically empty residual: fall back to the target law
            # itself, as the linear sampled path does
            p = p0
        return int(rng.choice(len(p), p=p)), None

    def _tick_spec_tree(self):
        """One TREE speculative round: propose a token tree per slot,
        prune grammar-forbidden branches, ONE tree-masked target pass
        over all slots, host best-path acceptance, a KV row permute
        for paths that left the trunk, retire.  Same skeleton as
        _tick_spec — one target pass per round is the headline metric
        — but acceptance can follow BRANCHES, so a single pass keeps
        tokens a linear draft of the same row budget loses at its
        first divergence.  The ancestor mask and depths are runtime
        arguments: topology changes round to round, the compiled
        executable keys only on the node COUNT."""
        if self._inflight is not None:
            self._drain_inflight()
            if not self._slots:
                return
        t0 = time.perf_counter()
        N = self._spec_tree_n
        # rows [pos, pos+N) per slot BEFORE any state mutates (the OOM
        # retry rule); the commit permute writes inside [pos+1, pos+N)
        # — covered by the same reservation
        self._ensure_decode_blocks(N)
        props = self._spec_tree_propose()
        tok, pos = self._feed_arrays()
        tokN = np.repeat(tok[:, None], N, axis=1)
        amask = np.zeros((self.max_batch, N, N), bool)
        amask[:, np.arange(N), np.arange(N)] = True  # idle rows: self
        depth = np.zeros((self.max_batch, N), np.int32)
        for slot, tp in props.items():
            n = len(tp["tokens"])
            for j in range(1, n):
                tokN[slot, j] = tp["tokens"][j]
            amask[slot, :n, :n] = generate.tree_ancestor_mask(
                tp["parent"])
            depth[slot, :n] = tp["depth"]
        kind = f"spec_tree_verify@{N}"
        self._fault_check(kind)
        fn = _get_spec_tree_verify_fn(self.cfg, N, self._paged,
                                      self._shard)
        logits, self.cache = fn(self.params, self.cache,
                                jnp.asarray(tokN), jnp.asarray(amask),
                                jnp.asarray(depth), jnp.asarray(pos))
        self._step_no += 1
        self._spec_rounds += 1
        if self._tel:
            _telemetry.count("spec.tree_rounds")
        lnp = np.asarray(logits)  # the round's one device->host fetch
        failed = []
        if self._resil and (_faults.active()
                            or _os.environ.get(
                                "PADDLE_TPU_NAN_GUARD_SERVING",
                                "") == "1"):  # noqa: E129
            if _faults.active():
                lnp = _faults.corrupt_nan("logits", lnp)
            finite = np.isfinite(lnp).all(axis=(-2, -1))
            failed = [s for s in self._slots if not finite[s]]
        done = []
        appended = []
        commit_src = None
        for slot, st in self._slots.items():
            if slot in failed:
                continue
            toks, sel = self._spec_tree_accept(st, lnp[slot],
                                               props.get(slot))
            if sel and any(s != i + 1 for i, s in enumerate(sel)):
                # the accepted path left the trunk: permute its rows
                # into the contiguous committed positions.  Trunk(-
                # prefix) acceptances skip this — the proposer lays the
                # trunk at node indices 1..D, already the committed
                # layout — so pure-chain trees never dispatch a commit
                if commit_src is None:
                    commit_src = np.tile(
                        np.arange(1, N, dtype=np.int32),
                        (self.max_batch, 1))
                commit_src[slot, :len(sel)] = sel
            old = st["pos"]
            kept = 0
            for t in toks:
                st["generated"].append(t)
                st["pos"] += 1
                kept += 1
                fin = self._constraint_push(st, t)
                if self._finished(st, t) or fin:
                    done.append(slot)
                    break
            appended.append((st, kept))
            if self._draft_cache is not None \
                    and not st.get("spec_off"):
                # draft rows [old, old+D) were fed feed+trunk tokens
                # this round; they stay valid through the committed
                # prefix that AGREES with the trunk (a branch
                # acceptance diverges earlier than a linear round's
                # cap) — catch-up re-feeds the rest next round
                tp = props.get(slot, {})
                trunk = tp.get("trunk", [])
                agree = 0
                for a, bt in zip(toks, trunk):
                    if a != bt:
                        break
                    agree += 1
                dsteps = tp.get("dsteps", 1)
                st["spec_dpos"] = min(
                    st["pos"], old + 1 + min(agree, dsteps - 1))
            self._spec_fallback_check(st)
        if commit_src is not None:
            # dispatched with the PRE-ROUND pos array: failed slots
            # keep identity rows, accepted slots permute [pos+1, ...)
            cfn = _get_spec_tree_commit_fn(self.cfg, N, self._paged,
                                           self._shard)
            self.cache = cfn(self.cache, jnp.asarray(commit_src),
                             jnp.asarray(pos))
        for slot in failed:
            st = self._slots.pop(slot)
            self._fail_request(st, slot,
                               "non-finite spec-tree-verify logits")
        steps = max([kept for _, kept in appended], default=1)
        self._tel_tokens(appended, t0, steps=max(steps, 1), kind=kind)
        self._retire(done)

    def close(self):
        """Release this server's compiled executables and KV cache.

        UNFINISHED requests (queued or mid-generation) are ABANDONED:
        their rids are remembered and ``result()`` raises a descriptive
        error for them — call only when the server is drained or the
        pending work is disposable.  The jit caches key by config VALUE,
        so entries may be shared with another live server of the same
        config — that server transparently recompiles on its next tick
        (correctness is unaffected; the cache exists to avoid recompiles,
        not to carry state).  The LRU bound on _STEP_CACHE already caps
        growth; close() is for eagerly dropping a cycled-out model's
        executables (and their implicit param refs).

        Shutdown hardening: the in-flight async dispatch is CANCELLED
        (its device tokens are never fetched — a wedged step cannot hang
        interpreter exit), the metrics HTTP server thread is joined with
        a bound, and a runtime-wedge verdict this server raised is
        cleared so a later server's /healthz starts clean.  Idempotent."""
        if self._wedged:
            self._wedged = False
            _telemetry.clear_runtime_wedge()
        if self._moe_stats is not None:
            # publish the final routing totals before the accumulator
            # (and its device buffer) is dropped with the executables
            try:
                self._moe_snapshot()
            except Exception:
                pass    # a wedged device must not block shutdown
            self._moe_stats = None
        if self.metrics_server is not None:
            self.metrics_server.close()   # joins the serve thread
            self.metrics_server = None
        # one pass over the Engine's OWN caches: every family (plain,
        # adapter_*, draft twins, generate-side executables) whose key
        # embeds either cfg drops — a new registry kind can't leak
        _engine.ENGINE.purge(self.cfg, self.draft_cfg)
        self.cache = None
        self._draft_cache = None
        self._step = None
        self._prefill = None
        self._prefill_chunk = None
        self._inflight = None
        for st in self._slots.values():
            self._dropped.add(st["rid"])
        for req in self._queue:
            self._dropped.add(req["rid"])
        self._slots.clear()
        self._queue.clear()
        if self._paged and self._pool is not None:
            self._pool.close()

    def shutdown(self):
        """Alias for :meth:`close` (the serving-fleet idiom): cancel
        in-flight work, join the metrics thread, drop executables."""
        self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def result(self, rid: int):
        """Generated tokens (no prompt) once the request finished.

        A request shed past its deadline raises
        ``resilience.DeadlineExceeded``; one rejected by admission
        control raises ``resilience.Overloaded`` (it never queued —
        back off and resubmit); one failed by the NaN guard or the
        evict-requeue bound raises ``RuntimeError`` — in all cases the
        request retired CLEANLY (slot freed, server alive) and
        :meth:`status` reports the disposition without raising."""
        if rid in self._dropped:
            raise RuntimeError(
                f"request {rid} was abandoned unfinished when the server "
                f"was closed")
        disp = self._status.get(rid)
        if disp == "timeout":
            raise _resilience.DeadlineExceeded(
                f"request {rid} was shed: still queued past its ttl")
        if disp == "rejected":
            raise _resilience.Overloaded(
                f"request {rid} was rejected by admission control "
                f"(rate limit, queue bound, or overload shed) — it "
                f"never queued; back off and resubmit")
        if disp == "error":
            raise RuntimeError(
                f"request {rid} failed: "
                f"{self._err_reason.get(rid, 'non-finite logits')} "
                f"(the request was retired cleanly; the server is "
                f"still serving)")
        return self._results[rid]

    def status(self, rid: int) -> str:
        """One of ``ok`` (result ready), ``timeout`` (deadline shed),
        ``rejected`` (admission control refused it at the door),
        ``error`` (NaN guard / evict-requeue bound), ``dropped``
        (abandoned by close), ``active`` (decoding), ``queued``."""
        if rid in self._results:
            return "ok"
        disp = self._status.get(rid)
        if disp is not None:
            return disp
        if rid in self._dropped:
            return "dropped"
        if any(st["rid"] == rid for st in self._slots.values()) \
                or (self._inflight is not None
                    and any(st["rid"] == rid
                            for _, st, _ in self._inflight["snap"])):
            return "active"
        if any(req["rid"] == rid for req in self._queue):
            return "queued"
        raise KeyError(f"unknown request id {rid}")

    # -- fleet surface: load, health, queue drain (text/fleet.py) -----------

    @property
    def wedged(self) -> bool:
        """The resilience watchdog's live verdict for THIS server (the
        fleet router's per-replica health bit; the process-global
        telemetry wedge state folds every server's verdict)."""
        return self._wedged

    def load_stats(self, include_spans: bool = False) -> dict:
        """The router's load-balancing inputs, read from the scheduler's
        host state — the SAME quantities the telemetry gauges sample
        (queue depth, active slots, slot occupancy, kv utilization),
        returned per server because the registry gauges are
        process-global and a fleet co-hosts many replicas.

        ``include_spans=True`` additionally drains this server's
        completed trace spans (DESTRUCTIVE, piggyback-capped) into
        ``spans``/``span_drops`` — the fleet router's collection ride;
        anything else polling load should leave it off."""
        act = len(self._slots)
        if self._paged:
            kv = self._pool.blocks_in_use / max(1, self._pool.N)
        else:
            rows = (int(self.cache["k"].shape[2])
                    if self.cache is not None else self.max_len)
            kv = sum(min(st["pos"], rows)
                     for st in self._slots.values()) \
                / (self.max_batch * rows)
        eff_cap = self._admit_cap
        if self._adm is not None:
            eff_cap = min(eff_cap,
                          self._adm.effective_admit_cap(self.max_batch))
        ad_active: dict[str, int] = {}
        if self._adapters is not None:
            for st in self._slots.values():
                nm = st.get("adapter_name") or "base"
                ad_active[nm] = ad_active.get(nm, 0) + 1
        return {
            "queue_depth": len(self._queue),
            "active_slots": act,
            "free_slots": min(len(self._free),
                              max(0, eff_cap - act)),
            "slot_occupancy": act / self.max_batch,
            "kv_utilization": kv,
            "admit_cap": self._admit_cap,
            "wedged": self._wedged,
            # budgeted admission: slots mid-prefill (their chunks eat
            # round budget) and the configured budget itself — a router
            # can prefer replicas with admission headroom
            "admitting_slots": sum(
                1 for st in self._slots.values()
                if st.get("admitting")),
            "prefill_budget": self._budget,
            # server-wide rolling acceptance (None until the first
            # proposal is scored) — the router's signal for whether
            # this replica's speculation is paying for itself
            "spec_accept_rate": ((self._spec_acc / self._spec_prop)
                                 if self._spec_prop else None),
            # tree mode: mean accepted root-to-leaf path length per
            # verify round (tokens committed beyond the plain-decode
            # floor ≈ this value) — None off tree mode / before the
            # first round
            "spec_tree_accept_len": (
                (self._tree_path_sum / self._tree_path_cnt)
                if self._tree_path_cnt else None),
            # admission-control verdict: the degradation ladder rung
            # (0 = healthy) — the fleet router folds the worst replica
            # rung into its OWN controller (absorb_fleet_rung) and
            # sheds at the front door instead of stacking queues
            "admission_rung": (0 if self._adm is None
                               else self._adm.rung),
            "slo_ok": self._adm is None or self._adm.rung == 0,
            # multi-tenant serving: slots decoding under a constraint
            # automaton (always present) and, with an adapter pool,
            # per-adapter active-slot counts — the same numbers the
            # adapters.active{adapter=} gauges sample, surfaced per
            # server so the fleet router's docs can point at them
            "constrained_slots": sum(
                1 for st in self._slots.values()
                if st.get("constraint") is not None),
            **({"adapters_active": ad_active}
               if self._adapters is not None else {}),
            # prefix-cache surface (paged only): the hit-rate gauge
            # (fraction of adoptable rows admission did NOT recompute),
            # the compact radix summary prefix-aware routing scores
            # overlap against, and the host spill tier's footprint
            **({"prefix_hit_rate": (
                    self._pool.prefix_hits
                    / max(1, self._pool.prefix_hits
                          + self._pool.prefix_misses)),
                "prefix_summary": self._pool.prefix_summary(),
                "host_spill_bytes": self._pool.host_spill_bytes}
               if self._paged else {}),
            # MoE serving: the device accumulator's honest routing
            # totals — cumulative dropped token→expert assignments and
            # per-expert kept load (the drain also advances the
            # moe.dropped_tokens counter / expert-load gauges).  The
            # fetch blocks on the in-flight step's stats future; the
            # scheduler's own ticks never pay it.
            **(dict(zip(("moe_dropped_tokens", "moe_expert_load"),
                        self._moe_snapshot()))
               if self._moe_stats is not None else {}),
            # fleet tracing: spans ride the stats collection when asked
            **(dict(zip(("spans", "span_drops"), self.drain_spans()))
               if include_spans else {}),
        }

    def drain_spans(self):
        """Destructively take this server's completed trace spans (the
        piggyback cap bounds one take) plus the drop count since the
        last take — what ``load_stats(include_spans=True)`` rides; the
        fleet router calls it directly each collection round."""
        return self._span_ring.drain(_flags.trace_piggyback_cap())

    def local_snapshot(self) -> dict:
        """This SERVER's latency distributions as JSON-safe
        :meth:`telemetry.Histogram.state` dicts keyed by histogram name
        — the fleet metrics plane's merge inputs.  Distinct from the
        process-global ``telemetry.snapshot()``: loopback fleets co-host
        replicas, so per-replica distributions need per-server buckets.
        ``counters`` carries the per-server token/request totals the
        fleet rollups aggregate."""
        return {
            "histograms": {name: h.state()
                           for name, h in sorted(
                               self._hist_local.items())},
            "counters": dict(sorted(self._counts_local.items())),
        }

    def _observe(self, name: str, v: float, n: int = 1) -> None:
        """Observe into the process-global histogram AND this server's
        local twin (see :meth:`local_snapshot`).  Call sites already
        gate on ``self._tel``."""
        _telemetry.observe(name, v, n)
        h = self._hist_local.get(name)
        if h is None:
            h = self._hist_local[name] = _telemetry.Histogram(name)
        h.observe(v, n)

    def _count_local(self, name: str, n: int = 1) -> None:
        """Per-server counter twin of ``telemetry.count`` (same
        loopback-fleet rationale as :meth:`_observe`)."""
        self._counts_local[name] = self._counts_local.get(name, 0) + n

    def drain_queue(self, rids=None) -> list:
        """Remove and return QUEUED request dicts (the fleet router's
        wedge-drain path: a wedged replica's queued work is re-routed
        to healthy replicas via :meth:`adopt_request`; its ACTIVE slots
        keep decoding here — their device work is already paid for and
        the wedge recovery replays it bit-exactly).

        ``rids`` restricts the drain to those request ids: the router
        passes the set it owns, so a request submitted DIRECTLY to this
        server (whose rid only the direct submitter holds) stays queued
        through the drain instead of vanishing."""
        if rids is None:
            out, self._queue[:] = list(self._queue), []
        else:
            out = [r for r in self._queue if r["rid"] in rids]
            self._queue[:] = [r for r in self._queue
                              if r["rid"] not in rids]
        for r in out:
            # a drained stream request leaves with its rid: the open
            # stream record dies here (the drainer fails the request
            # at the fleet level; late chunks would KeyError honestly)
            if r.get("stream"):
                self._streams.pop(r["rid"], None)
        if out and self._tel:
            _telemetry.count("serving.queue_drained", len(out))
        self._tel_gauges()
        return out

    # -- one tick: a single batched device step -----------------------------

    def _feed_arrays(self):
        """The batched (tok, pos) feed for the current slots: the token
        fed at position i is sequence[i] — prompt while i is inside it,
        the generated tail after.

        Donation audit: this (and every host-side helper here) reads
        only the per-slot HOST state (prompt/generated/pos lists) —
        never the device cache, whose buffers the jitted steps donate
        and whose old generations are therefore deleted.  The only
        device arrays the server retains are ``self.cache`` (always the
        newest, reassigned at every step) and the async in-flight token
        array (an output, never donated)."""
        tok = np.zeros((self.max_batch,), np.int32)
        pos = np.zeros((self.max_batch,), np.int32)
        for slot, st in self._slots.items():
            i = st["pos"]
            np_ = len(st["prompt"])
            # base = original prompt length (differs from len(prompt)
            # only for OOM-evicted re-admissions, whose carried tokens
            # live in both the extended prompt and generated)
            base = st.get("base", np_)
            tok[slot] = (st["prompt"][i] if i < np_
                         else st["generated"][i - base])
            pos[slot] = i
        return tok, pos

    def _finished(self, st, t: int) -> bool:
        return (len(st["generated"]) >= st["max_new"]
                or (self.eos_id is not None and t == self.eos_id)
                or _hits_stop(st))

    def _sampling_arrays(self):
        """Per-slot (temperature, top_k, top_p) for the current batch;
        free and prompt-feeding slots sample nothing (temp 0)."""
        temp = np.zeros((self.max_batch,), np.float32)
        tk = np.zeros((self.max_batch,), np.int32)
        tp = np.ones((self.max_batch,), np.float32)
        for slot, st in self._slots.items():
            # admitting slots sample nothing: their frontier may sit at
            # n-1 but the step's output there is never kept
            if st["pos"] >= len(st["prompt"]) - 1 \
                    and not st.get("admitting"):
                temp[slot] = st["temperature"]
                tk[slot] = st["top_k"]
                tp[slot] = st["top_p"]
        return temp, tk, tp

    # -- MoE serving: occupancy mask + stats plumbing (round 19) ------------

    def _moe_act(self):
        """The joint-routing occupancy mask [max_batch] bool: occupied
        slots route (prompt-feeding INCLUDED — their routing writes the
        KV rows deeper layers keep, so they must claim real capacity),
        free slots claim nothing, and ADMITTING slots are excluded —
        their frontier output is discarded and their rows rewritten by
        the next prefill chunk, so letting them contend would charge
        phantom capacity to batch-mates."""
        act = np.zeros((self.max_batch,), bool)
        for slot, st in self._slots.items():
            act[slot] = not st.get("admitting")
        return act

    def _moe_wrap(self, fn):
        """Adapt a joint-routing Engine kind to the dense calling
        convention: append (act, stats) at dispatch, peel the trailing
        stats output back into ``self._moe_stats``, return the rest —
        so every dense dispatch site (and Engine.warmup's ``srv._step``
        call) serves MoE unchanged."""
        def wrapped(*args):
            out = fn(*args, jnp.asarray(self._moe_act()),
                     self._moe_stats)
            self._moe_stats = out[-1]
            return out[:-1]

        return wrapped

    def _moe_snapshot(self):
        """Drain the device accumulator into telemetry (delta-exact:
        ``moe.dropped_tokens`` advances by what the device dropped since
        the last drain) and return (dropped_total, load_list)."""
        from . import moe_serving as _moe_serving

        dropped, load = _moe_serving.drain_drop_stats(
            self._moe_stats, counted=self._moe_counted, tel=self._tel)
        self._moe_counted = dropped
        return dropped, load

    # -- multi-tenant serving: adapter gather + constraint masks ------------

    def _constrained_active(self) -> bool:
        """Any ACTIVE slot decoding under a constraint automaton?  The
        gate every incompatible fast path (async pipelining, device
        blocks, speculation) checks before committing: a masked step
        needs the PREVIOUS token fetched to build the next mask, so
        constrained slots always run the stepwise sync path."""
        return any(st.get("constraint") is not None
                   for st in self._slots.values())

    def _gather_adapter_ids(self):
        """Per-slot int32 adapter ids [max_batch] for this dispatch —
        the gather_adapter index array every adapter step consumes
        (free slots read row 0, the all-zero base delta)."""
        ids = np.zeros((self.max_batch,), np.int32)
        for slot, st in self._slots.items():
            ids[slot] = st.get("adapter", 0)
        if self._tel:
            _telemetry.count("adapters.gather_steps")
        return ids

    def _mask_array(self):
        """The [B, V] additive constraint mask for the NEXT step, built
        host-side from each constrained slot's automaton state — or
        None when no decoding slot is constrained (the unmasked fast
        paths stay untouched).  Admitting / prompt-feeding slots are
        excluded: their step output is never kept, so masking it would
        only burn host time."""
        cons = {slot: st["constraint"]
                for slot, st in self._slots.items()
                if st.get("constraint") is not None
                and not st.get("admitting")
                and st["pos"] >= len(st["prompt"]) - 1}
        if not cons:
            return None
        from . import adapters as _ad

        return _ad.mask_logits(cons, self.max_batch, self.cfg.vocab_size)

    def _constraint_push(self, st, t: int) -> bool:
        """Advance the slot's automaton over the token it just emitted;
        True when the constraint is EXHAUSTED (the automaton accepted a
        complete output and allows nothing further) — the slot must
        retire even if max_new/eos/stop say otherwise."""
        cst = st.get("constraint")
        if cst is None:
            return False
        cst.advance(t)
        return cst.exhausted

    def _retire(self, done):
        for slot in done:
            st = self._slots.pop(slot)
            self._results[st["rid"]] = st["generated"]
            if self._paged:
                # blocks return to the pool (prefix-indexed ones stay
                # resident under the index's own reference)
                self._pool.free_slot(slot)
            self._free.append(slot)
            self._tel_retire(st, slot)
        self._admit()
        self._tel_gauges()

    # -- telemetry sampling (host values only — never a device sync) --------

    def _tel_gauges(self):
        """Occupancy gauges off the scheduler's host state: queue depth,
        active slots, slot occupancy, and KV-cache utilization (filled
        rows / window, from the per-slot host ``pos``).  Also the HBM
        sampling point: a rate-limited PJRT memory-stats query (host
        RPC, never a device sync) keeps live bytes_in_use/peak gauges
        next to the occupancy ones."""
        if not self._tel:
            return
        _telemetry.sample_device_stats()
        _telemetry.set_gauge("serving.queue_depth", len(self._queue))
        _telemetry.set_gauge("serving.active_slots", len(self._slots))
        _telemetry.set_gauge("serving.slot_occupancy",
                             len(self._slots) / self.max_batch)
        _telemetry.set_gauge(
            "serving.admitting_slots",
            sum(1 for st in self._slots.values()
                if st.get("admitting")))
        if self._adapters is not None:
            # per-adapter active-slot gauges, Prometheus-labeled
            # (telemetry._prom_name keeps {adapter="..."} intact).
            # Every registered name is written EVERY sample — a
            # retired adapter's gauge drops to 0 instead of freezing
            # at its last nonzero value
            counts: dict[str, int] = {}
            for st in self._slots.values():
                nm = st.get("adapter_name") or "base"
                counts[nm] = counts.get(nm, 0) + 1
            for nm in list(self._adapters.names()) + ["base"]:
                _telemetry.set_gauge(
                    f'adapters.active{{adapter="{nm}"}}',
                    counts.get(nm, 0))
        if self._spec_on and self._spec_prop:
            _telemetry.set_gauge("serving.spec_accept_rate",
                                 self._spec_acc / self._spec_prop)
        if self._spec_tree_n and self._tree_path_cnt:
            _telemetry.set_gauge(
                "serving.spec_tree_accept_len",
                self._tree_path_sum / self._tree_path_cnt)
        # kv_utilization = TRUE occupancy (round 8): under the paged
        # layout, blocks actually mapped / pool size; under contiguous,
        # filled rows / the slab's real (rounded) allocation — the old
        # max_len denominator under-reported whenever init_cache rounded
        # the row count up
        if self._paged:
            used = self._pool.blocks_in_use
            _telemetry.set_gauge("kv_pool.blocks_in_use", used)
            _telemetry.set_gauge("serving.kv_utilization",
                                 used / max(1, self._pool.N))
            _telemetry.set_gauge("kv_pool.host_spill_bytes",
                                 self._pool.host_spill_bytes)
            seen = self._pool.prefix_hits + self._pool.prefix_misses
            if seen:
                _telemetry.set_gauge("kv_pool.prefix_hit_rate",
                                     self._pool.prefix_hits / seen)
        else:
            rows = (int(self.cache["k"].shape[2])
                    if self.cache is not None else self.max_len)
            _telemetry.set_gauge(
                "serving.kv_utilization",
                sum(min(st["pos"], rows)
                    for st in self._slots.values())
                / (self.max_batch * rows))

    def _tel_retire(self, st, slot):
        """End-of-lifecycle records for one request: end-to-end latency
        histogram + the submit→retire span on the timeline."""
        if not self._tel:
            return
        now = time.perf_counter()
        t_sub = st.get("t_submit", now)
        self._observe("serving.e2e_ms", (now - t_sub) * 1e3)
        _telemetry.count("serving.requests_completed")
        self._count_local("serving.requests_completed")
        _telemetry.event("serving.request", t_sub, now, tid=slot,
                         rid=st["rid"], prompt_len=len(st["prompt"]),
                         tokens=len(st["generated"]))
        tr = st.get("trace")
        if tr:
            # the request's lifecycle on its trace: one decode span
            # (first token → retire) plus a zero-width retire marker
            self._span_ring.record(
                tr, "decode", st.get("t_first", t_sub), now,
                rid=st["rid"], tokens=len(st["generated"]))
            self._span_ring.record(
                tr, "retire", now, now, rid=st["rid"],
                tokens=len(st["generated"]))

    def _tel_tokens(self, appended, t0, steps: int = 1, kind=None):
        """Per-tick records from the host bookkeeping that JUST ran on
        the already-fetched token block: tick latency, first-token time
        for slots whose first kept token arrived this tick (the
        ``prefill=False`` path — prefill admission stamps TTFT itself),
        and per-token latency = tick wall / steps (each slot decoded
        every step of the block it was fed into).

        ``kind`` names the executable that ran (serving.<kind> — the
        instrument_compile name) so the device feed can join this wall,
        which genuinely covers dispatch→token-fetch even on the async
        path, with the executable's captured FLOPs into a live MFU."""
        if not self._tel:
            return
        now = time.perf_counter()
        dt_ms = (now - t0) * 1e3
        self._observe("serving.tick_ms", dt_ms)
        if kind is not None:
            _telemetry.note_step_time(f"serving.{kind}", dt_ms / 1e3)
        if appended:
            # decode-gap: wall time between consecutive rounds that
            # appended decode tokens — THE stall metric budgeted
            # admission exists to bound (a monolithic long-prompt
            # admission shows up as one huge gap here).  The anchor
            # resets to None on idle returns so a quiet queue doesn't
            # masquerade as a stall.
            if self._gap_anchor is not None:
                self._observe("serving.decode_gap_ms",
                              (now - self._gap_anchor) * 1e3)
            self._gap_anchor = now
        if not appended:
            return
        total = 0
        per_tok = dt_ms / max(steps, 1)
        spec = kind is not None and "spec" in kind
        for st, n in appended:
            total += n
            if "t_first" not in st:
                st["t_first"] = now
                self._observe(
                    "serving.ttft_ms",
                    (now - st.get("t_submit", t0)) * 1e3)
                if n > 1:
                    self._observe("serving.tpot_ms", per_tok,
                                  n=n - 1)
            else:
                self._observe("serving.tpot_ms", per_tok, n=n)
            st["t_last"] = now
            if spec and st.get("trace"):
                # one span per traced slot per speculative round:
                # the tick wall bounds every slot's draft+verify work
                self._span_ring.record(
                    st["trace"], "spec_round", t0, now,
                    rid=st["rid"], accepted=n)
        _telemetry.count("serving.tokens_generated", total)
        self._count_local("serving.tokens_generated", total)

    # -- resilience: guarded ticks, the OOM chain, wedge recovery -----------

    def _fault_check(self, kind: str):
        """Deterministic fault-injection hook, placed exactly where a
        real device OOM would surface (just before the jitted step
        call, with no host state mutated yet — so a retried tick is
        bit-exact).  No-op unless ``PADDLE_TPU_FAULTS`` installed.
        Fires REGARDLESS of the resilience switch: with
        ``PADDLE_TPU_RESILIENCE=0`` the injected fault propagates
        uncaught — fail-fast parity is part of the chaos contract."""
        if _faults.active():
            # async dispatch sites do NOT consume wedge faults: their
            # fetch (_process_inflight) has a real hang hook, which is
            # where a wedge belongs.  Sync sites have no hang hook, so
            # there a wedge spec raises InjectedWedge LOUDLY (faults.py's
            # no-silent-no-op promise) instead of vacuously passing a
            # drill — wedge recovery is an async-dispatch feature.
            kinds = (("oom", "error") if kind.startswith("async")
                     else ("oom", "error", "wedge"))
            _faults.check("tick", f"serving.{kind.split('@')[0]}",
                          f"serving.{kind}", kinds=kinds)

    def _guarded(self, fn):
        """Run one tick under the resilience guard: an allocator OOM
        engages the degradation chain (``_oom_degrade``) and re-ticks;
        anything else — or an OOM with the chain exhausted, or the
        cache's donated buffers already consumed — propagates (honest
        fail-fast).  A clean tick after a wedge recovery flips the
        runtime-wedge verdict back to healthy (/healthz 503 -> ok)."""
        if not self._resil or self._in_tick:
            return fn()
        self._in_tick = True
        self._wedge_event = False
        try:
            while True:
                try:
                    out = fn()
                except Exception as e:  # noqa: BLE001 - classified below
                    if _resilience.is_oom(e) and self._oom_degrade(e):
                        continue
                    raise
                if self._wedged and not self._wedge_event:
                    # a full tick completed after the wedge: recovered
                    self._wedged = False
                    _telemetry.clear_runtime_wedge()
                    if self._tel:
                        _telemetry.count("resilience.wedge_recoveries")
                return out
        finally:
            self._in_tick = False

    def _cache_consumed(self) -> bool:
        """True when any cache leaf's donated buffer is already deleted
        (the failing step consumed it): a re-tick would touch dead
        buffers, so the OOM chain must fail fast instead."""
        try:
            return any(getattr(v, "is_deleted", lambda: False)()
                       for c in (self.cache, self._draft_cache)
                       if c is not None for v in c.values())
        except Exception:  # noqa: BLE001 - can't tell = don't retry
            return True

    def _oom_degrade(self, exc) -> bool:
        """One link of the retry-on-OOM chain (the reference allocator's
        retry chain at scheduler granularity).  Returns True when a
        degradation was applied and the tick should retry:

        1. async -> sync dispatch (drains the in-flight step first: its
           tokens are real work, never discarded on this path);
        2. halve the admitted batch (future admissions; active slots
           beyond the cap are evicted back to the queue with their
           progress carried);
        3. evict the lowest-priority slot (ties: youngest first).

        Every engaged link counts ``resilience.oom_retries``."""
        if self._cache_consumed():
            return False
        applied = None
        if self._paged:
            from . import kv_pool as _kv
        # the first rung only relieves POOL exhaustion (prefix eviction
        # returns host-accounted pool blocks, zero device HBM — the pool
        # is preallocated): a real XLA RESOURCE_EXHAUSTED would retry
        # the identical failing dispatch once per batch, so it skips
        # straight to dispatch degradation.  Injected drill OOMs stay
        # routed through the rung so the chaos suite can drive it
        pool_relievable = self._paged and isinstance(
            exc, (_kv.PoolExhausted, _faults.InjectedOOM))
        if pool_relievable and self._evict_or_spill(
                max(_EVICT_BATCH, len(self._slots))) > 0:
            # NEW first rung (round 8): free pool blocks the prefix
            # cache alone holds — pure memory back for zero lost work —
            # before any dispatch degradation.  Batched (LRU-first), not
            # the whole index: the chain retries the tick and re-engages
            # this rung while cold entries remain, so sustained pressure
            # still drains the cache but a single blip keeps the hit rate
            applied = "evict_prefix_cache"
        elif self._async:
            try:
                self._drain_inflight()
            except Exception:  # noqa: BLE001 - the drain itself failing:
                # _drain_inflight already rolled the scheduler back (the
                # in-flight record is cancelled inside), so the retry
                # below re-decodes those steps from consistent host state
                pass
            self._async = False
            applied = "sync_dispatch"
        elif self._admit_cap > 1:
            self._admit_cap = max(1, self._admit_cap // 2)
            self._evict_to_cap()
            applied = f"admit_cap={self._admit_cap}"
        elif len(self._slots) > 1:
            self._evict_one()
            applied = "evict"
        if applied is None:
            return False
        if self._tel:
            _telemetry.count("resilience.oom_retries")
            _telemetry.set_gauge("resilience.admit_cap", self._admit_cap)
            _telemetry.event("resilience.oom_degrade",
                             time.perf_counter(), time.perf_counter(),
                             action=applied, error=str(exc)[:200])
        return True

    def _evict_one(self) -> bool:
        """Evict the lowest-priority (ties: youngest) active slot back
        to the FRONT of the queue with its progress carried — on
        re-admission its prompt is original-prompt + generated-so-far,
        so a greedy request still produces its exact full generation."""
        if not self._slots:
            return False
        slot = min(self._slots,
                   key=lambda s: (self._slots[s].get("priority", 0),
                                  -self._slots[s].get("t_submit", 0.0)))
        st = self._slots.pop(slot)
        if self._paged:
            self._pool.free_slot(slot)
        self._free.append(slot)
        # requeue aging (the starvation bound): a request evicted more
        # than PADDLE_TPU_EVICT_REQUEUE_MAX times is losing every race
        # for a slot — fail it HONESTLY (status "error", counted) so
        # the client learns, instead of the evict/re-admit/evict loop
        # burning its progress forever while higher-priority work keeps
        # arriving.  The slot still frees either way (the OOM chain got
        # what it came for).
        evictions = st.get("evictions", 0) + 1
        cap = _flags.requeue_max()
        if cap and evictions > cap:
            rid = st["rid"]
            self._status[rid] = "error"
            self._err_reason[rid] = (
                f"evicted {evictions} times (> "
                f"PADDLE_TPU_EVICT_REQUEUE_MAX={cap}); giving up")
            if self._tel:
                _telemetry.count("serving.requests_failed")
                _telemetry.count("resilience.evict_requeue_overflows")
                _telemetry.event("serving.request_failed",
                                 st.get("t_submit", time.perf_counter()),
                                 time.perf_counter(), tid=slot, rid=rid,
                                 reason="evict_requeue_overflow")
            return True
        # full sequence = ORIGINAL prompt + generated (prompt[:base]
        # strips a previous eviction's carry — generated already holds
        # it, so a double-evicted request must not duplicate it)
        base = st.get("base", len(st["prompt"]))
        self._queue.insert(0, {
            "rid": st["rid"],
            "prompt": st["prompt"][:base] + st["generated"],
            "max_new": st["max_new"], "stop": st.get("stop", []),
            "temperature": st.get("temperature", 0.0),
            "top_k": st.get("top_k", 0), "top_p": st.get("top_p", 1.0),
            "ttl": st.get("ttl"), "priority": st.get("priority", 0),
            "tenant": st.get("tenant"),
            # adapter id + constraint SPEC survive the requeue; _admit
            # recompiles the automaton and replays the carry through it
            "adapter": st.get("adapter", 0),
            "adapter_name": st.get("adapter_name"),
            "constraint": st.get("constraint_spec"),
            "evictions": evictions,
            "carry": list(st["generated"]),
            "t_submit": st.get("t_submit", time.perf_counter()),
            # fresh queue-entry clock: TTL bounds queue wait, and this
            # request's wait starts over (see _shed_expired)
            "t_enqueue": time.perf_counter(),
        })
        if self._tel:
            _telemetry.count("resilience.oom_evictions")
        return True

    def _evict_to_cap(self):
        while len(self._slots) > self._admit_cap:
            if not self._evict_one():
                break

    def _cancel_record(self, rec):
        """Roll the host scheduler back as if ``rec`` (an in-flight
        dispatch record) was never dispatched: every still-active slot's
        pos returns to its fed position and the PRNG step counter
        rewinds, so a re-dispatch replays the SAME steps (greedy:
        bit-identical tokens and cache rows; sampled: the same fold_in
        schedule)."""
        if rec is None:
            return
        for slot, st, i in rec["snap"]:
            if self._slots.get(slot) is st:
                st["pos"] = min(st["pos"], i)
        if "step_no0" in rec:
            self._step_no = min(self._step_no, rec["step_no0"])

    def _drain_inflight(self):
        """Fetch and process the pending async dispatch NOW (the
        async -> sync degradation path: its tokens are real work).  If
        the FETCH fails, the dispatch record is cancelled (slot pos +
        step counter rolled back) before re-raising, so the caller's
        retry re-decodes from consistent host state."""
        prev = self._inflight
        self._inflight = None
        if prev is not None:
            self._process_inflight(prev)

    def _recover_wedge(self, prev, exc):
        """The watchdog tripped: the async fetch blew its wall budget.
        Mark the process wedged (/healthz answers 503), cancel BOTH
        in-flight dispatches (the unfetched ``prev`` and the one
        dispatched this tick), and roll every affected slot back to its
        earliest dispatched position — the next ticks re-decode those
        steps, so unaffected requests still finish with bit-identical
        tokens (greedy decode is a deterministic function of the host
        state just restored).  The hung fetch thread is abandoned
        (daemon); its late result, if any, is discarded."""
        self._wedge_event = True
        self._wedged = True
        _telemetry.set_runtime_wedge(str(exc))
        self._cancel_record(self._inflight)
        self._inflight = None
        self._cancel_record(prev)
        if self._tel:
            _telemetry.event("resilience.wedge", time.perf_counter(),
                             time.perf_counter(), error=str(exc)[:200])

    def _rss_guard(self):
        """Host-RSS watchdog hook (``PADDLE_TPU_KV_SPILL_RSS_MB``):
        every 16th scheduler tick reads ``/proc`` and, over the
        threshold, runs ONE bounded allocator relief round (oldest
        spilled chains, then evict-cold LRU) — see
        ``PagedAllocator.rss_watchdog``.  Off (a single int compare)
        unless the flag armed the allocator."""
        pool = self._pool
        if pool is None or not pool.rss_limit_bytes:
            return
        self._rss_tick = (self._rss_tick + 1) & 15
        if not self._rss_tick:
            pool.rss_watchdog()

    def tick(self):
        if self._adm is not None:
            # the SLO control loop rides the scheduler tick: at most
            # one evaluation per PADDLE_TPU_SLO_WINDOW_S (control_tick
            # self-gates), so this is a float compare on idle ticks
            self._adm.control_tick(
                idle=not self._slots and not self._queue)
        self._rss_guard()
        self._guarded(self._tick_impl)

    def _tick_impl(self):
        if self._spec_on:
            # speculative routing sits ABOVE the dispatch modes: a
            # ready batch runs a draft-then-verify round (sync — async
            # servers drain their in-flight step inside), anything
            # else (prompt feeding, window edge, every slot fallen
            # back) takes the plain path below unchanged
            if not self._slots and not self._async:
                self._admit()
            if self._slots and self._spec_ready():
                self._tick_spec()
                return
            if self._slots:
                self._spec_plain_steps += 1
        if self._async:
            if not self._slots:
                self._admit()
            if self._constrained_active():
                # constrained slots cannot pipeline: the NEXT step's
                # mask is a function of the token the in-flight step
                # has not fetched yet.  Drain the pipeline and fall
                # through to the sync path — same tokens, one tick of
                # lost overlap per constrained batch
                self._drain_inflight()
                if self._tel:
                    _telemetry.count("constraint.sync_fallbacks")
            else:
                self._tick_async()
                return
        if not self._slots:
            self._admit()
            if not self._slots:
                self._gap_anchor = None   # idle, not stalled
                return
        # budgeted admission: at most ONE prefill chunk per round,
        # before the decode step — the stall-free interleaving
        self._advance_admitting()
        if not self._slots or all(st.get("admitting")
                                  for st in self._slots.values()):
            return   # nothing decodable this round (pure admission)
        t0 = time.perf_counter()
        self._ensure_decode_blocks(1)
        tok, pos = self._feed_arrays()
        temp, tk, tp = self._sampling_arrays()
        mask = self._mask_array()
        n = self._step_no
        if self._adapters is not None:
            # pool attached: every step gathers per-slot (a, b) pairs
            # by id — base-only batches gather row 0 (the zero delta)
            # and reproduce the plain server's tokens
            pk = self._adapters.pool_key()
            ad = self._adapters.stacks()
            ids = self._gather_adapter_ids()
            if temp.any() or mask is not None:
                kind = "adapter_sample_step"
                self._fault_check(kind)
                fn = _get_adapter_sample_step_fn(
                    self.cfg, pk, self._paged, self._shard)
                if mask is None:
                    # the executable takes the mask unconditionally
                    # (ONE compiled shape); all-zeros is the identity
                    mask = np.zeros(
                        (self.max_batch, self.cfg.vocab_size),
                        np.float32)
                nxt, self.cache = fn(
                    self.params, self.cache, ad, jnp.asarray(ids),
                    jnp.asarray(tok), jnp.asarray(pos),
                    jax.random.fold_in(self._base_key, n),
                    jnp.asarray(temp), jnp.asarray(tk),
                    jnp.asarray(tp), jnp.asarray(mask))
                nxt = np.asarray(nxt)
                logits = None
            else:
                kind = "adapter_step"
                self._fault_check(kind)
                fn = _get_adapter_step_fn(self.cfg, pk, self._paged,
                                          self._shard)
                logits, self.cache = fn(
                    self.params, self.cache, ad, jnp.asarray(ids),
                    jnp.asarray(tok), jnp.asarray(pos))
                nxt = np.asarray(jnp.argmax(logits, axis=-1))
        elif mask is not None:
            # constrained decode without a pool: the plain step plus
            # the [B, V] mask input.  Greedy slots take the masked
            # argmax inside _sample_batched, so this path consumes the
            # fold_in(n) key like the sampled path (all-greedy batches
            # draw nothing from it)
            kind = "masked_step"
            self._fault_check(kind)
            fn = _get_masked_step_fn(self.cfg, self._paged, self._shard)
            nxt, self.cache = fn(
                self.params, self.cache, jnp.asarray(tok),
                jnp.asarray(pos), jax.random.fold_in(self._base_key, n),
                jnp.asarray(temp), jnp.asarray(tk), jnp.asarray(tp),
                jnp.asarray(mask))
            nxt = np.asarray(nxt)
            logits = None
        elif temp.any():
            if self.cfg.moe is not None:
                kind = "moe_sample_step"
                self._fault_check(kind)
                fn = self._moe_wrap(_get_moe_sample_step_fn(
                    self.cfg, self._paged, self._shard))
            else:
                kind = "sample_step"
                self._fault_check(kind)
                fn = _get_sample_step_fn(self.cfg, self._paged,
                                         self._shard)
            nxt, self.cache = fn(
                self.params, self.cache, jnp.asarray(tok),
                jnp.asarray(pos), jax.random.fold_in(self._base_key, n),
                jnp.asarray(temp), jnp.asarray(tk), jnp.asarray(tp))
            nxt = np.asarray(nxt)
            logits = None
        else:
            kind = "step"
            self._fault_check(kind)
            logits, self.cache = self._step(self.params, self.cache,
                                            jnp.asarray(tok),
                                            jnp.asarray(pos))
            nxt = np.asarray(jnp.argmax(logits, axis=-1))
        # the step counter advances only AFTER the step call returned:
        # a failed call (real or injected OOM) leaves host state exactly
        # as before the tick, so the guard's retry is bit-exact
        self._step_no = n + 1
        # NaN guard on the tick logits (greedy path only — the sampled
        # path fetches tokens, not logits).  The full-logits fetch is
        # extra host traffic, so it only engages when a fault targets
        # logits or the operator opted in (PADDLE_TPU_NAN_GUARD_SERVING)
        nan_slots: set = set()
        if (logits is not None and self._resil
                and (_faults.active()
                     or _os.environ.get("PADDLE_TPU_NAN_GUARD_SERVING",
                                        "") == "1")):  # noqa: E129
            lnp = np.asarray(logits)
            if _faults.active():
                lnp = _faults.corrupt_nan("logits", lnp)
            finite = np.isfinite(lnp).all(axis=-1)
            nan_slots = {s for s in self._slots if not finite[s]}
        done = []
        failed = []
        appended = []
        for slot, st in self._slots.items():
            if st.get("admitting"):
                # rode the step at its prefill frontier: pos is owned by
                # the admission machinery, the output token discarded,
                # and a (mathematically valid, differently-rounded)
                # logits row must not trip the NaN guard collaterally
                continue
            i = st["pos"]
            st["pos"] = i + 1
            if i < len(st["prompt"]) - 1:
                continue                # still feeding prompt; logits unused
            if slot in nan_slots:
                # AFTER the prompt-feed skip: a mid-prompt slot never
                # consumes this tick's logits, so a non-finite row there
                # must not kill it collaterally
                failed.append(slot)
                continue
            t = int(nxt[slot])
            st["generated"].append(t)
            appended.append((st, 1))
            fin = self._constraint_push(st, t)
            if self._finished(st, t) or fin:
                done.append(slot)
        for slot in failed:
            st = self._slots.pop(slot)
            self._fail_request(st, slot, "non-finite tick logits")
        self._tel_tokens(appended, t0, kind=kind)
        self._retire(done)

    # -- async dispatch: one step/block in flight ---------------------------

    def _dispatch_feed(self, prev, block: int = 1):
        """Host-side feed snapshot for an async dispatch.

        Returns (host_tok, prev_mask, pos, temp, tk, tp, snap): per slot,
        the feed token comes from the host (prompt, or a generated token
        already fetched) unless it is the output of the still-in-flight
        previous dispatch — then ``prev_mask`` routes the DEVICE array
        through the jitted select instead (no host round trip).  ``snap``
        records (slot, st, fed_pos) for the deferred bookkeeping; each
        slot's pos advances by ``block`` optimistically (a slot that
        finishes mid-block retires at process time, where its stale pos
        no longer matters)."""
        B = self.max_batch
        ht = np.zeros((B,), np.int32)
        pm = np.zeros((B,), bool)
        pos = np.zeros((B,), np.int32)
        temp = np.zeros((B,), np.float32)
        tk = np.zeros((B,), np.int32)
        tp = np.ones((B,), np.float32)
        snap = []
        for slot, st in self._slots.items():
            i = st["pos"]
            n_p = len(st["prompt"])
            base = st.get("base", n_p)   # see _feed_arrays
            if st.get("admitting"):
                # mid-admission ride: feed the prefill frontier (the
                # written row is rewritten by the slot's next chunk).
                # NO snap entry and NO pos advance — the admission
                # machinery owns this slot's pos, its dispatch output
                # is never kept, and rollback/cancel must not touch it
                ht[slot] = st["prompt"][i]
                pos[slot] = i
                continue
            if i < n_p:
                ht[slot] = st["prompt"][i]
            elif i - base < len(st["generated"]):
                ht[slot] = st["generated"][i - base]
            else:
                # the feed token is the previous dispatch's output —
                # still on device, unfetched
                assert prev is not None, "in-flight feed without inflight"
                pm[slot] = True
            if i >= n_p - 1:  # the step at i produces a kept token
                temp[slot] = st["temperature"]
                tk[slot] = st["top_k"]
                tp[slot] = st["top_p"]
            pos[slot] = i
            snap.append((slot, st, i))
            st["pos"] = i + block
        return ht, pm, pos, temp, tk, tp, snap

    def _prev_feed(self, prev):
        """The [B] device token array feeding off the in-flight dispatch
        (step: its tokens; block: the block's last column)."""
        if prev is None:
            return jnp.zeros((self.max_batch,), jnp.int32)
        return prev["feed"]

    def _rollback_dispatch(self, snap, n):
        """Undo one ``_dispatch_feed``'s optimistic advances after the
        dispatch call itself failed (e.g. an injected/real OOM): the
        jitted fn raised, so neither the cache nor ``self.cache`` was
        reassigned — restoring pos and the step counter makes the retry
        bit-exact."""
        for slot, st, i in snap:
            if self._slots.get(slot) is st:
                st["pos"] = i
        self._step_no = n

    def _dispatch_step_async(self, prev):
        self._ensure_decode_blocks(1)
        ht, pm, pos, temp, tk, tp, snap = self._dispatch_feed(prev)
        n = self._step_no
        self._step_no = n + 1
        try:
            if self._adapters is not None:
                # async pipelining composes with the pool (gather rides
                # the in-flight select); constrained slots never reach
                # here — _tick_impl drains to sync first
                fname = "adapter_async_step"
                self._fault_check(fname)
                fn = _get_adapter_async_step_fn(
                    self.cfg, self._adapters.pool_key(), self._paged,
                    self._shard)
                nxt, self.cache = fn(
                    self.params, self.cache, self._adapters.stacks(),
                    jnp.asarray(self._gather_adapter_ids()),
                    jnp.asarray(ht), jnp.asarray(pm),
                    self._prev_feed(prev), jnp.asarray(pos),
                    jax.random.fold_in(self._base_key, n),
                    jnp.asarray(temp), jnp.asarray(tk),
                    jnp.asarray(tp))
            elif self.cfg.moe is not None:
                fname = "moe_async_step"
                self._fault_check(fname)
                fn = self._moe_wrap(_get_moe_async_step_fn(
                    self.cfg, self._paged, self._shard))
                nxt, self.cache = fn(
                    self.params, self.cache, jnp.asarray(ht),
                    jnp.asarray(pm),
                    self._prev_feed(prev), jnp.asarray(pos),
                    jax.random.fold_in(self._base_key, n),
                    jnp.asarray(temp),
                    jnp.asarray(tk), jnp.asarray(tp))
            else:
                fname = "async_step"
                self._fault_check(fname)
                fn = _get_async_step_fn(self.cfg, self._paged,
                                        self._shard)
                nxt, self.cache = fn(
                    self.params, self.cache, jnp.asarray(ht),
                    jnp.asarray(pm),
                    self._prev_feed(prev), jnp.asarray(pos),
                    jax.random.fold_in(self._base_key, n),
                    jnp.asarray(temp),
                    jnp.asarray(tk), jnp.asarray(tp))
        except Exception:
            self._rollback_dispatch(snap, n)
            raise
        self._inflight = {"kind": "step", "toks": nxt, "feed": nxt,
                          "fn": fname, "step_no0": n,
                          "snap": snap, "t_disp": time.perf_counter()}

    def _dispatch_block_async(self, prev, block: int):
        self._ensure_decode_blocks(block)
        ht, pm, pos, temp, tk, tp, snap = self._dispatch_feed(prev, block)
        n = self._step_no
        self._step_no = n + block
        try:
            if temp.any():
                fname = f"async_sample_block@{block}"
                self._fault_check(fname)
                fn = _get_async_sample_block_fn(self.cfg, block,
                                                self._paged, self._shard)
                toks, self.cache = fn(
                    self.params, self.cache, jnp.asarray(ht),
                    jnp.asarray(pm),
                    self._prev_feed(prev), jnp.asarray(pos),
                    self._base_key,
                    jnp.asarray(n), jnp.asarray(temp), jnp.asarray(tk),
                    jnp.asarray(tp))
                feed = toks[:, -1]  # the block's last token per slot
            else:
                fname = f"async_block@{block}"
                self._fault_check(fname)
                fn = _get_async_block_fn(self.cfg, block, self._paged,
                                         self._shard)
                toks, self.cache, feed, _ = fn(
                    self.params, self.cache, jnp.asarray(ht),
                    jnp.asarray(pm),
                    self._prev_feed(prev), jnp.asarray(pos))
        except Exception:
            self._rollback_dispatch(snap, n)
            raise
        self._inflight = {"kind": "block", "toks": toks, "feed": feed,
                          "fn": fname, "snap": snap, "block": block,
                          "step_no0": n, "t_disp": time.perf_counter()}

    def _process_inflight(self, prev):
        """Fetch a completed dispatch's tokens and run the deferred host
        bookkeeping.  Slots whose request retired (or was replaced by a
        new tenant) since the dispatch are skipped — their tokens are
        the overrun the async pipeline trades for overlap."""
        # the ONLY device->host fetch — watchdogged when a wall budget is
        # set (PADDLE_TPU_STEP_BUDGET_S): a wedged device step must not
        # hang the scheduler forever, so the fetch runs under
        # resilience.call_with_budget and a blown budget triggers
        # _recover_wedge instead of blocking.  Budget 0 (default) is the
        # plain inline fetch — zero overhead, today's behavior.
        try:
            if self._resil and (self._step_budget > 0
                                or _faults.active()):
                def _fetch():
                    _faults.hang("tick", "serving.fetch")
                    return np.asarray(prev["toks"])

                toks = _resilience.call_with_budget(
                    _fetch, self._step_budget, name="serving.fetch")
            else:
                toks = np.asarray(prev["toks"])
        except _resilience.WedgeError as e:
            self._recover_wedge(prev, e)
            return
        except Exception:
            # the fetch surfaced a device error (plain path included):
            # roll the scheduler back so host state matches the last
            # processed step, then let the guard classify (OOM chain or
            # propagate).  Any SUCCESSOR dispatched this tick is
            # cancelled too — its host bookkeeping assumed this record's
            # tokens would land first, and draining it after this
            # rollback would append its tokens out of order ahead of
            # the re-decoded ones
            self._cancel_record(self._inflight)
            self._inflight = None
            self._cancel_record(prev)
            raise
        done = []
        appended = []
        for slot, st, i in prev["snap"]:
            if self._slots.get(slot) is not st:
                continue  # retired/replaced while this step was in flight
            if prev["kind"] == "step":
                if i < len(st["prompt"]) - 1:
                    continue  # still feeding prompt; logits-token unused
                t = int(toks[slot])
                st["generated"].append(t)
                appended.append((st, 1))
                # constrained slots never dispatch async (the sync
                # fallback gate) — the push is a no-op kept for the
                # drain-on-transition edge
                fin = self._constraint_push(st, t)
                if self._finished(st, t) or fin:
                    done.append(slot)
            else:
                kept = 0
                for j in range(prev["block"]):
                    t = int(toks[slot, j])
                    st["generated"].append(t)
                    kept += 1
                    fin = self._constraint_push(st, t)
                    if self._finished(st, t) or fin:
                        done.append(slot)
                        break
                appended.append((st, kept))
        # latency window: dispatch -> this fetch (the async pipeline's
        # real step time, overlap included)
        self._tel_tokens(appended, prev.get("t_disp", time.perf_counter()),
                         steps=prev.get("block", 1), kind=prev.get("fn"))
        self._retire(done)

    def _tick_async(self):
        """One async tick: dispatch step N+1 FIRST (feeding the in-flight
        step's device tokens), then block on step N for bookkeeping —
        the device is never idle while the host schedules.  The last
        dispatch before a drain is overrun work whose results are simply
        never fetched."""
        prev = self._inflight
        self._inflight = None
        if not self._slots:
            self._admit()
            if not self._slots:
                self._gap_anchor = None
                return
        try:
            # one prefill chunk per round, before the dispatch (the
            # chunk chains on the in-flight step's cache future; device
            # order is step-then-chunk, so the frontier row the step
            # wrote is rewritten before anything attends it)
            self._advance_admitting()
        except Exception:
            # the chunk failed before any host state moved: restore
            # prev (its tokens are still fetchable) so the OOM chain's
            # sync fallback can drain it instead of losing a step
            self._inflight = prev
            raise
        if not self._slots or all(st.get("admitting")
                                  for st in self._slots.values()):
            if prev is not None:
                self._process_inflight(prev)
            return
        try:
            self._dispatch_step_async(prev)
        except Exception:
            # the dispatch failed before replacing the pipeline: restore
            # prev (see above)
            self._inflight = prev
            raise
        if prev is not None:
            self._process_inflight(prev)

    def _tick_block_async(self, block: int):
        """Async tick_block: one BLOCK in flight (see _tick_async).  The
        stepwise-prompt fallback first drains the in-flight dispatch —
        single async ticks then pipeline among themselves."""
        prev = self._inflight
        self._inflight = None
        if not self._slots:
            self._admit()
            if not self._slots:
                self._gap_anchor = None
                return
        if self._adapters is not None or self._constrained_active() \
                or self.cfg.moe is not None \
                or any(st["pos"] < len(st["prompt"]) - 1
                       or st.get("admitting")
                       for st in self._slots.values()):
            # adapter/constrained/MoE batches take stepwise async ticks
            # (the adapter async STEP pipelines; an async adapter BLOCK
            # executable isn't built; constrained slots need every
            # token fetched before the next mask; an MoE block would
            # freeze the occupancy mask across k steps while the async
            # overrun keeps retired slots contending — the stepwise
            # moe_async_step re-reads occupancy every tick) — same
            # tokens, the documented fallback
            if prev is not None:
                self._process_inflight(prev)
            for _ in range(block):
                self.tick()
                if not self._slots:
                    break
            return
        try:
            self._dispatch_block_async(prev, block)
        except Exception:
            self._inflight = prev   # see _tick_async
            raise
        if prev is not None:
            self._process_inflight(prev)

    # -- warmup: pre-compile what this server will serve --------------------

    def warmup(self, prompt_lens=None, blocks=(), sample: bool = False,
               constrained: bool = False):
        """Pre-compile the executables this server will serve, so the
        first request pays device time only (and re-launches hit the
        persistent compilation cache — framework.platform
        .init_compile_cache, called here).

        With an ``adapter_pool`` attached, every warm site compiles the
        ADAPTER twin instead (gathered steps/blocks/verify/prefill, ids
        all-zero — the executables are shape-keyed, so base-only warmup
        covers every adapter id), and ``sample=True`` warms the
        masked+sampled adapter step (the one executable constrained OR
        sampled pool traffic runs).  ``constrained=True`` warms the
        pool-less masked step for servers expecting ``constraint=``
        requests without a pool.

        This also warms the flash-decode kernel variants: tracing the
        step executables runs the split-KV Pallas kernel's availability
        probe (ops/decode_attention) and compiles the kernel for this
        server's exact (cache length, head, KV-dtype) configuration —
        under ``PADDLE_TPU_FLASH_DECODE``/``PADDLE_TPU_KV_DTYPE`` the
        first tick pays device time only, like every other executable
        here.

        ``prompt_lens``: prompt lengths to warm admission for — their
        power-of-two buckets dedupe to one compile each (default: every
        bucket up to the serving window; chunked-prefill servers have a
        single executable regardless).  ``blocks``: tick_block sizes to
        warm.  ``sample``: also warm the sampled-step twins.

        Warm steps run on the LIVE cache (donation chains it through),
        writing garbage rows at pos 0 for every slot — hidden by the
        same stale-row invariant as slot reuse: admission prefill
        overwrites rows [0, n), n >= 1, before any mask exposes them.
        That invariant only holds for requests admitted AFTER warmup, so
        warming an idle server is enforced: an active slot's already-
        prefilled rows would be silently corrupted.  The PRNG step
        counter is NOT advanced, so a warmed server produces
        bit-identical tokens to a cold one.

        Returns {executable: seconds} compile+first-run timings."""
        return _engine.ENGINE.warmup(
            self, prompt_lens=prompt_lens, blocks=blocks,
            sample=sample, constrained=constrained)

    def tick_block(self, block: int = 8):
        """``block`` greedy decode steps with ONE host round trip.

        Requires every active slot to be past its prompt (prefill
        admission guarantees this); when some slot is still consuming
        its prompt token-by-token (``prefill=False``), falls back to
        ``block`` single ticks — per-token host feedback is the whole
        point of that path.  Slots finishing mid-block overrun on device;
        the host discards their surplus tokens here.  MoE servers run
        the joint-routing ``moe_block`` kind for greedy batches (the
        occupancy mask frozen at dispatch) and fall back to stepwise
        ticks for sampled ones."""
        block = int(block)
        if block < 1:
            raise ValueError(f"block must be >= 1, got {block}")
        if self._adm is not None:
            self._adm.control_tick(
                idle=not self._slots and not self._queue)
        self._rss_guard()
        self._guarded(lambda: self._tick_block_impl(block))

    def _tick_block_impl(self, block: int):
        if self._spec_on:
            if not self._slots and not self._async:
                self._admit()
            if self._slots and self._spec_ready():
                # a block of N plain steps yields N tokens/slot; spec
                # rounds yield up to K each, so ceil(N/K) rounds covers
                # the block's work with the same one-fetch-per-dispatch
                # cadence (early exit when slots retire or the window
                # edge forces plain ticks)
                for _ in range(max(1, -(-block // self._spec_chunk()))):
                    if not self._slots or not self._spec_ready():
                        break
                    self._tick_spec()
                return
            if self._slots and not any(
                    st["pos"] < len(st["prompt"]) - 1
                    or st.get("admitting")
                    for st in self._slots.values()):
                # the prompt-feeding case (admitting included) falls
                # through to stepwise tick()s below, which count their
                # own plain steps
                self._spec_plain_steps += block
        if self._async:
            self._tick_block_async(block)
            return
        if not self._slots:
            self._admit()
            if not self._slots:
                self._gap_anchor = None
                return
        # a slot at pos == len(prompt)-1 is fine for block decode (its feed
        # token is the prompt's last; everything after is feedback) — only
        # slots with logits-discarded prompt positions left need stepwise.
        # Admitting slots force stepwise too: one prefill chunk per tick is
        # exactly the budgeted interleaving.  Constrained slots force
        # stepwise always (the mask for step j+1 needs step j's token on
        # the host), as do SAMPLED slots under an adapter pool (no
        # adapter sample-block executable — the stepwise path draws the
        # same fold_in(n) schedule, so tokens match tick() exactly)
        if self._constrained_active() \
                or ((self._adapters is not None
                     or self.cfg.moe is not None)
                    and any(st.get("temperature", 0.0) > 0.0
                            for st in self._slots.values())) \
                or any(st["pos"] < len(st["prompt"]) - 1
                       or st.get("admitting")
                       for st in self._slots.values()):
            for _ in range(block):
                self.tick()
                if not self._slots:
                    break
            return
        t0 = time.perf_counter()
        self._ensure_decode_blocks(block)
        tok, pos = self._feed_arrays()
        temp, tk, tp = self._sampling_arrays()
        n = self._step_no
        if self._adapters is not None:
            # greedy adapter block: gather once per step inside the
            # on-device scan — one host fetch for ``block`` tokens
            kind = f"adapter_block@{block}"
            self._fault_check(kind)
            fn = _get_adapter_block_fn(
                self.cfg, block, self._adapters.pool_key(),
                self._paged, self._shard)
            toks, self.cache, _, _ = fn(
                self.params, self.cache, self._adapters.stacks(),
                jnp.asarray(self._gather_adapter_ids()),
                jnp.asarray(tok), jnp.asarray(pos))
        elif temp.any():
            kind = f"sample_block@{block}"
            self._fault_check(kind)
            fn = _get_sample_block_fn(self.cfg, block, self._paged,
                                      self._shard)
            toks, self.cache = fn(
                self.params, self.cache, jnp.asarray(tok),
                jnp.asarray(pos), self._base_key, jnp.asarray(n),
                jnp.asarray(temp), jnp.asarray(tk), jnp.asarray(tp))
        elif self.cfg.moe is not None:
            # greedy MoE block: k joint-routing steps, the occupancy
            # mask frozen at dispatch (every slot here is past its
            # prompt — see the fallback above — so occupancy only
            # shrinks mid-block, the documented block-overrun tradeoff)
            kind = f"moe_block@{block}"
            self._fault_check(kind)
            fn = self._moe_wrap(_get_moe_block_fn(
                self.cfg, block, self._paged, self._shard))
            toks, self.cache, _, _ = fn(self.params, self.cache,
                                        jnp.asarray(tok), jnp.asarray(pos))
        else:
            kind = f"block@{block}"
            self._fault_check(kind)
            fn = _get_block_fn(self.cfg, block, self._paged, self._shard)
            toks, self.cache, _, _ = fn(self.params, self.cache,
                                        jnp.asarray(tok), jnp.asarray(pos))
        self._step_no = n + block   # after the call: see _tick_impl
        toks = np.asarray(toks)  # the block's single device->host fetch
        done = []
        appended = []
        for slot, st in self._slots.items():
            kept = 0
            for j in range(block):
                t = int(toks[slot, j])
                st["generated"].append(t)
                st["pos"] += 1
                kept += 1
                if self._finished(st, t):
                    done.append(slot)
                    break
            appended.append((st, kept))
        self._tel_tokens(appended, t0, steps=block, kind=kind)
        self._retire(done)
