"""Perplexity / language-model evaluation.

The eval half of the text stack: token-level negative log-likelihood and
perplexity over a corpus, batched and jitted, working unchanged on float,
weight-only int8/int4 (text/woq.py), and LoRA-adapted parameter trees —
every weight resolves through the same accessors the forward uses, which
is what makes "evaluate the quantized model's quality loss" a one-liner:

    ppl_f = perplexity(params, cfg, tokens)
    ppl_q = perplexity(woq.quantize_gpt_int8(params), cfg, tokens)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import engine as _engine
from . import generate, gpt

__all__ = ["nll", "perplexity", "cached_nll", "cached_perplexity"]

# back-compat alias: eval executables live in the Engine's generate-side
# cache now (keys embed flags.decode_jit_key via cfg_key, so a KV-dtype
# flip splits the key instead of needing a manual clear)
_EVAL_CACHE = _engine.ENGINE._gen


def _eval_fn(cfg: gpt.GPTConfig):
    def run(params, tokens):
        # tokens [B, T+1]: positions predict their successors
        logits, _aux = gpt.forward_with_aux(params, tokens[:, :-1], cfg)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        tgt = tokens[:, 1:]
        tok_nll = -jnp.take_along_axis(logp, tgt[..., None],
                                       -1)[..., 0]
        return tok_nll.sum(), tok_nll.size

    return _engine.ENGINE.jit(
        "evaluate.nll", ("eval_nll", _engine.cfg_key(cfg)), run)


def nll(params, cfg: gpt.GPTConfig, tokens) -> float:
    """Mean per-token negative log-likelihood of [B, T+1] token batches
    (a list/iterable of batches is accumulated)."""
    import numpy as np

    fn = _eval_fn(cfg)
    batches = tokens if isinstance(tokens, (list, tuple)) else [tokens]
    total, count = 0.0, 0
    for b in batches:
        b = jnp.asarray(np.asarray(b), jnp.int32)
        if b.ndim != 2 or b.shape[1] < 2:
            raise ValueError(f"eval batch must be [B, T+1] with T >= 1, "
                             f"got {b.shape}")
        s, n = fn(params, b)
        total += float(s)
        count += int(n)
    return total / max(count, 1)


def perplexity(params, cfg: gpt.GPTConfig, tokens) -> float:
    """exp(mean NLL) — the standard LM quality number."""
    import math

    return math.exp(nll(params, cfg, tokens))


def _cached_eval_fn(cfg: gpt.GPTConfig):
    def run(params, tokens):
        # feed token t at position t through the DECODE path; its
        # logits score token t+1 — one lax.scan over positions
        B, T1 = tokens.shape
        cache = generate.init_cache(cfg, B, T1 - 1)

        def step(cache, t):
            logits, cache = generate.decode_step(
                params, cache, tokens[:, t], t, cfg)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return cache, jnp.take_along_axis(
                logp, tokens[:, t + 1][:, None], -1)[:, 0]

        _, ll = jax.lax.scan(step, cache, jnp.arange(T1 - 1))
        return -ll.sum(), ll.size

    return _engine.ENGINE.jit(
        "evaluate.cached_nll",
        ("eval_cached_nll", _engine.cfg_key(cfg)), run)


def cached_nll(params, cfg: gpt.GPTConfig, tokens) -> float:
    """Mean per-token NLL scored through the KV-CACHE decode path
    (``generate.decode_step``), not the teacher-forced forward.

    With the default cache dtype this matches :func:`nll` to numerical
    tolerance (the cache is exact) — its purpose is measuring the quality
    cost of LOSSY cache settings: ``PADDLE_TPU_KV_DTYPE=int8`` quantizes
    what decode attends to, which the forward-pass perplexity can never
    see.  The README's int8 accuracy caveat cites this number."""
    import numpy as np

    fn = _cached_eval_fn(cfg)
    batches = tokens if isinstance(tokens, (list, tuple)) else [tokens]
    total, count = 0.0, 0
    for b in batches:
        b = jnp.asarray(np.asarray(b), jnp.int32)
        if b.ndim != 2 or b.shape[1] < 2:
            raise ValueError(f"eval batch must be [B, T+1] with T >= 1, "
                             f"got {b.shape}")
        s, n = fn(params, b)
        total += float(s)
        count += int(n)
    return total / max(count, 1)


def cached_perplexity(params, cfg: gpt.GPTConfig, tokens) -> float:
    """exp(mean cached_nll) — perplexity through the decode path."""
    import math

    return math.exp(cached_nll(params, cfg, tokens))
