"""paddle_tpu.text — language models (GPT flagship, BERT, MoE) + datasets."""
from . import bert  # noqa: F401
from . import ernie  # noqa: F401
from . import gpt  # noqa: F401
from . import gpt_hybrid  # noqa: F401
from . import datasets  # noqa: F401
from . import generate  # noqa: F401
from . import seq2seq  # noqa: F401
from . import moe  # noqa: F401
from . import woq  # noqa: F401
from . import serving  # noqa: F401
from . import fleet  # noqa: F401
from . import lora  # noqa: F401
from . import evaluate  # noqa: F401
from .gpt import GPTConfig, gpt_1p3b, gpt_13b  # noqa: F401
from .gpt_hybrid import build_gpt_train_step  # noqa: F401
from .datasets import (  # noqa: F401  (reference text/__init__.py __all__)
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)
