"""GPT decoder-only transformer — the flagship model family.

Reference capability: the reference trains ERNIE/GPT-scale transformers via
Fleet (BASELINE configs 4-5); its building blocks are fused attention CUDA
ops + Megatron-style parallel layers (fleet/meta_parallel/parallel_layers/
mp_layers.py).  TPU-first design:

- parameters are a flat pytree; all L transformer blocks are *stacked* along
  a leading axis and the forward scans them with ``lax.scan`` — one compiled
  block body regardless of depth (fast compiles) and a natural pipeline-
  parallel axis (shard the stack on 'pp').
- ``param_shardings`` returns Megatron shardings as PartitionSpecs; under
  pjit XLA inserts the same collectives the reference's ColumnParallel/
  RowParallel layers issue by hand (all_gather / reduce_scatter over 'mp').
- attention routes through the Pallas flash kernel on TPU.
"""
from __future__ import annotations

import dataclasses
import functools
import math
import os
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import attention_array
# weight access in _gqa_qkv/_block/forward resolves through woq.w /
# woq.embed / woq.logits: identity on float training params, fused dequant
# on weight-only int8/int4 decode params — forward on quantized params is
# a correct eval (perplexity) path, never silent garbage
from . import woq


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    ffn_ratio: int = 4
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16  # compute dtype; params stay fp32
    remat: bool = False
    # selective-checkpoint policy when remat=True (reference recompute
    # lists a subset of ops to keep; jax expresses it as a policy):
    # None = save nothing (full recompute, max memory saving);
    # "dots" = keep matmul outputs (recompute only cheap elementwise —
    #   a middle rung that may also sidestep backends where FULL-remat
    #   programs fail to compile);
    # "dots_no_batch" = keep only non-batch matmuls (weights-stationary)
    remat_policy: str | None = None
    use_flash: bool = True
    # sequence-parallel ring attention: cap the live score temp at
    # [B, H, Tl, sp_sub_block] by walking kv in sub-chunks (the flash
    # recurrence in XLA — ops/ring_attention.py _chunk_attend).  None =
    # whole-block scores; set for long local chunks.
    sp_sub_block: int | None = None
    # grouped-query attention (beyond the reference — the Llama/Mistral
    # family): ``num_kv_heads`` < num_heads shares each K/V head across a
    # group of query heads, shrinking qkv params and (the real win) the
    # decode KV cache by num_heads/num_kv_heads.  None = MHA; 1 = MQA.
    num_kv_heads: int | None = None
    moe: Any = None  # MoEConfig → every block's FFN becomes expert-parallel
    # Llama-family architecture switches (round-5; independent of each
    # other and of GQA — num_kv_heads + the three below give the
    # Llama/Mistral shape on the same GPT machinery):
    # "learned" = trained wpe table; "rope" = rotary embeddings applied
    # to q/k (no position table; the decode cache stores ROTATED keys)
    pos_embed: str = "learned"
    norm: str = "layernorm"        # "layernorm" | "rmsnorm" (gain-only)
    activation: str = "gelu"       # "gelu" | "swiglu" (gated FFN)

    def __post_init__(self):
        # the invariant lives on the config, not one entry point: every
        # consumer (count_params/shardings/init_cache/checkpoint-loaded
        # params) inherits the loud failure
        if (self.num_kv_heads is not None
                and self.num_heads % self.num_kv_heads):
            raise ValueError(
                f"num_kv_heads {self.num_kv_heads} must divide num_heads "
                f"{self.num_heads}")
        if self.pos_embed not in ("learned", "rope"):
            raise ValueError(f"unknown pos_embed {self.pos_embed!r}")
        if self.norm not in ("layernorm", "rmsnorm"):
            raise ValueError(f"unknown norm {self.norm!r}")
        if self.activation not in ("gelu", "swiglu"):
            raise ValueError(f"unknown activation {self.activation!r}")
        if self.pos_embed == "rope" and self.head_dim % 2:
            raise ValueError("rope needs an even head_dim")
        if self.moe is not None and self.activation != "gelu":
            raise ValueError(
                "MoE experts use the gelu FFN; activation='swiglu' with "
                "moe is not implemented")

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self):
        return self.num_kv_heads if self.num_kv_heads is not None \
            else self.num_heads

    @property
    def ffn_size(self):
        return self.ffn_ratio * self.hidden_size


def gpt_1p3b():
    return GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
                     max_seq_len=2048)


def gpt_13b():
    return GPTConfig(vocab_size=50304, hidden_size=5120, num_layers=40, num_heads=40,
                     max_seq_len=2048)


def init_params(cfg: GPTConfig, key) -> dict:
    """Stacked-block parameter pytree, fp32 master weights."""
    keys = jax.random.split(key, 10)
    D, F, L, V, T = cfg.hidden_size, cfg.ffn_size, cfg.num_layers, cfg.vocab_size, cfg.max_seq_len
    s = 0.02

    def nrm(k, shape, std=s):
        return std * jax.random.normal(k, shape, jnp.float32)

    blk_keys = jax.random.split(keys[9], 6)
    # fold_in, NOT split(…, 7): widening the split would silently change
    # blk_keys[0..5] and with them every existing config's initial
    # weights for the same seed (split has no prefix property) — old
    # recorded seeds must keep reproducing their models
    gate_key = jax.random.fold_in(keys[9], 6)
    blocks = {
        "ln1_g": jnp.ones((L, D), jnp.float32),
        "ln2_g": jnp.ones((L, D), jnp.float32),
        "proj_w": nrm(blk_keys[1], (L, D, D), std=s / math.sqrt(2 * L)),
        "proj_b": jnp.zeros((L, D), jnp.float32),
    }
    if cfg.norm == "layernorm":   # rmsnorm is gain-only
        blocks["ln1_b"] = jnp.zeros((L, D), jnp.float32)
        blocks["ln2_b"] = jnp.zeros((L, D), jnp.float32)
    if cfg.num_kv_heads is not None:
        Dkv = cfg.kv_heads * cfg.head_dim
        # GQA: q keeps the full width; k/v project to Dkv
        blocks["q_w"] = nrm(blk_keys[4], (L, D, D))
        blocks["q_b"] = jnp.zeros((L, D), jnp.float32)
        blocks["kv_w"] = nrm(blk_keys[5], (L, 2, D, Dkv))
        blocks["kv_b"] = jnp.zeros((L, 2, Dkv), jnp.float32)
    else:
        # qkv stored as separate [3, D, D] mats (not one [D, 3D]) so the
        # output dim shards cleanly per-projection under tensor parallel
        blocks["qkv_w"] = nrm(blk_keys[0], (L, 3, D, D))
        blocks["qkv_b"] = jnp.zeros((L, 3, D), jnp.float32)
    if cfg.moe is None:
        blocks.update({
            "fc_w": nrm(blk_keys[2], (L, D, F)),
            "fc_b": jnp.zeros((L, F), jnp.float32),
            "out_w": nrm(blk_keys[3], (L, F, D), std=s / math.sqrt(2 * L)),
            "out_b": jnp.zeros((L, D), jnp.float32),
        })
        if cfg.activation == "swiglu":
            # gated FFN: down(silu(gate(x)) * up(x)) — the third matmul
            blocks["gate_w"] = nrm(gate_key, (L, D, F))
            blocks["gate_b"] = jnp.zeros((L, F), jnp.float32)
    else:
        from .moe import init_moe_params

        per_layer = [init_moe_params(k, D, F, cfg.moe)
                     for k in jax.random.split(blk_keys[2], L)]
        blocks["moe"] = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_layer)
    params = {
        "wte": nrm(keys[0], (V, D)),
        "ln_f_g": jnp.ones((D,), jnp.float32),
        "blocks": blocks,
    }
    if cfg.pos_embed == "learned":   # rope has no position table
        params["wpe"] = nrm(keys[1], (T, D))
    if cfg.norm == "layernorm":
        params["ln_f_b"] = jnp.zeros((D,), jnp.float32)
    return params


def param_shardings(cfg: GPTConfig, dp="dp", mp="mp", pp=None, ep="ep") -> dict:
    """Megatron-style PartitionSpecs (reference mp_layers.py Column/RowParallel
    + VocabParallelEmbedding; ZeRO/pp compose by adding axes).  With MoE the
    expert dim shards over ``ep`` (expert parallelism)."""
    l = pp  # leading stacked-layer axis shards over pipeline stages if set
    blocks = {
        "ln1_g": P(l, None),
        "ln2_g": P(l, None),
        "qkv_w": P(l, None, None, mp),  # column parallel (per-projection)
        "qkv_b": P(l, None, mp),
        "proj_w": P(l, mp, None),  # row parallel
        "proj_b": P(l, None),
    }
    if cfg.norm == "layernorm":
        blocks["ln1_b"] = P(l, None)
        blocks["ln2_b"] = P(l, None)
    if cfg.num_kv_heads is not None:
        for k in ("qkv_w", "qkv_b"):
            del blocks[k]
        blocks.update({
            "q_w": P(l, None, mp), "q_b": P(l, mp),
            "kv_w": P(l, None, None, mp), "kv_b": P(l, None, mp),
        })
    if cfg.moe is None:
        blocks.update({
            "fc_w": P(l, None, mp),    # column parallel
            "fc_b": P(l, mp),
            "out_w": P(l, mp, None),   # row parallel
            "out_b": P(l, None),
        })
        if cfg.activation == "swiglu":
            blocks["gate_w"] = P(l, None, mp)   # column parallel like fc
            blocks["gate_b"] = P(l, mp)
    else:
        from .moe import moe_param_shardings

        # per-layer MoE specs with the stacked-layer axis prepended
        blocks["moe"] = {
            k: P(l, *v) for k, v in moe_param_shardings(ep=ep, mp=mp).items()
        }
    out = {
        "wte": P(mp, None),          # vocab-parallel embedding
        "ln_f_g": P(None),
        "blocks": blocks,
    }
    if cfg.pos_embed == "learned":
        out["wpe"] = P(None, None)
    if cfg.norm == "layernorm":
        out["ln_f_b"] = P(None)
    return out


def _layer_norm(x, g, b, eps=1e-5):
    m = jnp.mean(x, axis=-1, keepdims=True)
    v = jnp.var(x, axis=-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps) * g + b


def _rms_norm(x, g, eps=1e-5):
    """Gain-only RMS normalization (Llama family): no mean subtraction,
    no bias — x * rsqrt(mean(x^2)) * g, statistics in the caller's dtype
    (callers upcast to fp32 like _layer_norm's)."""
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(ms + eps) * g


def _norm(x, p, prefix: str, cfg):
    """Block-norm dispatch — THE single entry every block path (train,
    cached decode, prefill, verify) normalizes through.  LayerNorm keeps
    the fp32-stats/fused-kernel behavior of _ln; RMSNorm is gain-only
    (params carry no ``<prefix>_b``) and never takes the fused-LN kernel
    (different math)."""
    dt = cfg.dtype
    if cfg.norm == "rmsnorm":
        return _rms_norm(x.astype(jnp.float32),
                         p[prefix + "_g"]).astype(dt)
    return _ln(x, p[prefix + "_g"], p[prefix + "_b"], dt)


def apply_rope(x, positions, base: float = 10000.0):
    """Rotary position embedding on [..., T, H, hd] (hd even): the
    rotate-half convention, angles in fp32.  ``positions`` [T] int —
    decode passes the single cache position, verify/prefill pass
    pos0 + arange(K).  Defining property (tested): inner products depend
    only on POSITION DIFFERENCES, which is what lets the decode cache
    store rotated keys once and never re-rotate them."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[:, None] * freqs      # [T, half]
    cos = jnp.cos(ang)[:, None, :]                            # [T, 1, half]
    sin = jnp.sin(ang)[:, None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _ln(x, g, b, dt):
    """LayerNorm with fp32 statistics, output in the compute dtype.

    The plain path upcasts the whole activation to fp32 (the reference's
    layer_norm_op.cu accumulates fp32 the same way) — but under scan-over-
    layers autodiff those fp32 chains become the largest saved residuals
    (measured on v5e: 6x 288 MB fp32 buffers for GPT-760M at B=1).  The
    Pallas fused kernel (PADDLE_TPU_FUSED_LN=1) keeps x in the compute
    dtype end-to-end and saves only [N,1] statistics."""
    if os.environ.get("PADDLE_TPU_FUSED_LN", "") == "1":
        from ..ops.fused_norm import fused_layer_norm

        # belt-and-braces .astype(dt): the kernel returns x.dtype, which
        # equals dt everywhere in this stack — but the residual-stream
        # dtype is a scan-carry invariant, so enforce it at the call site
        return fused_layer_norm(x, g, b).astype(dt)
    return _layer_norm(x.astype(jnp.float32), g, b).astype(dt)


def _dropout(x, rate, key):
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros((), x.dtype))


def _remat_policy(name: str | None):
    """Map GPTConfig.remat_policy to a jax checkpoint policy.  The env
    var PADDLE_TPU_REMAT_POLICY lets on-device tooling
    (tools/remat_compile_check.py) A/B policies without rebuilding — but
    only when the config does NOT set one explicitly: an explicit config
    must stay authoritative (and keep raising on invalid values), or
    bench labels and HBM estimates silently desynchronize from the
    program actually compiled."""
    from ..ops.remat_policies import resolve

    if name is None:
        name = os.environ.get("PADDLE_TPU_REMAT_POLICY") or None
    return resolve(name)


def _gqa_qkv(h, p, cfg: GPTConfig, repeat_kv: bool = True,
             H: int | None = None, Hkv: int | None = None):
    """Grouped-query projections.  With ``repeat_kv`` the Hkv k/v heads
    are repeated across their query groups so every attention backend
    (flash included) sees the standard [B, T, H, hd] layout; the decode
    path passes False and keeps the cache at Hkv heads.  ``H``/``Hkv``
    override the config's global head counts with per-rank LOCAL ones
    when the weights are tensor-parallel shards (gpt_hybrid.mp_block).
    The GQA savings live in the params and the decode cache, not the
    training-time attention math."""
    B, T, D = h.shape
    H = H if H is not None else cfg.num_heads
    Hkv = Hkv if Hkv is not None else cfg.kv_heads
    hd = cfg.head_dim
    dt = cfg.dtype
    q = (woq.mm(h, p, "q_w", dt) + p["q_b"].astype(dt)).reshape(B, T, H, hd)
    kv = woq.mm_stacked(h, p, "kv_w", dt) \
        + p["kv_b"].astype(dt)[:, None, None]
    k = kv[0].reshape(B, T, Hkv, hd)
    v = kv[1].reshape(B, T, Hkv, hd)
    rep = H // Hkv
    if repeat_kv and rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return q, k, v


def _project_qkv(h, p, cfg: GPTConfig, repeat_kv: bool = True):
    """qkv projection for BOTH attention families: q [B,T,H,hd], k/v
    [B,T,H,hd] (dense / repeated GQA) or [B,T,Hkv,hd] (repeat_kv=False —
    the cache-row layout).  The single source the train block and every
    decode-path block (generate.py: cached/prefill/verify) project
    through."""
    B, T, _ = h.shape
    if cfg.num_kv_heads is not None:
        return _gqa_qkv(h, p, cfg, repeat_kv=repeat_kv)
    dt = cfg.dtype
    H, hd = cfg.num_heads, cfg.head_dim
    qkv = woq.mm_stacked(h, p, "qkv_w", dt) \
        + p["qkv_b"].astype(dt)[:, None, None]
    return (qkv[0].reshape(B, T, H, hd), qkv[1].reshape(B, T, H, hd),
            qkv[2].reshape(B, T, H, hd))


def _ffn_body(h, p, cfg: GPTConfig):
    """The FFN matmuls on a normalized input — gelu MLP or SwiGLU
    (down(silu(gate) * up)); the single implementation the train block
    and every decode-path block share."""
    dt = cfg.dtype
    if cfg.activation == "swiglu":
        gate = jax.nn.silu(woq.mm(h, p, "gate_w", dt)
                           + p["gate_b"].astype(dt))
        up = woq.mm(h, p, "fc_w", dt) + p["fc_b"].astype(dt)
        h = gate * up
    else:
        h = jax.nn.gelu(woq.mm(h, p, "fc_w", dt) + p["fc_b"].astype(dt))
    return woq.mm(h, p, "out_w", dt) + p["out_b"].astype(dt)


def _ffn_dense(x, p, cfg: GPTConfig):
    """Residual dense FFN half of a block: x + MLP(norm(x))."""
    return x + _ffn_body(_norm(x, p, "ln2", cfg), p, cfg)


# sentinel for _ffn_tail's legacy capacity rule (``None`` is a MEANINGFUL
# override there: moe_ffn's capacity-factor bound) — module-level so the
# MoE serving step can request cf-based capacity explicitly
_LEGACY = object()


def _ffn_tail(x, p, cfg: GPTConfig, valid=None, capacity=_LEGACY,
              stats=None):
    """Inference FFN half: dense MLP or MoE (aux loss discarded — it only
    matters for the training objective).  MoE capacity is computed from
    the CALL's token count (GShard semantics): at one token nothing can
    drop; a batched call's rows contend for capacity like training
    tokens.  ``valid`` (prefill path): pad mask over x's token dims —
    pads route nowhere, and capacity becomes the dropless bound so a
    padded prompt chunk routes exactly like its unpadded prefix
    (text/moe._route).

    ``capacity`` (round-19, MoE serving): left at the default sentinel it
    keeps the legacy rule — dropless token-count bound when ``valid`` is
    given, moe_ffn's capacity-factor bound otherwise.  An explicit value
    (``None`` included — the cf-based bound) overrides that rule: the
    expert-parallel decode step passes ``valid=act, capacity=None`` so
    occupied slots contend under the CONFIGURED capacity factor while
    free slots claim nothing.
    ``stats``: a ``{"dropped", "load"}`` int32 accumulator tree — when
    given, the call returns ``(x', stats')`` with the routing delta
    added (dense models pass it through unchanged)."""
    if cfg.moe is None:
        out = _ffn_dense(x, p, cfg)
        return (out, stats) if stats is not None else out
    from .moe import moe_ffn

    h = _norm(x, p, "ln2", cfg)
    if capacity is _LEGACY:
        n_tokens = 1
        for d in x.shape[:-1]:
            n_tokens *= d
        capacity = n_tokens if valid is not None else None
    if stats is None:
        y, _aux = moe_ffn(p["moe"], h, cfg.moe, key=None, valid=valid,
                          capacity=capacity)
        return x + y
    y, _aux, delta = moe_ffn(p["moe"], h, cfg.moe, key=None, valid=valid,
                             capacity=capacity, with_stats=True)
    stats = {"dropped": stats["dropped"] + delta["dropped"],
             "load": stats["load"] + delta["load"]}
    return x + y, stats


def _block(x, p, cfg: GPTConfig, dropout_key=None):
    """One transformer block on [B, T, D] activations (compute dtype)."""
    B, T, D = x.shape
    H, hd = cfg.num_heads, cfg.head_dim
    dt = cfg.dtype
    drop = cfg.dropout > 0.0 and dropout_key is not None
    h = _norm(x, p, "ln1", cfg)
    q, k, v = _project_qkv(h, p, cfg)
    if cfg.pos_embed == "rope":
        pos = jnp.arange(T)
        q, k = apply_rope(q, pos), apply_rope(k, pos)
    attn = attention_array(q, k, v, is_causal=True)
    attn = attn.reshape(B, T, D)
    a = woq.mm(attn, p, "proj_w", dt) + p["proj_b"].astype(dt)
    if drop:
        a = _dropout(a, cfg.dropout, jax.random.fold_in(dropout_key, 0))
    x = x + a
    h = _norm(x, p, "ln2", cfg)
    if cfg.moe is not None:
        from .moe import moe_ffn

        h, aux = moe_ffn(p["moe"], h, cfg.moe,
                         key=(jax.random.fold_in(dropout_key, 2)
                              if dropout_key is not None else None))
    else:
        h = _ffn_body(h, p, cfg)
        aux = jnp.zeros((), jnp.float32)
    if drop:
        h = _dropout(h, cfg.dropout, jax.random.fold_in(dropout_key, 1))
    return x + h, aux


def forward_with_aux(params: dict, tokens, cfg: GPTConfig, act_sharding=None,
                     key=None):
    """tokens [B, T] int32 → (logits [B, T, V], aux-loss scalar).

    aux is the summed MoE load-balancing loss (0 for dense models).
    act_sharding: optional NamedSharding constraint applied to the [B, T, D]
    activations — e.g. P('dp', 'sp', None) for sequence parallelism; XLA
    propagates it through the blocks and inserts the sp collectives.
    key: PRNG key enabling dropout (cfg.dropout > 0); None = eval mode."""
    B, T = tokens.shape
    dt = cfg.dtype
    x = woq.embed(params, tokens, dt)
    if cfg.pos_embed == "learned":
        x = x + params["wpe"][:T].astype(dt)[None]
    if act_sharding is not None:
        x = jax.lax.with_sharding_constraint(x, act_sharding)

    blk = functools.partial(_block, cfg=cfg)
    if cfg.remat:  # see _remat_policy for the policy names
        # prevent_cse=False: inside lax.scan the loop structure already
        # prevents the grad-of-checkpoint CSE hazard, and the default's
        # optimization_barriers send the TPU compiler into a tailspin
        # (observed: >15 min hangs on v5e for the 350M config).
        # PADDLE_TPU_REMAT_PREVENT_CSE=1 restores the default barriers so
        # tools/remat_compile_check.py can measure both variants on-device.
        _cse = os.environ.get("PADDLE_TPU_REMAT_PREVENT_CSE", "") == "1"
        blk = jax.checkpoint(blk, prevent_cse=_cse,
                             policy=_remat_policy(cfg.remat_policy))

    need_keys = key is not None and (cfg.dropout > 0.0 or cfg.moe is not None)
    if need_keys:
        layer_keys = jax.random.split(key, cfg.num_layers)

        def scan_body(x, pk):
            p, k = pk
            return blk(x, p, dropout_key=k)

        x, aux = jax.lax.scan(scan_body, x, (params["blocks"], layer_keys))
    else:
        def scan_body(x, layer_params):
            return blk(x, layer_params)

        x, aux = jax.lax.scan(scan_body, x, params["blocks"])
    x = _norm(x, params, "ln_f", cfg)
    logits = woq.logits(x, params, dt)
    return logits, jnp.sum(aux)


def forward(params: dict, tokens, cfg: GPTConfig, act_sharding=None, key=None):
    """tokens [B, T] int32 → logits [B, T, V] (compute dtype)."""
    return forward_with_aux(params, tokens, cfg, act_sharding, key)[0]


def loss_fn(params: dict, tokens, cfg: GPTConfig, act_sharding=None, key=None):
    """Next-token LM loss; softmax-CE in fp32 (reference
    c_softmax_with_cross_entropy keeps the reduction sharded — here XLA
    handles the sharded softmax under pjit).  MoE models add the router
    load-balancing aux loss."""
    logits, aux = forward_with_aux(params, tokens[:, :-1], cfg,
                                   act_sharding=act_sharding, key=key)
    tgt = tokens[:, 1:]
    if os.environ.get("PADDLE_TPU_FUSED_CE", "") == "1":
        # Pallas blockwise loss head: no [B, T, V] fp32 log-softmax in HBM
        # (ops/fused_ce.py; falls back to the expression below off-TPU).
        # Opt-in until the on-device parity check has passed on hardware.
        from ..ops.fused_ce import fused_softmax_ce

        return jnp.mean(fused_softmax_ce(logits, tgt)) + aux
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll) + aux


def count_params(cfg: GPTConfig) -> int:
    D, F, L, V, T = (cfg.hidden_size, cfg.ffn_size, cfg.num_layers, cfg.vocab_size,
                     cfg.max_seq_len)
    Dkv = cfg.kv_heads * cfg.head_dim
    qkv = (D * D + D + 2 * D * Dkv + 2 * Dkv
           if cfg.num_kv_heads is not None else 3 * D * D + 3 * D)
    norms = 4 * D if cfg.norm == "layernorm" else 2 * D  # 2 gains (+2 biases)
    ffn = D * F + F + F * D + D
    if cfg.activation == "swiglu":
        ffn += D * F + F                                  # gate matmul
    per_block = norms + qkv + D * D + D + ffn
    final_norm = 2 * D if cfg.norm == "layernorm" else D
    pos = T * D if cfg.pos_embed == "learned" else 0
    return V * D + pos + final_norm + L * per_block


def flops_per_token(cfg: GPTConfig, seq_len: int) -> float:
    """Training FLOPs/token = 6 * (matmul-weight params) + attention term.

    Matmul weights: qkv (3 D^2) + attn proj (D^2) + ffn (2 D F) per block,
    plus the tied-embedding head matmul (V D).  The embedding *lookup* is a
    gather (no MXU flops), so with tied weights V*D is counted exactly once;
    wpe, biases and layernorm params contribute no matmul flops.  Attention
    scores: QK^T + AV = 12 L D T training flops/token (full, non-causal
    accounting — the conservative standard for MFU)."""
    D, F, L, V = cfg.hidden_size, cfg.ffn_size, cfg.num_layers, cfg.vocab_size
    Dkv = cfg.kv_heads * cfg.head_dim
    qkv_w = (D * D + 2 * D * Dkv if cfg.num_kv_heads is not None
             else 3 * D * D)
    ffn_w = (3 if cfg.activation == "swiglu" else 2) * D * F
    n_matmul = L * (qkv_w + D * D + ffn_w) + V * D
    attn = 12 * L * D * seq_len
    return 6 * n_matmul + attn
