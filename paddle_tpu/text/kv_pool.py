"""Paged KV-cache subsystem: block pool, block tables, prefix reuse.

The serving cache was one contiguous ``[L, max_batch, rows, Hkv, hd]``
slab — every slot provisioned for the worst-case context, and identical
prompt prefixes (system prompts, few-shot headers) prefilled and stored
once per request.  This module reproduces the reference's allocator
stack (auto-growth best-fit chunks, retry-on-OOM chains) at KV-cache
granularity, in the mold of vLLM's PagedAttention and SGLang's
RadixAttention:

* **block pool** — device leaves ``[L, num_blocks, block_size, Hkv, hd]``
  (int8 scale planes ``[L, N, bs, Hkv]`` ride along exactly as in the
  contiguous layout), shared by every slot;
* **block tables** — an int32 ``[max_batch, nmax]`` leaf mapping each
  slot's logical block to a physical pool block (-1 = unmapped), carried
  in the cache pytree so the jitted steps stay pure pytree-in/pytree-out
  and donation composes unchanged;
* **free-list allocator with refcounts** (:class:`PagedAllocator`, host
  side) — blocks are allocated as a slot's ``pos`` crosses block
  boundaries instead of reserving ``max_len`` rows up front, and freed or
  dereferenced on retire;
* **prefix-hash index** — requests sharing a prompt prefix map their
  leading table entries to the SAME physical blocks (exact token-chain
  keys, refcounted), so shared prefixes are prefilled once; the first
  divergent write to a shared block copies it (copy-on-write).

Device math lives here too: :func:`paged_decode_step_batched` is the
pooled twin of ``serving.decode_step_batched`` (einsum fallback =
per-slot ``generate._cached_block`` on a gathered view — bit-identical
to the slab path holding the same rows; kernel route =
``ops/decode_attention.paged_decode_attention``, which resolves each
T-block through the table inside the grid), and
:func:`paged_prefill_chunk` is the pooled ``generate.prefill_slot_chunk``.
The contiguous layout stays the default (``PADDLE_TPU_KV_LAYOUT``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import generate, gpt, woq
from .. import flags as _flags
from .. import telemetry as _telemetry

__all__ = [
    "PoolExhausted", "PagedAllocator", "round_len", "init_paged_cache",
    "paged_decode_step_batched", "paged_prefill_chunk",
    "paged_verify_chunk_batched", "copy_blocks", "inject_rows",
]

# the value/scale leaves of a pooled cache (everything except "tables")
POOL_LEAVES = ("k", "v", "k_s", "v_s")


class PoolExhausted(RuntimeError):
    """KV block pool has no free block.  The message carries the literal
    ``RESOURCE_EXHAUSTED`` marker so ``resilience.is_oom`` classifies it
    exactly like a real allocator OOM — the serving tick's retry chain
    (evict cold prefix entries -> degrade dispatch -> evict slots)
    engages on it."""

    def __init__(self, need: int = 1, total: int = 0):
        super().__init__(
            f"RESOURCE_EXHAUSTED: KV block pool exhausted "
            f"(need {need} more block(s), pool size {total})")


def round_len(max_len: int, block_size: int) -> int:
    """A paged cache's per-slot logical row count: the contiguous
    layout's kernel-tileable rounding, then up to a whole number of
    blocks (so a slot's gathered view is exactly ``nmax * bs`` rows —
    pick ``block_size`` dividing ``generate._round_cache_len(max_len)``
    when bit-parity with a contiguous cache of the same window
    matters)."""
    T = generate._round_cache_len(max_len)
    bs = int(block_size)
    return -(-T // bs) * bs


def init_paged_cache(cfg: gpt.GPTConfig, batch: int, max_len: int,
                     block_size: int | None = None,
                     num_blocks: int | None = None) -> dict:
    """The pooled cache pytree (``generate.init_cache(layout="paged")``):
    value leaves ``[L, N, bs, Hkv, hd]`` (+ int8 scale planes
    ``[L, N, bs, Hkv]``) and an int32 ``tables`` leaf ``[batch, nmax]``
    initialized unmapped (-1).  ``num_blocks`` defaults to full
    provisioning (``batch * nmax`` — slab-equivalent capacity, the
    parity-safe default); operators shrink it to the budget actual
    traffic needs, which is the whole point of paging."""
    bs = _flags.kv_block_size() if block_size is None else int(block_size)
    if bs < 8 or bs % 8:
        raise ValueError(f"block_size {bs}: must be a positive multiple "
                         f"of 8 (the decode kernel's row tile)")
    T = round_len(max_len, bs)
    nmax = T // bs
    # `is None` (not falsy): num_blocks=0 must hit the validation below,
    # not silently provision the full slab-equivalent pool
    N = batch * nmax if num_blocks is None else int(num_blocks)
    if N < 1:
        raise ValueError(f"num_blocks must be >= 1, got {N}")
    L, H, hd = cfg.num_layers, cfg.kv_heads, cfg.head_dim
    dt = generate._kv_store_dtype(cfg)
    shape = (L, N, bs, H, hd)
    cache = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt),
             "tables": jnp.full((batch, nmax), -1, jnp.int32)}
    if dt == jnp.int8:
        cache["k_s"] = jnp.zeros(shape[:-1], jnp.float32)
        cache["v_s"] = jnp.zeros(shape[:-1], jnp.float32)
    return cache


def _geometry(cache: dict):
    """(num_blocks, block_size, nmax) of a pooled cache pytree."""
    N, bs = cache["k"].shape[1], cache["k"].shape[2]
    return N, bs, cache["tables"].shape[1]


def _gather_slot(pool_leaf, trow):
    """One slot's contiguous view of a per-layer pool leaf:
    ``pool_leaf`` [N, bs, ...] + table row [nmax] -> [1, nmax*bs, ...].
    Delegates to the kernel module's batched gather — ONE copy of the
    unmapped-entry (clamp-to-block-0, causally-masked) semantics shared
    with the oracle/fallback paths."""
    from ..ops import decode_attention as da

    return da.gather_paged_view(pool_leaf, trow[None])


def _scatter_rows(cache: dict, rows: dict, phys) -> dict:
    """Write per-layer row leaves into the pool at physical row indices
    ``phys`` (int32, out-of-bounds = dropped — the overrun/unmapped
    sink).  ``rows`` leaves [L, R, Hkv(, hd)] against pool leaves
    [L, N, bs, Hkv(, hd)]; the single row-write every paged decode/
    prefill path funnels through (the ``generate._write_rows`` twin)."""
    out = dict(cache)
    for name, val in rows.items():
        arr = cache[name]
        L, NR = arr.shape[0], arr.shape[1] * arr.shape[2]
        flat = arr.reshape((L, NR) + arr.shape[3:])
        flat = flat.at[:, phys].set(val.astype(arr.dtype), mode="drop")
        out[name] = flat.reshape(arr.shape)
    return out


def paged_decode_step_batched(params, cache, token, pos,
                              cfg: gpt.GPTConfig):
    """``serving.decode_step_batched`` on the pooled layout: token [B]
    int32, pos [B] int32 (each slot's write position), cache a
    :func:`init_paged_cache` tree -> (logits [B, V], cache).

    Fallback route (any backend): vmap over slots of the EXACT per-slot
    ``generate._cached_block`` math on a table-gathered view — the same
    ops at the same shapes as the contiguous step, so greedy decode is
    bit-identical to a slab holding the same rows.  Kernel route (TPU /
    interpret, ``PADDLE_TPU_FLASH_DECODE``): fresh rows scatter into the
    pool first, then ``ops/decode_attention.paged_decode_attention``
    streams each slot's mapped blocks through the grid — no [B, T]
    gather is ever materialized."""
    from ..ops import decode_attention as da

    N, bs, nmax = _geometry(cache)
    B = token.shape[0]
    H, hd = cfg.num_heads, cfg.head_dim
    use_kernel = (_flags.flash_decode()
                  and da.paged_available((B, 1, H, hd),
                                         cache["k"].shape[1:]))
    if use_kernel:
        return _paged_step_kernel(params, cache, token, pos, cfg)

    tables = cache["tables"]
    pool = {n: cache[n] for n in POOL_LEAVES if n in cache}

    def one(tok_b, pos_b, trow):
        dt = cfg.dtype
        x = generate._embed_step(params, tok_b[None], pos_b, cfg)

        def body(x, layer):
            p, pl = layer
            csl = {n: _gather_slot(v, trow) for n, v in pl.items()}
            x, rows = generate._cached_block(x, p, csl, pos_b, cfg)
            return x, rows

        x, rows = jax.lax.scan(body, x, (params["blocks"], pool))
        x = gpt._norm(x, params, "ln_f", cfg)
        logits = woq.logits(x, params, dt)[:, 0]
        return logits[0].astype(jnp.float32), rows

    logits, rows = jax.vmap(one, in_axes=(0, 0, 0),
                            out_axes=(0, 0))(token, pos, tables)
    # rows leaves [B, L, 1, Hkv(, hd)] -> [L, B, Hkv(, hd)]; physical row
    # per slot through the table (unmapped -> out of bounds -> dropped,
    # the slab path's clamp-into-masked-rows equivalent)
    tb = tables[jnp.arange(B), pos // bs]
    phys = jnp.where(tb >= 0, tb * bs + pos % bs, N * bs)
    stacked = {n: jnp.moveaxis(v[:, :, 0], 0, 1) for n, v in rows.items()}
    return logits, _scatter_rows(cache, stacked, phys)


def _paged_step_kernel(params, cache, token, pos, cfg: gpt.GPTConfig):
    """Kernel route of :func:`paged_decode_step_batched` — the layer
    loop runs at top level so the paged kernel sees the whole batch
    (grid ``(B*Hkv, nmax)``); the per-slot pre/post math stays vmapped
    (norm/projections/rope/MoE routing at the contiguous step's B=1
    shapes)."""
    from ..ops import decode_attention as da

    N, bs, nmax = _geometry(cache)
    B = token.shape[0]
    dt = cfg.dtype
    hd = cfg.head_dim
    tables = cache["tables"]
    tb = tables[jnp.arange(B), pos // bs]
    phys = jnp.where(tb >= 0, tb * bs + pos % bs, N * bs)
    pool = {n: cache[n] for n in POOL_LEAVES if n in cache}
    L = cache["k"].shape[0]

    def embed_one(tok_b, pos_b):
        return generate._embed_step(params, tok_b[None], pos_b, cfg)

    x = jax.vmap(embed_one)(token, pos)                  # [B, 1, 1, D]

    def body(carry, layer):
        x, pool = carry
        p, li = layer

        def pre(xb, pos_b):
            return generate._block_pre_attn(xb, p, pos_b, cfg)

        q3, rows = jax.vmap(pre)(x, pos)     # q3 [B,1,1,H,hd]
        # scatter the fresh rows into layer li BEFORE attending: the
        # kernel then reads exactly what later steps will read back
        # (scatter-then-attend == the slab path's splice-then-write)
        new_pool = {}
        for n, val in rows.items():
            arr = pool[n]
            NR = arr.shape[1] * arr.shape[2]
            flat = arr.reshape((arr.shape[0], NR) + arr.shape[3:])
            flat = flat.at[li, phys].set(val[:, 0].astype(arr.dtype),
                                         mode="drop")
            new_pool[n] = flat.reshape(arr.shape)
        pool = new_pool
        q = q3.reshape(B, 1, cfg.num_heads, hd)
        attn = da.paged_decode_attention(
            q, pool["k"][li], pool["v"][li], tables, pos,
            k_scale=pool["k_s"][li] if "k_s" in pool else None,
            v_scale=pool["v_s"][li] if "v_s" in pool else None)
        attn = attn.astype(dt).reshape(B, 1, 1, cfg.num_heads * hd)

        def post(xb, ab):
            return generate._block_post_attn(xb, ab, p, cfg)

        x = jax.vmap(post)(x, attn)
        return (x, pool), None

    (x, pool), _ = jax.lax.scan(
        body, (x, pool), (params["blocks"], jnp.arange(L)))

    def fin(xb):
        xb = gpt._norm(xb, params, "ln_f", cfg)
        return woq.logits(xb, params, dt)[0, 0]

    logits = jax.vmap(fin)(x)
    return logits.astype(jnp.float32), dict(cache, **pool)


def paged_prefill_chunk(params, cache, tokens, pos0, length, slot,
                        cfg: gpt.GPTConfig):
    """``generate.prefill_slot_chunk`` on the pooled layout: one chunk of
    a prompt at positions [pos0, pos0+C) for one slot, attending the
    slot's table-gathered cache rows [0, pos0) plus within-chunk
    causally (``generate._chunk_attend_block`` — the shared chunk math),
    writing rows [pos0, pos0+length) through the table (pads and
    unmapped entries dropped), returning (logits at the chunk's last
    valid position [V], cache).

    With a shared prefix adopted into the table, ``pos0`` starts at the
    first unshared row — the shared blocks are ATTENDED through the
    gather but never recomputed, which is where the prefix cache's
    prefill FLOPs saving comes from."""
    N, bs, nmax = _geometry(cache)
    tables = cache["tables"]
    trow = tables[slot]                                   # [nmax]
    pool = {n: cache[n] for n in POOL_LEAVES if n in cache}
    dt = cfg.dtype
    C = tokens.shape[1]
    x = woq.embed(params, tokens, dt)
    if cfg.pos_embed == "learned":
        x = x + jax.lax.dynamic_slice(
            params["wpe"], (pos0, 0), (C, cfg.hidden_size)).astype(dt)[None]
    valid_mask = (jnp.arange(C) < length)[None, :]        # [1, C]

    def body(x, layer):
        p, pl = layer
        csl = {n: _gather_slot(v, trow) for n, v in pl.items()}
        x, rows = generate._chunk_attend_block(x, p, csl, pos0, cfg,
                                               valid=valid_mask)
        return x, rows

    x, rows = jax.lax.scan(body, x, (params["blocks"], pool))
    logi = pos0 + jnp.arange(C)
    tb = trow[jnp.clip(logi // bs, 0, nmax - 1)]
    phys = jnp.where((jnp.arange(C) < length) & (tb >= 0)
                     & (logi // bs < nmax), tb * bs + logi % bs, N * bs)
    cache = _scatter_rows(cache, {n: v[:, 0] for n, v in rows.items()},
                          phys)
    last = jax.lax.dynamic_slice(x, (0, length - 1, 0),
                                 (1, 1, cfg.hidden_size))
    last = gpt._norm(last, params, "ln_f", cfg)
    logits = woq.logits(last, params, dt)[0, 0]
    return logits.astype(jnp.float32), cache


def paged_verify_chunk_batched(params, cache, tokens, pos, cfg):
    """``generate.verify_chunk`` on the pooled layout, batched over
    slots: tokens [B, K] int32 scored at per-slot positions
    [pos_b, pos_b + K) -> (logits [B, K, V] fp32, cache).

    Per slot this is the EXACT chunk math ``paged_prefill_chunk`` runs —
    ``generate._chunk_attend_block`` over the slot's table-gathered view
    — so row 0 of the verify logits equals the plain decode step's
    logits for the same feed token (greedy serving parity rests on
    this).  K/V rows for the whole chunk scatter through the block
    table; rejected rows land at/past the slot's position pointer where
    the causal mask hides them and the next round overwrites them (the
    stale-row invariant — no masked write needed).  Unmapped or
    past-the-table entries drop (the standard out-of-bounds sink).

    Kernel route (TPU / interpret, ``PADDLE_TPU_FLASH_DECODE``): the
    layer loop moves to top level and ``paged_decode_attention`` streams
    the whole batch at Tq=K — the ROADMAP "flash-verify" item."""
    from ..ops import decode_attention as da

    N, bs, nmax = _geometry(cache)
    B, K = tokens.shape
    if (_flags.flash_decode()
            and da.paged_available((B, K, cfg.num_heads, cfg.head_dim),
                                   cache["k"].shape[1:])):
        return _paged_verify_kernel(params, cache, tokens, pos, cfg)
    tables = cache["tables"]
    pool = {n: cache[n] for n in POOL_LEAVES if n in cache}
    dt = cfg.dtype

    def one(tok_k, p0, trow):
        x = woq.embed(params, tok_k[None], dt)            # [1, K, D]
        if cfg.pos_embed == "learned":
            x = x + jax.lax.dynamic_slice(
                params["wpe"], (p0, 0),
                (K, cfg.hidden_size)).astype(dt)[None]

        def body(x, layer):
            p, pl = layer
            csl = {n: _gather_slot(v, trow) for n, v in pl.items()}
            x, rows = generate._chunk_attend_block(x, p, csl, p0, cfg)
            return x, rows

        x, rows = jax.lax.scan(body, x, (params["blocks"], pool))
        x = gpt._norm(x, params, "ln_f", cfg)
        logits = woq.logits(x, params, dt)[0]             # [K, V]
        return logits.astype(jnp.float32), rows

    logits, rows = jax.vmap(one, in_axes=(0, 0, 0),
                            out_axes=(0, 0))(tokens, pos, tables)
    # rows leaves [B, L, 1, K, Hkv(, hd)] -> [L, B*K, Hkv(, hd)];
    # physical row per (slot, j) through the table
    logi = pos[:, None] + jnp.arange(K)[None, :]          # [B, K]
    tb = jnp.take_along_axis(tables, jnp.clip(logi // bs, 0, nmax - 1),
                             axis=1)
    phys = jnp.where((tb >= 0) & (logi // bs < nmax),
                     tb * bs + logi % bs, N * bs).reshape(B * K)
    stacked = {}
    for n, v in rows.items():
        v = jnp.moveaxis(v[:, :, 0], 0, 1)                # [L, B, K, ...]
        stacked[n] = v.reshape((v.shape[0], B * K) + v.shape[3:])
    return logits, _scatter_rows(cache, stacked, phys)


def _paged_verify_kernel(params, cache, tokens, pos, cfg: gpt.GPTConfig):
    """Kernel route of :func:`paged_verify_chunk_batched` — the
    :func:`_paged_step_kernel` structure at Tq=K: layer loop at top
    level so the paged kernel sees the whole batch per layer, per-slot
    pre/post math vmapped at the fallback's [1, K, D] shapes
    (``generate._chunk_pre_attn`` — rope needs per-slot offsets), and
    the chunk's fresh rows scattered through the tables BEFORE attending
    (scatter-then-attend == the fallback's splice-then-write; rejected
    rows stay hidden behind the position pointer as ever)."""
    from ..ops import decode_attention as da

    N, bs, nmax = _geometry(cache)
    B, K = tokens.shape
    dt = cfg.dtype
    H, hd = cfg.num_heads, cfg.head_dim
    tables = cache["tables"]
    pool = {n: cache[n] for n in POOL_LEAVES if n in cache}
    L = cache["k"].shape[0]
    logi = pos[:, None] + jnp.arange(K)[None, :]          # [B, K]
    tb = jnp.take_along_axis(tables, jnp.clip(logi // bs, 0, nmax - 1),
                             axis=1)
    phys = jnp.where((tb >= 0) & (logi // bs < nmax),
                     tb * bs + logi % bs, N * bs).reshape(B * K)

    def embed_one(tok_k, p0):
        x = woq.embed(params, tok_k[None], dt)            # [1, K, D]
        if cfg.pos_embed == "learned":
            x = x + jax.lax.dynamic_slice(
                params["wpe"], (p0, 0),
                (K, cfg.hidden_size)).astype(dt)[None]
        return x

    x = jax.vmap(embed_one)(tokens, pos)                  # [B, 1, K, D]

    def body(carry, layer):
        x, pool = carry
        p, li = layer

        def pre(xb, p0):
            return generate._chunk_pre_attn(xb, p, p0, cfg)

        q3, rows = jax.vmap(pre)(x, pos)  # q3 [B, 1, K, H, hd]
        new_pool = {}
        for n, val in rows.items():
            arr = pool[n]
            NR = arr.shape[1] * arr.shape[2]
            flat = arr.reshape((arr.shape[0], NR) + arr.shape[3:])
            v = val[:, 0].reshape((B * K,) + val.shape[3:])
            flat = flat.at[li, phys].set(v.astype(arr.dtype), mode="drop")
            new_pool[n] = flat.reshape(arr.shape)
        pool = new_pool
        attn = da.paged_decode_attention(
            q3.reshape(B, K, H, hd), pool["k"][li], pool["v"][li],
            tables, pos,
            k_scale=pool["k_s"][li] if "k_s" in pool else None,
            v_scale=pool["v_s"][li] if "v_s" in pool else None)
        attn = attn.astype(dt).reshape(B, 1, K, H * hd)

        def post(xb, ab):
            return generate._block_post_attn(xb, ab, p, cfg)

        return (jax.vmap(post)(x, attn), pool), None

    (x, pool), _ = jax.lax.scan(
        body, (x, pool), (params["blocks"], jnp.arange(L)))

    def fin(xb):
        xb = gpt._norm(xb, params, "ln_f", cfg)
        return woq.logits(xb, params, dt)[0]              # [K, V]

    logits = jax.vmap(fin)(x)
    return logits.astype(jnp.float32), dict(cache, **pool)


def inject_rows(cache: dict, rows: dict, start, length, slot) -> dict:
    """Write externally computed cache rows (a prefill worker's output —
    leaves ``[L, 1, C, Hkv(, hd)]``, valid through ``length``) into one
    slot's rows [start, length) through its block table — the paged
    half of the fleet's prefill/decode handoff
    (``generate._merge_slot_rows`` is the contiguous twin).  ``start``
    skips rows an adopted prefix already holds (shared blocks must
    never be rewritten); pad rows beyond ``length`` and unmapped table
    entries drop (the standard out-of-bounds sink); the caller has
    already allocated/COW'd the write range (``ensure_rows``)."""
    N, bs, nmax = _geometry(cache)
    trow = cache["tables"][slot]                          # [nmax]
    C = rows["k"].shape[2]
    logi = jnp.arange(C)
    tb = trow[jnp.clip(logi // bs, 0, nmax - 1)]
    phys = jnp.where((logi >= start) & (logi < length) & (tb >= 0)
                     & (logi // bs < nmax),
                     tb * bs + logi % bs, N * bs)
    return _scatter_rows(cache, {n: v[:, 0] for n, v in rows.items()},
                         phys)


def copy_blocks(cache: dict, src, dst) -> dict:
    """Copy physical blocks ``src`` -> ``dst`` (int32 [P]) across every
    pool leaf — the device half of copy-on-write.  Destinations are
    freshly allocated (never in ``src``), so the gather/scatter pair has
    no ordering hazard; callers jit + donate the cache so the pool
    updates in place."""
    out = dict(cache)
    for name in POOL_LEAVES:
        if name in cache:
            arr = cache[name]
            out[name] = arr.at[:, dst].set(arr[:, src])
    return out


# ---------------------------------------------------------------------------
# host allocator: free list + refcounts + prefix index
# ---------------------------------------------------------------------------


class _PrefixEntry:
    """One indexed prompt block: the physical pool block, its LRU clock,
    and its position in the interned chain (``key`` = the intern-table
    key, ``parent`` = the previous block's chain id, 0 at the root) —
    enough to drop the entry and its intern record together."""

    __slots__ = ("block", "last_hit", "key", "parent")

    def __init__(self, block: int, tick: int, key, parent: int):
        self.block = block
        self.last_hit = tick
        self.key = key
        self.parent = parent


class PagedAllocator:
    """Host-side block accounting for one pooled cache: the free list,
    per-block refcounts, the per-slot table mirror (pushed to the device
    leaf when dirty), pending COW copies, and the prefix index.

    Prefix identity is an INTERNED parent-id chain (round 9, the ROADMAP
    open item): block ``li``'s chain id is interned under
    ``(parent_chain_id, tuple(block li's tokens))``, so looking up or
    registering a whole prompt touches each token exactly once — O(n)
    host memory and hashing per distinct prompt, where the old exact
    full-prefix keys (``tuple(prompt[:(li+1)*bs])``) materialized
    O(n²/bs).  The no-collision guarantee is unchanged: interning is an
    exact dict on (parent id, block tokens), and by induction a chain id
    corresponds to exactly one token chain — two different prefixes can
    never alias onto one block's rows.  The index holds its own
    reference on every registered block, so a retired request's prefix
    blocks survive for the next request until :meth:`evict_cold` (the
    OOM chain's first rung) or :meth:`close` releases them."""

    def __init__(self, num_blocks: int, block_size: int, nmax: int,
                 max_batch: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.N = int(num_blocks)
        self.bs = int(block_size)
        self.nmax = int(nmax)
        self.max_batch = int(max_batch)
        self.tables = np.full((max_batch, nmax), -1, np.int32)
        # pop() takes from the end: keep ids ascending-on-pop for
        # deterministic layouts in tests
        self._free = list(range(self.N - 1, -1, -1))
        self._ref = np.zeros(self.N, np.int64)
        self._prefix: dict = {}              # chain id -> _PrefixEntry
        self._interned: dict = {}            # (parent id, tokens) -> chain id
        self._children: dict = {}            # chain id -> interned child count
        self._next_chain = 1                 # 0 is the root sentinel
        self._pending_copies: list = []      # [(src, dst)] for copy_blocks
        self._tick = 0                       # LRU clock for the index
        self.dirty = True                    # tables need a device push
        # host mirrors of the telemetry counters (tests/bench read these
        # without the registry)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.cow_copies = 0
        self.peak_blocks_in_use = 0

    # -- pool accounting ----------------------------------------------------

    @property
    def blocks_in_use(self) -> int:
        return self.N - len(self._free)

    def _alloc_block(self) -> int:
        """One block off the free list (ref 1) — every allocation path
        funnels through here."""
        if not self._free:
            raise PoolExhausted(1, self.N)
        b = self._free.pop()
        self._ref[b] = 1
        _telemetry.count("kv_pool.blocks_allocated")
        self.peak_blocks_in_use = max(self.peak_blocks_in_use,
                                      self.blocks_in_use)
        return b

    def _decref_free(self, b: int) -> None:
        """Drop one reference; a block reaching zero returns to the free
        list — the single release path (slot retire, COW remap, prefix
        eviction all delegate here).  Pending COW pairs whose destination
        just died are discarded with it: a stale (src, dst) surviving
        into a later drain could copy into a REALLOCATED dst and corrupt
        another request's rows (the failure-path free between a COW and
        its _apply_pool_ops drain)."""
        self._ref[b] -= 1
        if self._ref[b] < 0:
            raise AssertionError(f"block {b} refcount went negative")
        if self._ref[b] == 0:
            self._free.append(b)
            if self._pending_copies:
                self._pending_copies = [p for p in self._pending_copies
                                        if p[1] != b]
            _telemetry.count("kv_pool.blocks_freed")

    def _cow_block(self, slot: int, li: int) -> int:
        """Copy-on-write: the slot is about to write into a block some
        other holder (another slot or the prefix index) also references
        — allocate a fresh block, queue the device copy, remap the table
        entry, and drop the shared reference."""
        src = int(self.tables[slot, li])
        dst = self._alloc_block()
        self._pending_copies.append((src, dst))
        self.tables[slot, li] = dst
        self._decref_free(src)
        self.dirty = True
        self.cow_copies += 1
        _telemetry.count("kv_pool.cow_copies")
        return dst

    def ensure_rows(self, slot: int, start: int, stop: int) -> None:
        """Make rows [start, stop) of ``slot`` writable: allocate
        unmapped logical blocks, copy-on-write shared ones.  Raises
        :exc:`PoolExhausted` when the free list runs dry (the caller's
        OOM chain evicts and retries); row indices clamp to the slot's
        logical window (block-decode overrun rows write nowhere, the
        slab path's masked-rows equivalent)."""
        if stop <= start:
            return
        lo = max(0, start // self.bs)
        hi = min(self.nmax - 1, (stop - 1) // self.bs)
        for li in range(lo, hi + 1):
            b = int(self.tables[slot, li])
            if b < 0:
                self.tables[slot, li] = self._alloc_block()
                self.dirty = True
            elif self._ref[b] > 1:
                self._cow_block(slot, li)

    def free_slot(self, slot: int) -> None:
        """Retire a slot: every mapped block loses the slot's reference
        (prefix-indexed blocks stay resident under the index's own
        ref)."""
        for li in range(self.nmax):
            b = int(self.tables[slot, li])
            if b >= 0:
                self._decref_free(b)
        self.tables[slot] = -1
        self.dirty = True

    def take_copies(self) -> list:
        """Drain the pending COW (src, dst) pairs for ``copy_blocks``."""
        out, self._pending_copies = self._pending_copies, []
        return out

    # -- prefix index -------------------------------------------------------

    def _chain_key(self, parent: int, prompt, li: int):
        """Intern key of prompt block ``li`` under its parent chain:
        O(block_size) tokens, never the whole prefix."""
        return (parent, tuple(prompt[li * self.bs:(li + 1) * self.bs]))

    def adopt_prefix(self, slot: int, prompt) -> int:
        """Map the longest indexed block-chain prefix of ``prompt`` into
        ``slot``'s table (incref per adopted block) and return the
        shared row count, capped at ``len(prompt) - 1`` so admission
        always computes at least the last token's logits (a fully
        shared prompt COWs its final block on that one-row write).

        The walk follows the interned chain (parent id + this block's
        tokens per step) and stops at the first block the index does not
        hold — O(n) total work over the prompt."""
        n = len(prompt)
        self._tick += 1
        matched = 0
        parent = 0
        for li in range(n // self.bs):
            cid = self._interned.get(self._chain_key(parent, prompt, li))
            if cid is None:
                break
            ent = self._prefix[cid]
            b = ent.block
            self._ref[b] += 1
            self.tables[slot, li] = b
            ent.last_hit = self._tick
            matched += 1
            parent = cid
        if matched:
            self.dirty = True
            self.prefix_hits += matched
            _telemetry.count("kv_pool.prefix_hits", matched)
        missed = n // self.bs - matched
        if missed:
            self.prefix_misses += missed
            _telemetry.count("kv_pool.prefix_misses", missed)
        return min(matched * self.bs, n - 1)

    def register_prefix(self, slot: int, prompt) -> None:
        """Index ``slot``'s full prompt blocks for future sharing (the
        index takes its own reference per newly registered block).  The
        owner never rewrites a full prompt block — decode writes start
        at ``len(prompt)`` — so registered blocks are immutable until
        released.  Each block interns one (parent id, block tokens)
        record — registration is O(n) over the prompt."""
        self._tick += 1
        parent = 0
        for li in range(len(prompt) // self.bs):
            b = int(self.tables[slot, li])
            if b < 0:
                break
            key = self._chain_key(parent, prompt, li)
            cid = self._interned.get(key)
            if cid is None:
                cid = self._next_chain
                self._next_chain += 1
                self._interned[key] = cid
                self._prefix[cid] = _PrefixEntry(b, self._tick, key,
                                                 parent)
                if parent:
                    self._children[parent] = \
                        self._children.get(parent, 0) + 1
                self._ref[b] += 1
            parent = cid

    @property
    def prefix_entries(self) -> int:
        return len(self._prefix)

    def _drop_entry(self, cid: int) -> None:
        """Remove one index entry plus its intern record (and its
        parent's child count) — the single removal path eviction and
        close share, keeping entry/intern/children consistent."""
        ent = self._prefix.pop(cid)
        self._interned.pop(ent.key, None)
        if ent.parent and ent.parent in self._children:
            self._children[ent.parent] -= 1
            if not self._children[ent.parent]:
                del self._children[ent.parent]
        self._decref_free(ent.block)

    def evict_cold(self, max_entries: int | None = None) -> int:
        """Drop prefix-cache entries no live slot references (block ref
        == 1: the index alone), coldest (LRU) first — the OOM retry
        chain's FIRST rung, and admission's last resort before parking a
        request back in the queue.  Returns the number of blocks
        actually freed.

        Only chain LEAVES (entries with no interned children) are
        candidates: dropping an inner block would orphan its
        descendants' chain ids.  A cold inner block's whole subtree is
        cold too (a slot adopting a child block always adopted its
        parents), so repeated engagements drain chains tail-first."""
        cold = sorted(
            (ent.last_hit, cid) for cid, ent in self._prefix.items()
            if self._ref[ent.block] == 1 and not self._children.get(cid))
        if max_entries is not None:
            cold = cold[:max_entries]
        freed = 0
        for _, cid in cold:
            self._drop_entry(cid)
            freed += 1
        if freed:
            _telemetry.count("kv_pool.prefix_evictions", freed)
        return freed

    def close(self) -> None:
        """Release the whole index and every table (server shutdown)."""
        for cid in list(self._prefix):
            if cid in self._prefix:
                self._drop_entry(cid)
        for slot in range(self.max_batch):
            if (self.tables[slot] >= 0).any():
                self.free_slot(slot)

    def stats(self) -> dict:
        return {
            "num_blocks": self.N, "block_size": self.bs,
            "blocks_in_use": self.blocks_in_use,
            "peak_blocks_in_use": self.peak_blocks_in_use,
            "prefix_entries": self.prefix_entries,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "cow_copies": self.cow_copies,
        }
