"""Paged KV-cache subsystem: block pool, block tables, prefix reuse.

The serving cache was one contiguous ``[L, max_batch, rows, Hkv, hd]``
slab — every slot provisioned for the worst-case context, and identical
prompt prefixes (system prompts, few-shot headers) prefilled and stored
once per request.  This module reproduces the reference's allocator
stack (auto-growth best-fit chunks, retry-on-OOM chains) at KV-cache
granularity, in the mold of vLLM's PagedAttention and SGLang's
RadixAttention:

* **block pool** — device leaves ``[L, num_blocks, block_size, Hkv, hd]``
  (int8 scale planes ``[L, N, bs, Hkv]`` ride along exactly as in the
  contiguous layout), shared by every slot;
* **block tables** — an int32 ``[max_batch, nmax]`` leaf mapping each
  slot's logical block to a physical pool block (-1 = unmapped), carried
  in the cache pytree so the jitted steps stay pure pytree-in/pytree-out
  and donation composes unchanged;
* **free-list allocator with refcounts** (:class:`PagedAllocator`, host
  side) — blocks are allocated as a slot's ``pos`` crosses block
  boundaries instead of reserving ``max_len`` rows up front, and freed or
  dereferenced on retire;
* **radix prefix index** — requests sharing a prompt prefix map their
  leading table entries to the SAME physical blocks (exact token-chain
  keys, refcounted), so shared prefixes are prefilled once; the first
  divergent write to a shared block copies it (copy-on-write).  Matching
  is token-granular: a prompt sharing only part of an indexed block's
  tokens SPLITS that node (``PADDLE_TPU_KV_RADIX``) instead of missing,
  so admission adopts the longest *token* prefix;
* **host-RAM spill tier** — the evict-cold rung can demote cold prefix
  chains to host buffers (one batched ``device_get`` per round,
  ``PADDLE_TPU_KV_SPILL_MB``) and admission restores them with one
  batched ``device_put`` through the existing :func:`inject_rows`
  buckets instead of a recompute walk.

Device math lives here too: :func:`paged_decode_step_batched` is the
pooled twin of ``serving.decode_step_batched`` (einsum fallback =
per-slot ``generate._cached_block`` on a gathered view — bit-identical
to the slab path holding the same rows; kernel route =
``ops/decode_attention.paged_decode_attention``, which resolves each
T-block through the table inside the grid), and
:func:`paged_prefill_chunk` is the pooled ``generate.prefill_slot_chunk``.
The contiguous layout stays the default (``PADDLE_TPU_KV_LAYOUT``).
"""
from __future__ import annotations

import zlib

import jax
import jax.numpy as jnp
import numpy as np

from . import generate, gpt, woq
from .. import flags as _flags
from .. import telemetry as _telemetry

__all__ = [
    "PoolExhausted", "PagedAllocator", "round_len", "init_paged_cache",
    "paged_decode_step_batched", "paged_prefill_chunk",
    "paged_verify_chunk_batched", "paged_tree_verify_chunk_batched",
    "paged_tree_commit", "copy_blocks", "inject_rows",
]

# the value/scale leaves of a pooled cache (everything except "tables")
POOL_LEAVES = ("k", "v", "k_s", "v_s")


class PoolExhausted(RuntimeError):
    """KV block pool has no free block.  The message carries the literal
    ``RESOURCE_EXHAUSTED`` marker so ``resilience.is_oom`` classifies it
    exactly like a real allocator OOM — the serving tick's retry chain
    (evict cold prefix entries -> degrade dispatch -> evict slots)
    engages on it."""

    def __init__(self, need: int = 1, total: int = 0):
        super().__init__(
            f"RESOURCE_EXHAUSTED: KV block pool exhausted "
            f"(need {need} more block(s), pool size {total})")


def round_len(max_len: int, block_size: int) -> int:
    """A paged cache's per-slot logical row count: the contiguous
    layout's kernel-tileable rounding, then up to a whole number of
    blocks (so a slot's gathered view is exactly ``nmax * bs`` rows —
    pick ``block_size`` dividing ``generate._round_cache_len(max_len)``
    when bit-parity with a contiguous cache of the same window
    matters)."""
    T = generate._round_cache_len(max_len)
    bs = int(block_size)
    return -(-T // bs) * bs


def init_paged_cache(cfg: gpt.GPTConfig, batch: int, max_len: int,
                     block_size: int | None = None,
                     num_blocks: int | None = None) -> dict:
    """The pooled cache pytree (``generate.init_cache(layout="paged")``):
    value leaves ``[L, N, bs, Hkv, hd]`` (+ int8 scale planes
    ``[L, N, bs, Hkv]``) and an int32 ``tables`` leaf ``[batch, nmax]``
    initialized unmapped (-1).  ``num_blocks`` defaults to full
    provisioning (``batch * nmax`` — slab-equivalent capacity, the
    parity-safe default); operators shrink it to the budget actual
    traffic needs, which is the whole point of paging."""
    bs = _flags.kv_block_size() if block_size is None else int(block_size)
    if bs < 8 or bs % 8:
        raise ValueError(f"block_size {bs}: must be a positive multiple "
                         f"of 8 (the decode kernel's row tile)")
    T = round_len(max_len, bs)
    nmax = T // bs
    # `is None` (not falsy): num_blocks=0 must hit the validation below,
    # not silently provision the full slab-equivalent pool
    N = batch * nmax if num_blocks is None else int(num_blocks)
    if N < 1:
        raise ValueError(f"num_blocks must be >= 1, got {N}")
    L, H, hd = cfg.num_layers, cfg.kv_heads, cfg.head_dim
    dt = generate._kv_store_dtype(cfg)
    shape = (L, N, bs, H, hd)
    cache = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt),
             "tables": jnp.full((batch, nmax), -1, jnp.int32)}
    if dt == jnp.int8:
        cache["k_s"] = jnp.zeros(shape[:-1], jnp.float32)
        cache["v_s"] = jnp.zeros(shape[:-1], jnp.float32)
    return cache


def _geometry(cache: dict):
    """(num_blocks, block_size, nmax) of a pooled cache pytree."""
    N, bs = cache["k"].shape[1], cache["k"].shape[2]
    return N, bs, cache["tables"].shape[1]


def _gather_slot(pool_leaf, trow):
    """One slot's contiguous view of a per-layer pool leaf:
    ``pool_leaf`` [N, bs, ...] + table row [nmax] -> [1, nmax*bs, ...].
    Delegates to the kernel module's batched gather — ONE copy of the
    unmapped-entry (clamp-to-block-0, causally-masked) semantics shared
    with the oracle/fallback paths."""
    from ..ops import decode_attention as da

    return da.gather_paged_view(pool_leaf, trow[None])


def _scatter_rows(cache: dict, rows: dict, phys) -> dict:
    """Write per-layer row leaves into the pool at physical row indices
    ``phys`` (int32, out-of-bounds = dropped — the overrun/unmapped
    sink).  ``rows`` leaves [L, R, Hkv(, hd)] against pool leaves
    [L, N, bs, Hkv(, hd)]; the single row-write every paged decode/
    prefill path funnels through (the ``generate._write_rows`` twin)."""
    out = dict(cache)
    for name, val in rows.items():
        arr = cache[name]
        L, NR = arr.shape[0], arr.shape[1] * arr.shape[2]
        flat = arr.reshape((L, NR) + arr.shape[3:])
        flat = flat.at[:, phys].set(val.astype(arr.dtype), mode="drop")
        out[name] = flat.reshape(arr.shape)
    return out


def paged_decode_step_batched(params, cache, token, pos,
                              cfg: gpt.GPTConfig):
    """``serving.decode_step_batched`` on the pooled layout: token [B]
    int32, pos [B] int32 (each slot's write position), cache a
    :func:`init_paged_cache` tree -> (logits [B, V], cache).

    Fallback route (any backend): vmap over slots of the EXACT per-slot
    ``generate._cached_block`` math on a table-gathered view — the same
    ops at the same shapes as the contiguous step, so greedy decode is
    bit-identical to a slab holding the same rows.  Kernel route (TPU /
    interpret, ``PADDLE_TPU_FLASH_DECODE``): fresh rows scatter into the
    pool first, then ``ops/decode_attention.paged_decode_attention``
    streams each slot's mapped blocks through the grid — no [B, T]
    gather is ever materialized."""
    from ..ops import decode_attention as da

    N, bs, nmax = _geometry(cache)
    B = token.shape[0]
    H, hd = cfg.num_heads, cfg.head_dim
    use_kernel = (_flags.flash_decode()
                  and da.paged_available((B, 1, H, hd),
                                         cache["k"].shape[1:]))
    if use_kernel:
        return _paged_step_kernel(params, cache, token, pos, cfg)

    tables = cache["tables"]
    pool = {n: cache[n] for n in POOL_LEAVES if n in cache}

    def one(tok_b, pos_b, trow):
        dt = cfg.dtype
        x = generate._embed_step(params, tok_b[None], pos_b, cfg)

        def body(x, layer):
            p, pl = layer
            csl = {n: _gather_slot(v, trow) for n, v in pl.items()}
            x, rows = generate._cached_block(x, p, csl, pos_b, cfg)
            return x, rows

        x, rows = jax.lax.scan(body, x, (params["blocks"], pool))
        x = gpt._norm(x, params, "ln_f", cfg)
        logits = woq.logits(x, params, dt)[:, 0]
        return logits[0].astype(jnp.float32), rows

    logits, rows = jax.vmap(one, in_axes=(0, 0, 0),
                            out_axes=(0, 0))(token, pos, tables)
    # rows leaves [B, L, 1, Hkv(, hd)] -> [L, B, Hkv(, hd)]; physical row
    # per slot through the table (unmapped -> out of bounds -> dropped,
    # the slab path's clamp-into-masked-rows equivalent)
    tb = tables[jnp.arange(B), pos // bs]
    phys = jnp.where(tb >= 0, tb * bs + pos % bs, N * bs)
    stacked = {n: jnp.moveaxis(v[:, :, 0], 0, 1) for n, v in rows.items()}
    return logits, _scatter_rows(cache, stacked, phys)


def _paged_step_kernel(params, cache, token, pos, cfg: gpt.GPTConfig):
    """Kernel route of :func:`paged_decode_step_batched` — the layer
    loop runs at top level so the paged kernel sees the whole batch
    (grid ``(B*Hkv, nmax)``); the per-slot pre/post math stays vmapped
    (norm/projections/rope/MoE routing at the contiguous step's B=1
    shapes)."""
    from ..ops import decode_attention as da

    N, bs, nmax = _geometry(cache)
    B = token.shape[0]
    dt = cfg.dtype
    hd = cfg.head_dim
    tables = cache["tables"]
    tb = tables[jnp.arange(B), pos // bs]
    phys = jnp.where(tb >= 0, tb * bs + pos % bs, N * bs)
    pool = {n: cache[n] for n in POOL_LEAVES if n in cache}
    L = cache["k"].shape[0]

    def embed_one(tok_b, pos_b):
        return generate._embed_step(params, tok_b[None], pos_b, cfg)

    x = jax.vmap(embed_one)(token, pos)                  # [B, 1, 1, D]

    def body(carry, layer):
        x, pool = carry
        p, li = layer

        def pre(xb, pos_b):
            return generate._block_pre_attn(xb, p, pos_b, cfg)

        q3, rows = jax.vmap(pre)(x, pos)     # q3 [B,1,1,H,hd]
        # scatter the fresh rows into layer li BEFORE attending: the
        # kernel then reads exactly what later steps will read back
        # (scatter-then-attend == the slab path's splice-then-write)
        new_pool = {}
        for n, val in rows.items():
            arr = pool[n]
            NR = arr.shape[1] * arr.shape[2]
            flat = arr.reshape((arr.shape[0], NR) + arr.shape[3:])
            flat = flat.at[li, phys].set(val[:, 0].astype(arr.dtype),
                                         mode="drop")
            new_pool[n] = flat.reshape(arr.shape)
        pool = new_pool
        q = q3.reshape(B, 1, cfg.num_heads, hd)
        attn = da.paged_decode_attention(
            q, pool["k"][li], pool["v"][li], tables, pos,
            k_scale=pool["k_s"][li] if "k_s" in pool else None,
            v_scale=pool["v_s"][li] if "v_s" in pool else None)
        attn = attn.astype(dt).reshape(B, 1, 1, cfg.num_heads * hd)

        def post(xb, ab):
            return generate._block_post_attn(xb, ab, p, cfg)

        x = jax.vmap(post)(x, attn)
        return (x, pool), None

    (x, pool), _ = jax.lax.scan(
        body, (x, pool), (params["blocks"], jnp.arange(L)))

    def fin(xb):
        xb = gpt._norm(xb, params, "ln_f", cfg)
        return woq.logits(xb, params, dt)[0, 0]

    logits = jax.vmap(fin)(x)
    return logits.astype(jnp.float32), dict(cache, **pool)


def paged_prefill_chunk(params, cache, tokens, pos0, length, slot,
                        cfg: gpt.GPTConfig):
    """``generate.prefill_slot_chunk`` on the pooled layout: one chunk of
    a prompt at positions [pos0, pos0+C) for one slot, attending the
    slot's table-gathered cache rows [0, pos0) plus within-chunk
    causally (``generate._chunk_attend_block`` — the shared chunk math),
    writing rows [pos0, pos0+length) through the table (pads and
    unmapped entries dropped), returning (logits at the chunk's last
    valid position [V], cache).

    With a shared prefix adopted into the table, ``pos0`` starts at the
    first unshared row — the shared blocks are ATTENDED through the
    gather but never recomputed, which is where the prefix cache's
    prefill FLOPs saving comes from."""
    N, bs, nmax = _geometry(cache)
    tables = cache["tables"]
    trow = tables[slot]                                   # [nmax]
    pool = {n: cache[n] for n in POOL_LEAVES if n in cache}
    dt = cfg.dtype
    C = tokens.shape[1]
    x = woq.embed(params, tokens, dt)
    if cfg.pos_embed == "learned":
        x = x + jax.lax.dynamic_slice(
            params["wpe"], (pos0, 0), (C, cfg.hidden_size)).astype(dt)[None]
    valid_mask = (jnp.arange(C) < length)[None, :]        # [1, C]

    def body(x, layer):
        p, pl = layer
        csl = {n: _gather_slot(v, trow) for n, v in pl.items()}
        x, rows = generate._chunk_attend_block(x, p, csl, pos0, cfg,
                                               valid=valid_mask)
        return x, rows

    x, rows = jax.lax.scan(body, x, (params["blocks"], pool))
    logi = pos0 + jnp.arange(C)
    tb = trow[jnp.clip(logi // bs, 0, nmax - 1)]
    phys = jnp.where((jnp.arange(C) < length) & (tb >= 0)
                     & (logi // bs < nmax), tb * bs + logi % bs, N * bs)
    cache = _scatter_rows(cache, {n: v[:, 0] for n, v in rows.items()},
                          phys)
    last = jax.lax.dynamic_slice(x, (0, length - 1, 0),
                                 (1, 1, cfg.hidden_size))
    last = gpt._norm(last, params, "ln_f", cfg)
    logits = woq.logits(last, params, dt)[0, 0]
    return logits.astype(jnp.float32), cache


def paged_verify_chunk_batched(params, cache, tokens, pos, cfg):
    """``generate.verify_chunk`` on the pooled layout, batched over
    slots: tokens [B, K] int32 scored at per-slot positions
    [pos_b, pos_b + K) -> (logits [B, K, V] fp32, cache).

    Per slot this is the EXACT chunk math ``paged_prefill_chunk`` runs —
    ``generate._chunk_attend_block`` over the slot's table-gathered view
    — so row 0 of the verify logits equals the plain decode step's
    logits for the same feed token (greedy serving parity rests on
    this).  K/V rows for the whole chunk scatter through the block
    table; rejected rows land at/past the slot's position pointer where
    the causal mask hides them and the next round overwrites them (the
    stale-row invariant — no masked write needed).  Unmapped or
    past-the-table entries drop (the standard out-of-bounds sink).

    Kernel route (TPU / interpret, ``PADDLE_TPU_FLASH_DECODE``): the
    layer loop moves to top level and ``paged_decode_attention`` streams
    the whole batch at Tq=K — the ROADMAP "flash-verify" item."""
    from ..ops import decode_attention as da

    N, bs, nmax = _geometry(cache)
    B, K = tokens.shape
    if (_flags.flash_decode()
            and da.paged_available((B, K, cfg.num_heads, cfg.head_dim),
                                   cache["k"].shape[1:])):
        return _paged_verify_kernel(params, cache, tokens, pos, cfg)
    tables = cache["tables"]
    pool = {n: cache[n] for n in POOL_LEAVES if n in cache}
    dt = cfg.dtype

    def one(tok_k, p0, trow):
        x = woq.embed(params, tok_k[None], dt)            # [1, K, D]
        if cfg.pos_embed == "learned":
            x = x + jax.lax.dynamic_slice(
                params["wpe"], (p0, 0),
                (K, cfg.hidden_size)).astype(dt)[None]

        def body(x, layer):
            p, pl = layer
            csl = {n: _gather_slot(v, trow) for n, v in pl.items()}
            x, rows = generate._chunk_attend_block(x, p, csl, p0, cfg)
            return x, rows

        x, rows = jax.lax.scan(body, x, (params["blocks"], pool))
        x = gpt._norm(x, params, "ln_f", cfg)
        logits = woq.logits(x, params, dt)[0]             # [K, V]
        return logits.astype(jnp.float32), rows

    logits, rows = jax.vmap(one, in_axes=(0, 0, 0),
                            out_axes=(0, 0))(tokens, pos, tables)
    # rows leaves [B, L, 1, K, Hkv(, hd)] -> [L, B*K, Hkv(, hd)];
    # physical row per (slot, j) through the table
    logi = pos[:, None] + jnp.arange(K)[None, :]          # [B, K]
    tb = jnp.take_along_axis(tables, jnp.clip(logi // bs, 0, nmax - 1),
                             axis=1)
    phys = jnp.where((tb >= 0) & (logi // bs < nmax),
                     tb * bs + logi % bs, N * bs).reshape(B * K)
    stacked = {}
    for n, v in rows.items():
        v = jnp.moveaxis(v[:, :, 0], 0, 1)                # [L, B, K, ...]
        stacked[n] = v.reshape((v.shape[0], B * K) + v.shape[3:])
    return logits, _scatter_rows(cache, stacked, phys)


def paged_tree_verify_chunk_batched(params, cache, tokens, amask, depth,
                                    pos, cfg: gpt.GPTConfig):
    """``generate.tree_verify_chunk`` on the pooled layout, batched over
    slots: tokens [B, N] int32 (node 0 = feed token), amask [B, N, N]
    ancestor-or-self bool, depth [B, N] int32, pos [B] — ONE pass over
    each slot's token tree stored at table-translated rows
    [pos_b, pos_b + N) -> (logits [B, N, V] fp32, cache).

    Per slot this runs ``generate._tree_attend_block`` over the slot's
    table-gathered view — the EXACT shared tree math the contiguous
    route runs, so the two layouts cannot drift (and a chain tree
    reduces to ``paged_verify_chunk_batched``'s fallback bit-for-bit).
    Topology is a runtime argument; only N is a compiled shape.  Always
    the einsum route: the flash kernels assume causal masks (see
    ``generate._attend_cache_tree``).  Rejected nodes land at/past the
    slot's pointer through the table where the next round overwrites
    them — the stale-row invariant, unchanged; unmapped or
    past-the-table entries drop (the standard out-of-bounds sink)."""
    N, bs, nmax = _geometry(cache)
    B, K = tokens.shape
    tables = cache["tables"]
    pool = {n: cache[n] for n in POOL_LEAVES if n in cache}
    dt = cfg.dtype
    T = nmax * bs

    def one(tok_k, am, dp, p0, trow):
        x = woq.embed(params, tok_k[None], dt)            # [1, K, D]
        if cfg.pos_embed == "learned":
            x = x + jnp.take(params["wpe"], p0 + dp,
                             axis=0).astype(dt)[None]
        tmask = jnp.broadcast_to(jnp.arange(T)[None, None, :] < p0,
                                 (1, K, T))
        tmask = jax.lax.dynamic_update_slice(tmask, am[None], (0, 0, p0))

        def body(x, layer):
            p, pl = layer
            csl = {n: _gather_slot(v, trow) for n, v in pl.items()}
            x, rows = generate._tree_attend_block(x, p, csl, p0, dp,
                                                  tmask, cfg)
            return x, rows

        x, rows = jax.lax.scan(body, x, (params["blocks"], pool))
        x = gpt._norm(x, params, "ln_f", cfg)
        logits = woq.logits(x, params, dt)[0]             # [K, V]
        return logits.astype(jnp.float32), rows

    logits, rows = jax.vmap(one, in_axes=(0, 0, 0, 0, 0),
                            out_axes=(0, 0))(tokens, amask, depth, pos,
                                             tables)
    logi = pos[:, None] + jnp.arange(K)[None, :]          # [B, K]
    tb = jnp.take_along_axis(tables, jnp.clip(logi // bs, 0, nmax - 1),
                             axis=1)
    phys = jnp.where((tb >= 0) & (logi // bs < nmax),
                     tb * bs + logi % bs, N * bs).reshape(B * K)
    stacked = {}
    for n, v in rows.items():
        v = jnp.moveaxis(v[:, :, 0], 0, 1)                # [L, B, K, ...]
        stacked[n] = v.reshape((v.shape[0], B * K) + v.shape[3:])
    return logits, _scatter_rows(cache, stacked, phys)


def paged_tree_commit(cache, src, pos):
    """``generate.tree_commit_rows`` on the pooled layout: per slot b,
    copy the pool rows at logical positions ``pos_b + src_b[i]`` to
    logical ``pos_b + 1 + i`` (both sides translated through the block
    table).  Gather-then-scatter per leaf, so in-place aliasing under
    donation is safe even when source and destination rows share a
    block; identity entries rewrite themselves and out-of-bounds /
    unmapped destinations drop (source rows are inside the window the
    serving tick just ensured blocks for)."""
    N, bs, nmax = _geometry(cache)
    B, M = src.shape
    tables = cache["tables"]

    def phys_of(logi):
        tb = jnp.take_along_axis(
            tables, jnp.clip(logi // bs, 0, nmax - 1), axis=1)
        return jnp.where((tb >= 0) & (logi // bs < nmax),
                         tb * bs + logi % bs, N * bs)

    src_p = phys_of(pos[:, None] + src).reshape(B * M)
    dst_p = phys_of(pos[:, None] + 1
                    + jnp.arange(M)[None, :]).reshape(B * M)
    out = dict(cache)
    for name in POOL_LEAVES:
        if name not in cache:
            continue
        arr = cache[name]
        L, NR = arr.shape[0], arr.shape[1] * arr.shape[2]
        flat = arr.reshape((L, NR) + arr.shape[3:])
        rows = flat[:, jnp.clip(src_p, 0, NR - 1)]
        flat = flat.at[:, dst_p].set(rows, mode="drop")
        out[name] = flat.reshape(arr.shape)
    return out


def _paged_verify_kernel(params, cache, tokens, pos, cfg: gpt.GPTConfig):
    """Kernel route of :func:`paged_verify_chunk_batched` — the
    :func:`_paged_step_kernel` structure at Tq=K: layer loop at top
    level so the paged kernel sees the whole batch per layer, per-slot
    pre/post math vmapped at the fallback's [1, K, D] shapes
    (``generate._chunk_pre_attn`` — rope needs per-slot offsets), and
    the chunk's fresh rows scattered through the tables BEFORE attending
    (scatter-then-attend == the fallback's splice-then-write; rejected
    rows stay hidden behind the position pointer as ever)."""
    from ..ops import decode_attention as da

    N, bs, nmax = _geometry(cache)
    B, K = tokens.shape
    dt = cfg.dtype
    H, hd = cfg.num_heads, cfg.head_dim
    tables = cache["tables"]
    pool = {n: cache[n] for n in POOL_LEAVES if n in cache}
    L = cache["k"].shape[0]
    logi = pos[:, None] + jnp.arange(K)[None, :]          # [B, K]
    tb = jnp.take_along_axis(tables, jnp.clip(logi // bs, 0, nmax - 1),
                             axis=1)
    phys = jnp.where((tb >= 0) & (logi // bs < nmax),
                     tb * bs + logi % bs, N * bs).reshape(B * K)

    def embed_one(tok_k, p0):
        x = woq.embed(params, tok_k[None], dt)            # [1, K, D]
        if cfg.pos_embed == "learned":
            x = x + jax.lax.dynamic_slice(
                params["wpe"], (p0, 0),
                (K, cfg.hidden_size)).astype(dt)[None]
        return x

    x = jax.vmap(embed_one)(tokens, pos)                  # [B, 1, K, D]

    def body(carry, layer):
        x, pool = carry
        p, li = layer

        def pre(xb, p0):
            return generate._chunk_pre_attn(xb, p, p0, cfg)

        q3, rows = jax.vmap(pre)(x, pos)  # q3 [B, 1, K, H, hd]
        new_pool = {}
        for n, val in rows.items():
            arr = pool[n]
            NR = arr.shape[1] * arr.shape[2]
            flat = arr.reshape((arr.shape[0], NR) + arr.shape[3:])
            v = val[:, 0].reshape((B * K,) + val.shape[3:])
            flat = flat.at[li, phys].set(v.astype(arr.dtype), mode="drop")
            new_pool[n] = flat.reshape(arr.shape)
        pool = new_pool
        attn = da.paged_decode_attention(
            q3.reshape(B, K, H, hd), pool["k"][li], pool["v"][li],
            tables, pos,
            k_scale=pool["k_s"][li] if "k_s" in pool else None,
            v_scale=pool["v_s"][li] if "v_s" in pool else None)
        attn = attn.astype(dt).reshape(B, 1, K, H * hd)

        def post(xb, ab):
            return generate._block_post_attn(xb, ab, p, cfg)

        return (jax.vmap(post)(x, attn), pool), None

    (x, pool), _ = jax.lax.scan(
        body, (x, pool), (params["blocks"], jnp.arange(L)))

    def fin(xb):
        xb = gpt._norm(xb, params, "ln_f", cfg)
        return woq.logits(xb, params, dt)[0]              # [K, V]

    logits = jax.vmap(fin)(x)
    return logits.astype(jnp.float32), dict(cache, **pool)


def inject_rows(cache: dict, rows: dict, start, length, slot) -> dict:
    """Write externally computed cache rows (a prefill worker's output —
    leaves ``[L, 1, C, Hkv(, hd)]``, valid through ``length``) into one
    slot's rows [start, length) through its block table — the paged
    half of the fleet's prefill/decode handoff
    (``generate._merge_slot_rows`` is the contiguous twin).  ``start``
    skips rows an adopted prefix already holds (shared blocks must
    never be rewritten); pad rows beyond ``length`` and unmapped table
    entries drop (the standard out-of-bounds sink); the caller has
    already allocated/COW'd the write range (``ensure_rows``)."""
    N, bs, nmax = _geometry(cache)
    trow = cache["tables"][slot]                          # [nmax]
    C = rows["k"].shape[2]
    logi = jnp.arange(C)
    tb = trow[jnp.clip(logi // bs, 0, nmax - 1)]
    phys = jnp.where((logi >= start) & (logi < length) & (tb >= 0)
                     & (logi // bs < nmax),
                     tb * bs + logi % bs, N * bs)
    return _scatter_rows(cache, {n: v[:, 0] for n, v in rows.items()},
                         phys)


def copy_blocks(cache: dict, src, dst) -> dict:
    """Copy physical blocks ``src`` -> ``dst`` (int32 [P]) across every
    pool leaf — the device half of copy-on-write.  Destinations are
    freshly allocated (never in ``src``), so the gather/scatter pair has
    no ordering hazard; callers jit + donate the cache so the pool
    updates in place."""
    out = dict(cache)
    for name in POOL_LEAVES:
        if name in cache:
            arr = cache[name]
            out[name] = arr.at[:, dst].set(arr[:, src])
    return out


# ---------------------------------------------------------------------------
# host allocator: free list + refcounts + radix prefix index + spill tier
# ---------------------------------------------------------------------------


def _read_rss_bytes() -> int:
    """Current process resident set in bytes — ``/proc/self/statm``
    (field 2, pages) on Linux, ``getrusage`` peak-RSS as the portable
    fallback, 0 when neither is readable (watchdog disarms rather than
    guessing)."""
    try:
        with open("/proc/self/statm", "rb") as f:
            pages = int(f.read().split()[1])
        import resource

        return pages * resource.getpagesize()
    except (OSError, ValueError, IndexError, ImportError):
        try:
            import resource

            # ru_maxrss is KiB on Linux (bytes on macOS — either way a
            # conservative upper bound, which is the safe direction for
            # a pressure watchdog)
            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss << 10
        except Exception:
            return 0


def prefix_fingerprint(tokens) -> int:
    """Deterministic fingerprint of a token run for the router-side
    prefix summaries (crc32 over the int64 bytes — Python's ``hash()``
    is salted per process, so it can never be compared across a fleet's
    replicas)."""
    return zlib.crc32(np.asarray(tuple(tokens), np.int64).tobytes())


class _PrefixEntry:
    """One indexed radix node: the physical pool block, its LRU clock,
    its position in the interned tree (``key`` = the intern-table key
    ``(parent chain id, token run)``, ``parent`` = the previous node's
    chain id, 0 at the root) and ``end`` — the cumulative token count of
    the chain through this node.  A node's run never crosses a block
    boundary, and its block holds bit-valid rows for in-block offsets
    ``[0, end - 1 mod bs]`` — split siblings share a block precisely
    because their common rows are identical."""

    __slots__ = ("block", "last_hit", "key", "parent", "end")

    def __init__(self, block: int, tick: int, key, parent: int,
                 end: int):
        self.block = block
        self.last_hit = tick
        self.key = key
        self.parent = parent
        self.end = end


class PagedAllocator:
    """Host-side block accounting for one pooled cache: the free list,
    per-block refcounts, the per-slot table mirror (pushed to the device
    leaf when dirty), pending COW copies, and the prefix index.

    Prefix identity is an INTERNED parent-id RADIX tree (round 9 built
    the linear chain; this round generalizes it): a node's chain id is
    interned under ``(parent_chain_id, token_run)`` where the run never
    crosses a block boundary, and siblings under one parent always
    diverge on their FIRST token (``_children`` maps parent ->
    {first token -> child id}), so lookup walks O(n) tokens with O(1)
    child steps.  A prompt sharing only part of a node's run SPLITS the
    node (:meth:`_split_entry`): a new parent takes the shared tokens
    and an extra refcount on the SAME physical block — the shared rows
    are bit-identical by the chain invariant, so no device copy happens
    at split time; the adopter's first divergent write copies the block
    through the normal COW drain.  The no-collision guarantee is
    unchanged: interning is an exact dict on (parent id, token run), and
    by induction a chain id corresponds to exactly one token chain.

    The index holds its own reference on every registered block (one
    per node — split siblings stack refs on a shared block, mirrored in
    ``_blk_ents``), so a retired request's prefix blocks survive for
    the next request until :meth:`evict_cold` / :meth:`spill_cold` (the
    OOM chain's first rung) or :meth:`close` releases them.  With
    ``PADDLE_TPU_KV_SPILL_MB`` set, :meth:`spill_cold` demotes cold
    block-aligned chains to host RAM instead of dropping them and
    :meth:`adopt_prefix` restores them on the next match — the restore
    rows ride :meth:`take_restores` to the caller's batched
    ``inject_rows`` scatter."""

    def __init__(self, num_blocks: int, block_size: int, nmax: int,
                 max_batch: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.N = int(num_blocks)
        self.bs = int(block_size)
        self.nmax = int(nmax)
        self.max_batch = int(max_batch)
        self.tables = np.full((max_batch, nmax), -1, np.int32)
        # pop() takes from the end: keep ids ascending-on-pop for
        # deterministic layouts in tests
        self._free = list(range(self.N - 1, -1, -1))
        self._ref = np.zeros(self.N, np.int64)
        self._blk_ents = np.zeros(self.N, np.int64)  # index entries per block
        self._prefix: dict = {}              # chain id -> _PrefixEntry
        self._interned: dict = {}            # (parent id, run) -> chain id
        self._children: dict = {}            # chain id -> {tok0 -> child id}
        self._next_chain = 1                 # 0 is the root sentinel
        self._pending_copies: list = []      # [(src, dst)] for copy_blocks
        self._tick = 0                       # LRU clock for the index
        self.dirty = True                    # tables need a device push
        self.radix_on = _flags.kv_radix()
        self.restore_on = _flags.kv_restore()
        self.spill_limit_bytes = _flags.kv_spill_mb() << 20
        self.spill_batch = _flags.kv_spill_batch()
        self.rss_limit_bytes = _flags.kv_spill_rss_mb() << 20
        self._spilled: dict = {}   # full chain tokens -> (host rows, nbytes)
        self._pending_restores: list = []    # [(slot, start, rows, block)]
        # host mirrors of the telemetry counters (tests/bench read these
        # without the registry)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.cow_copies = 0
        self.peak_blocks_in_use = 0
        self.radix_splits = 0
        self.spilled_blocks = 0
        self.restored_blocks = 0
        self.host_spill_bytes = 0
        self.chain_migrations = 0
        self.rss_spills = 0

    # -- pool accounting ----------------------------------------------------

    @property
    def blocks_in_use(self) -> int:
        return self.N - len(self._free)

    def _alloc_block(self) -> int:
        """One block off the free list (ref 1) — every allocation path
        funnels through here."""
        if not self._free:
            raise PoolExhausted(1, self.N)
        b = self._free.pop()
        self._ref[b] = 1
        _telemetry.count("kv_pool.blocks_allocated")
        self.peak_blocks_in_use = max(self.peak_blocks_in_use,
                                      self.blocks_in_use)
        return b

    def _decref_free(self, b: int) -> None:
        """Drop one reference; a block reaching zero returns to the free
        list — the single release path (slot retire, COW remap, prefix
        eviction all delegate here).  Pending COW pairs whose destination
        just died are discarded with it: a stale (src, dst) surviving
        into a later drain could copy into a REALLOCATED dst and corrupt
        another request's rows (the failure-path free between a COW and
        its _apply_pool_ops drain)."""
        self._ref[b] -= 1
        if self._ref[b] < 0:
            raise AssertionError(f"block {b} refcount went negative")
        if self._ref[b] == 0:
            self._free.append(b)
            if self._pending_copies:
                self._pending_copies = [p for p in self._pending_copies
                                        if p[1] != b]
            if self._pending_restores:
                # same rule for undrained restores: injecting into a
                # REALLOCATED block would corrupt another request's rows
                self._pending_restores = [r for r in
                                          self._pending_restores
                                          if r[3] != b]
            _telemetry.count("kv_pool.blocks_freed")

    def _cow_block(self, slot: int, li: int) -> int:
        """Copy-on-write: the slot is about to write into a block some
        other holder (another slot or the prefix index) also references
        — allocate a fresh block, queue the device copy, remap the table
        entry, and drop the shared reference."""
        src = int(self.tables[slot, li])
        dst = self._alloc_block()
        self._pending_copies.append((src, dst))
        self.tables[slot, li] = dst
        self._decref_free(src)
        self.dirty = True
        self.cow_copies += 1
        _telemetry.count("kv_pool.cow_copies")
        return dst

    def ensure_rows(self, slot: int, start: int, stop: int) -> None:
        """Make rows [start, stop) of ``slot`` writable: allocate
        unmapped logical blocks, copy-on-write shared ones.  Raises
        :exc:`PoolExhausted` when the free list runs dry (the caller's
        OOM chain evicts and retries); row indices clamp to the slot's
        logical window (block-decode overrun rows write nowhere, the
        slab path's masked-rows equivalent)."""
        if stop <= start:
            return
        lo = max(0, start // self.bs)
        hi = min(self.nmax - 1, (stop - 1) // self.bs)
        for li in range(lo, hi + 1):
            b = int(self.tables[slot, li])
            if b < 0:
                self.tables[slot, li] = self._alloc_block()
                self.dirty = True
            elif self._ref[b] > 1:
                self._cow_block(slot, li)

    def free_slot(self, slot: int) -> None:
        """Retire a slot: every mapped block loses the slot's reference
        (prefix-indexed blocks stay resident under the index's own
        ref)."""
        for li in range(self.nmax):
            b = int(self.tables[slot, li])
            if b >= 0:
                self._decref_free(b)
        self.tables[slot] = -1
        self.dirty = True

    def take_copies(self) -> list:
        """Drain the pending COW (src, dst) pairs for ``copy_blocks``."""
        out, self._pending_copies = self._pending_copies, []
        return out

    # -- radix prefix index -------------------------------------------------

    def adopt_prefix(self, slot: int, prompt) -> int:
        """Map the longest indexed TOKEN prefix of ``prompt`` into
        ``slot``'s table (one incref per mapped block) and return the
        shared row count, capped at ``len(prompt) - 1`` so admission
        always computes at least the last token's logits (a fully
        shared prompt COWs its final block on that one-row write).

        The walk descends the radix tree one node per step (children
        are keyed by first token, runs compared tokenwise — O(n) total
        over the prompt).  A node matching only partially is SPLIT at
        the divergence point (``PADDLE_TPU_KV_RADIX``) so the shared
        head still adopts; a missing child may instead be RESTORED from
        the host spill tier.  Hits/misses count in TOKEN rows: the
        hit-rate gauge is the fraction of adoptable rows admission did
        not have to recompute."""
        n = len(prompt)
        self._tick += 1
        matched = 0
        parent = 0
        deepest = {}                 # block index -> deepest node's block
        while matched < n:
            cid = self._children.get(parent, {}).get(prompt[matched])
            if cid is None and self.restore_on:
                cid = self._restore_spilled(slot, parent, prompt, matched)
            if cid is None:
                break
            ent = self._prefix[cid]
            run = ent.key[1]
            lim = min(len(run), n - matched)
            m = 0
            while m < lim and run[m] == prompt[matched + m]:
                m += 1
            if m == len(run):
                ent.last_hit = self._tick
                matched += m
                deepest[(ent.end - 1) // self.bs] = ent.block
                parent = cid
                continue
            # partial match: split iff it buys adoptable rows
            if self.radix_on and m and min(matched + m, n - 1) > matched:
                scid = self._split_entry(cid, m)
                sent = self._prefix[scid]
                sent.last_hit = self._tick
                matched += m
                deepest[(sent.end - 1) // self.bs] = sent.block
            break
        for bi, b in deepest.items():
            self._ref[b] += 1
            self.tables[slot, bi] = b
        if deepest:
            self.dirty = True
        shared = min(matched, n - 1)
        if shared > 0:
            self.prefix_hits += shared
            _telemetry.count("kv_pool.prefix_hits", shared)
        missed = (n - 1) - shared
        if missed > 0:
            self.prefix_misses += missed
            _telemetry.count("kv_pool.prefix_misses", missed)
        return shared

    def register_prefix(self, slot: int, prompt) -> None:
        """Index ``slot``'s full prompt blocks for future sharing (the
        index takes its own reference per node).  The owner never
        rewrites a full prompt block — decode writes start at
        ``len(prompt)`` — so registered blocks are immutable until
        released; partial tail blocks are never registered.  The walk
        descends existing nodes, splits at mid-run divergence (the new
        sibling is backed by the slot's own block) and interns the
        remainder one block-run per node — O(n) over the prompt."""
        self._tick += 1
        n_full = (len(prompt) // self.bs) * self.bs
        off = 0
        parent = 0
        while off < n_full:
            b = int(self.tables[slot, off // self.bs])
            if b < 0:
                break
            stop = (off // self.bs + 1) * self.bs
            run = tuple(prompt[off:stop])
            cid = self._children.get(parent, {}).get(run[0])
            if cid is None:
                key = (parent, run)
                cid = self._next_chain
                self._next_chain += 1
                self._interned[key] = cid
                self._prefix[cid] = _PrefixEntry(b, self._tick, key,
                                                 parent, stop)
                self._children.setdefault(parent, {})[run[0]] = cid
                self._blk_ents[b] += 1
                self._ref[b] += 1
                parent = cid
                off = stop
                continue
            ent = self._prefix[cid]
            erun = ent.key[1]
            # a node's run never crosses a block boundary, so erun fits
            # inside run's remainder
            m = 0
            while m < len(erun) and erun[m] == run[m]:
                m += 1
            if m == len(erun):
                ent.last_hit = self._tick
                parent = cid
                off += m
                continue
            if not (self.radix_on and m):
                # block-granular baseline: a mid-run divergence is a
                # stop (same-first-token siblings need the split)
                break
            parent = self._split_entry(cid, m)
            self._prefix[parent].last_hit = self._tick
            off += m

    def _split_entry(self, cid: int, m: int) -> int:
        """COW-split an indexed node at run offset ``m``: a new parent
        node takes tokens ``[:m]`` and an extra refcount on the SAME
        physical block (rows up to the split point are bit-identical by
        the chain invariant), while the deep node keeps its chain id
        with tokens ``[m:]`` — its descendants' parent pointers stay
        valid, so a split never orphans children.  No device copy
        happens here: the first writer adopting the split node sees the
        stacked refcount and copies through the normal COW drain.
        Returns the new parent's chain id."""
        ent = self._prefix[cid]
        run = ent.key[1]
        parent = ent.parent
        skey = (parent, run[:m])
        scid = self._next_chain
        self._next_chain += 1
        self._interned[skey] = scid
        self._prefix[scid] = _PrefixEntry(ent.block, self._tick, skey,
                                          parent,
                                          ent.end - (len(run) - m))
        self._blk_ents[ent.block] += 1
        self._ref[ent.block] += 1
        # re-key the deep node under the split node (same cid)
        del self._interned[ent.key]
        ent.key = (scid, run[m:])
        ent.parent = scid
        self._interned[ent.key] = cid
        self._children.setdefault(parent, {})[run[0]] = scid
        self._children[scid] = {run[m]: cid}
        self.radix_splits += 1
        _telemetry.count("kv_pool.radix_splits")
        return scid

    @property
    def prefix_entries(self) -> int:
        return len(self._prefix)

    def _drop_entry(self, cid: int) -> None:
        """Remove one index entry plus its intern record (and its
        parent's child-map slot) — the single removal path eviction,
        spill and close share, keeping entry/intern/children
        consistent."""
        ent = self._prefix.pop(cid)
        self._interned.pop(ent.key, None)
        pm = self._children.get(ent.parent)
        if pm is not None:
            tok0 = ent.key[1][0]
            if pm.get(tok0) == cid:
                del pm[tok0]
            if not pm:
                del self._children[ent.parent]
        self._blk_ents[ent.block] -= 1
        self._decref_free(ent.block)

    def _cold_leaves(self, max_entries: int | None) -> list:
        """Eviction/spill candidates: tree LEAVES no live slot
        references, coldest (LRU) first.  "No slot references" means
        every ref on the block is index-held (``_blk_ents`` — split
        siblings stack refs on one shared block); only leaves are
        candidates because dropping an inner node would orphan its
        descendants' chain ids."""
        cold = sorted(
            (ent.last_hit, cid) for cid, ent in self._prefix.items()
            if self._ref[ent.block] == self._blk_ents[ent.block]
            and not self._children.get(cid))
        return cold if max_entries is None else cold[:max_entries]

    def evict_cold(self, max_entries: int | None = None) -> int:
        """Drop cold prefix-cache leaves — the OOM retry chain's FIRST
        rung, and admission's last resort before parking a request back
        in the queue.  Returns the number of entries actually dropped.

        A cold inner block's whole subtree is cold too (a slot adopting
        a child block always adopted its parents), so repeated
        engagements drain chains tail-first."""
        freed = 0
        for _, cid in self._cold_leaves(max_entries):
            self._drop_entry(cid)
            freed += 1
        if freed:
            _telemetry.count("kv_pool.prefix_evictions", freed)
        return freed

    # -- host-RAM spill tier ------------------------------------------------

    def _chain_tokens(self, cid: int) -> tuple:
        """Full token chain of a node, root to ``cid`` — the spill-store
        key.  Parents are always live: only childless nodes are ever
        dropped."""
        parts = []
        while cid:
            ent = self._prefix[cid]
            parts.append(ent.key[1])
            cid = ent.parent
        return tuple(t for run in reversed(parts) for t in run)

    def spill_cold(self, max_entries: int | None = None,
                   fetch=None) -> int:
        """The evict-cold rung, spill-aware: demote cold block-aligned
        leaf chains to host RAM before freeing their blocks — ``fetch``
        (the caller's ONE batched ``device_get`` over the pool leaves)
        is called once per round with the block list and must return
        ``{leaf: [L, P, bs, ...]}``.  Entries falling outside the spill
        contract (mid-block split remnants, blocks with undrained
        copies/restores, past the ``PADDLE_TPU_KV_SPILL_BATCH`` cap or
        the ``PADDLE_TPU_KV_SPILL_MB`` budget) drop exactly as
        :meth:`evict_cold` would.  Returns entries freed (the OOM
        chain's contract)."""
        if fetch is None or not self.spill_limit_bytes:
            return self.evict_cold(max_entries)
        cold = self._cold_leaves(max_entries)
        if not cold:
            return 0
        # blocks whose device rows are not authoritative yet: pending
        # COW destinations and pending restore targets — spilling one
        # would capture garbage
        pend = {d for _, d in self._pending_copies}
        pend.update(r[3] for r in self._pending_restores)
        spill, drop = [], []
        for _, cid in cold:
            ent = self._prefix[cid]
            if (len(spill) < self.spill_batch and ent.end % self.bs == 0
                    and ent.block not in pend):
                spill.append(cid)
            else:
                drop.append(cid)
        if spill:
            rows = fetch([self._prefix[cid].block for cid in spill])
            kept = 0
            for j, cid in enumerate(spill):
                rec = {name: np.asarray(arr[:, j])
                       for name, arr in rows.items()}
                nb = sum(a.nbytes for a in rec.values())
                key = self._chain_tokens(cid)
                old = self._spilled.pop(key, None)
                if old is not None:
                    self.host_spill_bytes -= old[1]
                if self.host_spill_bytes + nb > self.spill_limit_bytes:
                    self._drop_entry(cid)    # over budget: plain drop
                    continue
                self._spilled[key] = (rec, nb)
                self.host_spill_bytes += nb
                self._drop_entry(cid)
                kept += 1
            if kept:
                self.spilled_blocks += kept
                _telemetry.count("kv_pool.spilled_blocks", kept)
        for cid in drop:
            self._drop_entry(cid)
        freed = len(spill) + len(drop)
        if freed:
            _telemetry.count("kv_pool.prefix_evictions", freed)
        return freed

    def rss_watchdog(self, rss_bytes: int | None = None) -> int:
        """Host-memory relief rung (``PADDLE_TPU_KV_SPILL_RSS_MB``):
        when the process resident set exceeds the threshold, release up
        to ``spill_batch`` entries — OLDEST host-spilled chains first
        (the spill store is the host tier this watchdog guards;
        insertion order is spill order, so the front of the dict is the
        LRU end), then cold device-index leaves through the plain
        :meth:`evict_cold` rung.  Bounded work per engagement: a server
        over the threshold sheds pressure across ticks instead of
        stalling one.  ``rss_bytes`` overrides the ``/proc`` read
        (tests; schedulers with their own sampler).  Returns entries
        released; counts ``kv_pool.rss_spills``."""
        if not self.rss_limit_bytes:
            return 0
        rss = _read_rss_bytes() if rss_bytes is None else int(rss_bytes)
        if rss <= self.rss_limit_bytes:
            return 0
        freed = 0
        while self._spilled and freed < self.spill_batch:
            key = next(iter(self._spilled))
            _, nb = self._spilled.pop(key)
            self.host_spill_bytes -= nb
            freed += 1
        if freed < self.spill_batch:
            freed += self.evict_cold(self.spill_batch - freed)
        if freed:
            self.rss_spills += freed
            _telemetry.count("kv_pool.rss_spills", freed)
        return freed

    def _restore_spilled(self, slot: int, parent: int, prompt,
                         matched: int):
        """Adoption-side promotion of one spilled chain block: re-intern
        the node on a fresh block and queue its host rows for the
        caller's batched ``device_put`` + ``inject_rows`` table scatter
        (:meth:`take_restores` — zero new executable families).  Chains
        restore block-by-block as the adopt walk descends.  Returns the
        new chain id, or None when nothing matches."""
        if not self._spilled or not self._free or matched % self.bs:
            return None
        end = matched + self.bs
        if end > len(prompt):
            return None
        item = self._spilled.pop(tuple(prompt[:end]), None)
        if item is None:
            return None
        rec, nb = item
        self.host_spill_bytes -= nb
        b = self._alloc_block()              # the index's own ref
        run = tuple(prompt[matched:end])
        key = (parent, run)
        cid = self._next_chain
        self._next_chain += 1
        self._interned[key] = cid
        self._prefix[cid] = _PrefixEntry(b, self._tick, key, parent, end)
        self._children.setdefault(parent, {})[run[0]] = cid
        self._blk_ents[b] += 1
        self._pending_restores.append((slot, matched, rec, b))
        self.restored_blocks += 1
        _telemetry.count("kv_pool.restored_blocks")
        return cid

    def take_restores(self) -> list:
        """Drain the pending restore records ``(slot, start_row, rows,
        block)`` for the caller's batched device_put + inject scatter
        (``serving._drain_restores``)."""
        out, self._pending_restores = self._pending_restores, []
        if out:
            _telemetry.count("kv_pool.restore_drains")
        return out

    # -- cross-replica chain migration --------------------------------------

    def migrate_out(self, prompt) -> list:
        """Detach every spilled chain that prefixes ``prompt`` for
        shipment to another replica's pool (the router calls this on
        every OTHER replica right before a dispatch, so a tenant's
        spilled KV follows its traffic to wherever prefix-aware
        routing now sends it).  A move, not a copy: the chains leave
        this pool's spill store and budget.  Returns wire-ready
        entries ``{"tokens": [...], "rows": {leaf: [L, bs, ...]}}`` —
        ndarray leaves, so the fleet codec ships them as raw buffer
        frames (``kv_pool.chain_migrations_out``)."""
        if not self._spilled:
            return []
        pl = tuple(int(t) for t in prompt)
        out = []
        for key in list(self._spilled):
            if len(key) <= len(pl) and pl[:len(key)] == key:
                rec, nb = self._spilled.pop(key)
                self.host_spill_bytes -= nb
                out.append({"tokens": list(key), "rows": rec})
        if out:
            _telemetry.count("kv_pool.chain_migrations_out", len(out))
        return out

    def migrate_in(self, entries) -> int:
        """Adopt migrated chains into THIS pool's spill store: the next
        admission's ``adopt_prefix`` walk promotes them through
        :meth:`_restore_spilled` → the caller's batched ``device_put``
        + ``inject_rows`` scatter — the exact restore path local spill
        uses, so migrated rows land bit-identically to rows this
        replica spilled itself.  Entries over the host budget drop
        (the prompt recomputes, never corrupts).  Returns chains kept
        (``kv_pool.chain_migrations``)."""
        added = 0
        for ent in entries:
            key = tuple(int(t) for t in ent["tokens"])
            if not key or len(key) % self.bs:
                continue          # not a block-aligned chain: refuse
            rec = {name: np.asarray(v)
                   for name, v in ent["rows"].items()}
            nb = sum(a.nbytes for a in rec.values())
            old = self._spilled.pop(key, None)
            if old is not None:
                self.host_spill_bytes -= old[1]
            if self.spill_limit_bytes \
                    and self.host_spill_bytes + nb \
                    > self.spill_limit_bytes:
                continue
            self._spilled[key] = (rec, nb)
            self.host_spill_bytes += nb
            added += 1
        if added:
            self.chain_migrations += added
            _telemetry.count("kv_pool.chain_migrations", added)
        return added

    # -- routing summary ----------------------------------------------------

    def prefix_summary(self, max_roots: int = 16) -> list:
        """Compact shape of the index for prefix-aware routing: per
        root-fanout subtree, ``(run_len, fingerprint, resident_tokens)``
        — the router matches a prompt's head against the fingerprint and
        uses resident tokens as the expected-overlap bound.  Top
        ``max_roots`` subtrees by resident tokens."""
        out = []
        for cid in self._children.get(0, {}).values():
            run = self._prefix[cid].key[1]
            resident = 0
            stack = [cid]
            while stack:
                c = stack.pop()
                resident += len(self._prefix[c].key[1])
                stack.extend(self._children.get(c, {}).values())
            out.append((len(run), prefix_fingerprint(run), resident))
        out.sort(key=lambda t: (-t[2], t[1]))
        return out[:max_roots]

    def close(self) -> None:
        """Release the whole index, every table, and the spill store
        (server shutdown)."""
        for cid in list(self._prefix):
            if cid in self._prefix:
                self._drop_entry(cid)
        for slot in range(self.max_batch):
            if (self.tables[slot] >= 0).any():
                self.free_slot(slot)
        self._spilled.clear()
        self._pending_restores.clear()
        self.host_spill_bytes = 0

    def stats(self) -> dict:
        return {
            "num_blocks": self.N, "block_size": self.bs,
            "blocks_in_use": self.blocks_in_use,
            "peak_blocks_in_use": self.peak_blocks_in_use,
            "prefix_entries": self.prefix_entries,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "cow_copies": self.cow_copies,
            "radix_splits": self.radix_splits,
            "spilled_blocks": self.spilled_blocks,
            "restored_blocks": self.restored_blocks,
            "spilled_entries": len(self._spilled),
            "host_spill_bytes": self.host_spill_bytes,
            "chain_migrations": self.chain_migrations,
        }
