"""ERNIE: knowledge-masked BERT pretraining (Baidu's flagship NLP family).

The BASELINE north star names "GPT-3/ERNIE 1.3B" as the model pair the
framework must train.  Architecturally ERNIE 1.0 IS the BERT encoder
(text/bert.py) — its contribution is the MASKING STRATEGY: instead of
masking independent word-piece positions, whole *knowledge units*
(phrases, named entities) are masked atomically, forcing the model to
recover them from context rather than from the unit's other pieces.
ERNIE's reference implementation lives outside the Paddle core repo; the
snapshot at /root/reference ships only the framework that trains it, so
this module provides the same capability the TPU-first way: a pure
data-side masking transform feeding the existing jitted BERT pretrain
step (bert.pretrain_loss — one XLA program, MXU matmuls, no new model
code to maintain).

Usage:
    cfg = ernie.ernie_base()
    batch = ernie.knowledge_mask(tokens, spans, key, cfg)  # host side
    loss = bert.pretrain_loss(params, batch, cfg, key)     # jitted step
"""
from __future__ import annotations

import numpy as np

from .bert import BertConfig

MASK_ID = 3          # ERNIE vocab convention: [MASK]
NUM_SPECIAL = 4      # PAD/UNK/CLS/MASK — excluded from random replacement
IGNORE = -100        # unmasked positions in mlm_labels


def ernie_base() -> BertConfig:
    """ERNIE 1.0 base: BERT-base geometry over the 18k Chinese-char
    vocab (model/ernie config in the public release)."""
    return BertConfig(vocab_size=18000, hidden_size=768, num_layers=12,
                      num_heads=12, max_seq_len=513)


def ernie_large() -> BertConfig:
    return BertConfig(vocab_size=18000, hidden_size=1024, num_layers=24,
                      num_heads=16, max_seq_len=513)


def knowledge_mask(tokens, spans, key, cfg: BertConfig, *,
                   mask_rate: float = 0.15, max_predictions: int = 76,
                   nsp_labels=None):
    """Whole-span MLM batch from tokens [B, T] + knowledge spans.

    ``spans`` is a list (len B) of ``(start, end)`` half-open unit
    boundaries per sequence — word/phrase/entity segmentation from the
    host-side pipeline (basic-level units are single-token spans, so the
    classic BERT scheme is the degenerate case).  Units are sampled
    WITHOUT splitting until ~``mask_rate`` of tokens are covered; each
    chosen unit is masked ATOMICALLY with the standard 80/10/10
    mask/keep/random-replace split applied per UNIT (the whole unit gets
    one treatment — replacing half an entity would leak its identity).

    Pure numpy on the host (data pipeline territory — the reference
    feeds masked batches through DataFeed the same way); the returned
    dict is ``bert.pretrain_loss``'s batch contract with fixed-shape
    [B, max_predictions] mlm tensors, so ONE jitted step serves every
    batch.  ``key`` is a numpy Generator or int seed.
    """
    rng = (key if isinstance(key, np.random.Generator)
           else np.random.default_rng(key))
    toks = np.asarray(tokens)
    B, T = toks.shape
    out = toks.copy()
    mlm_pos = np.zeros((B, max_predictions), np.int32)
    mlm_lab = np.full((B, max_predictions), IGNORE, np.int64)
    budget = max(1, int(round(mask_rate * T)))
    for b in range(B):
        units = [(s, e) for s, e in spans[b] if 0 <= s < e <= T]
        order = rng.permutation(len(units))
        covered = 0
        k = 0
        for ui in order:
            s, e = units[ui]
            if covered >= budget or k + (e - s) > max_predictions:
                continue
            # one draw per UNIT: 80% mask, 10% keep, 10% random token
            r = rng.random()
            for t in range(s, e):
                mlm_pos[b, k] = t
                mlm_lab[b, k] = toks[b, t]
                k += 1
                if r < 0.8:
                    out[b, t] = MASK_ID
                elif r < 0.9:
                    # replacement pool excludes special ids: drawing
                    # MASK_ID here would mix [MASK] into a "replaced"
                    # unit, breaking the one-treatment-per-unit invariant
                    out[b, t] = rng.integers(NUM_SPECIAL, cfg.vocab_size)
            covered += e - s
    return {
        "input_ids": out,
        "mlm_positions": mlm_pos,
        "mlm_labels": mlm_lab,
        "nsp_labels": (np.zeros((B,), np.int64) if nsp_labels is None
                       else np.asarray(nsp_labels, np.int64)),
    }
