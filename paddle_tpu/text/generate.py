"""Autoregressive GPT generation with a KV cache.

Beyond-reference capability (the v2.1 reference ships no generate API): a
TPU-first decode loop — the whole generation is ONE ``lax.scan`` over
positions with per-layer K/V caches updated via ``dynamic_update_slice``,
so XLA compiles a single program per (batch, max_len) and every decode step
is a fixed-shape cached-attention block (no re-running the prefix).

Works with the dense `gpt.GPTConfig` models (tied embeddings); sampling is
greedy or temperature/top-k off an explicit PRNG key.
"""
from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from . import engine as _engine
from . import gpt, woq
from .. import flags as _flags

__all__ = ["init_cache", "decode_step", "generate"]


def _kv_store_dtype(cfg: gpt.GPTConfig):
    """The cache STORAGE dtype (flags.kv_cache_dtype): '' = the model's
    compute dtype (the default, pre-flag behavior)."""
    name = _flags.kv_cache_dtype()
    if name == "fp32":
        return jnp.float32
    if name == "bf16":
        return jnp.bfloat16
    if name == "int8":
        return jnp.int8
    return cfg.dtype


def _round_cache_len(n: int) -> int:
    """Round a cache length up to a flash-decode-tileable size (8-multiple
    up to 512, 128-multiple beyond): the row count is pure ALLOCATION —
    the causal mask hides rows past the write position — so padding a few
    rows costs a sliver of HBM while an unaligned length would silently
    pin every decode of that cache on the einsum fallback (callers pass
    arbitrary prompt+max_new totals)."""
    n = max(int(n), 1)
    if n <= 512:
        return -(-n // 8) * 8
    return -(-n // 128) * 128


def init_cache(cfg: gpt.GPTConfig, batch: int, max_len: int,
               layout: str = "contiguous", block_size: int | None = None,
               num_blocks: int | None = None):
    """Per-layer K/V cache [L, B, T, Hkv, hd] with T = ``max_len`` rounded
    up to a kernel-tileable length (_round_cache_len — extra rows stay
    masked); the caller tracks the write position (generate's scan
    carries it implicitly).  Under GQA (cfg.num_kv_heads) the cache holds
    only the Hkv shared heads — the num_heads/Hkv decode-memory saving is
    the feature's point.

    ``PADDLE_TPU_KV_DTYPE`` selects the storage dtype; int8 caches carry
    per-(position, head) fp32 scale planes ``k_s``/``v_s``
    [L, B, T, Hkv] beside the values (~hd x smaller), written by
    the same row writes and dequantized at the attention site (inside
    the flash-decode kernel, or before the XLA einsum).

    ``layout="paged"`` returns the pooled format instead (text/kv_pool:
    value leaves [L, num_blocks, block_size, Hkv, hd] + an int32
    ``tables`` leaf [batch, nmax], same pytree API — HBM scales with
    blocks actually mapped, not worst-case context).  The serving layer
    owns the allocator; the contiguous slab stays the default
    (``PADDLE_TPU_KV_LAYOUT`` flips ``DecodeServer``'s default)."""
    if layout == "paged":
        from . import kv_pool

        return kv_pool.init_paged_cache(cfg, batch, max_len,
                                        block_size=block_size,
                                        num_blocks=num_blocks)
    if layout not in ("contiguous", None, ""):
        raise ValueError(
            f"layout {layout!r}: expected 'contiguous' or 'paged'")
    L, H, hd = cfg.num_layers, cfg.kv_heads, cfg.head_dim
    dt = _kv_store_dtype(cfg)
    shape = (L, batch, _round_cache_len(max_len), H, hd)
    cache = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
    if dt == jnp.int8:
        cache["k_s"] = jnp.zeros(shape[:-1], jnp.float32)
        cache["v_s"] = jnp.zeros(shape[:-1], jnp.float32)
    return cache


def _store_rows(k_rows, v_rows, cfg: gpt.GPTConfig) -> dict:
    """Compute-dtype K/V rows [..., Hkv, hd] → cache-storage leaves (the
    dict mirrors the cache structure minus the time axis handling): int8
    quantizes per-(row, head) and adds the scale leaves."""
    from ..ops import decode_attention as da

    dt = _kv_store_dtype(cfg)
    if dt == jnp.int8:
        qk, sk = da.quantize_kv(k_rows)
        qv, sv = da.quantize_kv(v_rows)
        return {"k": qk, "v": qv, "k_s": sk, "v_s": sv}
    return {"k": k_rows.astype(dt), "v": v_rows.astype(dt)}


def _use_decode_kernel(cfg: gpt.GPTConfig, q_shape, kv_shape) -> bool:
    """Route this cached-attention site through the split-KV Pallas
    kernel?  Flag + backend/shape gate (ops/decode_attention.available);
    the per-config probe then runs inside the op itself.  False keeps the
    site on its original einsum math — bit-identical to pre-kernel
    behavior (and the only path off-TPU outside interpret tests)."""
    from ..ops import decode_attention as da

    return _flags.flash_decode() and da.available(q_shape, kv_shape)


def _attend_cache(q, full, pos, cfg: gpt.GPTConfig):
    """Cached attention for a Tq-row query block against one layer's
    cache slice ``full`` (rows through the current positions already
    written): q [B, Tq, H, hd], full leaves k/v [B, T, Hkv, hd]
    (+ scales), row i of batch b attends rows t <= pos + i.  Returns
    [B, Tq, H*hd] in the compute dtype.

    Kernel path: ops/decode_attention (GQA-aware split-KV streaming,
    int8 dequant in-kernel).  Fallback: the original grouped einsum —
    int8 caches dequantize via the shared helper first."""
    B, Tq, H, hd = q.shape
    dt = cfg.dtype
    k_all, v_all = full["k"], full["v"]
    ks, vs = full.get("k_s"), full.get("v_s")
    if _use_decode_kernel(cfg, q.shape, k_all.shape):
        from ..ops import decode_attention as da

        pos_b = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,))
        out = da.decode_attention(q, k_all, v_all, pos_b,
                                  k_scale=ks, v_scale=vs)
        return out.astype(dt).reshape(B, Tq, H * hd)
    if ks is not None:
        from ..ops import decode_attention as da

        k_all = da.dequantize_kv(k_all, ks, dt)
        v_all = da.dequantize_kv(v_all, vs, dt)
    # a non-compute storage dtype (fp32/bf16 flag) joins the einsums in
    # the COMPUTE dtype — the residual stream's dtype is a scan-carry
    # invariant, and mixed-dtype einsums would silently promote it
    k_all = k_all.astype(dt)
    v_all = v_all.astype(dt)
    T = k_all.shape[1]
    Hkv = k_all.shape[2]
    g = H // Hkv
    qg = q.reshape(B, Tq, Hkv, g, hd)
    scores = jnp.einsum("bikgd,btkd->bkgit", qg, k_all) / jnp.sqrt(
        jnp.asarray(hd, jnp.float32)).astype(dt)
    mask = (jnp.arange(T)[None, :]
            <= pos + jnp.arange(Tq)[:, None])[None, None, None]
    scores = jnp.where(mask, scores.astype(jnp.float32), -1e30)
    w = jax.nn.softmax(scores, axis=-1).astype(dt)
    return jnp.einsum("bkgit,btkd->bikgd", w, v_all).reshape(B, Tq, -1)


def _embed_step(params, token, pos, cfg: gpt.GPTConfig):
    """Embed one decode step's tokens [B] at position ``pos`` ->
    [B, 1, D] — the single embed+wpe shared by the contiguous decode
    step and the paged (kv_pool) routes."""
    x = woq.embed(params, token, cfg.dtype)[:, None]
    if cfg.pos_embed == "learned":
        x = x + jax.lax.dynamic_slice(
            params["wpe"], (pos, 0),
            (1, cfg.hidden_size)).astype(cfg.dtype)[None]
    return x


def _block_pre_attn(x, p, pos, cfg: gpt.GPTConfig):
    """Pre-attention half of one decode block on a single position
    [B, 1, D]: ln1 -> qkv projection (the Hkv heads kept, never
    repeated) -> rope at ``pos`` -> storage-dtype rows.  Returns
    (q3, rows); every cached-decode route (contiguous AND paged kernel)
    shares this, so the per-layer math can never drift between them."""
    B = x.shape[0]
    hd = cfg.head_dim
    h = gpt._norm(x, p, "ln1", cfg)
    q3, k3, v3 = gpt._project_qkv(h, p, cfg, repeat_kv=False)
    if cfg.pos_embed == "rope":
        # rotate q and the NEW key row at this position; the cache holds
        # already-rotated keys (rope's relative-offset property makes
        # them valid forever)
        pos_arr = jnp.asarray(pos, jnp.int32).reshape(1)
        q3 = gpt.apply_rope(q3, pos_arr)
        k3 = gpt.apply_rope(k3, pos_arr)
    k_new = k3.reshape(B, -1, hd)   # Hkv rows under GQA, H otherwise
    v_new = v3.reshape(B, -1, hd)
    return q3, _store_rows(k_new, v_new, cfg)


def _block_post_attn(x, attn, p, cfg: gpt.GPTConfig, valid=None,
                     capacity=gpt._LEGACY, stats=None):
    """Post-attention half: output projection + residual + FFN tail
    (the other shared side of :func:`_block_pre_attn`).  The MoE serving
    step calls this ONCE for the whole batch (``valid``/``capacity``/
    ``stats`` forwarded to :func:`gpt._ffn_tail`) so the slot tokens
    route jointly under the configured capacity factor — the same layer
    math as the dense route, a different token grouping."""
    dt = cfg.dtype
    a = woq.mm(attn, p, "proj_w", dt) + p["proj_b"].astype(dt)
    return gpt._ffn_tail(x + a, p, cfg, valid=valid, capacity=capacity,
                         stats=stats)


def _cached_block(x, p, csl, pos, cfg: gpt.GPTConfig):
    """One block on a SINGLE position [B, 1, D] against one layer's cache
    slice ``csl`` (leaves k/v [B, T, Hkv, hd], plus scales for int8).
    Returns (x, rows): storage-dtype row leaves for the caller to write
    at pos."""
    q3, rows = _block_pre_attn(x, p, pos, cfg)
    # attend over cache rows [B, max_len, Hkv, hd] with the fresh row at
    # pos — spliced in STORAGE form, so what this step attends is exactly
    # what later steps will read back (int8 included)
    full = {name: jax.lax.dynamic_update_slice(
                csl[name], val[:, None],
                (0, pos) + (0,) * (csl[name].ndim - 2))
            for name, val in rows.items()}
    attn = _attend_cache(q3, full, pos, cfg)           # [B, 1, D]
    return _block_post_attn(x, attn, p, cfg), rows


def _write_rows(cache: dict, rows: dict, pos) -> dict:
    """Write stacked per-layer rows (leaves [L, B, P?, Hkv(, hd)]) into
    the cache at time index ``pos`` — the single row-write every decode/
    verify path funnels through.  Rows without a time axis (single-token
    decode: [L, B, Hkv(, hd)]) get one inserted."""
    out = {}
    for name, val in rows.items():
        arr = cache[name]
        if val.ndim == arr.ndim - 1:
            val = jnp.expand_dims(val, 2)
        out[name] = jax.lax.dynamic_update_slice(
            arr, val.astype(arr.dtype),
            (0, 0, pos) + (0,) * (arr.ndim - 3))
    return out


def decode_step(params, cache, token, pos, cfg: gpt.GPTConfig):
    """token [B] int32 at position pos → (logits [B, V], updated cache).

    MoE models decode too: the expert FFN routes the step's B tokens
    jointly (GShard capacity from the call's token count, C =
    ceil(B*top_k/E*cf)) — at B == 1 nothing can drop; at B > 1 batch rows
    contend for capacity exactly as training tokens do, so a batched
    sequence's tokens can depend on its batch-mates (inherent to
    capacity-bounded routing, not a cache artifact)."""
    dt = cfg.dtype
    x = _embed_step(params, token, pos, cfg)

    def body(x, layer):
        p, csl = layer
        x, rows = _cached_block(x, p, csl, pos, cfg)
        return x, rows

    x, rows = jax.lax.scan(body, x, (params["blocks"], cache))
    new_cache = _write_rows(cache, rows, pos)
    x = gpt._norm(x, params, "ln_f", cfg)
    logits = woq.logits(x, params, dt)[:, 0]
    return logits.astype(jnp.float32), new_cache


# round 15: the Engine (text/engine.py) is the single step-compilation
# authority — the LRU cache class, the cfg/flags key, cache donation, and
# the recompile-watch wrapper all live there now.  These names stay as
# aliases because half the test surface (and downstream callers) address
# them here, and because _GEN_CACHE must keep being THE object tests
# clear() between flag flips — it aliases the Engine's gen-domain cache.
_LRU = _engine._LRU
_GEN_CACHE = _engine.ENGINE._gen
_donate_cache = _engine.donate_cache
_watch_jit = _engine._watch_jit
_cfg_key = _engine.cfg_key


def _get_generate_fn(cfg, max_new_tokens, top_k, top_p=1.0):
    """Engine shim: one executable per (config VALUE, gen params) —
    GPTConfig is closed over (dataclass isn't hashable for
    static_argnames); the 'generate' registry entry folds the knobs
    into the key after ``cfg_key``."""
    return _engine.ENGINE.get("generate", _engine.StepSpec(
        cfg=cfg, extra=(max_new_tokens, top_k, float(top_p))))


def _generate_impl(params, prompt, key, temperature, *, cfg,
                   max_new_tokens, top_k, top_p):
    B, P = prompt.shape
    total = P + max_new_tokens
    cache = init_cache(cfg, B, total)
    tokens = jnp.zeros((B, total), jnp.int32)
    tokens = tokens.at[:, :P].set(prompt)

    def step(carry, pos):
        tokens, cache, key = carry
        tok = jax.lax.dynamic_slice(tokens, (0, pos), (B, 1))[:, 0]
        logits, cache = decode_step(params, cache, tok, pos, cfg)
        key, sub = jax.random.split(key)
        # the canonical temperature -> top-k -> nucleus pipeline
        # (_filter_logits is the single implementation all samplers
        # share; advisor r4: temperature must scale BEFORE the nucleus
        # cut).  Skipped entirely when both filters are statically off —
        # the plain-sampling path then pays no vocab sorts per step.
        if top_k > 0 or top_p < 1.0:
            logits = _filter_logits(logits, temperature, top_k, top_p)
        else:
            logits = jnp.where(jnp.asarray(temperature) > 0.0,
                               logits / jnp.maximum(temperature, 1e-6),
                               logits)
        nxt = jax.lax.cond(
            jnp.asarray(temperature) > 0.0,
            lambda: jax.random.categorical(sub, logits),
            lambda: jnp.argmax(logits, axis=-1).astype(jnp.int32))
        nxt = nxt.astype(jnp.int32)
        # prompt positions keep their given token; past-prompt write samples
        write = jnp.where(pos + 1 < P, tokens[:, pos + 1], nxt)
        tokens = jax.lax.dynamic_update_slice(
            tokens, write[:, None], (0, pos + 1))
        return (tokens, cache, key), None

    (tokens, cache, _), _ = jax.lax.scan(
        step, (tokens, cache, key), jnp.arange(total - 1))
    return tokens


def generate(params, cfg: gpt.GPTConfig, prompt, max_new_tokens=32,
             temperature=0.0, top_k=0, top_p=1.0, key=None):
    """prompt [B, P] int → [B, P + max_new_tokens] tokens (greedy when
    temperature == 0).  ``top_k`` keeps the k highest logits; ``top_p``
    (nucleus) keeps the smallest probability-mass prefix reaching p —
    both compose (k filter first, then p over what survives)."""
    import numpy as np

    prompt = jnp.asarray(np.asarray(prompt), jnp.int32)
    total = prompt.shape[1] + int(max_new_tokens)
    if total > cfg.max_seq_len:
        raise ValueError(
            f"prompt ({prompt.shape[1]}) + max_new_tokens "
            f"({max_new_tokens}) = {total} exceeds cfg.max_seq_len "
            f"{cfg.max_seq_len}: positions past the table would silently "
            "reuse the last positional embedding")
    if key is None:
        key = jax.random.PRNGKey(0)
    top_k = min(int(top_k), cfg.vocab_size)  # top-k over the whole vocab
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    fn = _get_generate_fn(cfg, int(max_new_tokens), top_k, top_p)
    return fn(params, prompt, key, jnp.asarray(float(temperature)))


# ---------------------------------------------------------------------------
# beam search — width-k max-probability decoding (serving staple)
# ---------------------------------------------------------------------------


def _beam_impl(params, prompt, *, cfg, max_new_tokens, num_beams,
               length_penalty, eos_id):
    B, P = prompt.shape
    W = num_beams
    V = cfg.vocab_size
    total = P + max_new_tokens
    NEG = jnp.float32(-1e30)

    # every beam shares the prompt: run it once at beam-batch width so the
    # cache is already [B*W] and generation never reshapes it
    cache = init_cache(cfg, B * W, total)
    toks = jnp.zeros((B, W, total), jnp.int32)
    toks = toks.at[:, :, :P].set(prompt[:, None, :])

    def feed(carry, pos):
        cache, = carry
        tok = jnp.repeat(prompt[:, pos], W)            # [B*W]
        _, cache = decode_step(params, cache, tok, pos, cfg)
        return (cache,), None

    if P > 1:
        (cache,), _ = jax.lax.scan(feed, (cache,), jnp.arange(P - 1))

    # scores: beam 0 seeds the search; duplicates start at -inf so the
    # first expansion yields W DISTINCT continuations
    scores = jnp.full((B, W), NEG).at[:, 0].set(0.0)
    alive = jnp.ones((B, W), bool)
    lengths = jnp.zeros((B, W), jnp.int32)

    def step(carry, pos):
        cache, toks, scores, alive, lengths = carry
        tok = jax.lax.dynamic_slice(
            toks, (0, 0, pos), (B, W, 1)).reshape(B * W)
        logits, cache = decode_step(params, cache, tok, pos, cfg)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = logp.reshape(B, W, V)
        if eos_id is not None:
            # a finished beam must survive unexpanded: exactly one
            # candidate (continue with eos) at zero added score
            only_eos = jnp.full((V,), NEG).at[eos_id].set(0.0)
            logp = jnp.where(alive[:, :, None], logp, only_eos)
        cand = scores[:, :, None] + logp               # [B, W, V]
        new_scores, idx = jax.lax.top_k(cand.reshape(B, W * V), W)
        parent = idx // V                              # [B, W]
        new_tok = (idx % V).astype(jnp.int32)
        gather = lambda a: jnp.take_along_axis(a, parent, axis=1)  # noqa
        toks = jnp.take_along_axis(
            toks, parent[:, :, None], axis=1)
        toks = jax.lax.dynamic_update_slice(
            toks, new_tok[:, :, None], (0, 0, pos + 1))
        # cache rows follow their beam: gather along the B*W axis
        flat_parent = (jnp.arange(B)[:, None] * W + parent).reshape(-1)
        cache = {k: jnp.take(v, flat_parent, axis=1)
                 for k, v in cache.items()}
        alive = gather(alive)
        lengths = gather(lengths)
        if eos_id is not None:
            lengths = jnp.where(alive, lengths + 1, lengths)
            alive = alive & (new_tok != eos_id)
        else:
            lengths = lengths + 1
        return (cache, toks, new_scores, alive, lengths), None

    (cache, toks, scores, alive, lengths), _ = jax.lax.scan(
        step, (cache, toks, scores, alive, lengths),
        P - 1 + jnp.arange(max_new_tokens))
    norm = scores / jnp.power(jnp.maximum(lengths, 1).astype(jnp.float32),
                              length_penalty)
    best = jnp.argmax(norm, axis=1)                    # [B]
    return (jnp.take_along_axis(toks, best[:, None, None], axis=1)[:, 0],
            jnp.take_along_axis(norm, best[:, None], axis=1)[:, 0])


def beam_search(params, cfg: gpt.GPTConfig, prompt, max_new_tokens=32,
                num_beams=4, length_penalty: float = 0.0,
                eos_id: int | None = None):
    """Width-``num_beams`` beam search → (tokens [B, P+max_new], score [B]).

    TPU-first shape: ONE jitted program — the prompt feeds at beam-batch
    width (cache is [B*W] from step 0, no mid-flight reshape), each
    generation step is a batched cached-attention decode + top-k over the
    W*V joint candidates, and beam reordering is a gather on the cache's
    batch axis.  Static shapes throughout; finished beams (``eos_id``)
    survive unexpanded via a single zero-delta eos candidate.

    ``length_penalty`` alpha normalizes final scores by generated-length
    ** alpha (0 = pure sum-logprob).  With ``num_beams`` >= V**max_new
    the search is exhaustive — the tests use that to prove optimality.
    Beyond-reference capability: the v2.1 reference ships no generation
    API at all (text/gpt.py docstring)."""
    import numpy as np

    prompt = jnp.asarray(np.asarray(prompt), jnp.int32)
    total = prompt.shape[1] + int(max_new_tokens)
    if total > cfg.max_seq_len:
        raise ValueError(
            f"prompt + max_new_tokens = {total} exceeds cfg.max_seq_len "
            f"{cfg.max_seq_len}")
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    fn = _engine.ENGINE.get("beam", _engine.StepSpec(
        cfg=cfg, extra=(int(max_new_tokens), int(num_beams),
                        float(length_penalty), eos_id)))
    return fn(params, prompt)


# ---------------------------------------------------------------------------
# tensor-parallel (sharded) decode — serving models too big for one chip
# ---------------------------------------------------------------------------


def _decode_param_specs(params, cfg: gpt.GPTConfig, mp: str):
    """A PartitionSpec tree matching ``params`` — float OR weight-only
    quantized (text/woq.py) OR LoRA-adapted (text/lora.py): quantized
    weights take their float twin's Megatron spec (same shape), while the
    small ``*_s`` scale tensors and ``*_lora_a``/``*_lora_b`` low-rank
    adapter pairs replicate (PartitionSpec() is rank-agnostic 'all
    replicated'; the adapter delta is recomputed per rank — rank-r
    matmuls are noise next to the sharded base weights, and GSPMD
    reshards the delta to match the consumer)."""
    from jax.sharding import PartitionSpec as P

    base = gpt.param_shardings(cfg, mp=mp)
    blocks = {}
    for name, v in params["blocks"].items():
        if (name.endswith("_s") or name.endswith("_lora_a")
                or name.endswith("_lora_b")):
            blocks[name] = P()
        else:
            blocks[name] = base["blocks"][name]
    out = {k: (base[k] if k in base else P()) for k in params if k != "blocks"}
    out["blocks"] = blocks
    return out


def sharded_cache_specs(cfg: gpt.GPTConfig, cache: dict, mesh,
                        mp: str = "mp") -> dict:
    """PartitionSpec per cache leaf for tensor-parallel decode — ONE
    rule for both layouts: the Hkv axis (axis 3 of the contiguous slab
    ``[L, B, T, Hkv(, hd)]`` AND of the paged pool
    ``[L, N, bs, Hkv(, hd)]``, scale planes included) shards over ``mp``
    when divisible, everything else replicates; the paged ``tables``
    leaf (host-scheduler state, int32 indices) always replicates."""
    from jax.sharding import PartitionSpec as P

    mp_size = mesh.shape[mp]

    def _spec(name, arr):
        if name == "tables" or cfg.kv_heads % mp_size:
            return P()
        return P(*([None] * 3 + [mp] + [None] * (arr.ndim - 4)))

    return {name: _spec(name, arr) for name, arr in cache.items()}


def build_sharded_decode(params, cfg: gpt.GPTConfig, mesh, mp: str = "mp",
                         layout: str | None = None,
                         block_size: int | None = None):
    """Megatron-sharded single-token decode over ``mesh`` (the serving
    analog of gpt_hybrid's TP training: reference mp_layers.py shards
    projections by hand + NCCL; here the SAME decode_step is pjit'd under
    the param PartitionSpecs and XLA inserts the collectives over ICI).

    The KV cache shards over the head axis when the mesh divides it —
    with GQA this composes: Hkv heads spread across mp ranks, so a 13B
    model's cache splits like its weights.  ``layout`` (default: the
    ``PADDLE_TPU_KV_LAYOUT`` flag) picks the cache format: the pooled
    layout (round 9) shards the pool's Hkv axis exactly the way the slab
    shards its head axis (``sharded_cache_specs`` — one rule for both),
    tables replicate, and the step routes through
    ``kv_pool.paged_decode_step_batched`` with the scalar ``pos``
    broadcast per slot.  Returns ``(sharded_params, make_cache,
    decode_fn)``:
        sharded_params     params placed per the Megatron specs
        make_cache(B, T, num_blocks=None)   sharded cache
        decode_fn(p, cache, token [B] int32, pos scalar) -> (logits, cache)
    Weight-only int8/int4 params (woq.quantize_gpt_*) shard identically —
    scales replicate.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if cfg.moe is not None:
        raise NotImplementedError("sharded decode supports dense models")
    lay = _flags.kv_layout() if layout is None else layout
    if lay not in ("contiguous", "paged"):
        raise ValueError(f"layout {lay!r}: expected 'contiguous' or "
                         f"'paged'")
    pspecs = _decode_param_specs(params, cfg, mp)
    ns = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    sharded_params = jax.tree_util.tree_map(
        lambda v, s: jax.device_put(v, ns(s)), params, pspecs,
        is_leaf=lambda v: not isinstance(v, dict))

    bs = None
    if lay == "paged":
        from . import kv_pool as _kvp

        bs = _flags.kv_block_size() if block_size is None \
            else int(block_size)
        template = _kvp.init_paged_cache(cfg, 1, 1, block_size=bs)
    else:
        template = init_cache(cfg, 1, 1)
    cache_specs = sharded_cache_specs(cfg, template, mesh, mp)
    cache_shardings = {name: ns(s) for name, s in cache_specs.items()}
    repl = P()

    def _step(p, cache, token, pos):
        if lay == "paged":
            from . import kv_pool as _kvp

            pos_b = jnp.broadcast_to(jnp.asarray(pos, jnp.int32),
                                     token.shape)
            return _kvp.paged_decode_step_batched(p, cache, token, pos_b,
                                                  cfg)
        return decode_step(p, cache, token, pos, cfg)

    # the sharded cache is donated like the single-chip steps' — in and
    # out shardings match, so aliasing is exact per shard
    decode_fn = _engine.ENGINE.get("sharded_decode", _engine.StepSpec(
        cfg=cfg, extra=(lay, bs),
        payload=(_step, dict(
            in_shardings=(jax.tree_util.tree_map(
                ns, pspecs, is_leaf=lambda s: isinstance(s, P)),
                cache_shardings,
                ns(repl), ns(repl)),
            out_shardings=(ns(repl), cache_shardings),
            donate_argnums=_donate_cache()))))

    def make_cache(batch: int, max_len: int,
                   num_blocks: int | None = None):
        # the builder pins the FLAG-derived layout/block at build time
        # (the explicit-argument form is the caller's own contract): a
        # flag flip after build would otherwise be silently ignored
        # here while every OTHER init_cache site in the process honors
        # it — fail loudly instead of serving two layouts at once
        if layout is None and _flags.kv_layout() != lay:
            raise ValueError(
                f"PADDLE_TPU_KV_LAYOUT changed since "
                f"build_sharded_decode (built {lay!r}, flag now "
                f"{_flags.kv_layout()!r}); rebuild the sharded decoder")
        if lay == "paged" and block_size is None \
                and _flags.kv_block_size() != bs:
            raise ValueError(
                f"PADDLE_TPU_KV_BLOCK changed since "
                f"build_sharded_decode (built {bs}, flag now "
                f"{_flags.kv_block_size()}); rebuild the sharded "
                f"decoder")
        fresh = init_cache(cfg, batch, max_len, layout=lay,
                           block_size=bs, num_blocks=num_blocks)
        if set(fresh) != set(cache_shardings):
            # init_cache re-reads PADDLE_TPU_KV_DTYPE at call time
            # (layout/block flips were caught above), but decode_fn
            # baked the build-time structure into its
            # in_shardings/donation — a flag flip in between must fail
            # loudly here, not as a pytree mismatch inside the jit
            raise ValueError(
                "PADDLE_TPU_KV_DTYPE changed since build_sharded_decode "
                f"(built {sorted(cache_shardings)}, now {sorted(fresh)}); "
                "rebuild the sharded decoder")
        if lay == "paged" and fresh["k"].shape[2] != bs:
            raise ValueError(
                f"PADDLE_TPU_KV_BLOCK changed since build_sharded_decode "
                f"(built block_size={bs}, now {fresh['k'].shape[2]}); "
                "rebuild the sharded decoder")
        return {name: jax.device_put(arr, cache_shardings[name])
                for name, arr in fresh.items()}

    return sharded_params, make_cache, decode_fn


# ---------------------------------------------------------------------------
# chunked prefill — whole-prompt cache fill in one step
# ---------------------------------------------------------------------------


def _prefill_block(x, p, cfg: gpt.GPTConfig, valid=None):
    """One block over a PADDED prompt chunk [B, P, D] with within-chunk
    causal attention (the cache is empty at prefill: pos0 == 0), returning
    (x, rows) — storage-dtype row leaves for the caller to merge.
    ``valid`` [B, P]: pad mask forwarded to the MoE router (pads claim no
    expert capacity); dense models ignore it."""
    B, P, D = x.shape
    H, hd = cfg.num_heads, cfg.head_dim
    dt = cfg.dtype
    h = gpt._norm(x, p, "ln1", cfg)
    # project ONCE (unrepeated); derive GQA attention copies by repeat
    q, k_rows, v_rows = gpt._project_qkv(h, p, cfg, repeat_kv=False)
    if cfg.pos_embed == "rope":
        pos_arr = jnp.arange(P)
        q = gpt.apply_rope(q, pos_arr)
        k_rows = gpt.apply_rope(k_rows, pos_arr)
    rows = _store_rows(k_rows, v_rows, cfg)
    # attend the STORAGE view of the fresh rows (the sibling sites'
    # attend-what-you-store invariant): under int8 the admission path
    # sees exactly the rows later decode steps will read back, so
    # prefill and token-by-token feeding stay in lockstep
    if "k_s" in rows:
        from ..ops import decode_attention as da

        k_att = da.dequantize_kv(rows["k"], rows["k_s"], dt)
        v_att = da.dequantize_kv(rows["v"], rows["v_s"], dt)
    else:
        k_att = rows["k"].astype(dt)
        v_att = rows["v"].astype(dt)
    rep = H // k_att.shape[2]
    k = jnp.repeat(k_att, rep, axis=2) if rep > 1 else k_att
    v = jnp.repeat(v_att, rep, axis=2) if rep > 1 else v_att
    from ..ops.attention import attention_array

    attn = attention_array(q, k, v, is_causal=True).reshape(B, P, D)
    a = woq.mm(attn, p, "proj_w", dt) + p["proj_b"].astype(dt)
    return gpt._ffn_tail(x + a, p, cfg, valid=valid), rows


def prefill_slot(params, cache, tokens, length, slot, cfg: gpt.GPTConfig):
    """Process one request's whole (padded) prompt in a single step.

    tokens [1, P] int32 padded to P; ``length`` (traced scalar) = valid
    prompt tokens; ``slot`` (traced scalar) = batch row of the serving
    cache [L, B, T, Hkv, hd].  Writes cache rows [0, length) for that slot
    (padded rows are NOT written — stale tenants' data beyond ``length``
    stays hidden by the decode-time causal mask until overwritten) and
    returns (greedy logits at position length-1 [V], cache).

    MoE models prefill too (round-5 verdict Next #4): the pad mask
    reaches every block's router, where padding claims no expert
    capacity, and the per-chunk capacity is the dropless bound — so the
    padded chunk routes exactly like feeding the prompt token-by-token
    (tests/test_serving.py MoE prefill parity)."""
    dt = cfg.dtype
    P = tokens.shape[1]
    x = woq.embed(params, tokens, dt)
    if cfg.pos_embed == "learned":
        x = x + params["wpe"][:P].astype(dt)[None]
    valid_mask = (jnp.arange(P) < length)[None, :]       # [1, P]

    def body(x, p):
        x, rows = _prefill_block(x, p, cfg, valid=valid_mask)
        return x, rows

    x, rows = jax.lax.scan(body, x, params["blocks"])
    # masked merge into this slot's rows [0, P): only the valid prefix
    cache = _merge_slot_rows(cache, rows, slot, jnp.asarray(0), valid_mask)
    # slice the last valid row before the (per-row) final norm
    last = jax.lax.dynamic_slice(x, (0, length - 1, 0),
                                 (1, 1, cfg.hidden_size))
    last = gpt._norm(last, params, "ln_f", cfg)
    logits = woq.logits(last, params, dt)[0, 0]
    return logits.astype(jnp.float32), cache


def _chunk_pre_attn(x, p, pos0, cfg: gpt.GPTConfig):
    """Pre-attention half of one block on a K-token chunk [B, K, D] at
    positions [pos0, pos0+K): ln1 -> qkv projection (Hkv heads kept) ->
    rope over the chunk's positions -> storage-dtype rows.  Returns
    (q [B, K, H, hd], rows); :func:`_chunk_attend_block` and the batched
    kernel verify routes (here and kv_pool) all project through this
    one copy, so the chunk math can never drift between the einsum and
    flash routes."""
    K = x.shape[1]
    q, k_new, v_new = gpt._project_qkv(
        gpt._norm(x, p, "ln1", cfg), p, cfg, repeat_kv=False)
    if cfg.pos_embed == "rope":
        chunk_pos = pos0 + jnp.arange(K)
        q = gpt.apply_rope(q, chunk_pos)
        k_new = gpt.apply_rope(k_new, chunk_pos)
    return q, _store_rows(k_new, v_new, cfg)


def _chunk_attend_block(x, p, csl, pos0, cfg: gpt.GPTConfig,
                        valid=None):
    """One transformer block over a K-token chunk at positions
    [pos0, pos0+K) against a per-layer cache slice ``csl`` (leaves k/v
    [B, T, Hkv, hd] + scales) whose rows [0, pos0) are already filled:
    row i attends cache rows t <= pos0 + i.  THE shared body of
    verify_chunk and prefill_slot_chunk (one copy of the chunk-attention
    math).  PRECONDITION: pos0 + K <= T — dynamic_update_slice CLAMPS
    start indices, so an overrunning window would silently write the
    chunk's rows at a shifted offset while the mask/positions still use
    pos0 (callers guarantee the bound; the serving walk overlaps its
    last window instead of overrunning).  Returns (x_out, rows)."""
    dt = cfg.dtype
    q, rows = _chunk_pre_attn(x, p, pos0, cfg)
    full = {name: jax.lax.dynamic_update_slice(
                csl[name], val, (0, pos0) + (0,) * (csl[name].ndim - 2))
            for name, val in rows.items()}
    attn = _attend_cache(q, full, pos0, cfg)           # [B, K, D]
    a = woq.mm(attn, p, "proj_w", dt) + p["proj_b"].astype(dt)
    return gpt._ffn_tail(x + a, p, cfg, valid=valid), rows


def _merge_slot_rows(cache, rows, slot, pos0, valid):
    """Masked write of per-layer chunk row leaves [L, 1, P, Hkv(, hd)]
    into one slot's cache rows [pos0, pos0+P): only rows where ``valid``
    [1, P] is True are written (pads leave the old tenant's rows
    untouched — the stale-row invariant).  Shared by prefill_slot
    (pos0 == 0) and prefill_slot_chunk; int8 scale planes merge under
    the same mask."""
    P = rows["k"].shape[2]
    out = dict(cache)
    for name, val in rows.items():
        arr = cache[name]
        start = (0, slot, pos0) + (0,) * (arr.ndim - 3)
        old = jax.lax.dynamic_slice(
            arr, start, (arr.shape[0], 1, P) + arr.shape[3:])
        vmask = valid.reshape((1, 1, P) + (1,) * (arr.ndim - 3))
        merged = jnp.where(vmask, val.astype(arr.dtype), old)
        out[name] = jax.lax.dynamic_update_slice(arr, merged, start)
    return out


def prefill_slot_chunk(params, cache, tokens, pos0, length, slot,
                       cfg: gpt.GPTConfig):
    """One FIXED-SIZE chunk of a prompt at positions [pos0, pos0+P) for
    one serving slot — the multi-chunk admission step (round-5): long
    prompts feed as a sequence of these, each attending the slot's
    already-filled cache rows [0, pos0), so activation memory is bounded
    by the chunk and ONE executable serves any prompt length (vs one
    compile per power-of-two bucket).

    tokens [1, P] int32 (pad tail beyond ``length``); ``pos0``/``length``
    /``slot`` are traced scalars.  PRECONDITION pos0 + P <= cache rows
    (and the wpe table) — see _chunk_attend_block; DecodeServer's walk
    overlaps the last window rather than overrunning.  Writes cache rows
    [pos0, pos0+length) (pads unwritten, and routed nowhere under MoE —
    the valid mask + dropless capacity, exactly prefill_slot's rule);
    returns (logits at the chunk's last valid position [V], cache)."""
    dt = cfg.dtype
    P = tokens.shape[1]
    x = woq.embed(params, tokens, dt)
    if cfg.pos_embed == "learned":
        x = x + jax.lax.dynamic_slice(
            params["wpe"], (pos0, 0), (P, cfg.hidden_size)).astype(dt)[None]
    valid_mask = (jnp.arange(P) < length)[None, :]       # [1, P]
    # this slot's cache rows [L, 1, T, Hkv(, hd)] per leaf
    sl = {name: jax.lax.dynamic_slice(
              arr, (0, slot) + (0,) * (arr.ndim - 2),
              (arr.shape[0], 1) + arr.shape[2:])
          for name, arr in cache.items()}

    def body(x, layer):
        p, csl = layer
        x, rows = _chunk_attend_block(x, p, csl, pos0, cfg,
                                      valid=valid_mask)
        return x, rows

    x, rows = jax.lax.scan(body, x, (params["blocks"], sl))
    cache = _merge_slot_rows(cache, rows, slot, pos0, valid_mask)
    # slice the last valid row FIRST: the final norm is per-row, so
    # normalizing all P rows per chunk would be pure waste
    last = jax.lax.dynamic_slice(x, (0, length - 1, 0),
                                 (1, 1, cfg.hidden_size))
    last = gpt._norm(last, params, "ln_f", cfg)
    logits = woq.logits(last, params, dt)[0, 0]
    return logits.astype(jnp.float32), cache


# ---------------------------------------------------------------------------
# speculative decoding (greedy): draft proposes, target verifies in 1 chunk
# ---------------------------------------------------------------------------


def verify_chunk(params, cache, tokens, pos0, cfg: gpt.GPTConfig):
    """Score K tokens in one pass against an existing cache.

    tokens [1, K] int32 fed at positions [pos0, pos0+K); attends cache
    rows [0, pos0) plus within-chunk causally; writes the chunk's K/V rows
    at [pos0, pos0+K) (rows past an eventual rejection point stay hidden
    behind the caller's position pointer until overwritten — the same
    stale-row invariant the serving slots rely on).  Returns
    (logits [1, K, V], cache).

    MoE: the K chunk tokens route JOINTLY (capacity C from N=K), so a
    chunk can drop tokens a one-at-a-time decode would not — chunked
    verification is therefore not bit-equal to stepwise decode for MoE;
    speculative_generate rejects MoE targets for exactly this reason."""
    dt = cfg.dtype
    B, K = tokens.shape
    x = woq.embed(params, tokens, dt)
    if cfg.pos_embed == "learned":
        x = x + jax.lax.dynamic_slice(
            params["wpe"], (pos0, 0), (K, cfg.hidden_size)).astype(dt)[None]

    def body(x, layer):
        p, csl = layer
        x, rows = _chunk_attend_block(x, p, csl, pos0, cfg)
        return x, rows

    x, rows = jax.lax.scan(body, x, (params["blocks"], cache))
    new_cache = _write_rows(cache, rows, pos0)
    x = gpt._norm(x, params, "ln_f", cfg)
    logits = woq.logits(x, params, dt)
    return logits.astype(jnp.float32), new_cache


def _write_rows_batched(cache: dict, rows: dict, pos) -> dict:
    """Per-slot-offset form of :func:`_write_rows`: stacked chunk row
    leaves [L, B, K, Hkv(, hd)] land at each slot's own positions
    [pos_b, pos_b+K) (pos [B] int32) — the contiguous-layout write the
    batched verify kernel route needs, since its slots sit at different
    frontiers."""
    out = {}
    for name, val in rows.items():
        arr = cache[name]

        def one(arr_b, val_b, p0, _a=arr):
            return jax.lax.dynamic_update_slice(
                arr_b, val_b.astype(_a.dtype),
                (0, p0) + (0,) * (arr_b.ndim - 2))

        out[name] = jax.vmap(one, in_axes=(1, 1, 0), out_axes=1)(
            arr, val, pos)
    return out


def verify_chunk_batched(params, cache, tokens, pos, cfg: gpt.GPTConfig):
    """Batched :func:`verify_chunk` with the layer loop at TOP level so
    the Tq>=1 flash-decode kernel sees the whole batch per layer (ONE
    kernel launch over [B, K] query rows per block instead of a vmapped
    per-slot einsum — the ROADMAP "flash-verify" item): tokens [B, K]
    int32 scored at per-slot positions [pos_b, pos_b+K) ->
    (logits [B, K, V] fp32, cache).

    The per-slot pre/post math stays vmapped at the fallback's [1, K, D]
    shapes (:func:`_chunk_pre_attn` — rope needs each slot's own
    offsets); only the attention itself batches, with the fresh rows
    spliced into each slot's cache slice BEFORE attending so the kernel
    reads exactly what later rounds read back (splice-then-write, the
    :func:`_chunk_attend_block` rule).  Callers gate on
    :func:`_use_decode_kernel` at q [B, K, H, hd] — off-kernel the
    vmapped einsum route stays the (bit-identical-to-decode) default."""
    from ..ops import decode_attention as da

    dt = cfg.dtype
    B, K = tokens.shape
    H, hd = cfg.num_heads, cfg.head_dim

    def embed_one(tok_k, p0):
        x = woq.embed(params, tok_k[None], dt)            # [1, K, D]
        if cfg.pos_embed == "learned":
            x = x + jax.lax.dynamic_slice(
                params["wpe"], (p0, 0),
                (K, cfg.hidden_size)).astype(dt)[None]
        return x

    x = jax.vmap(embed_one)(tokens, pos)                  # [B, 1, K, D]

    def body(x, layer):
        p, csl = layer                # csl leaves [B, T, Hkv(, hd)]

        def pre(xb, p0):
            return _chunk_pre_attn(xb, p, p0, cfg)

        q3, rows = jax.vmap(pre)(x, pos)  # q3 [B, 1, K, H, hd]

        def splice(arr_b, val_b, p0):
            return jax.lax.dynamic_update_slice(
                arr_b, val_b.astype(arr_b.dtype),
                (p0,) + (0,) * (arr_b.ndim - 1))

        full = {name: jax.vmap(splice)(csl[name], val[:, 0], pos)
                for name, val in rows.items()}
        attn = da.decode_attention(
            q3.reshape(B, K, H, hd), full["k"], full["v"], pos,
            k_scale=full.get("k_s"), v_scale=full.get("v_s"))
        attn = attn.astype(dt).reshape(B, 1, K, H * hd)

        def post(xb, ab):
            return _block_post_attn(xb, ab, p, cfg)

        return jax.vmap(post)(x, attn), rows

    x, rows = jax.lax.scan(body, x, (params["blocks"], cache))
    # rows leaves [L, B, 1, K, ...] -> per-slot offset write
    new_cache = _write_rows_batched(
        cache, {n: v[:, :, 0] for n, v in rows.items()}, pos)

    def fin(xb):
        xb = gpt._norm(xb, params, "ln_f", cfg)
        return woq.logits(xb, params, dt)[0]              # [K, V]

    logits = jax.vmap(fin)(x)
    return logits.astype(jnp.float32), new_cache


# ---------------------------------------------------------------------------
# tree speculation: ONE verify pass over a branching token tree
# ---------------------------------------------------------------------------


def tree_depths(parent):
    """Per-node depths [N] int32 of a parent-index tree (parent[0] == -1
    is the root/feed node; parents precede children — every propose
    layout in this repo is topologically ordered).  Pure host work."""
    import numpy as np

    n = len(parent)
    d = np.zeros(n, np.int32)
    for j in range(1, n):
        d[j] = d[parent[j]] + 1
    return d


def tree_ancestor_mask(parent):
    """Ancestor-or-self mask [N, N] bool of a parent-index tree:
    ``m[j, t]`` is True iff node t lies on node j's root path (j
    included) — the within-chunk half of the tree-attention mask.  Built
    host-side (numpy, one |= per node off the parent's finished row);
    the device only ever sees the finished mask as a RUNTIME argument,
    so per-round topology changes never retrace."""
    import numpy as np

    n = len(parent)
    m = np.zeros((n, n), bool)
    for j in range(n):
        m[j, j] = True
        if parent[j] >= 0:
            m[j] |= m[parent[j]]
    return m


def _attend_cache_tree(q, full, tmask, cfg: gpt.GPTConfig):
    """:func:`_attend_cache` with the causal ``t <= pos + i`` rule
    replaced by an explicit per-row visibility mask ``tmask`` [B, N, T]
    (True = attend): each tree node sees the committed prefix plus its
    OWN ancestor path, nothing from sibling branches.  Einsum-only on
    purpose — the flash-decode kernels assume causal masks, so tree
    verify keeps one route that exists on every backend (an on-device
    tree kernel is a ROADMAP follow-up)."""
    B, Tq, H, hd = q.shape
    dt = cfg.dtype
    k_all, v_all = full["k"], full["v"]
    ks, vs = full.get("k_s"), full.get("v_s")
    if ks is not None:
        from ..ops import decode_attention as da

        k_all = da.dequantize_kv(k_all, ks, dt)
        v_all = da.dequantize_kv(v_all, vs, dt)
    k_all = k_all.astype(dt)
    v_all = v_all.astype(dt)
    Hkv = k_all.shape[2]
    g = H // Hkv
    qg = q.reshape(B, Tq, Hkv, g, hd)
    scores = jnp.einsum("bikgd,btkd->bkgit", qg, k_all) / jnp.sqrt(
        jnp.asarray(hd, jnp.float32)).astype(dt)
    scores = jnp.where(tmask[:, None, None], scores.astype(jnp.float32),
                       -1e30)
    w = jax.nn.softmax(scores, axis=-1).astype(dt)
    return jnp.einsum("bkgit,btkd->bikgd", w, v_all).reshape(B, Tq, -1)


def _tree_pre_attn(x, p, pos0, depth, cfg: gpt.GPTConfig):
    """:func:`_chunk_pre_attn` for a tree chunk: node j ropes at its
    LOGICAL position ``pos0 + depth[j]`` (depth [N] int32), not its
    storage index ``pos0 + j`` — siblings at one depth share a
    position.  Rope's relative-offset property keeps the stored key
    rows valid after the post-acceptance permute moves a node to the
    storage index matching its logical position."""
    q, k_new, v_new = gpt._project_qkv(
        gpt._norm(x, p, "ln1", cfg), p, cfg, repeat_kv=False)
    if cfg.pos_embed == "rope":
        node_pos = pos0 + depth
        q = gpt.apply_rope(q, node_pos)
        k_new = gpt.apply_rope(k_new, node_pos)
    return q, _store_rows(k_new, v_new, cfg)


def _tree_attend_block(x, p, csl, pos0, depth, tmask, cfg: gpt.GPTConfig):
    """One transformer block over an N-node tree chunk stored at rows
    [pos0, pos0+N) against a per-layer cache slice ``csl`` (leaves k/v
    [B, T, Hkv, hd] + scales): node j ropes at ``pos0 + depth[j]`` and
    attends exactly ``tmask[:, j]``.  THE shared body of the contiguous
    and paged tree verify routes — one copy of the tree math, the
    :func:`_chunk_attend_block` rule, same PRECONDITION pos0 + N <= T
    (dynamic_update_slice clamps; callers guarantee the bound)."""
    dt = cfg.dtype
    q, rows = _tree_pre_attn(x, p, pos0, depth, cfg)
    full = {name: jax.lax.dynamic_update_slice(
                csl[name], val, (0, pos0) + (0,) * (csl[name].ndim - 2))
            for name, val in rows.items()}
    attn = _attend_cache_tree(q, full, tmask, cfg)     # [B, N, D]
    a = woq.mm(attn, p, "proj_w", dt) + p["proj_b"].astype(dt)
    return gpt._ffn_tail(x + a, p, cfg), rows


def tree_verify_chunk(params, cache, tokens, amask, depth, pos0,
                      cfg: gpt.GPTConfig):
    """Score one slot's N-node token tree in ONE pass: tokens [1, N]
    int32 stored at cache rows [pos0, pos0+N) (node 0 = the feed token
    = the tree root); ``amask`` [1, N, N] bool (ancestor-or-self) and
    ``depth`` [1, N] int32 describe the topology as RUNTIME arguments —
    only N is a compiled shape, so per-round topology changes never
    retrace.  Node j attends the committed rows [0, pos0) plus its own
    ancestor path inside the chunk; rejected nodes just stay at/past
    the caller's position pointer as stale rows (the PR 11 invariant),
    so no rollback executable exists — acceptance off the trunk is a
    row PERMUTE (:func:`tree_commit_rows`), not an unwrite.  Returns
    (logits [1, N, V] fp32, cache).  Unused node slots (short trees pad
    with self-only mask rows) write garbage rows past every live node's
    visibility — stale by the same invariant.

    MoE: the N nodes would route jointly (the verify_chunk caveat,
    worse under branching); serving rejects MoE targets before this."""
    dt = cfg.dtype
    B, N = tokens.shape
    T = cache["k"].shape[2]
    x = woq.embed(params, tokens, dt)
    if cfg.pos_embed == "learned":
        x = x + jnp.take(params["wpe"], pos0 + depth[0],
                         axis=0).astype(dt)[None]
    tmask = jnp.broadcast_to(jnp.arange(T)[None, None, :] < pos0,
                             (B, N, T))
    tmask = jax.lax.dynamic_update_slice(tmask, amask, (0, 0, pos0))

    def body(x, layer):
        p, csl = layer
        x, rows = _tree_attend_block(x, p, csl, pos0, depth[0], tmask,
                                     cfg)
        return x, rows

    x, rows = jax.lax.scan(body, x, (params["blocks"], cache))
    new_cache = _write_rows(cache, rows, pos0)
    x = gpt._norm(x, params, "ln_f", cfg)
    logits = woq.logits(x, params, dt)
    return logits.astype(jnp.float32), new_cache


def tree_verify_chunk_batched(params, cache, tokens, amask, depth, pos,
                              cfg: gpt.GPTConfig):
    """Batched :func:`tree_verify_chunk` over per-slot frontiers:
    tokens [B, N], amask [B, N, N], depth [B, N], pos [B] int32 ->
    (logits [B, N, V] fp32, cache).  vmapped at the per-slot [1, N]
    shapes (rope and the committed-prefix boundary need each slot's own
    offset); einsum-only — see :func:`_attend_cache_tree`."""

    def one(tok, am, dp, csl, p0):
        sl = {name: v[:, None] for name, v in csl.items()}
        lg, nc = tree_verify_chunk(params, sl, tok[None], am[None],
                                   dp[None], p0, cfg)
        return lg[0], {n: v[:, 0] for n, v in nc.items()}

    logits, new_cache = jax.vmap(
        one, in_axes=(0, 0, 0, 1, 0), out_axes=(0, 1))(
        tokens, amask, depth, cache, pos)
    return logits.astype(jnp.float32), new_cache


def tree_commit_rows(cache, src, pos):
    """Post-acceptance KV permute for tree speculation on the contiguous
    layout: per slot b, gather rows ``pos_b + src_b[i]`` and write them
    back at ``pos_b + 1 + i`` for i in [0, M) (src [B, M] int32, pos [B]
    the slot's pre-round pointer).  An accepted root-to-leaf path is
    strictly increasing in node index and every source row sits at or
    past ``pos_b + 1``, so gather-then-scatter over ALL M rows is
    alias-safe and needs no keep-mask: identity entries rewrite
    themselves, and rows past the accepted pointer are stale either way
    (the PR 11 invariant).  Cache-only — the Engine donates the cache
    like ``kv_copy``; host code skips the dispatch entirely when every
    slot accepted a trunk prefix (src == identity everywhere)."""
    out = {}
    for name, arr in cache.items():

        def one(arr_b, s, p0, _a=arr):
            rows = jnp.take(arr_b, p0 + s, axis=1)
            return jax.lax.dynamic_update_slice(
                arr_b, rows.astype(_a.dtype),
                (0, p0 + 1) + (0,) * (arr_b.ndim - 2))

        out[name] = jax.vmap(one, in_axes=(1, 0, 0), out_axes=1)(
            arr, src, pos)
    return out


def _jit_by_cfg(tag: str, fn, cfg):
    """Engine shim: value-keyed jit cache (the _GEN_CACHE rationale:
    per-call jax.jit wrappers would recompile per invocation and leak
    executables).  The cache (arg 1) is DONATED — callers reassign it
    from the return.  ``tag`` pins the step fn's identity, so ``fn``
    rides in the spec's un-keyed payload."""
    return _engine.ENGINE.get("jit_by_cfg", _engine.StepSpec(
        cfg=cfg, extra=(tag,), payload=fn))


def _key_seed(key):
    """np.random seed material from a jax PRNG key (typed keys need
    key_data; raw PRNGKey uint32 arrays convert directly)."""
    import numpy as np

    try:
        return np.asarray(jax.random.key_data(key)).ravel()
    except Exception:  # noqa: BLE001 - raw uint32 key array
        return np.asarray(key).ravel()


def _filter_logits(logits, temperature, top_k, top_p, xp=jnp):
    """THE temperature → top-k → nucleus filter over [..., V] logits —
    the single source of truth for every sampler: ``_generate_impl``
    (device, scalar params), ``serving._sample_batched`` (device,
    per-slot param arrays), and ``_filtered_probs`` (host mirror for the
    speculative rejection rule, ``xp=numpy``).  Backend-agnostic on
    purpose: one formula cannot drift between the three call sites (the
    chi-square tests additionally pin host and device statistically).

    temperature/top_k/top_p broadcast over the leading dims; top_k == 0
    and top_p == 1 disable their stages; temperature == 0 leaves logits
    unscaled (greedy callers take the argmax, which every stage
    preserves — the top token always survives)."""
    V = logits.shape[-1]
    lead = logits.shape[:-1]

    def bc(a, dt):
        return xp.broadcast_to(xp.asarray(a, dt), lead)[..., None]

    t = bc(temperature, xp.float32)
    tk = bc(top_k, xp.int32)
    tp = bc(top_p, xp.float32)
    x = xp.where(t > 0, logits / xp.maximum(t, 1e-6), logits)
    srt = xp.sort(x, axis=-1)[..., ::-1]               # descending
    kth = xp.take_along_axis(srt, xp.clip(tk - 1, 0, V - 1), axis=-1)
    x = xp.where((tk > 0) & (x < kth), -1e30, x)
    srt2 = xp.sort(x, axis=-1)[..., ::-1]
    e = xp.exp(srt2 - srt2[..., :1])
    probs = e / xp.sum(e, axis=-1, keepdims=True)
    keep = xp.cumsum(probs, axis=-1) - probs < tp  # mass BEFORE the token
    kth_idx = xp.sum(keep, axis=-1, keepdims=True) - 1
    cutoff = xp.take_along_axis(srt2, kth_idx, axis=-1)
    return xp.where((tp < 1.0) & (x < cutoff), -1e30, x)


def _filtered_probs(logits, temperature, top_k, top_p):
    """Host-side probability vector of the sampling law on a [V] logit
    vector — evaluates the SAME ``_filter_logits`` formula under numpy
    (float64), then normalizes.  The rejection-sampling accept/resample
    math needs q and p as explicit vectors."""
    import numpy as np

    x = _filter_logits(np.asarray(logits, np.float64), float(temperature),
                       int(top_k), float(top_p), xp=np)
    e = np.exp(x - x.max())
    return e / e.sum()


def ngram_propose(sequence, k, max_order=3, window=256):
    """Model-free draft proposals: match the sequence's trailing n-gram
    (longest order first, down to a single token) against its most
    recent earlier occurrence and copy the continuation — the
    "self-drafting" / prompt-lookup decoding trick (zero extra model
    FLOPs, pure host work).  Returns k proposed tokens, or None when no
    order matches (the caller speculates nothing that round).  Short
    continuations pad by repeating the last copied token — a cheap
    guess the verify step rejects at worst.  ``window`` bounds the
    backward scan so long contexts stay O(window) per call."""
    seq = list(sequence)
    n = len(seq)
    if n < 2:
        return None
    lo = max(0, n - int(window))
    for order in range(min(int(max_order), n - 1), 0, -1):
        tail = tuple(seq[n - order:])
        for s in range(n - order - 1, lo - 1, -1):
            if tuple(seq[s:s + order]) == tail:
                out = list(seq[s + order:s + order + k])
                while len(out) < k:
                    out.append(out[-1])
                return out
    return None


def ngram_propose_tree(sequence, nodes, branch=2, max_order=3,
                       window=256):
    """Tree-shaped self-drafting: like :func:`ngram_propose`, but
    instead of stopping at the first (most recent, longest-order) n-gram
    match, collect up to ``branch`` DISTINCT continuations and merge
    them into a prefix trie of at most ``nodes`` node slots — branching
    exactly where the history itself disagrees about what comes next.
    Node slot 0 is reserved for the feed token (the caller owns it); the
    first continuation becomes the TRUNK, laid out as nodes 1..D before
    any alternate, so a trunk-prefix acceptance needs no KV permute.

    Returns ``(tokens, parent)`` lists — ``tokens[0]`` is None,
    ``parent[0] == -1``, parents precede children (topological order,
    what :func:`tree_ancestor_mask` assumes) — or None when no order
    matches.  May return fewer than ``nodes`` entries; callers pad the
    device arrays with self-only mask rows (stale, never selected)."""
    seq = list(sequence)
    n = len(seq)
    if n < 2:
        return None
    lo = max(0, n - int(window))
    cap = int(nodes) - 1                     # token-bearing node slots
    branch = max(1, int(branch))
    if cap < 1:
        return None
    conts, seen = [], set()
    for order in range(min(int(max_order), n - 1), 0, -1):
        tail = tuple(seq[n - order:])
        for s in range(n - order - 1, lo - 1, -1):
            if tuple(seq[s:s + order]) == tail:
                c = tuple(seq[s + order:s + order + cap])
                if c and c not in seen:
                    seen.add(c)
                    conts.append(list(c))
                    if len(conts) >= branch:
                        break
        if len(conts) >= branch:
            break
    if not conts:
        return None
    # the trunk is NOT padded (unused node slots stay idle, masked
    # self-only by the caller) and leaves one slot per alternate so a
    # long first match can't starve the branches out of the budget
    trunk = conts[0][:max(1, cap - (len(conts) - 1))]
    tokens, parent = [None], [-1]
    children = {0: {}}
    for i, t in enumerate(trunk):
        tokens.append(int(t))
        parent.append(i)                     # trunk node i+1's parent
        children[i][int(t)] = i + 1
        children[i + 1] = {}
    for c in conts[1:]:                      # graft where they diverge
        cur = 0
        for t in c:
            t = int(t)
            nxt = children[cur].get(t)
            if nxt is None:
                if len(tokens) >= int(nodes):
                    break
                tokens.append(t)
                parent.append(cur)
                nxt = len(tokens) - 1
                children[cur][t] = nxt
                children[nxt] = {}
            cur = nxt
    return tokens, parent


def speculative_generate(tparams, tcfg, dparams, dcfg, prompt,
                         max_new_tokens=32, k=4, temperature=0.0,
                         top_k=0, top_p=1.0, key=None):
    """Speculative decoding: a small DRAFT model proposes ``k``
    tokens per round (k cheap decode steps), the TARGET verifies them in
    ONE verify_chunk pass.

    Greedy (``temperature == 0``): accept the longest prefix where the
    target's own greedy choice agrees, substituting its token at the
    first disagreement.  Output is EXACTLY the target's greedy
    generation — the draft only changes how many target passes it takes.

    Sampling (``temperature > 0``, round-5 verdict Next #3): the draft
    SAMPLES each proposal from its filtered distribution q (same
    temperature/top-k/top-p pipeline as ``generate``); token j is
    accepted with probability min(1, p_j(x_j)/q_j(x_j)) against the
    target's filtered p_j, and the first rejection resamples from the
    residual max(p_j - q_j, 0) — the standard rejection rule, whose
    per-token marginal is exactly p_j, so the OUTPUT DISTRIBUTION equals
    target-only sampling (proven statistically in
    tests/test_speculative.py by chi-square against the target's exact
    next-token law).  No bonus token is drawn on a fully-accepted round:
    a round yields at most k tokens, which keeps the draft-cache
    stale-row invariant identical to the greedy path (a bonus token
    would leave a K/V hole at the last draft position).

    Both models keep KV caches; rejected rows in either cache stay hidden
    behind the position pointers and are overwritten on the next round
    (the serving slots' stale-row invariant).  Returns a python list of
    the generated tokens (no prompt)."""
    import numpy as np

    prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
    if not prompt:
        raise ValueError("empty prompt")
    if tcfg.moe is not None or dcfg.moe is not None:
        # verify_chunk routes K tokens jointly while plain decode routes
        # 1: capacity drops could make "accepted" tokens differ from the
        # target's own greedy decode, silently breaking the exactness
        # guarantee this function exists for
        raise NotImplementedError(
            "speculative decoding requires dense models (MoE capacity "
            "routing differs between chunked verify and stepwise decode)")
    total = len(prompt) + max_new_tokens
    if total > min(tcfg.max_seq_len, dcfg.max_seq_len):
        raise ValueError("prompt + max_new_tokens exceeds a model's window")
    if temperature > 0.0:
        return _speculative_sample(tparams, tcfg, dparams, dcfg, prompt,
                                   max_new_tokens, k, temperature,
                                   min(int(top_k), tcfg.vocab_size),
                                   float(top_p), key, total)
    t_step = _jit_by_cfg("decode", decode_step, tcfg)
    d_step = _jit_by_cfg("decode", decode_step, dcfg)
    t_verify = _jit_by_cfg("verify", verify_chunk, tcfg)
    t_cache = init_cache(tcfg, 1, total)
    d_cache = init_cache(dcfg, 1, total)

    # prompt: feed both models token-by-token (simple; prefill would also
    # work) — target logits at the last prompt position seed generation
    t_logits = None
    for pos in range(len(prompt)):
        tok = jnp.asarray([prompt[pos]], jnp.int32)
        t_logits, t_cache = t_step(tparams, t_cache, tok, pos)
        _, d_cache = d_step(dparams, d_cache, tok, pos)

    out = [int(np.asarray(jnp.argmax(t_logits, -1))[0])]
    t_pos = len(prompt)          # target cache rows [0, t_pos) are final
    while len(out) < max_new_tokens:
        kk = min(k, max_new_tokens - len(out), total - 1 - t_pos)
        if kk <= 0:
            break
        # 1) draft proposes kk tokens from the current accepted tail
        draft = []
        cur = out[-1]
        for j in range(kk):
            dl, d_cache = d_step(dparams, d_cache,
                                 jnp.asarray([cur], jnp.int32), t_pos + j)
            cur = int(np.asarray(jnp.argmax(dl, -1))[0])
            draft.append(cur)
        # 2) target scores [out[-1], draft[0..kk-2]] in one chunk: row j's
        # logits are the target's choice AFTER seeing draft[j-1]
        chunk = jnp.asarray([[out[-1]] + draft[:-1]], jnp.int32)
        vl, t_cache = t_verify(tparams, t_cache, chunk, t_pos)
        tchoice = np.asarray(jnp.argmax(vl[0], -1))
        for j in range(kk):
            out.append(int(tchoice[j]))
            t_pos += 1
            if int(tchoice[j]) != draft[j]:
                break   # target disagrees: its token wins, round ends
        # no draft-cache resync is needed: after a rejection the draft's
        # first stale row sits exactly at the new t_pos — the position the
        # next round's first proposal overwrites (fed the corrected
        # out[-1]); rows before it were fed accepted (= identical) tokens
    return out[:max_new_tokens]


def _speculative_sample(tparams, tcfg, dparams, dcfg, prompt,
                        max_new_tokens, k, temperature, top_k, top_p,
                        key, total):
    """Rejection-sampling speculative decode body (see speculative_generate).

    Host-side control flow with fetched logit vectors (the framework's
    reference implementation: tests run tiny models; a production server
    would keep accept/resample on device).  The draft-cache invariant is
    the greedy path's: accepted tokens equal the draft's own proposals,
    so draft rows up to the rejection point were fed the true sequence,
    and the next round's first feed overwrites the first stale row."""
    import numpy as np

    if key is None:
        key = jax.random.PRNGKey(0)
    # one host RNG drives draft draws, accept draws, and resamples —
    # deterministic per key
    rng = np.random.default_rng(_key_seed(key))

    t_step = _jit_by_cfg("decode", decode_step, tcfg)
    d_step = _jit_by_cfg("decode", decode_step, dcfg)
    t_verify = _jit_by_cfg("verify", verify_chunk, tcfg)
    t_cache = init_cache(tcfg, 1, total)
    d_cache = init_cache(dcfg, 1, total)

    t_logits = None
    for pos in range(len(prompt)):
        tok = jnp.asarray([prompt[pos]], jnp.int32)
        t_logits, t_cache = t_step(tparams, t_cache, tok, pos)
        _, d_cache = d_step(dparams, d_cache, tok, pos)

    def draw(p):
        return int(rng.choice(len(p), p=p))

    p0 = _filtered_probs(np.asarray(t_logits)[0], temperature, top_k, top_p)
    out = [draw(p0)]
    t_pos = len(prompt)
    while len(out) < max_new_tokens:
        kk = min(k, max_new_tokens - len(out), total - 1 - t_pos)
        if kk <= 0:
            break
        # 1) draft proposes kk tokens, each SAMPLED from its filtered q
        draft, qs = [], []
        cur = out[-1]
        for j in range(kk):
            dl, d_cache = d_step(dparams, d_cache,
                                 jnp.asarray([cur], jnp.int32), t_pos + j)
            q = _filtered_probs(np.asarray(dl)[0], temperature, top_k,
                                top_p)
            cur = draw(q)
            draft.append(cur)
            qs.append(q)
        # 2) target scores the proposals in one chunk: row j's (filtered)
        # distribution is p_j — the law of the token at position t_pos+j
        chunk = jnp.asarray([[out[-1]] + draft[:-1]], jnp.int32)
        vl, t_cache = t_verify(tparams, t_cache, chunk, t_pos)
        ps = [_filtered_probs(np.asarray(vl)[0, j], temperature, top_k,
                              top_p) for j in range(kk)]
        # 3) accept x_j with prob min(1, p_j/q_j); first rejection
        # resamples from the residual (p_j - q_j)+ and ends the round
        for j in range(kk):
            x = draft[j]
            if rng.uniform() < min(1.0, ps[j][x] / max(qs[j][x], 1e-300)):
                out.append(x)
                t_pos += 1
                continue
            resid = np.maximum(ps[j] - qs[j], 0.0)
            mass = resid.sum()
            # degenerate residual (q == p to rounding): draw from p itself
            out.append(draw(resid / mass) if mass > 0 else draw(ps[j]))
            t_pos += 1
            break
    return out[:max_new_tokens]
