"""Sequence (ragged) ops (reference operators/sequence_ops/ — LoD-aware
seq_pool/pad/unpad/softmax/reverse/expand over LoDTensor, ~15k LoC C++).

TPU-first redesign: the reference's LoD (level-of-detail offset vectors +
dynamic-shaped kernels) becomes the **lengths / segment-ids convention**
with STATIC shapes, the representation XLA actually runs well:

* packed form: ``values [N, ...]`` + ``lengths [B]`` (sum == N) — the
  LoDTensor analog; ``segment_ids`` derived with static bounds;
* padded form: ``[B, T, ...]`` + lengths — what the compute wants.

Each op is a jnp/segment-op program (jax.ops.segment_* lower to one-pass
scatter-adds on TPU); packed↔padded conversion is a gather/scatter with
static output shape (maxlen is a required static argument when tracing).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["sequence_mask", "sequence_pad", "sequence_unpad",
           "sequence_pool", "sequence_softmax", "sequence_reverse",
           "sequence_expand", "sequence_first_step", "sequence_last_step",
           "sequence_concat", "sequence_conv", "sequence_enumerate",
           "sequence_expand_as", "sequence_reshape", "sequence_scatter",
           "sequence_slice", "segment_ids_from_lengths"]


def _unwrap(x):
    from ..core.tensor import Tensor

    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def segment_ids_from_lengths(lengths, total: int):
    """lengths [B] → segment ids [total] (rows past sum(lengths) get B —
    an out-of-range segment that jax segment ops drop)."""
    lengths = _unwrap(lengths)
    B = lengths.shape[0]
    starts = jnp.cumsum(lengths) - lengths
    # mark each segment start with +1 and prefix-sum (static-shape trick)
    marks = jnp.zeros((total + 1,), jnp.int32)
    marks = marks.at[starts].add(1)
    ids = jnp.cumsum(marks[:total]) - 1
    valid = jnp.arange(total) < jnp.sum(lengths)
    return jnp.where(valid, ids, B)


def sequence_mask(lengths, maxlen: int, dtype=jnp.bool_):
    """lengths [B] → mask [B, maxlen] (reference sequence_mask_op)."""
    lengths = _unwrap(lengths)
    return (jnp.arange(maxlen)[None, :] < lengths[:, None]).astype(dtype)


def sequence_pad(values, lengths, maxlen: int, pad_value=0.0):
    """Packed [N, ...] + lengths [B] → padded [B, maxlen, ...]
    (reference sequence_pad_op)."""
    values = _unwrap(values)
    lengths = _unwrap(lengths)
    B = lengths.shape[0]
    starts = jnp.cumsum(lengths) - lengths
    pos = jnp.arange(maxlen)
    idx = starts[:, None] + pos[None, :]              # [B, maxlen]
    take = jnp.clip(idx, 0, values.shape[0] - 1)
    out = values[take]                                # [B, maxlen, ...]
    mask = sequence_mask(lengths, maxlen)
    mshape = mask.shape + (1,) * (out.ndim - 2)
    return jnp.where(mask.reshape(mshape), out,
                     jnp.asarray(pad_value, out.dtype))


def sequence_unpad(padded, lengths):
    """Padded [B, T, ...] + lengths → packed [B*T, ...] with the valid rows
    front-packed and a valid-count (static total; reference
    sequence_unpad_op emits dynamic N — mask with the count)."""
    padded = _unwrap(padded)
    lengths = _unwrap(lengths)
    B, T = padded.shape[:2]
    flat = padded.reshape((B * T,) + padded.shape[2:])
    mask = sequence_mask(lengths, T).reshape(-1)
    # stable front-pack permutation: valid rows keep order, pads go last
    order = jnp.argsort(~mask, stable=True)
    return flat[order], jnp.sum(lengths)


def sequence_pool(values, lengths, pool_type: str = "sum"):
    """Packed [N, D] + lengths [B] → [B, D] (reference sequence_pool_op:
    sum/mean/max/min/sqrt/first/last)."""
    values = _unwrap(values)
    lengths = _unwrap(lengths)
    N = values.shape[0]
    B = lengths.shape[0]
    seg = segment_ids_from_lengths(lengths, N)
    pt = pool_type.lower()
    if pt == "sum":
        return jax.ops.segment_sum(values, seg, num_segments=B)
    if pt == "mean":
        s = jax.ops.segment_sum(values, seg, num_segments=B)
        return s / jnp.maximum(lengths, 1).astype(s.dtype)[:, None]
    if pt == "sqrt":
        s = jax.ops.segment_sum(values, seg, num_segments=B)
        return s / jnp.sqrt(jnp.maximum(lengths, 1).astype(s.dtype))[:, None]
    if pt == "max":
        return jax.ops.segment_max(values, seg, num_segments=B)
    if pt == "min":
        return jax.ops.segment_min(values, seg, num_segments=B)
    if pt == "first":
        starts = jnp.cumsum(lengths) - lengths
        return values[jnp.clip(starts, 0, N - 1)]
    if pt == "last":
        ends = jnp.cumsum(lengths) - 1
        return values[jnp.clip(ends, 0, N - 1)]
    raise ValueError(pool_type)


def sequence_first_step(values, lengths):
    return sequence_pool(values, lengths, "first")


def sequence_last_step(values, lengths):
    return sequence_pool(values, lengths, "last")


def sequence_softmax(values, lengths):
    """Packed [N] (or [N, 1]) + lengths → per-segment softmax (reference
    sequence_softmax_op)."""
    values = _unwrap(values)
    lengths = _unwrap(lengths)
    squeeze = values.ndim == 2 and values.shape[1] == 1
    v = values.reshape(-1)
    N = v.shape[0]
    B = lengths.shape[0]
    seg = segment_ids_from_lengths(lengths, N)
    vmax = jax.ops.segment_max(v, seg, num_segments=B + 1)
    v = v - vmax[seg]
    e = jnp.exp(v)
    valid = seg < B
    e = jnp.where(valid, e, 0.0)
    denom = jax.ops.segment_sum(e, seg, num_segments=B + 1)
    out = e / jnp.maximum(denom[seg], 1e-30)
    return out[:, None] if squeeze else out


def sequence_reverse(values, lengths):
    """Packed [N, ...]: reverse each segment in place (reference
    sequence_reverse_op — the Bi-RNN building block)."""
    values = _unwrap(values)
    lengths = _unwrap(lengths)
    N = values.shape[0]
    B = lengths.shape[0]
    seg = segment_ids_from_lengths(lengths, N)
    segc = jnp.clip(seg, 0, B - 1)
    starts = (jnp.cumsum(lengths) - lengths)[segc]
    ends = (jnp.cumsum(lengths) - 1)[segc]
    pos = jnp.arange(N)
    src = jnp.where(seg < B, (starts + (ends - pos)).astype(pos.dtype), pos)
    return values[jnp.clip(src, 0, N - 1)]


def sequence_expand(values, lengths, repeat_lengths, total_out: int):
    """Repeat segment i of ``values`` ``repeat_lengths[i]`` times
    (reference sequence_expand_op).  ``total_out`` is the static output
    row count (sum(lengths * repeats) padded up)."""
    values = _unwrap(values)
    lengths = _unwrap(lengths)
    repeats = _unwrap(repeat_lengths)
    B = lengths.shape[0]
    # output segment structure: segment i appears repeats[i] times, each
    # copy with lengths[i] rows (jnp.repeat pads the tail past
    # sum(repeats); padded tail rows are masked out below)
    out_seg_len = jnp.repeat(lengths, repeats, total_repeat_length=total_out)
    src_seg = jnp.repeat(jnp.arange(B), repeats,
                         total_repeat_length=total_out)
    ids = segment_ids_from_lengths(out_seg_len, total_out)
    idsc = jnp.clip(ids, 0, total_out - 1)
    starts_out = (jnp.cumsum(out_seg_len) - out_seg_len)[idsc]
    offs = jnp.arange(total_out) - starts_out
    starts_in = jnp.cumsum(lengths) - lengths
    src = starts_in[jnp.clip(src_seg[idsc], 0, B - 1)] + offs
    n_rows = jnp.sum(lengths * repeats)  # true output rows
    row_valid = jnp.arange(total_out) < n_rows
    out = values[jnp.clip(src, 0, values.shape[0] - 1)]
    vshape = (total_out,) + (1,) * (out.ndim - 1)
    return jnp.where(row_valid.reshape(vshape), out, jnp.zeros_like(out))


def sequence_concat(values_list, lengths_list):
    """Concatenate ragged batches along TIME per sample (reference
    sequence_concat_op): sample b's output = concat of its rows from each
    input.  Inputs: lists of ([Ni, D], [B]) pairs; returns
    (values [sum Ni, D], lengths [B])."""
    vals = [_unwrap(v) for v in values_list]
    lens = [_unwrap(l) for l in lengths_list]
    B = lens[0].shape[0]
    total = sum(v.shape[0] for v in vals)
    out_len = sum(lens)
    # output row -> (sample, which input, offset) via gather: build source
    # indices per output position
    starts_out = jnp.cumsum(out_len) - out_len  # [B]
    out = jnp.zeros((total,) + vals[0].shape[1:], vals[0].dtype)
    cursor = starts_out
    for v, l in zip(vals, lens):
        starts_in = jnp.cumsum(l) - l
        n = v.shape[0]
        # scatter each input row to its output slot
        seg = segment_ids_from_lengths(l, n)
        segc = jnp.clip(seg, 0, B - 1)
        offs = jnp.arange(n) - starts_in[segc]
        dest = cursor[segc] + offs
        valid = seg < B
        dest = jnp.where(valid, dest, total)  # dropped by scatter-clip
        out = out.at[jnp.clip(dest, 0, total - 1)].add(
            jnp.where(valid.reshape((-1,) + (1,) * (v.ndim - 1)), v, 0))
        cursor = cursor + l
    return out, out_len


def sequence_expand_as(values, lengths, ref_lengths):
    """Expand each sample's single row run to match ref_lengths (reference
    sequence_expand_as_op: every row of sample b repeats so the sample has
    ref_lengths[b] rows; requires lengths[b] == 1 semantics)."""
    values = _unwrap(values)
    lengths = _unwrap(lengths)
    ref = _unwrap(ref_lengths)
    B = lengths.shape[0]
    try:
        total_out = int(ref.sum())  # static output row count
    except jax.errors.TracerIntegerConversionError as e:
        raise ValueError(
            "sequence_expand_as needs concrete ref_lengths (static output "
            "shape); pass a host value or use sequence_expand with "
            "total_out") from e
    starts_in = jnp.cumsum(lengths) - lengths
    ids = segment_ids_from_lengths(ref, total_out)
    idsc = jnp.clip(ids, 0, B - 1)
    return jnp.take(values, starts_in[idsc], axis=0), ref


def sequence_enumerate(values, lengths, win_size: int, pad_value=0):
    """Sliding windows of ids per sample (reference sequence_enumerate_op):
    [N] int ids → [N, win_size]; windows crossing a sample end fill with
    pad_value."""
    v = _unwrap(values).reshape(-1)
    lengths = _unwrap(lengths)
    N = v.shape[0]
    B = lengths.shape[0]
    seg = segment_ids_from_lengths(lengths, N)
    ends = jnp.cumsum(lengths)  # [B]
    segc = jnp.clip(seg, 0, B - 1)
    end_of_row = ends[segc]
    cols = []
    for w in range(win_size):
        idx = jnp.arange(N) + w
        ok = (idx < end_of_row) & (seg < B)
        cols.append(jnp.where(ok, v[jnp.clip(idx, 0, N - 1)], pad_value))
    return jnp.stack(cols, axis=1)


def sequence_slice(values, lengths, offset, length):
    """Per-sample slice (reference sequence_slice_op): sample b keeps rows
    [offset[b], offset[b]+length[b]).  Returns (values [same N, D] with
    kept rows compacted to the front of each output segment, lengths)."""
    v = _unwrap(values)
    lens = _unwrap(lengths)
    off = _unwrap(offset).reshape(-1)
    ln = _unwrap(length).reshape(-1)
    B = lens.shape[0]
    N = v.shape[0]
    starts_in = jnp.cumsum(lens) - lens
    out_len = ln
    starts_out = jnp.cumsum(out_len) - out_len
    ids = segment_ids_from_lengths(out_len, N)
    idsc = jnp.clip(ids, 0, B - 1)
    offs = jnp.arange(N) - starts_out[idsc]
    src = starts_in[idsc] + off[idsc] + offs
    # a slice must stay inside its own sample (reference enforces
    # offset+length <= sample length; rows past the boundary zero out
    # rather than leaking the NEXT sample's data)
    inside = (off[idsc] + offs) < lens[idsc]
    valid = (ids < B) & inside
    out = jnp.where(valid.reshape((-1,) + (1,) * (v.ndim - 1)),
                    jnp.take(v, jnp.clip(src, 0, N - 1), axis=0), 0)
    return out, out_len


def sequence_conv(values, lengths, weight, context_size: int,
                  context_start: int = None, bias=None):
    """Time-window convolution over ragged rows (reference
    sequence_conv_op): out[t] = sum_w values[t + start + w] @ W[w], windows
    clipped at sample boundaries."""
    v = _unwrap(values)
    lens = _unwrap(lengths)
    W = _unwrap(weight)  # [context_size * D, out]
    if context_start is None:
        context_start = -(context_size // 2)
    N, D = v.shape
    B = lens.shape[0]
    seg = segment_ids_from_lengths(lens, N)
    segc = jnp.clip(seg, 0, B - 1)
    starts = (jnp.cumsum(lens) - lens)[segc]
    ends = jnp.cumsum(lens)[segc]
    pieces = []
    for w in range(context_size):
        idx = jnp.arange(N) + context_start + w
        ok = (idx >= starts) & (idx < ends) & (seg < B)
        rows = jnp.where(ok[:, None],
                         jnp.take(v, jnp.clip(idx, 0, N - 1), axis=0), 0)
        pieces.append(rows)
    ctx = jnp.concatenate(pieces, axis=1)  # [N, context_size * D]
    out = ctx @ W
    if bias is not None:
        out = out + _unwrap(bias)
    return out


def sequence_reshape(values, lengths, new_dim: int):
    """Re-chunk each sample's flattened elements into rows of new_dim
    (reference sequence_reshape_op); each sample's element count
    (lengths[b] * D) must be divisible BY new_dim — the reference op
    enforces this and so do we (silent merging would blend samples)."""
    v = _unwrap(values)
    lens = _unwrap(lengths)
    D = v.shape[1]
    try:
        bad = np.asarray((lens * D) % new_dim != 0)
        if bad.any():
            raise ValueError(
                f"sequence_reshape: sample element counts "
                f"{np.asarray(lens * D).tolist()} must divide by "
                f"new_dim={new_dim}")
    except (TypeError, jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError):
        pass  # traced lengths: caller guarantees divisibility
    out = v.reshape(-1, new_dim)
    new_len = lens * D // new_dim
    return out, new_len


def sequence_scatter(x, index_values, index_lengths, updates):
    """Scatter-add ragged updates into x (reference sequence_scatter_op):
    sample b adds updates-rows at column indices index[b] of x's row b."""
    xv = _unwrap(x)
    idx = _unwrap(index_values).reshape(-1)
    lens = _unwrap(index_lengths)
    upd = _unwrap(updates).reshape(-1)
    B = lens.shape[0]
    N = idx.shape[0]
    seg = segment_ids_from_lengths(lens, N)
    valid = seg < B
    rows = jnp.clip(seg, 0, B - 1)
    return xv.at[rows, jnp.clip(idx, 0, xv.shape[1] - 1)].add(
        jnp.where(valid, upd, 0))
