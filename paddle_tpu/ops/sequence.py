"""Sequence (ragged) ops (reference operators/sequence_ops/ — LoD-aware
seq_pool/pad/unpad/softmax/reverse/expand over LoDTensor, ~15k LoC C++).

TPU-first redesign: the reference's LoD (level-of-detail offset vectors +
dynamic-shaped kernels) becomes the **lengths / segment-ids convention**
with STATIC shapes, the representation XLA actually runs well:

* packed form: ``values [N, ...]`` + ``lengths [B]`` (sum == N) — the
  LoDTensor analog; ``segment_ids`` derived with static bounds;
* padded form: ``[B, T, ...]`` + lengths — what the compute wants.

Each op is a jnp/segment-op program (jax.ops.segment_* lower to one-pass
scatter-adds on TPU); packed↔padded conversion is a gather/scatter with
static output shape (maxlen is a required static argument when tracing).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["sequence_mask", "sequence_pad", "sequence_unpad",
           "sequence_pool", "sequence_softmax", "sequence_reverse",
           "sequence_expand", "sequence_first_step", "sequence_last_step",
           "segment_ids_from_lengths"]


def _unwrap(x):
    from ..core.tensor import Tensor

    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def segment_ids_from_lengths(lengths, total: int):
    """lengths [B] → segment ids [total] (rows past sum(lengths) get B —
    an out-of-range segment that jax segment ops drop)."""
    lengths = _unwrap(lengths)
    B = lengths.shape[0]
    starts = jnp.cumsum(lengths) - lengths
    # mark each segment start with +1 and prefix-sum (static-shape trick)
    marks = jnp.zeros((total + 1,), jnp.int32)
    marks = marks.at[starts].add(1)
    ids = jnp.cumsum(marks[:total]) - 1
    valid = jnp.arange(total) < jnp.sum(lengths)
    return jnp.where(valid, ids, B)


def sequence_mask(lengths, maxlen: int, dtype=jnp.bool_):
    """lengths [B] → mask [B, maxlen] (reference sequence_mask_op)."""
    lengths = _unwrap(lengths)
    return (jnp.arange(maxlen)[None, :] < lengths[:, None]).astype(dtype)


def sequence_pad(values, lengths, maxlen: int, pad_value=0.0):
    """Packed [N, ...] + lengths [B] → padded [B, maxlen, ...]
    (reference sequence_pad_op)."""
    values = _unwrap(values)
    lengths = _unwrap(lengths)
    B = lengths.shape[0]
    starts = jnp.cumsum(lengths) - lengths
    pos = jnp.arange(maxlen)
    idx = starts[:, None] + pos[None, :]              # [B, maxlen]
    take = jnp.clip(idx, 0, values.shape[0] - 1)
    out = values[take]                                # [B, maxlen, ...]
    mask = sequence_mask(lengths, maxlen)
    mshape = mask.shape + (1,) * (out.ndim - 2)
    return jnp.where(mask.reshape(mshape), out,
                     jnp.asarray(pad_value, out.dtype))


def sequence_unpad(padded, lengths):
    """Padded [B, T, ...] + lengths → packed [B*T, ...] with the valid rows
    front-packed and a valid-count (static total; reference
    sequence_unpad_op emits dynamic N — mask with the count)."""
    padded = _unwrap(padded)
    lengths = _unwrap(lengths)
    B, T = padded.shape[:2]
    flat = padded.reshape((B * T,) + padded.shape[2:])
    mask = sequence_mask(lengths, T).reshape(-1)
    # stable front-pack permutation: valid rows keep order, pads go last
    order = jnp.argsort(~mask, stable=True)
    return flat[order], jnp.sum(lengths)


def sequence_pool(values, lengths, pool_type: str = "sum"):
    """Packed [N, D] + lengths [B] → [B, D] (reference sequence_pool_op:
    sum/mean/max/min/sqrt/first/last)."""
    values = _unwrap(values)
    lengths = _unwrap(lengths)
    N = values.shape[0]
    B = lengths.shape[0]
    seg = segment_ids_from_lengths(lengths, N)
    pt = pool_type.lower()
    if pt == "sum":
        return jax.ops.segment_sum(values, seg, num_segments=B)
    if pt == "mean":
        s = jax.ops.segment_sum(values, seg, num_segments=B)
        return s / jnp.maximum(lengths, 1).astype(s.dtype)[:, None]
    if pt == "sqrt":
        s = jax.ops.segment_sum(values, seg, num_segments=B)
        return s / jnp.sqrt(jnp.maximum(lengths, 1).astype(s.dtype))[:, None]
    if pt == "max":
        return jax.ops.segment_max(values, seg, num_segments=B)
    if pt == "min":
        return jax.ops.segment_min(values, seg, num_segments=B)
    if pt == "first":
        starts = jnp.cumsum(lengths) - lengths
        return values[jnp.clip(starts, 0, N - 1)]
    if pt == "last":
        ends = jnp.cumsum(lengths) - 1
        return values[jnp.clip(ends, 0, N - 1)]
    raise ValueError(pool_type)


def sequence_first_step(values, lengths):
    return sequence_pool(values, lengths, "first")


def sequence_last_step(values, lengths):
    return sequence_pool(values, lengths, "last")


def sequence_softmax(values, lengths):
    """Packed [N] (or [N, 1]) + lengths → per-segment softmax (reference
    sequence_softmax_op)."""
    values = _unwrap(values)
    lengths = _unwrap(lengths)
    squeeze = values.ndim == 2 and values.shape[1] == 1
    v = values.reshape(-1)
    N = v.shape[0]
    B = lengths.shape[0]
    seg = segment_ids_from_lengths(lengths, N)
    vmax = jax.ops.segment_max(v, seg, num_segments=B + 1)
    v = v - vmax[seg]
    e = jnp.exp(v)
    valid = seg < B
    e = jnp.where(valid, e, 0.0)
    denom = jax.ops.segment_sum(e, seg, num_segments=B + 1)
    out = e / jnp.maximum(denom[seg], 1e-30)
    return out[:, None] if squeeze else out


def sequence_reverse(values, lengths):
    """Packed [N, ...]: reverse each segment in place (reference
    sequence_reverse_op — the Bi-RNN building block)."""
    values = _unwrap(values)
    lengths = _unwrap(lengths)
    N = values.shape[0]
    B = lengths.shape[0]
    seg = segment_ids_from_lengths(lengths, N)
    segc = jnp.clip(seg, 0, B - 1)
    starts = (jnp.cumsum(lengths) - lengths)[segc]
    ends = (jnp.cumsum(lengths) - 1)[segc]
    pos = jnp.arange(N)
    src = jnp.where(seg < B, (starts + (ends - pos)).astype(pos.dtype), pos)
    return values[jnp.clip(src, 0, N - 1)]


def sequence_expand(values, lengths, repeat_lengths, total_out: int):
    """Repeat segment i of ``values`` ``repeat_lengths[i]`` times
    (reference sequence_expand_op).  ``total_out`` is the static output
    row count (sum(lengths * repeats) padded up)."""
    values = _unwrap(values)
    lengths = _unwrap(lengths)
    repeats = _unwrap(repeat_lengths)
    B = lengths.shape[0]
    # output segment structure: segment i appears repeats[i] times, each
    # copy with lengths[i] rows (jnp.repeat pads the tail past
    # sum(repeats); padded tail rows are masked out below)
    out_seg_len = jnp.repeat(lengths, repeats, total_repeat_length=total_out)
    src_seg = jnp.repeat(jnp.arange(B), repeats,
                         total_repeat_length=total_out)
    ids = segment_ids_from_lengths(out_seg_len, total_out)
    idsc = jnp.clip(ids, 0, total_out - 1)
    starts_out = (jnp.cumsum(out_seg_len) - out_seg_len)[idsc]
    offs = jnp.arange(total_out) - starts_out
    starts_in = jnp.cumsum(lengths) - lengths
    src = starts_in[jnp.clip(src_seg[idsc], 0, B - 1)] + offs
    n_rows = jnp.sum(lengths * repeats)  # true output rows
    row_valid = jnp.arange(total_out) < n_rows
    out = values[jnp.clip(src, 0, values.shape[0] - 1)]
    vshape = (total_out,) + (1,) * (out.ndim - 1)
    return jnp.where(row_valid.reshape(vshape), out, jnp.zeros_like(out))
