"""Ring attention — context parallelism over a mesh axis.

Capability beyond the reference: xymyeah/Paddle has no sequence/context
parallelism (`grep 'ring.attention|context.parallel|sequence_parallel'` over
python/paddle/distributed is empty — SURVEY.md §2.3); long-context training is
a required capability of the TPU build (BASELINE north star).

Design (RingAttention, Liu et al. — blockwise attention + ring passing):
q/k/v live sharded on the sequence dim over the ``axis`` ring.  Each of the
``ring_size`` steps computes blockwise attention of the LOCAL q chunk against
the k/v chunk currently held, merges it into a running (max, denominator,
accumulator) online-softmax state, then passes k/v to the next ring neighbour
via ``lax.ppermute`` — an ICI neighbour hop that XLA overlaps with the
compute.  The full [T, T] score matrix never exists; per-device memory is
O(T_local * T_local) per step (and the step loop is rematerialized), or
O(T_local * sub_block) — masks included — with ``sub_block`` set (the
flash recurrence over kv sub-chunks; see ``_chunk_attend``).

Causality uses GLOBAL positions: chunk c holds rows [c*Tl, (c+1)*Tl);
diagonal pairs get a triangular mask, off-diagonal pairs an all-or-nothing
one.  Note every ring step still computes its block einsum even when fully
masked — causal runs carry ~2x the minimal FLOPs; masked scores only zero
out through the where.  For balanced causal work use
:func:`ring_attention_zigzag` below (2x less per-device compute).

Differentiable by construction (scan + ppermute both have transposes), so it
composes with jax.grad/pipeline/TP with no custom VJP.
"""
from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax

from ..compat import axis_size as _axis_size

_NEG = -1e30


def _block_attend(q, k, v, scale, mask=None):
    """One dense score block: returns (scores-max m, exp-sum l, weighted
    acc) for merging.  q [B,Tq,H,D]; k/v [B,Tk,Hkv,D] where Hkv may be a
    DIVISOR of H (grouped-query attention: q head h shares kv head
    h // (H//Hkv), matching gpt._gqa_qkv's repeat layout) — the group
    dim folds into the einsums so the shared kv heads are never
    materialized H/Hkv times (and never ride the ring repeated)."""
    B, Tq, H, hd = q.shape
    Hkv = k.shape[2]
    if H % Hkv:
        raise ValueError(f"kv heads {Hkv} must divide q heads {H}")
    g = H // Hkv  # 1 = plain MHA; the grouped form is identical math
    Tk = k.shape[1]
    qg = q.reshape(B, Tq, Hkv, g, hd).astype(jnp.float32)
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg,
                   k.astype(jnp.float32)) * scale   # [B,Hkv,g,Tq,Tk]
    s = s.reshape(B, H, Tq, Tk)
    if mask is not None:
        s = jnp.where(mask, s, _NEG)
    m = jnp.max(s, axis=-1)                          # [B,H,Tq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                          # [B,H,Tq]
    pg = p.reshape(B, Hkv, g, Tq, Tk)
    acc = jnp.einsum("bkgqs,bskd->bkgqd", pg,
                     v.astype(jnp.float32)).reshape(B, H, Tq, hd)
    return m, l, acc


def _chunk_attend(q, k, v, scale, pos=None, sub: int | None = None):
    """Blockwise partial attention with an optional causal mask given as
    POSITIONS, not a dense array: ``pos = (q_pos [Tq], k_pos [Tk])``
    global position ids; rows attend columns with q_pos >= k_pos.

    ``sub`` bounds the score temp: instead of one [B,H,Tq,Tk] block, the
    kv rows are walked in sub-chunks of that many rows with an inner
    online-softmax scan (the flash-attention recurrence in pure XLA), so
    the largest live tensor is [B,H,Tq,sub] — masks included: each
    [Tq, sub] mask slice is built inside the scan body from the linear-
    size position ids, never as one [Tq, Tk] array.  This is what keeps
    per-device memory flat as the LOCAL chunk grows — the ring bounds
    memory in the ring size R, sub-blocking bounds it in Tl."""
    if sub is not None and sub <= 0:
        raise ValueError(f"sub_block must be positive (got {sub})")
    if sub is None or sub >= k.shape[1]:
        mask = (None if pos is None else
                (pos[0][:, None] >= pos[1][None, :])[None, None])
        return _block_attend(q, k, v, scale, mask)
    B, Tk, Hkv, D = k.shape  # Hkv may be a divisor of q's head count
    if Tk % sub:
        raise ValueError(f"sub_block {sub} must divide the kv chunk {Tk}")
    n = Tk // sub
    Tq = q.shape[1]
    ks = jnp.moveaxis(k.reshape(B, n, sub, Hkv, D), 1, 0)
    vs = jnp.moveaxis(v.reshape(B, n, sub, Hkv, D), 1, 0)
    kp = None if pos is None else pos[1].reshape(n, sub)

    def body(carry, xs):
        m_acc, l_acc, o_acc = carry
        if kp is None:
            kk, vv = xs
            mm = None
        else:
            kk, vv, kps = xs
            mm = (pos[0][:, None] >= kps[None, :])[None, None]
        st = _block_attend(q, kk, vv, scale, mm)
        return _merge(m_acc, l_acc, o_acc, *st), None

    Hq = q.shape[2]  # may exceed k's Hkv under grouped-query attention
    m0 = jnp.full((B, Hq, Tq), _NEG, jnp.float32)
    l0 = jnp.zeros((B, Hq, Tq), jnp.float32)
    o0 = jnp.zeros((B, Hq, Tq, D), jnp.float32)
    xs = (ks, vs) if kp is None else (ks, vs, kp)
    # checkpoint the inner body too: without it the inner scan's VJP
    # stacks per-sub-chunk score residuals back up to ~[B,H,Tq,Tk] —
    # defeating the cap exactly where it matters (training).  Recomputing
    # scores per sub-chunk in the backward is the flash-attention trade.
    # prevent_cse=False: the scan structure supplies the CSE protection,
    # and the default's optimization barriers hang the axon TPU compile
    # (text/gpt.py, round-3 evidence).
    (m, l, acc), _ = lax.scan(jax.checkpoint(body, prevent_cse=False),
                              (m0, l0, o0), xs)
    return m, l, acc


def _merge(m_acc, l_acc, o_acc, m_new, l_new, acc_new):
    """Online-softmax merge of one blockwise partial into the running
    (max, denominator, accumulator) state."""
    m_next = jnp.maximum(m_acc, m_new)
    a_old = jnp.exp(m_acc - m_next)
    a_new = jnp.exp(m_new - m_next)
    return (m_next, l_acc * a_old + l_new * a_new,
            o_acc * a_old[..., None] + acc_new * a_new[..., None])


def ring_attention(q, k, v, axis: str, causal: bool = True, scale=None,
                   sub_block: int | None = None):
    """Sequence-sharded attention inside a ``shard_map`` region.

    q,k,v: LOCAL chunks [B, T_local, H, D], sequence dim sharded over
    ``axis`` (ring of size R; global T = R * T_local).  k/v may carry
    Hkv < H heads (grouped-query attention): the UNREPEATED shared heads
    ride the ring — H/Hkv less KV traffic per hop — and the group dim
    folds into the block einsums.  Returns the local output chunk
    [B, T_local, H, D].  ``sub_block`` caps the live score
    temp at [B,H,Tl,sub_block] (see _chunk_attend) — required for long
    local chunks, where a full [Tl,Tl] block would defeat the point of
    the ring.
    """
    B, Tl, H, D = q.shape
    scale = scale if scale is not None else 1.0 / (D**0.5)
    R = _axis_size(axis)
    my = lax.axis_index(axis)
    perm = [(i, (i + 1) % R) for i in range(R)]  # pass kv forward round-robin

    rows = jnp.arange(Tl)

    def step(carry, r):
        k_cur, v_cur, m_acc, l_acc, o_acc = carry
        src = (my - r) % R  # which chunk we hold at ring step r
        if causal:
            # global causal positions of q-chunk `my` and kv-chunk `src`
            # (linear size; the dense mask is built blockwise downstream)
            pos = (my * Tl + rows, src * Tl + rows)
        else:
            pos = None
        m_new, l_new, acc_new = _chunk_attend(q, k_cur, v_cur, scale, pos,
                                              sub=sub_block)
        # online-softmax merge of the partial result into the running state
        m_next, l_next, o_next = _merge(m_acc, l_acc, o_acc,
                                        m_new, l_new, acc_new)
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        return (k_nxt, v_nxt, m_next, l_next, o_next), None

    m0 = jnp.full((B, H, Tl), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    o0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    body = jax.checkpoint(step)  # remat each ring step: O(Tl*Tl) live, not R×
    (k_f, v_f, m_f, l_f, o_f), _ = lax.scan(
        body, (k, v, m0, l0, o0), jnp.arange(R))
    l_safe = jnp.where(l_f == 0.0, 1.0, l_f)
    out = (o_f / l_safe[..., None]).astype(q.dtype)   # [B,H,Tl,D]
    return jnp.swapaxes(out, 1, 2)                    # [B,Tl,H,D]


# ---------------------------------------------------------------------------
# zigzag layout: causal load balancing
# ---------------------------------------------------------------------------
# With the contiguous layout above, causality wastes ~half the ring's
# compute: at every step roughly half the devices hold a fully-masked
# (q-chunk, kv-chunk) pair, but the ring is lockstep, so they wait on the
# devices that do have work.  The zigzag layout (as popularized by
# Megatron-LM context parallelism / llama3 training) splits the sequence
# into 2R chunks and gives rank i the PAIR (i, 2R-1-i).  Then at every ring
# step each rank has exactly two unmasked blocks to compute — the high
# chunk 2R-1-i attends every kv chunk it meets, and exactly one of
# {low-vs-low, high-vs-high} is live depending on the ring direction — so
# causal compute is T^2/(2R) scores per device: perfect 1/R scaling, 2x
# better than the contiguous layout's worst-case T^2/R.


def zigzag_permutation(T: int, R: int):
    """Global row order placing chunk pair (i, 2R-1-i) on rank i.

    Returns int32 index array ``perm`` with ``x_zig = x[perm]``; chunks are
    T/(2R) rows each.  Apply to tokens AND anything position-aligned
    (labels, position ids) BEFORE sharding the sequence dim over the ring
    axis; invert with :func:`zigzag_inverse`."""
    import numpy as np

    if T % (2 * R):
        raise ValueError(f"zigzag needs seq len divisible by 2R "
                         f"(T={T}, R={R})")
    Tc = T // (2 * R)
    idx = []
    for i in range(R):
        idx.extend(range(i * Tc, (i + 1) * Tc))            # low chunk i
        idx.extend(range((2 * R - 1 - i) * Tc,
                         (2 * R - i) * Tc))                # high chunk
    return np.asarray(idx, np.int32)


def zigzag_inverse(T: int, R: int):
    """Inverse permutation: ``x == x_zig[zigzag_inverse(T, R)]``."""
    import numpy as np

    perm = zigzag_permutation(T, R)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(T, dtype=np.int32)
    return inv


def ring_attention_zigzag(q, k, v, axis: str, scale=None,
                          sub_block: int | None = None):
    """Causal ring attention over ``axis`` in the zigzag layout.

    q,k,v: LOCAL [B, 2*Tc, H, D] — rows [:Tc] are global chunk ``i`` (the
    rank index), rows [Tc:] global chunk ``2R-1-i``, i.e. the input
    sequence was reordered with :func:`zigzag_permutation` before sharding.
    As with :func:`ring_attention`, k/v may carry Hkv < H grouped-query
    heads and circulate unrepeated.
    Returns the local output in the same layout (undo at the end with
    :func:`zigzag_inverse`).  Causal only — zigzag exists to balance the
    causal mask; use :func:`ring_attention` for the non-causal case.
    """
    B, T2, H, D = q.shape
    if T2 % 2:
        raise ValueError("zigzag local chunk must hold an even row count")
    Tc = T2 // 2
    scale = scale if scale is not None else 1.0 / (D**0.5)
    R = _axis_size(axis)
    my = lax.axis_index(axis)
    perm = [(i, (i + 1) % R) for i in range(R)]

    qa, qb = q[:, :Tc], q[:, Tc:]      # global chunks my, 2R-1-my
    rows = jnp.arange(Tc)
    diag = (rows, rows)  # same-chunk positions → within-chunk tril mask

    def split(kv):
        return kv[:, :Tc], kv[:, Tc:]

    # step 0 (j == my): qa sees its own diagonal; qb sees ka fully
    # (2R-1-my > my for every rank) plus its own diagonal
    ka, kb = split(k)
    va, vb = split(v)
    st_a = _chunk_attend(qa, ka, va, scale, diag, sub=sub_block)
    st_b = _merge(*_chunk_attend(qb, ka, va, scale, sub=sub_block),
                  *_chunk_attend(qb, kb, vb, scale, diag, sub=sub_block))

    def step(carry, r):
        k_cur, v_cur, st_a, st_b = carry
        k_cur = lax.ppermute(k_cur, axis, perm)
        v_cur = lax.ppermute(v_cur, axis, perm)
        j = (my - r) % R                   # rank whose kv we now hold
        ka, kb = split(k_cur)
        va, vb = split(v_cur)
        # always live: high q-chunk vs low kv-chunk (2R-1-my >= R > j)
        st_b2 = _merge(*st_b, *_chunk_attend(qb, ka, va, scale,
                                             sub=sub_block))
        # exactly one of the remaining pairs is causally live:
        #   j < my:  low-vs-low  (my > j)       — update st_a
        #   j > my:  high-vs-high (2R-1-my > 2R-1-j) — update st_b
        st_a2, st_b2 = lax.cond(
            j < my,
            lambda sa, sb: (_merge(*sa, *_chunk_attend(qa, ka, va, scale,
                                                       sub=sub_block)),
                            sb),
            lambda sa, sb: (sa,
                            _merge(*sb, *_chunk_attend(qb, kb, vb, scale,
                                                       sub=sub_block))),
            st_a, st_b2)
        return (k_cur, v_cur, st_a2, st_b2), None

    body = jax.checkpoint(step)
    (k_f, v_f, st_a, st_b), _ = lax.scan(
        body, (k, v, st_a, st_b), jnp.arange(1, R))

    def finish(st):
        m_f, l_f, o_f = st
        l_safe = jnp.where(l_f == 0.0, 1.0, l_f)
        out = (o_f / l_safe[..., None]).astype(q.dtype)  # [B,H,Tc,D]
        return jnp.swapaxes(out, 1, 2)                   # [B,Tc,H,D]

    return jnp.concatenate([finish(st_a), finish(st_b)], axis=1)
