"""Ring attention — context parallelism over a mesh axis.

Capability beyond the reference: xymyeah/Paddle has no sequence/context
parallelism (`grep 'ring.attention|context.parallel|sequence_parallel'` over
python/paddle/distributed is empty — SURVEY.md §2.3); long-context training is
a required capability of the TPU build (BASELINE north star).

Design (RingAttention, Liu et al. — blockwise attention + ring passing):
q/k/v live sharded on the sequence dim over the ``axis`` ring.  Each of the
``ring_size`` steps computes blockwise attention of the LOCAL q chunk against
the k/v chunk currently held, merges it into a running (max, denominator,
accumulator) online-softmax state, then passes k/v to the next ring neighbour
via ``lax.ppermute`` — an ICI neighbour hop that XLA overlaps with the
compute.  The full [T, T] score matrix never exists; per-device memory is
O(T_local * T_local) per step (and the step loop is rematerialized).

Causality uses GLOBAL positions: chunk c holds rows [c*Tl, (c+1)*Tl);
diagonal pairs get a triangular mask, off-diagonal pairs an all-or-nothing
one.  Note every ring step still computes its block einsum even when fully
masked — causal runs carry ~2x the minimal FLOPs (no zigzag load-balancing
yet); masked scores only zero out through the where.

Differentiable by construction (scan + ppermute both have transposes), so it
composes with jax.grad/pipeline/TP with no custom VJP.
"""
from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30


def _chunk_attend(q, k, v, scale, mask=None):
    """One blockwise partial attention: returns (scores-max m, exp-sum l,
    weighted acc) for merging.  q [B,Tq,H,D], k/v [B,Tk,H,D]."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG)
    m = jnp.max(s, axis=-1)                      # [B,H,Tq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                      # [B,H,Tq]
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return m, l, acc


def ring_attention(q, k, v, axis: str, causal: bool = True, scale=None):
    """Sequence-sharded attention inside a ``shard_map`` region.

    q,k,v: LOCAL chunks [B, T_local, H, D], sequence dim sharded over
    ``axis`` (ring of size R; global T = R * T_local).  Returns the local
    output chunk [B, T_local, H, D].
    """
    B, Tl, H, D = q.shape
    scale = scale if scale is not None else 1.0 / (D**0.5)
    R = lax.axis_size(axis)
    my = lax.axis_index(axis)
    perm = [(i, (i + 1) % R) for i in range(R)]  # pass kv forward round-robin

    rows = jnp.arange(Tl)

    def step(carry, r):
        k_cur, v_cur, m_acc, l_acc, o_acc = carry
        src = (my - r) % R  # which chunk we hold at ring step r
        if causal:
            # global causal mask between q-chunk `my` and kv-chunk `src`
            q_pos = my * Tl + rows                     # [Tl]
            k_pos = src * Tl + rows                    # [Tl]
            mask = q_pos[:, None] >= k_pos[None, :]    # [Tq, Tk]
            mask = mask[None, None]                    # [1,1,Tq,Tk]
        else:
            mask = None
        m_new, l_new, acc_new = _chunk_attend(q, k_cur, v_cur, scale, mask)
        # online-softmax merge of the partial result into the running state
        m_next = jnp.maximum(m_acc, m_new)
        a_old = jnp.exp(m_acc - m_next)
        a_new = jnp.exp(m_new - m_next)
        l_next = l_acc * a_old + l_new * a_new
        o_next = o_acc * a_old[..., None] + acc_new * a_new[..., None]
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        return (k_nxt, v_nxt, m_next, l_next, o_next), None

    m0 = jnp.full((B, H, Tl), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    o0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    body = jax.checkpoint(step)  # remat each ring step: O(Tl*Tl) live, not R×
    (k_f, v_f, m_f, l_f, o_f), _ = lax.scan(
        body, (k, v, m0, l0, o0), jnp.arange(R))
    l_safe = jnp.where(l_f == 0.0, 1.0, l_f)
    out = (o_f / l_safe[..., None]).astype(q.dtype)   # [B,H,Tl,D]
    return jnp.swapaxes(out, 1, 2)                    # [B,Tl,H,D]
