"""Pallas TPU W4A16 dequant-matmul — fused weight-only-int4 decode GEMM.

Weight-only int4 decode params (text/woq.py) store two signed nibbles per
int8 byte, half-split along the input dim (low nibble = rows [0, K/2),
high = rows [K/2, K)) with group-wise scales.  The XLA path must
materialize the dequantized bf16 [K, M] weight before the matmul — the
unpack (shift + concat) and group-scale reshape are producers XLA does
not fuse into a dot — so the HBM traffic is bf16-sized and the entire
point of the 4-bit format (weight-BYTES per decoded token) is lost.
Measured on the v5e through the serving bench, packed int4 decoded at
0.78x the bf16 rate before the half-split relayout.

This kernel streams the PACKED bytes through VMEM instead: each grid
step loads an int8 [BKp, BM] block (4-bit pair rows), sign-extends both
nibbles with two arithmetic shifts, applies the per-group scales in the
activation dtype (bit-identical dequant math to ``woq.w``), and feeds
the MXU with two [N, BKp] x [BKp, BM] dots accumulated in float32 —
HBM reads the int4 bytes ONCE and never writes a dequantized copy.

Forward-only by design: packed int4 weights exist only on the frozen
decode path (training and LoRA fine-tuning keep float masters).

Availability probing + XLA fallback follow ops/flash_attention.py; the
routing gate lives in ``woq.mm`` (env ``PADDLE_TPU_W4_KERNEL``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_FALLBACK: dict = {}
_INTERPRET = False  # tests flip this to run the kernel on CPU (interpret)

_N_CAP = 256  # decode/serving batches; prefill-sized N stays on XLA


def _blocks(N: int, Kp: int, M: int, gs: int):
    """(BKp, BM) or None when the shapes don't tile.

    BKp is a block of PACKED rows (= BKp original rows per nibble half);
    it must be a multiple of the scale group size so a block's rows use
    whole groups, and divide the packed row count.  M needs lane
    alignment."""
    if M % 128 or Kp % 8 or N > _N_CAP:
        return None
    bm = 256 if M % 256 == 0 else 128
    for cand in (512, 256, 128, 64, 32, 16, 8):
        if Kp % cand == 0 and cand % gs == 0:
            return cand, bm
    return None


def _xla_w4(x, packed, scale):
    """Reference path: dequant exactly like woq.w's packed branch, then
    one matmul.  Also the kernel's parity oracle."""
    dt = x.dtype
    lo = jnp.right_shift(jnp.left_shift(packed, 4), 4)
    hi = jnp.right_shift(packed, 4)
    K = packed.shape[0] * 2
    G = scale.shape[0]
    q = jnp.concatenate([lo, hi], axis=0)
    grouped = q.reshape(G, K // G, -1)
    w = (grouped.astype(dt) * scale.astype(dt)).reshape(K, -1)
    return x @ w


def _probe(dtype, N: int, Kp: int, M: int, gs: int) -> bool:
    """True = fall back; probes the exact tiling the real call uses."""
    from ._pallas_probe import probe_once

    def thunk():
        x = jax.device_put(jnp.zeros((N, Kp * 2), dtype))
        pk = jax.device_put(jnp.zeros((Kp, M), jnp.int8))
        s = jax.device_put(jnp.ones((Kp * 2 // gs, 1, M), jnp.float32))
        return _w4_call(x, pk, s, gs)

    return probe_once(
        _FALLBACK,
        (jnp.dtype(dtype).name, int(N), int(Kp), int(M), int(gs)), thunk)


def w4_matmul(x, packed, scale):
    """x [..., K] @ dequant(packed [K/2, M] int8, scale [G, 1, M]) →
    [..., M] in x.dtype.  Rows pad to the sublane multiple; falls back
    to the XLA dequant+matmul when the Pallas path is unavailable
    (non-TPU backend, unaligned shapes, prefill-sized N)."""
    lead = x.shape[:-1]
    K = x.shape[-1]
    Kp, M = packed.shape
    G = scale.shape[0]
    if K != 2 * Kp or K % G:
        raise ValueError(f"shape mismatch: x[..., {K}], packed[{Kp}, {M}],"
                         f" scale[{G}, ...]")
    gs = K // G
    N = 1
    for d in lead:
        N *= d
    x2 = x.reshape(N, K)
    Np = -(-N // 8) * 8
    blk = _blocks(Np, Kp, M, gs)
    if blk is None or (not _INTERPRET
                       and _probe(x.dtype, Np, Kp, M, gs)):
        return _xla_w4(x2, packed, scale).reshape(*lead, M)
    if Np != N:
        x2 = jnp.pad(x2, ((0, Np - N), (0, 0)))
    return _w4_call(x2, packed, scale, gs)[:N].reshape(*lead, M)


def _w4_call(x, packed, scale, gs):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, K = x.shape
    Kp, M = packed.shape
    BKp, BM = _blocks(N, Kp, M, gs)
    nk, nm = Kp // BKp, M // BM
    G2 = BKp // gs  # scale groups per block (per nibble half)
    dt = x.dtype

    # half-split layout: low nibbles hold original rows [0, K/2), high
    # [K/2, K) — pass each half of x (and of the scale table) as its own
    # contiguous operand so every BlockSpec is a plain strided slice
    x_lo, x_hi = x[:, :Kp], x[:, Kp:]
    s_lo, s_hi = scale[:Kp // gs], scale[Kp // gs:]

    def kernel(xlo_ref, xhi_ref, pk_ref, slo_ref, shi_ref, o_ref, acc):
        k = pl.program_id(1)

        @pl.when(k == 0)
        def _init():
            acc[...] = jnp.zeros_like(acc)

        # Mosaic can't legalize arith.shli/shrsi on i8 vectors (v5e cert
        # failure, window 3): widen to i32 and sign-extend the nibbles
        # with 28-bit shift pairs — value-identical to the i8 math in
        # _xla_w4 (shl-28 + ashr-28 == keep low nibble with sign)
        pk = pk_ref[...].astype(jnp.int32)
        lo = jnp.right_shift(jnp.left_shift(pk, 28), 28)
        hi = jnp.right_shift(pk, 4)

        def dq(q, s_ref):
            # dequant in the activation dtype — bit-identical to woq.w
            s = s_ref[...].astype(dt)          # [G2, 1, BM]
            qg = q.astype(dt).reshape(G2, gs, BM)
            return (qg * s).reshape(BKp, BM)

        acc[...] += (
            jnp.dot(xlo_ref[...], dq(lo, slo_ref),
                    preferred_element_type=jnp.float32)
            + jnp.dot(xhi_ref[...], dq(hi, shi_ref),
                      preferred_element_type=jnp.float32))

        @pl.when(k == nk - 1)
        def _finish():
            o_ref[...] = acc[...].astype(o_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid=(nm, nk),  # k innermost: each out tile's reduction completes
        in_specs=[
            pl.BlockSpec((N, BKp), lambda m, k: (0, k)),
            pl.BlockSpec((N, BKp), lambda m, k: (0, k)),
            pl.BlockSpec((BKp, BM), lambda m, k: (k, m)),
            pl.BlockSpec((G2, 1, BM), lambda m, k: (k, 0, m)),
            pl.BlockSpec((G2, 1, BM), lambda m, k: (k, 0, m)),
        ],
        out_specs=pl.BlockSpec((N, BM), lambda m, k: (0, m)),
        out_shape=jax.ShapeDtypeStruct((N, M), dt),
        scratch_shapes=[pltpu.VMEM((N, BM), jnp.float32)],
        interpret=_INTERPRET,
    )(x_lo, x_hi, packed, s_lo, s_hi)
