"""Single source of truth for the kernel sources behind FUSED_KERNELS_OK.json.

Both gates key on this list:
- ``tools/check_flash_tpu.py`` hashes these files into the resume-cache
  signature (a kernel edit voids partial certification progress);
- ``bench.py::_fused_kernels_ok`` ignores a certification marker older than
  any of these files (certification does not survive a kernel edit).

Two hand-maintained copies of this list drifted in round 4 (the bench gate
missed ``attention.py``) — hence this module.  Keep it import-light: the
bench gate runs before the benchmark process decides which backend to pin.
"""

KERNEL_SOURCE_FILES = (
    "fused_norm.py",
    "fused_ce.py",
    "flash_attention.py",
    "_pallas_probe.py",
    "attention.py",
    "woq_matmul.py",
)
