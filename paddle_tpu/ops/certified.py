"""Single source of truth for the kernel sources behind FUSED_KERNELS_OK.json.

Both gates key on this list:
- ``tools/check_flash_tpu.py`` hashes these files into the resume-cache
  signature (a kernel edit voids partial certification progress);
- ``bench.py::_fused_kernels_ok`` ignores a certification marker older than
  any of these files (certification does not survive a kernel edit).

Two hand-maintained copies of this list drifted in round 4 (the bench gate
missed ``attention.py``) — hence this module.  Keep it import-light: the
bench gate runs before the benchmark process decides which backend to pin.
"""

KERNEL_SOURCE_FILES = (
    "fused_norm.py",
    "fused_ce.py",
    "flash_attention.py",
    "_pallas_probe.py",
    "attention.py",
    "woq_matmul.py",
    "decode_attention.py",
)

# Certification FAMILIES (round-5): the marker records a source signature
# per family, so a failure or edit in one kernel can no longer gate the
# others — the training rungs need only TRAINING_FAMILIES, while the
# serving W4 kernel needs "w4".  Family values are ops/-relative files
# (the kernel + its parity oracle); SHARED files and the checker script
# fold into every family's signature.
KERNEL_FAMILIES = {
    "flash": ("flash_attention.py", "attention.py"),
    "fused_ln": ("fused_norm.py",),
    "fused_ce": ("fused_ce.py",),
    "w4": ("woq_matmul.py",),
    # split-KV flash-decode + quantized-KV format: kernel, XLA oracle,
    # and quantize/dequantize all live in decode_attention.py; the
    # production einsum fallback it must match lives in generate.py
    "decode": ("decode_attention.py",),
}
SHARED_KERNEL_FILES = ("_pallas_probe.py",)
TRAINING_FAMILIES = ("flash", "fused_ln", "fused_ce")
# repo-root-relative extra oracle sources a family's parity math uses
FAMILY_EXTRA_SOURCES = {"w4": ("paddle_tpu/text/woq.py",),
                        "decode": ("paddle_tpu/text/generate.py",)}

# the families must exactly cover the registry — the same no-drift rule
# the registry itself exists for
assert (set(sum((list(v) for v in KERNEL_FAMILIES.values()),
               list(SHARED_KERNEL_FILES)))
        == set(KERNEL_SOURCE_FILES)), "KERNEL_FAMILIES drifted"
