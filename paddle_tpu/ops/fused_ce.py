"""Pallas TPU fused softmax cross-entropy — forward + backward, blockwise
over the vocabulary.

The plain XLA path (jax.nn.log_softmax + take_along_axis) materializes a
float32 [N, V] log-probability tensor in HBM plus its cotangent — for a
GPT-class vocab (V ≈ 50k) that is the single largest activation in the
model.  This kernel streams vocab blocks through VMEM instead:

* forward: one online-softmax sweep per row block keeps a running
  max/denominator (exactly flash attention's trick applied to the loss
  head) and picks out the label logit with an in-block iota compare — HBM
  traffic is one read of the logits, and the residuals are two [N] vectors
  (logsumexp and label logit), not an [N, V] softmax;
* backward: dlogits[i, j] = (exp(x[i,j] - lse[i]) - 1{j == label[i]}) *
  dloss[i], recomputed blockwise from the same logits — the softmax is
  never stored.

Statistics and accumulation are float32 regardless of the logits dtype.

Reference parity: this is the loss-head half of the reference's
softmax_with_cross_entropy_op.cu (fused softmax+CE kernel); the
vocab-sharded collective variant (c_softmax_with_cross_entropy, used by
ParallelCrossEntropy) stays on the XLA+psum path in
distributed/megatron.py — there the shard-local max/sum reductions are
tiny and the collectives dominate, so a Pallas body buys nothing.

Availability probing + XLA fallback follow ops/flash_attention.py.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ._pallas_probe import pad_rows as _pad_rows
from ._pallas_probe import row_block as _row_block_for

_FALLBACK: dict = {}
_INTERPRET = False  # tests flip this to run the kernels on CPU (interpret)


def _blocks(N: int, V: int):
    bv = None
    for cand in (2048, 1024, 512, 256, 128):
        if V % cand == 0:
            bv = cand
            break
    if bv is None:
        return None
    bn = _row_block_for(N, bv)
    return None if bn is None else (bn, bv)


def _xla_ce(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]


def _probe(dtype, V: int, BN: int) -> bool:
    """True = fall back.  Probes the SAME kernel configuration the real
    call will use (the row-block size changes the Mosaic lowering);
    shared scaffolding in ops/_pallas_probe.py."""
    from ._pallas_probe import probe_once

    def thunk():
        x = jax.device_put(jnp.zeros((BN, V), dtype))
        lbl = jax.device_put(jnp.zeros((BN,), jnp.int32))
        loss, vjp_fn = jax.vjp(lambda a: _fused_ce(a, lbl), x)
        return vjp_fn(loss)

    return probe_once(_FALLBACK, (jnp.dtype(dtype).name, int(V), int(BN)),
                      thunk)


def fused_softmax_ce(logits, labels):
    """Per-row cross-entropy: logits [..., V], int labels [...] → loss
    [...] float32.  Rows are padded up to the kernel's row-block multiple
    (pad rows' cotangents are zero by construction, so dlogits stays
    exact — without this, GPT-style row counts like B*(T-1) would
    silently miss the fused path); falls back to the XLA expression when
    the Pallas path is unavailable (non-TPU backend, unaligned vocab)."""
    V = logits.shape[-1]
    lead = logits.shape[:-1]
    N = 1
    for d in lead:
        N *= d
    l2 = logits.reshape(N, V)
    lbl = labels.reshape(N).astype(jnp.int32)
    Np = _pad_rows(N)
    blk = _blocks(Np, V)
    if blk is None or (not _INTERPRET and _probe(logits.dtype, V, blk[0])):
        return _xla_ce(l2, lbl).reshape(lead)
    if Np != N:
        l2 = jnp.pad(l2, ((0, Np - N), (0, 0)))
        lbl = jnp.pad(lbl, (0, Np - N))
    return _fused_ce(l2, lbl)[:N].reshape(lead)


@jax.custom_vjp
def _fused_ce(logits, labels):
    loss, _ = _ce_fwd_impl(logits, labels)
    return loss


def _ce_fwd(logits, labels):
    loss, lse = _ce_fwd_impl(logits, labels)
    return loss, (logits, labels, lse)


def _ce_bwd(res, dloss):
    import numpy as np

    logits, labels, lse = res
    # integer primal → float0 cotangent (jax's "no gradient" dtype)
    dlbl = np.zeros(labels.shape, jax.dtypes.float0)
    return _ce_bwd_impl(logits, labels, lse, dloss), dlbl


_fused_ce.defvjp(_ce_fwd, _ce_bwd)


_NEG = -1e30


def _ce_fwd_impl(logits, labels):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, V = logits.shape
    BN, BV = _blocks(N, V)
    nv = V // BV
    lbl2 = labels.reshape(N, 1)

    def kernel(x_ref, lbl_ref, lse_ref, pick_ref, m_scr, l_scr, p_scr):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            m_scr[:] = jnp.full_like(m_scr, _NEG)
            l_scr[:] = jnp.zeros_like(l_scr)
            p_scr[:] = jnp.zeros_like(p_scr)

        xb = x_ref[...].astype(jnp.float32)
        cols = j * BV + jax.lax.broadcasted_iota(jnp.int32, (BN, BV), 1)
        hit = cols == lbl_ref[...]  # [BN, 1] broadcasts over the block
        p_scr[:, 0] += jnp.sum(jnp.where(hit, xb, 0.0), axis=1)
        m_prev = m_scr[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(xb, axis=1))
        l_scr[:, 0] = l_scr[:, 0] * jnp.exp(m_prev - m_cur) \
            + jnp.sum(jnp.exp(xb - m_cur[:, None]), axis=1)
        m_scr[:, 0] = m_cur

        @pl.when(j == nv - 1)
        def _finish():
            lse_ref[:, 0] = m_scr[:, 0] + jnp.log(l_scr[:, 0])
            pick_ref[:, 0] = p_scr[:, 0]

    lse, pick = pl.pallas_call(
        kernel,
        grid=(N // BN, nv),
        in_specs=[
            pl.BlockSpec((BN, BV), lambda i, j: (i, j)),
            pl.BlockSpec((BN, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BN, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((BN, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BN, 1), jnp.float32),
            pltpu.VMEM((BN, 1), jnp.float32),
            pltpu.VMEM((BN, 1), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(logits, lbl2)
    return (lse - pick)[:, 0], lse


def _ce_bwd_impl(logits, labels, lse, dloss):
    from jax.experimental import pallas as pl

    N, V = logits.shape
    BN, BV = _blocks(N, V)
    lbl2 = labels.reshape(N, 1)
    dl2 = dloss.reshape(N, 1).astype(jnp.float32)

    def kernel(x_ref, lbl_ref, lse_ref, dl_ref, dx_ref):
        j = pl.program_id(1)
        xb = x_ref[...].astype(jnp.float32)
        p = jnp.exp(xb - lse_ref[...])
        cols = j * BV + jax.lax.broadcasted_iota(jnp.int32, (BN, BV), 1)
        onehot = (cols == lbl_ref[...]).astype(jnp.float32)
        dx_ref[...] = ((p - onehot) * dl_ref[...]).astype(dx_ref.dtype)

    dx = pl.pallas_call(
        kernel,
        grid=(N // BN, V // BV),
        in_specs=[
            pl.BlockSpec((BN, BV), lambda i, j: (i, j)),
            pl.BlockSpec((BN, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((BN, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((BN, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((BN, BV), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((N, V), logits.dtype),
        interpret=_INTERPRET,
    )(logits, lbl2, lse, dl2)
    return dx
