"""Attention ops: XLA reference path + Pallas flash-attention fast path.

Reference capability: operators/fused/multihead_matmul_op.cu and
math/bert_encoder_functor (fused QKV attention for BERT-era serving).
TPU-first: a blockwise flash attention Pallas kernel (paddle_tpu/ops/
flash_attention.py) keeps the softmax running-max online so the full
[T, T] score matrix never materialises in HBM; the XLA path below is the
correctness reference and the fallback for CPU tests.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor


def _v(x):
    return x.value if isinstance(x, Tensor) else x


FLASH_ENABLED = True  # flipped off automatically when the kernel can't run


def _use_flash(q_shape) -> bool:
    # flash kernel needs TPU backend + seq len divisible by block
    if not FLASH_ENABLED:
        return False
    # ablation kill-switch ("0"/"" = flash stays on, matching the
    # PADDLE_TPU_REMAT_PREVENT_CSE flag convention)
    if os.environ.get("PADDLE_TPU_NO_FLASH", "") not in ("", "0"):
        return False
    try:
        dev = jax.devices()[0]
        if dev.platform not in ("tpu", "axon"):
            return False
    except Exception:
        return False
    B, T, H, D = q_shape
    return T % 128 == 0 and D in (64, 128, 256)


def xla_attention(q, k, v, mask=None, is_causal=False, scale=None):
    """Plain XLA attention on [B, T, H, D]; XLA fuses this well for short T."""
    B, T, H, D = q.shape
    scale = scale if scale is not None else 1.0 / (D**0.5)
    qT = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    if is_causal:
        causal = jnp.tril(jnp.ones((T, k.shape[1]), bool))
        qT = jnp.where(causal[None, None], qT, -1e30)
    if mask is not None:
        qT = qT + mask
    p = jax.nn.softmax(qT.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", p, v)


def attention_array(q, k, v, mask=None, is_causal=False, scale=None):
    """Array-level entry used by jitted model code (GPT flagship)."""
    if mask is None and _use_flash(q.shape):
        from . import flash_attention as fa

        return fa.flash_attention(q, k, v, causal=is_causal, scale=scale)
    return xla_attention(q, k, v, mask=mask, is_causal=is_causal, scale=scale)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True):
    mask = _v(attn_mask) if attn_mask is not None else None

    def fn(q, k, v):
        out = attention_array(q, k, v, mask=mask, is_causal=is_causal)
        return out

    out = dispatch(fn, query, key, value, op_name="sdpa")
    if dropout_p > 0.0 and training:
        from ..nn import functional as F

        out = F.dropout(out, dropout_p, training=training)
    return out
