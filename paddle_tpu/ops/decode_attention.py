"""Pallas TPU split-KV flash-decode attention + the quantized-KV helpers.

The training/prefill flash kernel (ops/flash_attention.py) rejects decode
shapes (T_q = 1), so long-context decode attention ran as plain XLA over
the full [B, T, Hkv, hd] cache — per-token HBM traffic scales with the
context length, which is the serving bottleneck once dispatch and weight
reads are optimized (PR 1/2).  This kernel streams the KV cache through
VMEM in T-blocks with the same online-softmax recurrence as
``_flash_fwd_impl``, specialized for small T_q:

* **split-KV grid** ``(B * Hkv, T // BT)``: each cell owns one (batch,
  kv-head) pair and walks the KV blocks keeping a running max/denominator
  in VMEM scratch — no [T] score row ever hits HBM, and blocks entirely
  past the causal frontier (``base > pos + Tq - 1``) are skipped;
* **GQA-aware**: the q rows for one kv head are its whole query group
  ([Tq * G, hd], G = Hq // Hkv), so the kernel consumes the Hkv-head
  cache DIRECTLY (the ``repeat_kv=False`` layout ``_gqa_qkv`` already
  produces) instead of materializing repeated K/V heads — the HBM read
  is the cache's true size, not G times it;
* **int8 cache**: per-(position, head) scales (``quantize_kv``) dequantize
  inside the kernel right after the VMEM load — HBM reads a quarter of
  the fp32 bytes, and no dequantized copy is ever written back.

Forward-only by design (decode is inference).  Availability probing +
XLA fallback follow ops/flash_attention.py; the routing gate is
``PADDLE_TPU_FLASH_DECODE`` (read by text/generate.py, which keeps its
original einsum math as the off/fallback path).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_FALLBACK: dict = {}
_INTERPRET = False  # tests flip this to run the kernel on CPU (interpret)

_NEG = -1e30  # large-negative instead of -inf (flash_attention's rule)

_R_CAP = 1024  # q rows (Tq * G) per grid cell; verify chunks stay under it


def _kv_block(T: int) -> int | None:
    """KV block length: the largest standard tile dividing T, or T itself
    for short test-sized caches (interpret mode / tiny serving windows)."""
    for cand in (512, 256, 128):
        if T % cand == 0:
            return cand
    if T <= 512 and T % 8 == 0:
        return T
    return None


def supported(q_shape, kv_shape) -> bool:
    """Static shape gate: q [B, Tq, Hq, hd] against cache [B, T, Hkv, hd]."""
    B, Tq, Hq, hd = q_shape
    T, Hkv = kv_shape[1], kv_shape[2]
    return (hd in (64, 128, 256) and Hq % Hkv == 0
            and Tq * (Hq // Hkv) <= _R_CAP
            and _kv_block(T) is not None)


def available(q_shape, kv_shape) -> bool:
    """supported() + a backend that can run the kernel (TPU, or interpret
    mode for CPU tests).  The per-configuration probe runs inside
    ``decode_attention`` — this is the cheap trace-time routing check
    text/generate.py consults before leaving its einsum path."""
    if not supported(q_shape, kv_shape):
        return False
    if _INTERPRET:
        return True
    from ._pallas_probe import tpu_backend

    return tpu_backend()


# ---------------------------------------------------------------------------
# quantized-KV helpers — THE int8 cache format, in one place
# ---------------------------------------------------------------------------


def quantize_kv(x):
    """Symmetric per-(…, head) int8 over the trailing head_dim axis:
    returns (q int8 like x, scale fp32 of x.shape[:-1]).  One K/V row's
    head vector shares one scale — the scale array rides beside the cache
    at hd*... /1 of its size (~1-2%), and dequant inside the kernel is a
    single broadcast multiply."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def dequantize_kv(q, s, dt):
    """Inverse of quantize_kv, in fp32 then cast (matches the kernel's
    internal math) — the XLA-fallback attention path uses this."""
    return (q.astype(jnp.float32) * s[..., None]).astype(dt)


def random_filled_cache(cache: dict, key, amp: float = 1.0) -> dict:
    """A ``generate.init_cache`` tree filled with synthetic normal K/V
    (scaled by ``amp``), quantizing through the real format when the
    cache carries scale planes — THE cache-format-aware fill the bench
    and on-device certification share (one copy; a format change edits
    exactly here)."""
    ks = jax.random.split(key, 2)
    kf = jax.random.normal(ks[0], cache["k"].shape) * amp
    vf = jax.random.normal(ks[1], cache["v"].shape) * amp
    if "k_s" in cache:
        k, k_s = quantize_kv(kf)
        v, v_s = quantize_kv(vf)
        return dict(cache, k=k, v=v, k_s=k_s, v_s=v_s)
    return dict(cache, k=kf.astype(cache["k"].dtype),
                v=vf.astype(cache["v"].dtype))


# ---------------------------------------------------------------------------
# XLA reference (parity oracle + runtime fallback)
# ---------------------------------------------------------------------------


def _xla_decode(q, k, v, pos, k_scale, v_scale, scale):
    """Grouped-query cached attention in plain XLA: q [B, Tq, Hq, hd],
    cache [B, T, Hkv, hd] (+ scales for int8), mask t <= pos[b] + i for
    q row i.  fp32 softmax like every attention path in this repo."""
    B, Tq, Hq, hd = q.shape
    T, Hkv = k.shape[1], k.shape[2]
    G = Hq // Hkv
    scale = scale if scale is not None else 1.0 / (hd ** 0.5)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    if k_scale is not None:
        kf = kf * k_scale[..., None]
        vf = vf * v_scale[..., None]
    qg = q.reshape(B, Tq, Hkv, G, hd).astype(jnp.float32)
    s = jnp.einsum("bikgd,btkd->bkgit", qg, kf) * scale
    mask = (jnp.arange(T)[None, :]
            <= pos[:, None, None, None, None] + jnp.arange(Tq)[:, None])
    s = jnp.where(mask, s, _NEG)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgit,btkd->bikgd", w, vf)
    return out.reshape(B, Tq, Hq, hd).astype(q.dtype)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------


def _probe(q_dtype, kv_dtype, Tq: int, G: int, hd: int, BT: int) -> bool:
    """True = fall back.  Probes the exact (block shapes, dtypes)
    configuration the real call lowers with, per _pallas_probe's rules."""
    from ._pallas_probe import probe_once

    def thunk():
        quant = jnp.dtype(kv_dtype) == jnp.int8
        q = jax.device_put(jnp.zeros((1, Tq, G, hd), q_dtype))
        k = jax.device_put(jnp.zeros((1, BT, 1, hd), kv_dtype))
        ks = (jax.device_put(jnp.ones((1, BT, 1), jnp.float32))
              if quant else None)
        pos = jax.device_put(jnp.zeros((1,), jnp.int32))
        return _decode_call(q, k, k, pos, ks, ks, None)

    return probe_once(
        _FALLBACK,
        (jnp.dtype(q_dtype).name, jnp.dtype(kv_dtype).name,
         int(Tq), int(G), int(hd), int(BT)), thunk)


def decode_attention(q, k, v, pos, k_scale=None, v_scale=None, scale=None):
    """q [B, Tq, Hq, hd] against a cache [B, T, Hkv, hd] → [B, Tq, Hq, hd]
    (q.dtype).  ``pos`` [B] int32: q row i of batch b attends cache rows
    t <= pos[b] + i (decode passes Tq=1 and the current position; verify/
    chunked-prefill pass the chunk and its first position).  int8 caches
    pass per-row ``k_scale``/``v_scale`` [B, T, Hkv].  Falls back to the
    XLA expression when the Pallas path is unavailable.

    Not jitted itself: the availability probe must execute eagerly
    (flash_attention's rule — it still works when tracing)."""
    if not supported(q.shape, k.shape):
        return _xla_decode(q, k, v, pos, k_scale, v_scale, scale)
    G = q.shape[2] // k.shape[2]
    BT = _kv_block(k.shape[1])
    if not _INTERPRET and _probe(q.dtype, k.dtype, q.shape[1], G,
                                 q.shape[-1], BT):
        return _xla_decode(q, k, v, pos, k_scale, v_scale, scale)
    return _decode_call(q, k, v, pos, k_scale, v_scale, scale)


def _decode_call(q, k, v, pos, k_scale, v_scale, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, Tq, Hq, hd = q.shape
    T, Hkv = k.shape[1], k.shape[2]
    G = Hq // Hkv
    R = Tq * G
    BT = _kv_block(T)
    nt = T // BT
    scale = scale if scale is not None else 1.0 / (hd ** 0.5)
    quant = k_scale is not None

    # rows for kv head h are its whole query group, causally ordered:
    # row r = tq * G + g  (mask recovers tq as r // G)
    qh = q.reshape(B, Tq, Hkv, G, hd).swapaxes(1, 2).reshape(B, Hkv, R, hd)
    pos2 = pos.reshape(B, 1).astype(jnp.int32)

    def kernel(pos_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
        else:
            o_ref, m_scr, l_scr, acc_scr = rest
        ti = pl.program_id(1)

        @pl.when(ti == 0)
        def _init():
            m_scr[:] = jnp.full_like(m_scr, _NEG)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc_scr[:] = jnp.zeros_like(acc_scr)

        p_b = pos_ref[0, 0]
        base = ti * BT

        # skip KV blocks entirely past the causal frontier
        @pl.when(base <= p_b + Tq - 1)
        def _run():
            qb = q_ref[0, 0].astype(jnp.float32)           # [R, hd]
            kb = k_ref[0, :, 0, :].astype(jnp.float32)     # [BT, hd]
            vb = v_ref[0, :, 0, :].astype(jnp.float32)
            if quant:
                kb = kb * ks_ref[0, :, 0][:, None]
                vb = vb * vs_ref[0, :, 0][:, None]
            s = scale * jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)        # [R, BT]
            rows_tq = jax.lax.broadcasted_iota(jnp.int32, (R, BT), 0) // G
            cols = base + jax.lax.broadcasted_iota(jnp.int32, (R, BT), 1)
            s = jnp.where(cols <= p_b + rows_tq, s, _NEG)
            m_prev = m_scr[:, 0]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
            p = jnp.exp(s - m_cur[:, None])
            alpha = jnp.exp(m_prev - m_cur)
            l_scr[:, 0] = l_scr[:, 0] * alpha + jnp.sum(p, axis=1)
            acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[:, 0] = m_cur

        @pl.when(ti == nt - 1)
        def _fin():
            l = l_scr[:, 0]
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, 0] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)

    in_specs = [
        pl.BlockSpec((1, 1), lambda i, t: (i // Hkv, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1, R, hd), lambda i, t: (i // Hkv, i % Hkv, 0, 0)),
        pl.BlockSpec((1, BT, 1, hd), lambda i, t: (i // Hkv, t, i % Hkv, 0)),
        pl.BlockSpec((1, BT, 1, hd), lambda i, t: (i // Hkv, t, i % Hkv, 0)),
    ]
    args = [pos2, qh, k, v]
    if quant:
        in_specs += [
            pl.BlockSpec((1, BT, 1), lambda i, t: (i // Hkv, t, i % Hkv)),
            pl.BlockSpec((1, BT, 1), lambda i, t: (i // Hkv, t, i % Hkv)),
        ]
        args += [k_scale, v_scale]

    out = pl.pallas_call(
        kernel,
        grid=(B * Hkv, nt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, R, hd),
                               lambda i, t: (i // Hkv, i % Hkv, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, R, hd), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, hd), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(*args)
    return (out.reshape(B, Hkv, Tq, G, hd).swapaxes(1, 2)
            .reshape(B, Tq, Hq, hd))
