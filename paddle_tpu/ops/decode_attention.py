"""Pallas TPU split-KV flash-decode attention + the quantized-KV helpers.

The training/prefill flash kernel (ops/flash_attention.py) rejects decode
shapes (T_q = 1), so long-context decode attention ran as plain XLA over
the full [B, T, Hkv, hd] cache — per-token HBM traffic scales with the
context length, which is the serving bottleneck once dispatch and weight
reads are optimized (PR 1/2).  This kernel streams the KV cache through
VMEM in T-blocks with the same online-softmax recurrence as
``_flash_fwd_impl``, specialized for small T_q:

* **split-KV grid** ``(B * Hkv, T // BT)``: each cell owns one (batch,
  kv-head) pair and walks the KV blocks keeping a running max/denominator
  in VMEM scratch — no [T] score row ever hits HBM, and blocks entirely
  past the causal frontier (``base > pos + Tq - 1``) are skipped;
* **GQA-aware**: the q rows for one kv head are its whole query group
  ([Tq * G, hd], G = Hq // Hkv), so the kernel consumes the Hkv-head
  cache DIRECTLY (the ``repeat_kv=False`` layout ``_gqa_qkv`` already
  produces) instead of materializing repeated K/V heads — the HBM read
  is the cache's true size, not G times it;
* **int8 cache**: per-(position, head) scales (``quantize_kv``) dequantize
  inside the kernel right after the VMEM load — HBM reads a quarter of
  the fp32 bytes, and no dequantized copy is ever written back.

Forward-only by design (decode is inference).  Availability probing +
XLA fallback follow ops/flash_attention.py; the routing gate is
``PADDLE_TPU_FLASH_DECODE`` (read by text/generate.py, which keeps its
original einsum math as the off/fallback path).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_FALLBACK: dict = {}
_INTERPRET = False  # tests flip this to run the kernel on CPU (interpret)

_NEG = -1e30  # large-negative instead of -inf (flash_attention's rule)

_R_CAP = 1024  # q rows (Tq * G) per grid cell; verify chunks stay under it


def _kv_block(T: int) -> int | None:
    """KV block length: the largest standard tile dividing T, or T itself
    for short test-sized caches (interpret mode / tiny serving windows)."""
    for cand in (512, 256, 128):
        if T % cand == 0:
            return cand
    if T <= 512 and T % 8 == 0:
        return T
    return None


def supported(q_shape, kv_shape) -> bool:
    """Static shape gate: q [B, Tq, Hq, hd] against cache [B, T, Hkv, hd]."""
    B, Tq, Hq, hd = q_shape
    T, Hkv = kv_shape[1], kv_shape[2]
    return (hd in (64, 128, 256) and Hq % Hkv == 0
            and Tq * (Hq // Hkv) <= _R_CAP
            and _kv_block(T) is not None)


def available(q_shape, kv_shape) -> bool:
    """supported() + a backend that can run the kernel (TPU, or interpret
    mode for CPU tests).  The per-configuration probe runs inside
    ``decode_attention`` — this is the cheap trace-time routing check
    text/generate.py consults before leaving its einsum path."""
    if not supported(q_shape, kv_shape):
        return False
    if _INTERPRET:
        return True
    from ._pallas_probe import tpu_backend

    return tpu_backend()


# ---------------------------------------------------------------------------
# quantized-KV helpers — THE int8 cache format, in one place
# ---------------------------------------------------------------------------


def quantize_kv(x):
    """Symmetric per-(…, head) int8 over the trailing head_dim axis:
    returns (q int8 like x, scale fp32 of x.shape[:-1]).  One K/V row's
    head vector shares one scale — the scale array rides beside the cache
    at hd*... /1 of its size (~1-2%), and dequant inside the kernel is a
    single broadcast multiply."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def dequantize_kv(q, s, dt):
    """Inverse of quantize_kv, in fp32 then cast (matches the kernel's
    internal math) — the XLA-fallback attention path uses this."""
    return (q.astype(jnp.float32) * s[..., None]).astype(dt)


def random_filled_cache(cache: dict, key, amp: float = 1.0) -> dict:
    """A ``generate.init_cache`` tree filled with synthetic normal K/V
    (scaled by ``amp``), quantizing through the real format when the
    cache carries scale planes — THE cache-format-aware fill the bench
    and on-device certification share (one copy; a format change edits
    exactly here).

    Paged caches (``text/kv_pool.py`` trees with a ``tables`` leaf) fill
    the whole [L, N, bs, Hkv, hd] pool and, when the tables are still
    unmapped (-1), lay slots out identity-style (slot b owns blocks
    [b*nmax, (b+1)*nmax)) so the kernel-parity oracle and bench arms
    exercise real block-table gathers without a host allocator."""
    ks = jax.random.split(key, 2)
    kf = jax.random.normal(ks[0], cache["k"].shape) * amp
    vf = jax.random.normal(ks[1], cache["v"].shape) * amp
    if "k_s" in cache:
        k, k_s = quantize_kv(kf)
        v, v_s = quantize_kv(vf)
        out = dict(cache, k=k, v=v, k_s=k_s, v_s=v_s)
    else:
        out = dict(cache, k=kf.astype(cache["k"].dtype),
                   v=vf.astype(cache["v"].dtype))
    if "tables" in out and bool((out["tables"] < 0).all()):
        B, nmax = out["tables"].shape
        N = out["k"].shape[1]
        out["tables"] = (jnp.arange(B * nmax, dtype=jnp.int32)
                         .reshape(B, nmax) % N)
    return out


# ---------------------------------------------------------------------------
# XLA reference (parity oracle + runtime fallback)
# ---------------------------------------------------------------------------


def _xla_decode(q, k, v, pos, k_scale, v_scale, scale):
    """Grouped-query cached attention in plain XLA: q [B, Tq, Hq, hd],
    cache [B, T, Hkv, hd] (+ scales for int8), mask t <= pos[b] + i for
    q row i.  fp32 softmax like every attention path in this repo."""
    B, Tq, Hq, hd = q.shape
    T, Hkv = k.shape[1], k.shape[2]
    G = Hq // Hkv
    scale = scale if scale is not None else 1.0 / (hd ** 0.5)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    if k_scale is not None:
        kf = kf * k_scale[..., None]
        vf = vf * v_scale[..., None]
    qg = q.reshape(B, Tq, Hkv, G, hd).astype(jnp.float32)
    s = jnp.einsum("bikgd,btkd->bkgit", qg, kf) * scale
    mask = (jnp.arange(T)[None, :]
            <= pos[:, None, None, None, None] + jnp.arange(Tq)[:, None])
    s = jnp.where(mask, s, _NEG)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgit,btkd->bikgd", w, vf)
    return out.reshape(B, Tq, Hq, hd).astype(q.dtype)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------


def _probe(q_dtype, kv_dtype, Tq: int, G: int, hd: int, BT: int) -> bool:
    """True = fall back.  Probes the exact (block shapes, dtypes)
    configuration the real call lowers with, per _pallas_probe's rules."""
    from ._pallas_probe import probe_once

    def thunk():
        quant = jnp.dtype(kv_dtype) == jnp.int8
        q = jax.device_put(jnp.zeros((1, Tq, G, hd), q_dtype))
        k = jax.device_put(jnp.zeros((1, BT, 1, hd), kv_dtype))
        ks = (jax.device_put(jnp.ones((1, BT, 1), jnp.float32))
              if quant else None)
        pos = jax.device_put(jnp.zeros((1,), jnp.int32))
        return _decode_call(q, k, k, pos, ks, ks, None)

    return probe_once(
        _FALLBACK,
        (jnp.dtype(q_dtype).name, jnp.dtype(kv_dtype).name,
         int(Tq), int(G), int(hd), int(BT)), thunk)


def decode_attention(q, k, v, pos, k_scale=None, v_scale=None, scale=None):
    """q [B, Tq, Hq, hd] against a cache [B, T, Hkv, hd] → [B, Tq, Hq, hd]
    (q.dtype).  ``pos`` [B] int32: q row i of batch b attends cache rows
    t <= pos[b] + i (decode passes Tq=1 and the current position; verify/
    chunked-prefill pass the chunk and its first position).  int8 caches
    pass per-row ``k_scale``/``v_scale`` [B, T, Hkv].  Falls back to the
    XLA expression when the Pallas path is unavailable.

    Not jitted itself: the availability probe must execute eagerly
    (flash_attention's rule — it still works when tracing)."""
    if not supported(q.shape, k.shape):
        return _xla_decode(q, k, v, pos, k_scale, v_scale, scale)
    G = q.shape[2] // k.shape[2]
    BT = _kv_block(k.shape[1])
    if not _INTERPRET and _probe(q.dtype, k.dtype, q.shape[1], G,
                                 q.shape[-1], BT):
        return _xla_decode(q, k, v, pos, k_scale, v_scale, scale)
    return _decode_call(q, k, v, pos, k_scale, v_scale, scale)


def _decode_call(q, k, v, pos, k_scale, v_scale, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, Tq, Hq, hd = q.shape
    T, Hkv = k.shape[1], k.shape[2]
    G = Hq // Hkv
    R = Tq * G
    BT = _kv_block(T)
    nt = T // BT
    scale = scale if scale is not None else 1.0 / (hd ** 0.5)
    quant = k_scale is not None

    # rows for kv head h are its whole query group, causally ordered:
    # row r = tq * G + g  (mask recovers tq as r // G)
    qh = q.reshape(B, Tq, Hkv, G, hd).swapaxes(1, 2).reshape(B, Hkv, R, hd)
    pos2 = pos.reshape(B, 1).astype(jnp.int32)

    def kernel(pos_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
        else:
            o_ref, m_scr, l_scr, acc_scr = rest
        ti = pl.program_id(1)

        @pl.when(ti == 0)
        def _init():
            m_scr[:] = jnp.full_like(m_scr, _NEG)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc_scr[:] = jnp.zeros_like(acc_scr)

        p_b = pos_ref[0, 0]
        base = ti * BT

        # skip KV blocks entirely past the causal frontier
        @pl.when(base <= p_b + Tq - 1)
        def _run():
            qb = q_ref[0, 0].astype(jnp.float32)           # [R, hd]
            kb = k_ref[0, :, 0, :].astype(jnp.float32)     # [BT, hd]
            vb = v_ref[0, :, 0, :].astype(jnp.float32)
            if quant:
                kb = kb * ks_ref[0, :, 0][:, None]
                vb = vb * vs_ref[0, :, 0][:, None]
            s = scale * jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)        # [R, BT]
            rows_tq = jax.lax.broadcasted_iota(jnp.int32, (R, BT), 0) // G
            cols = base + jax.lax.broadcasted_iota(jnp.int32, (R, BT), 1)
            s = jnp.where(cols <= p_b + rows_tq, s, _NEG)
            m_prev = m_scr[:, 0]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
            p = jnp.exp(s - m_cur[:, None])
            alpha = jnp.exp(m_prev - m_cur)
            l_scr[:, 0] = l_scr[:, 0] * alpha + jnp.sum(p, axis=1)
            acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[:, 0] = m_cur

        @pl.when(ti == nt - 1)
        def _fin():
            l = l_scr[:, 0]
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, 0] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)

    in_specs = [
        pl.BlockSpec((1, 1), lambda i, t: (i // Hkv, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1, R, hd), lambda i, t: (i // Hkv, i % Hkv, 0, 0)),
        pl.BlockSpec((1, BT, 1, hd), lambda i, t: (i // Hkv, t, i % Hkv, 0)),
        pl.BlockSpec((1, BT, 1, hd), lambda i, t: (i // Hkv, t, i % Hkv, 0)),
    ]
    args = [pos2, qh, k, v]
    if quant:
        in_specs += [
            pl.BlockSpec((1, BT, 1), lambda i, t: (i // Hkv, t, i % Hkv)),
            pl.BlockSpec((1, BT, 1), lambda i, t: (i // Hkv, t, i % Hkv)),
        ]
        args += [k_scale, v_scale]

    out = pl.pallas_call(
        kernel,
        grid=(B * Hkv, nt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, R, hd),
                               lambda i, t: (i // Hkv, i % Hkv, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, R, hd), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, hd), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(*args)
    return (out.reshape(B, Hkv, Tq, G, hd).swapaxes(1, 2)
            .reshape(B, Tq, Hq, hd))


# ---------------------------------------------------------------------------
# paged (block-table) kernel — the pool layout's decode hot path
# ---------------------------------------------------------------------------


def gather_paged_view(k_pool, tables):
    """Per-slot contiguous view of a pooled leaf: k_pool [N, bs, ...] +
    tables [B, nmax] -> [B, nmax*bs, ...].  Unmapped entries (-1) clamp
    to block 0 — their rows sit past every causal frontier (the
    allocator maps blocks through the write position), so the garbage is
    masked exactly like a slab's unwritten rows.  THE oracle/fallback
    materialization; the Pallas path resolves the same table per grid
    cell instead."""
    idx = jnp.clip(tables, 0, k_pool.shape[0] - 1)          # [B, nmax]
    g = k_pool[idx]                                          # [B,nmax,bs,...]
    return g.reshape((g.shape[0], g.shape[1] * g.shape[2]) + g.shape[3:])


def paged_supported(q_shape, pool_shape) -> bool:
    """Static shape gate for the paged kernel: q [B, Tq, Hq, hd] against
    a pool [N, bs, Hkv, hd] (the KV block is the pool's own block)."""
    B, Tq, Hq, hd = q_shape
    N, bs, Hkv = pool_shape[0], pool_shape[1], pool_shape[2]
    return (hd in (64, 128, 256) and Hq % Hkv == 0
            and Tq * (Hq // Hkv) <= _R_CAP
            and bs >= 8 and bs % 8 == 0)


def paged_available(q_shape, pool_shape) -> bool:
    """paged_supported + a backend that can run the kernel (TPU, or
    interpret mode for CPU tests) — the trace-time routing check
    text/kv_pool.py consults before leaving the gather-einsum path."""
    if not paged_supported(q_shape, pool_shape):
        return False
    if _INTERPRET:
        return True
    from ._pallas_probe import tpu_backend

    return tpu_backend()


def _xla_paged(q, k_pool, v_pool, tables, pos, k_scale, v_scale, scale):
    """Oracle/fallback: gather the per-slot views through the tables and
    run the contiguous XLA reference — bit-identical values to a slab
    holding the same rows (the gather only relocates blocks)."""
    k = gather_paged_view(k_pool, tables)
    v = gather_paged_view(v_pool, tables)
    ks = gather_paged_view(k_scale, tables) if k_scale is not None else None
    vs = gather_paged_view(v_scale, tables) if v_scale is not None else None
    return _xla_decode(q, k, v, pos, ks, vs, scale)


def _paged_probe(q_dtype, kv_dtype, Tq: int, G: int, hd: int,
                 bs: int) -> bool:
    """True = fall back.  Probes the exact paged configuration the real
    call lowers with (block geometry + dtypes + scalar-prefetch path)."""
    from ._pallas_probe import probe_once

    def thunk():
        quant = jnp.dtype(kv_dtype) == jnp.int8
        q = jax.device_put(jnp.zeros((1, Tq, G, hd), q_dtype))
        kp = jax.device_put(jnp.zeros((2, bs, 1, hd), kv_dtype))
        ks = (jax.device_put(jnp.ones((2, bs, 1), jnp.float32))
              if quant else None)
        tables = jax.device_put(jnp.zeros((1, 1), jnp.int32))
        pos = jax.device_put(jnp.zeros((1,), jnp.int32))
        return _paged_call(q, kp, kp, tables, pos, ks, ks, None)

    return probe_once(
        _FALLBACK,
        ("paged", jnp.dtype(q_dtype).name, jnp.dtype(kv_dtype).name,
         int(Tq), int(G), int(hd), int(bs)), thunk)


def paged_decode_attention(q, k_pool, v_pool, tables, pos,
                           k_scale=None, v_scale=None, scale=None):
    """Block-table decode attention: q [B, Tq, Hq, hd] against a pooled
    cache k/v [N, bs, Hkv, hd] addressed through ``tables`` [B, nmax]
    int32 (physical block per logical block; -1 = unmapped) ->
    [B, Tq, Hq, hd] (q.dtype).  ``pos`` [B] as in :func:`decode_attention`
    — logical row t of slot b is table[b, t // bs] row t % bs, and rows
    t <= pos[b] + i are attended.  int8 pools pass per-row scales
    [N, bs, Hkv].  Falls back to gather + the XLA reference when the
    Pallas path is unavailable.

    Not jitted itself (the probe must execute eagerly — decode_attention's
    rule); the grid cell resolves its T-block THROUGH the table via
    scalar prefetch, so the HBM read is each slot's mapped blocks only —
    never a materialized [B, T] gather — and causally-dead or unmapped
    blocks are skipped."""
    if not paged_supported(q.shape, k_pool.shape):
        return _xla_paged(q, k_pool, v_pool, tables, pos, k_scale, v_scale,
                          scale)
    G = q.shape[2] // k_pool.shape[2]
    bs = k_pool.shape[1]
    if not _INTERPRET and _paged_probe(q.dtype, k_pool.dtype, q.shape[1],
                                       G, q.shape[-1], bs):
        return _xla_paged(q, k_pool, v_pool, tables, pos, k_scale, v_scale,
                          scale)
    return _paged_call(q, k_pool, v_pool, tables, pos, k_scale, v_scale,
                       scale)


def _paged_call(q, k_pool, v_pool, tables, pos, k_scale, v_scale, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, Tq, Hq, hd = q.shape
    N, bs, Hkv = k_pool.shape[0], k_pool.shape[1], k_pool.shape[2]
    G = Hq // Hkv
    R = Tq * G
    nmax = tables.shape[1]
    scale = scale if scale is not None else 1.0 / (hd ** 0.5)
    quant = k_scale is not None

    qh = q.reshape(B, Tq, Hkv, G, hd).swapaxes(1, 2).reshape(B, Hkv, R, hd)
    tab = tables.astype(jnp.int32)
    pos2 = pos.reshape(B).astype(jnp.int32)

    def kernel(tab_ref, pos_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
        else:
            o_ref, m_scr, l_scr, acc_scr = rest
        i = pl.program_id(0)
        ti = pl.program_id(1)
        b = i // Hkv

        @pl.when(ti == 0)
        def _init():
            m_scr[:] = jnp.full_like(m_scr, _NEG)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc_scr[:] = jnp.zeros_like(acc_scr)

        p_b = pos_ref[b]
        base = ti * bs          # LOGICAL row base of this block

        # skip blocks past the causal frontier AND unmapped table slots
        # (an unmapped block holds another tenant's rows; the allocator
        # maps every block through the write position, so a mapped-but-
        # stale row is already behind the mask like a slab's)
        @pl.when((base <= p_b + Tq - 1) & (tab_ref[b, ti] >= 0))
        def _run():
            qb = q_ref[0, 0].astype(jnp.float32)           # [R, hd]
            kb = k_ref[0, :, 0, :].astype(jnp.float32)     # [bs, hd]
            vb = v_ref[0, :, 0, :].astype(jnp.float32)
            if quant:
                kb = kb * ks_ref[0, :, 0][:, None]
                vb = vb * vs_ref[0, :, 0][:, None]
            s = scale * jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)        # [R, bs]
            rows_tq = jax.lax.broadcasted_iota(jnp.int32, (R, bs), 0) // G
            cols = base + jax.lax.broadcasted_iota(jnp.int32, (R, bs), 1)
            s = jnp.where(cols <= p_b + rows_tq, s, _NEG)
            m_prev = m_scr[:, 0]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
            p = jnp.exp(s - m_cur[:, None])
            alpha = jnp.exp(m_prev - m_cur)
            l_scr[:, 0] = l_scr[:, 0] * alpha + jnp.sum(p, axis=1)
            acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[:, 0] = m_cur

        @pl.when(ti == nmax - 1)
        def _fin():
            l = l_scr[:, 0]
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, 0] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)

    # the pool block for grid cell (i, t) is resolved THROUGH the
    # prefetched table: physical block tab[b, t] (clamped — the kernel
    # body skips the compute for unmapped entries, but the DMA engine
    # still needs an in-bounds address)
    def _kv_idx(i, t, tab_ref, pos_ref):
        pb = jnp.clip(tab_ref[i // Hkv, t], 0, N - 1)
        return (pb, 0, i % Hkv, 0)

    def _ks_idx(i, t, tab_ref, pos_ref):
        pb = jnp.clip(tab_ref[i // Hkv, t], 0, N - 1)
        return (pb, 0, i % Hkv)

    in_specs = [
        pl.BlockSpec((1, 1, R, hd),
                     lambda i, t, tab_ref, pos_ref: (i // Hkv, i % Hkv,
                                                     0, 0)),
        pl.BlockSpec((1, bs, 1, hd), _kv_idx),
        pl.BlockSpec((1, bs, 1, hd), _kv_idx),
    ]
    args = [qh, k_pool, v_pool]
    if quant:
        in_specs += [pl.BlockSpec((1, bs, 1), _ks_idx),
                     pl.BlockSpec((1, bs, 1), _ks_idx)]
        args += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * Hkv, nmax),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, R, hd),
            lambda i, t, tab_ref, pos_ref: (i // Hkv, i % Hkv, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, R, hd), q.dtype),
        interpret=_INTERPRET,
    )(tab, pos2, *args)
    return (out.reshape(B, Hkv, Tq, G, hd).swapaxes(1, 2)
            .reshape(B, Tq, Hq, hd))
