"""Shared availability-probe scaffolding for Pallas TPU kernels.

Every Pallas kernel module (flash_attention, fused_norm, fused_ce) wants the
same contract: try the kernel once per *configuration*, remember whether the
Mosaic lowering worked, and fall back to the plain XLA expression forever
after if it didn't — so the kernels are safe to call from any path on any
backend.

Two lessons are encoded here so they stay single-site:

* the probe must run under ``jax.ensure_compile_time_eval()`` — "eager" jax
  ops inside an outer jit trace are otherwise silently staged into that
  trace (stackless tracing), nothing compiles or raises, and a broken
  Pallas path reports healthy;
* the probe must execute the kernel with the SAME configuration the real
  call will use (block shapes, dtypes) — a fixed tiny probe config can
  lower fine while the production one fails, letting the exception escape
  into the training step.  Callers are responsible for keying the cache on
  everything that changes the lowering.
"""
from __future__ import annotations

import jax

# Shared block geometry for row-sweep kernels (fused_norm, fused_ce): one
# row-block of fp32 working set per buffer, a handful of buffers resident —
# well under the ~16 MB VMEM core budget.  Single-site so a retune for a
# new TPU generation applies to every kernel at once.
BLOCK_BYTES = 2 * 1024 * 1024
ROW_PAD = 8  # row counts are padded up to this multiple before blocking


def row_block(N: int, row_elems: int, limit: int = BLOCK_BYTES) -> int | None:
    """Largest row-block size dividing ``N`` whose fp32 working block of
    ``row_elems`` columns fits the budget; None if no candidate divides."""
    for bn in (256, 128, 64, 32, 16, 8):
        if N % bn == 0 and bn * row_elems * 4 <= limit:
            return bn
    return None


def pad_rows(N: int) -> int:
    return -(-N // ROW_PAD) * ROW_PAD


def tpu_backend() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def probe_once(cache: dict, key, thunk) -> bool:
    """True = fall back.  ``thunk`` must compile+run the kernel fwd+bwd on
    concrete arrays shaped like the real call; any exception marks ``key``
    as unavailable permanently (for this process)."""
    if key not in cache:
        if not tpu_backend():
            cache[key] = True
            return True
        try:
            with jax.ensure_compile_time_eval():
                # device_get, not block_until_ready: an execution-time
                # kernel failure must be caught HERE and mark the kernel
                # unavailable (axon's block_until_ready can return before
                # execution finishes, deferring the crash to the real call)
                jax.device_get(jax.tree_util.tree_leaves(thunk()))
            cache[key] = False
        except Exception:
            cache[key] = True
    return cache[key]
