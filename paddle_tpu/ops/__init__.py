"""paddle_tpu.ops — hand-written TPU kernels (Pallas) and their XLA fallbacks.

This package plays the role of the reference's hand-optimised CUDA kernels
(/root/reference/paddle/fluid/operators/fused/ — multihead_matmul,
fused_attention precursors), re-done as Pallas TPU kernels.
"""
from . import attention, sequence  # noqa: F401
from . import crf  # noqa: F401
