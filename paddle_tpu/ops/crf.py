"""Linear-chain CRF: training loss + Viterbi decoding.

Reference capability: linear_chain_crf_op.{h,cc} (forward algorithm over
emission+transition scores, normalizer via log-space alpha recursion) and
crf_decoding_op.h (Viterbi max-backtrace) — the sequence-labeling family
(SRL/NER, paired with text.datasets.Conll05st).

TPU-first: both recursions are ``lax.scan`` over time with masked updates
for padded steps — static shapes, fully differentiable loss (grads of the
normalizer give the expected-count statistics, so jax autodiff reproduces
the reference's hand-written backward).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor

__all__ = ["linear_chain_crf", "viterbi_decode"]


def _v(x):
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def linear_chain_crf(emission, transition, label, length=None,
                     start=None, stop=None):
    """Negative log-likelihood of label paths under a linear-chain CRF.

    emission: [B, T, C] unary scores; transition: [C, C] (from→to);
    label: [B, T] int; length: [B] valid steps (defaults to T);
    start/stop: optional [C] boundary scores. Returns [B] losses.
    """
    lab = _v(label).astype(jnp.int32)
    B, T = lab.shape
    lens = (_v(length).astype(jnp.int32) if length is not None
            else jnp.full((B,), T, jnp.int32))

    def fn(em, tr, *rest):
        i = 0
        st = rest[i] if start is not None else jnp.zeros(tr.shape[0])
        i += 1 if start is not None else 0
        sp = rest[i] if stop is not None else jnp.zeros(tr.shape[0])
        em = em.astype(jnp.float32)
        tr = tr.astype(jnp.float32)
        mask = (jnp.arange(T)[None, :] < lens[:, None])  # [B, T]

        # path score: sum of emissions on labels + transitions along path
        unary = jnp.take_along_axis(em, lab[..., None], 2)[..., 0]  # [B,T]
        unary = (unary * mask).sum(1)
        pair = tr[lab[:, :-1], lab[:, 1:]]  # [B, T-1]
        pair = (pair * mask[:, 1:]).sum(1)
        first = st[lab[:, 0]]
        last_idx = jnp.clip(lens - 1, 0, T - 1)
        last_lab = jnp.take_along_axis(lab, last_idx[:, None], 1)[:, 0]
        score = unary + pair + first + sp[last_lab]

        # normalizer: alpha recursion in log space
        alpha0 = em[:, 0] + st[None, :]

        def step(alpha, t):
            em_t = em[:, t]
            nxt = jax.nn.logsumexp(alpha[:, :, None] + tr[None], axis=1) \
                + em_t
            keep = mask[:, t][:, None]
            return jnp.where(keep, nxt, alpha), None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        logz = jax.nn.logsumexp(alpha + sp[None, :], axis=1)
        return logz - score

    args = [emission, transition]
    if start is not None:
        args.append(start)
    if stop is not None:
        args.append(stop)
    return dispatch(fn, *args, op_name="linear_chain_crf")


def viterbi_decode(emission, transition, length=None, start=None, stop=None,
                   include_bos_eos_tag=False):
    """Most-likely label path (reference crf_decoding_op /
    paddle.text.ViterbiDecoder): returns (scores [B], paths [B, T]).

    include_bos_eos_tag=True follows the reference convention: the LAST TWO
    tags of the transition matrix are BOS and EOS — transitions out of BOS
    provide the start scores, transitions into EOS the stop scores, and
    neither tag may appear in the decoded path."""
    em = _v(emission).astype(jnp.float32)
    tr = _v(transition).astype(jnp.float32)
    B, T, C = em.shape
    lens = (_v(length).astype(jnp.int32) if length is not None
            else jnp.full((B,), T, jnp.int32))
    st = _v(start).astype(jnp.float32) if start is not None else jnp.zeros(C)
    sp = _v(stop).astype(jnp.float32) if stop is not None else jnp.zeros(C)
    if include_bos_eos_tag:
        bos, eos = C - 2, C - 1
        st = st + tr[bos]  # scores for the first real tag
        sp = sp + tr[:, eos]
        bar = jnp.full((C,), -1e30, jnp.float32)
        bar = bar.at[:C - 2].set(0.0)
        em = em + bar[None, None, :]  # BOS/EOS never emitted mid-sequence
    mask = (jnp.arange(T)[None, :] < lens[:, None])

    def step(delta, t):
        cand = delta[:, :, None] + tr[None]  # [B, C_from, C_to]
        best = cand.max(1) + em[:, t]
        back = cand.argmax(1).astype(jnp.int32)
        keep = mask[:, t][:, None]
        new_delta = jnp.where(keep, best, delta)
        back = jnp.where(keep, back,
                         jnp.arange(C, dtype=jnp.int32)[None, :])
        return new_delta, back

    delta0 = em[:, 0] + st[None, :]
    delta, backs = jax.lax.scan(step, delta0, jnp.arange(1, T))
    final = delta + sp[None, :]
    scores = final.max(1)
    last = final.argmax(1).astype(jnp.int32)

    def backtrace(tok, back_t):
        prev = jnp.take_along_axis(back_t, tok[:, None], 1)[:, 0]
        return prev, tok

    first_tok, path_rev = jax.lax.scan(backtrace, last, backs[::-1])
    # scan outputs are [l_{T-1}, ..., l_1]; the final carry is l_0
    path = jnp.concatenate([first_tok[None], path_rev[::-1]], axis=0).T
    # padded steps report label 0
    path = jnp.where(mask, path, 0)
    return Tensor(scores), Tensor(path.astype(jnp.int64))
