"""Pallas TPU flash attention (forward + backward via custom_vjp).

Blockwise online-softmax attention: per (batch, head, q-block) grid cell,
stream k/v blocks through VMEM keeping running max/denominator, so the
[T, T] score matrix never hits HBM.  Backward recomputes blockwise scores
(flash-style) using the saved softmax statistics.

This is the TPU-native replacement for the reference's fused attention CUDA
kernels (operators/fused/multihead_matmul_op.cu).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_FALLBACK = None


def _xla(q, k, v, causal, scale):
    from .attention import xla_attention

    return xla_attention(q, k, v, is_causal=causal, scale=scale)


@functools.partial(jax.jit, static_argnames=("causal", "scale"))
def flash_attention(q, k, v, causal: bool = False, scale=None):
    """q,k,v: [B, T, H, D] → [B, T, H, D].  Falls back to XLA attention if the
    Pallas path is unavailable (non-TPU backend or unsupported shape)."""
    global _FALLBACK
    if _FALLBACK is None:
        try:
            _pallas_flash(jnp.zeros((1, 128, 1, 64), jnp.float32),
                          jnp.zeros((1, 128, 1, 64), jnp.float32),
                          jnp.zeros((1, 128, 1, 64), jnp.float32), False, None)
            _FALLBACK = False
        except Exception:
            _FALLBACK = True
    if _FALLBACK:
        return _xla(q, k, v, causal, scale)
    return _pallas_flash(q, k, v, causal, scale)


def _pallas_flash(q, k, v, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    S = k.shape[1]
    scale = scale if scale is not None else 1.0 / (D**0.5)
    BQ = min(128 if T >= 128 else T, 512)
    BK = min(128 if S >= 128 else S, 512)
    # layout: move heads next to batch → grid (B*H, T/BQ)
    qh = jnp.swapaxes(q, 1, 2).reshape(B * H, T, D)
    kh = jnp.swapaxes(k, 1, 2).reshape(B * H, S, D)
    vh = jnp.swapaxes(v, 1, 2).reshape(B * H, S, D)

    nq, nk = T // BQ, S // BK

    def kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr):
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc_scr[:] = jnp.zeros_like(acc_scr)

        run = True
        if causal:
            run = (ki * BK) <= (qi * BQ + BQ - 1)

        def body():
            qb = q_ref[0].astype(jnp.float32) * scale
            kb = k_ref[0].astype(jnp.float32)
            s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if causal:
                rows = qi * BQ + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 0)
                cols = ki * BK + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 1)
                s = jnp.where(rows >= cols, s, -jnp.inf)
            m_prev = m_scr[:, 0]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
            p = jnp.exp(s - m_cur[:, None])
            alpha = jnp.exp(m_prev - m_cur)
            l_scr[:, 0] = l_scr[:, 0] * alpha + jnp.sum(p, axis=1)
            acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
                p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_scr[:, 0] = m_cur

        if causal:
            @pl.when((ki * BK) <= (qi * BQ + BQ - 1))
            def _run():
                body()
        else:
            body()

        @pl.when(ki == nk - 1)
        def _finish():
            o_ref[0] = (acc_scr[:] / l_scr[:, 0][:, None]).astype(o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, BK, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, BK, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((BQ, 1), jnp.float32),
            pltpu.VMEM((BQ, 1), jnp.float32),
            pltpu.VMEM((BQ, D), jnp.float32),
        ],
    )(qh, kh, vh)
    return jnp.swapaxes(out.reshape(B, H, T, D), 1, 2)
