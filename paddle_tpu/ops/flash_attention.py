"""Pallas TPU flash attention — forward + backward via custom_vjp.

Blockwise online-softmax attention (FlashAttention-2 style): per
(batch*head, q-block) grid cell the forward streams k/v blocks through VMEM
keeping a running max/denominator, so the [T, T] score matrix never hits
HBM; it also emits the per-row logsumexp.  The backward recomputes blockwise
scores from q/k and the saved logsumexp — two kernels, one accumulating dq
over k-blocks, one accumulating dk/dv over q-blocks.

This is the TPU-native replacement for the reference's fused attention CUDA
kernels (operators/fused/multihead_matmul_op.cu,
operators/math/bert_encoder_functor).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_FALLBACK: dict = {}
_INTERPRET = False  # tests flip this to run the kernels on CPU (interpret)


def _xla(q, k, v, causal, scale):
    from .attention import xla_attention

    return xla_attention(q, k, v, is_causal=causal, scale=scale)


def _shape_supported(q_shape, s_len) -> bool:
    B, T, H, D = q_shape
    return T % 128 == 0 and s_len % 128 == 0 and D in (64, 128, 256)


def _probe(dtype, causal: bool, D: int) -> bool:
    """Eagerly compile+run a tiny fwd+bwd pair once per (dtype, causal, D)
    configuration; True = must fall back.  Keyed per config so e.g. a
    bf16- or causal-specific lowering failure can't hide behind a healthy
    fp32 non-causal probe; execution discipline (ensure_compile_time_eval,
    platform gate) lives in ops/_pallas_probe.py."""
    from ._pallas_probe import probe_once

    def thunk():
        z = jax.device_put(jnp.zeros((1, 128, 1, D), dtype))
        out, vjp_fn = jax.vjp(
            lambda a, b, c: _flash(a, b, c, causal, None), z, z, z)
        return vjp_fn(out)

    return probe_once(_FALLBACK,
                      (jnp.dtype(dtype).name, bool(causal), int(D)), thunk)


def flash_attention(q, k, v, causal: bool = False, scale=None):
    """q,k,v: [B, T, H, D] → [B, T, H, D].  Falls back to XLA attention if the
    Pallas path is unavailable (non-TPU backend or unsupported shape).

    Not jitted itself: the availability probe must execute eagerly (it still
    works when tracing — the probe runs on its own concrete arrays)."""
    if not _shape_supported(q.shape, k.shape[1]) \
            or (not _INTERPRET and _probe(q.dtype, causal, q.shape[-1])):
        return _xla(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, scale):
    out, _ = _flash_fwd_impl(q, k, v, causal, scale)
    return out


def _flash_fwd(q, k, v, causal, scale):
    out, lse = _flash_fwd_impl(q, k, v, causal, scale)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, res, do):
    q, k, v, out, lse = res
    return _flash_bwd_impl(q, k, v, out, lse, do, causal, scale)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _heads_first(x):
    B, T, H, D = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(B * H, T, D)


def _heads_last(x, B, H):
    BH, T, D = x.shape
    return jnp.swapaxes(x.reshape(B, H, T, D), 1, 2)


_NEG = -1e30  # large-negative instead of -inf: keeps lse finite on empty rows


def _block_sizes(T, S):
    BQ = 128 if T % 128 == 0 else T
    BK = 128 if S % 128 == 0 else S
    return BQ, BK


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _flash_fwd_impl(q, k, v, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    S = k.shape[1]
    scale = scale if scale is not None else 1.0 / (D**0.5)
    BQ, BK = _block_sizes(T, S)
    qh, kh, vh = _heads_first(q), _heads_first(k), _heads_first(v)
    nq, nk = T // BQ, S // BK

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr):
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            m_scr[:] = jnp.full_like(m_scr, _NEG)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc_scr[:] = jnp.zeros_like(acc_scr)

        def body():
            qb = q_ref[0].astype(jnp.float32)
            kb = k_ref[0].astype(jnp.float32)
            s = scale * jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if causal:
                rows = qi * BQ + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 0)
                cols = ki * BK + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 1)
                s = jnp.where(rows >= cols, s, _NEG)
            m_prev = m_scr[:, 0]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
            p = jnp.exp(s - m_cur[:, None])
            alpha = jnp.exp(m_prev - m_cur)
            l_scr[:, 0] = l_scr[:, 0] * alpha + jnp.sum(p, axis=1)
            acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
                p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_scr[:, 0] = m_cur

        if causal:
            @pl.when((ki * BK) <= (qi * BQ + BQ - 1))
            def _run():
                body()
        else:
            body()

        @pl.when(ki == nk - 1)
        def _finish():
            l = l_scr[:, 0]
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)
            lse_ref[0] = (m_scr[:, 0] + jnp.log(l_safe))[:, None]

    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, BK, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, BK, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, BQ, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BQ, 1), jnp.float32),
            pltpu.VMEM((BQ, 1), jnp.float32),
            pltpu.VMEM((BQ, D), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(qh, kh, vh)
    return _heads_last(out, B, H), lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _flash_bwd_impl(q, k, v, out, lse, do, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    S = k.shape[1]
    scale = scale if scale is not None else 1.0 / (D**0.5)
    BQ, BK = _block_sizes(T, S)
    nq, nk = T // BQ, S // BK
    qh, kh, vh = _heads_first(q), _heads_first(k), _heads_first(v)
    doh = _heads_first(do)
    # delta_i = sum_d do_i * o_i  (rescaling term of the softmax transpose)
    delta = jnp.sum(doh.astype(jnp.float32) * _heads_first(out).astype(jnp.float32),
                    axis=-1, keepdims=True)  # [BH, T, 1]

    def scores(q_ref, k_ref, lse_ref, qi, ki):
        qb = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        s = scale * jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                        preferred_element_type=jnp.float32)
        if causal:
            rows = qi * BQ + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 0)
            cols = ki * BK + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 1)
            s = jnp.where(rows >= cols, s, _NEG)
        return jnp.exp(s - lse_ref[0])  # p, normalized (lse block is [BQ, 1])

    # -- dq: grid (BH, nq, nk), accumulate over k blocks --------------------
    def dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref, acc):
        qi, ki = pl.program_id(1), pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            acc[:] = jnp.zeros_like(acc)

        def body():
            p = scores(q_ref, k_ref, lse_ref, qi, ki)
            dp = jax.lax.dot_general(
                do_ref[0].astype(jnp.float32), v_ref[0].astype(jnp.float32),
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
            ds = p * (dp - dl_ref[0])
            acc[:] += scale * jax.lax.dot_general(
                ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        if causal:
            @pl.when((ki * BK) <= (qi * BQ + BQ - 1))
            def _run():
                body()
        else:
            body()

        @pl.when(ki == nk - 1)
        def _fin():
            dq_ref[0] = acc[:].astype(dq_ref.dtype)

    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, BK, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, BK, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, BQ, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, BQ, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((BQ, D), jnp.float32)],
        interpret=_INTERPRET,
    )(qh, kh, vh, doh, lse, delta)

    # -- dk/dv: grid (BH, nk, nq), accumulate over q blocks -----------------
    def dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                   dk_ref, dv_ref, dk_acc, dv_acc):
        ki, qi = pl.program_id(1), pl.program_id(2)

        @pl.when(qi == 0)
        def _init():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        def body():
            p = scores(q_ref, k_ref, lse_ref, qi, ki)
            dov = do_ref[0].astype(jnp.float32)
            dv_acc[:] += jax.lax.dot_general(
                p, dov, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                dov, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - dl_ref[0])
            dk_acc[:] += scale * jax.lax.dot_general(
                ds, q_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        if causal:
            @pl.when((qi * BQ + BQ - 1) >= (ki * BK))
            def _run():
                body()
        else:
            body()

        @pl.when(qi == nq - 1)
        def _fin():
            dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, BQ, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, BK, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, BK, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, BQ, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, BQ, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, BQ, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, BK, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, BK, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((BK, D), jnp.float32),
            pltpu.VMEM((BK, D), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(qh, kh, vh, doh, lse, delta)

    return (_heads_last(dq, B, H), _heads_last(dk, B, H), _heads_last(dv, B, H))
