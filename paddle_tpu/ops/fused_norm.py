"""Pallas TPU fused LayerNorm — forward + backward via custom_vjp.

One VMEM-resident pass per row-block: the forward computes mean/rstd and the
normalized-affine output without materializing the centered tensor in HBM;
the backward fuses dx with the dgamma/dbeta row-reductions by revisiting a
single output block across the sequential TPU grid (the accumulator lives in
VMEM for the whole sweep).  Statistics and accumulation are always float32
regardless of the input dtype (bf16-safe, matching the reference kernels'
fp32 mean/variance accumulators).

This is the TPU-native replacement for the reference's fused LayerNorm CUDA
kernels (operators/layer_norm_op.cu, and the inference-side fusions
operators/fused/fused_fc_elementwise_layernorm_op.cu,
operators/fused/skip_layernorm_op.cu) — there the fusion is hand-scheduled
per kernel pair; here XLA already fuses the surrounding elementwise ops and
the Pallas kernel only takes over the row-statistics pattern XLA handles
with an extra HBM round-trip.

Like ops/flash_attention.py, the public entry probes availability once per
configuration and falls back to the plain XLA expression (non-TPU backends,
unsupported shapes), so it is safe to call from any path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ._pallas_probe import pad_rows as _pad_rows
from ._pallas_probe import row_block as _row_block_for

_FALLBACK: dict = {}
_INTERPRET = False  # tests flip this to run the kernels on CPU (interpret)


def _row_block(N: int, F: int) -> int | None:
    return _row_block_for(N, F)


def _xla_ln(x, g, b, eps):
    # cast back: fp32 affine params promote a bf16 x to fp32, but the
    # public contract is output dtype == x.dtype (what the Pallas path
    # returns) — a probe-triggered mid-stack fallback must not flip the
    # residual-stream dtype (it broke the fused GPT rungs' scan carry on
    # the chip, round-5 window 2)
    m = jnp.mean(x, axis=-1, keepdims=True)
    v = jnp.var(x, axis=-1, keepdims=True)
    return ((x - m) * jax.lax.rsqrt(v + eps) * g + b).astype(x.dtype)


def _probe(dtype, gdtype, bdtype, F: int, BN: int) -> bool:
    """True = fall back.  Probes the SAME kernel configuration the real
    call will use (the row-block size and each parameter dtype change the
    Mosaic lowering); shared scaffolding in ops/_pallas_probe.py."""
    from ._pallas_probe import probe_once

    def thunk():
        x = jax.device_put(jnp.zeros((BN, F), dtype))
        g = jax.device_put(jnp.ones((F,), gdtype))
        b = jax.device_put(jnp.zeros((F,), bdtype))
        out, vjp_fn = jax.vjp(lambda a, w, c: _fused_ln(a, w, c, 1e-5),
                              x, g, b)
        return vjp_fn(out)

    return probe_once(
        _FALLBACK,
        (jnp.dtype(dtype).name, jnp.dtype(gdtype).name,
         jnp.dtype(bdtype).name, int(F), int(BN)),
        thunk)


def fused_layer_norm(x, weight=None, bias=None, eps: float = 1e-5):
    """LayerNorm over the last axis of ``x`` ([..., F] -> [..., F]).

    ``weight``/``bias`` are optional [F] affine parameters.  Rows are
    padded up to the kernel's row-block multiple (pad rows' cotangents are
    zero by construction, so grads stay exact); falls back to the XLA
    expression when the Pallas path is unavailable (non-TPU backend,
    unaligned feature width)."""
    F = x.shape[-1]
    N = 1
    for d in x.shape[:-1]:
        N *= d
    g = jnp.ones((F,), x.dtype) if weight is None else weight
    b = jnp.zeros((F,), x.dtype) if bias is None else bias
    Np = _pad_rows(N)
    BN = _row_block(Np, F) if F % 128 == 0 else None
    if x.ndim < 2 or BN is None or \
            (not _INTERPRET and _probe(x.dtype, g.dtype, b.dtype, F, BN)):
        return _xla_ln(x, g, b, eps)
    x2 = x.reshape(N, F)
    if Np != N:
        x2 = jnp.pad(x2, ((0, Np - N), (0, 0)))
    y2d = _fused_ln(x2, g, b, eps)
    return y2d[:N].reshape(x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_ln(x, g, b, eps):
    y, _, _ = _ln_fwd_impl(x, g, b, eps)
    return y


def _ln_fwd(x, g, b, eps):
    y, mu, rstd = _ln_fwd_impl(x, g, b, eps)
    # b rides the residuals only for its dtype: the bias cotangent must
    # match the bias primal (which may differ from the weight's dtype)
    return y, (x, g, b, mu, rstd)


def _ln_bwd(eps, res, dy):
    x, g, b, mu, rstd = res
    dx, dg, db = _ln_bwd_impl(x, g, mu, rstd, dy)
    return dx, dg.astype(g.dtype), db.astype(b.dtype)


_fused_ln.defvjp(_ln_fwd, _ln_bwd)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _ln_fwd_impl(x, g, b, eps):
    from jax.experimental import pallas as pl

    N, F = x.shape
    BN = _row_block(N, F)
    g2, b2 = g.reshape(1, F), b.reshape(1, F)

    def kernel(x_ref, g_ref, b_ref, y_ref, mu_ref, rstd_ref):
        xb = x_ref[...].astype(jnp.float32)
        m = jnp.mean(xb, axis=1)
        c = xb - m[:, None]
        v = jnp.mean(c * c, axis=1)
        r = jax.lax.rsqrt(v + eps)
        xhat = c * r[:, None]
        y_ref[...] = (xhat * g_ref[...].astype(jnp.float32)
                      + b_ref[...].astype(jnp.float32)).astype(y_ref.dtype)
        mu_ref[...] = m[:, None]
        rstd_ref[...] = r[:, None]

    y, mu, rstd = pl.pallas_call(
        kernel,
        grid=(N // BN,),
        in_specs=[
            pl.BlockSpec((BN, F), lambda i: (i, 0)),
            pl.BlockSpec((1, F), lambda i: (0, 0)),
            pl.BlockSpec((1, F), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BN, F), lambda i: (i, 0)),
            pl.BlockSpec((BN, 1), lambda i: (i, 0)),
            pl.BlockSpec((BN, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, F), x.dtype),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(x, g2, b2)
    return y, mu, rstd


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _ln_bwd_impl(x, g, mu, rstd, dy):
    from jax.experimental import pallas as pl

    N, F = x.shape
    BN = _row_block(N, F)
    nb = N // BN
    g2 = g.reshape(1, F)

    # dgamma/dbeta accumulate into one (1, F) output block revisited by every
    # sequential grid step — the block stays VMEM-resident across the sweep
    def kernel(x_ref, g_ref, mu_ref, rstd_ref, dy_ref,
               dx_ref, dg_ref, db_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            dg_ref[...] = jnp.zeros_like(dg_ref)
            db_ref[...] = jnp.zeros_like(db_ref)

        xb = x_ref[...].astype(jnp.float32)
        dyb = dy_ref[...].astype(jnp.float32)
        r = rstd_ref[...][:, 0]
        xhat = (xb - mu_ref[...]) * r[:, None]
        wdy = dyb * g_ref[...].astype(jnp.float32)
        c1 = jnp.mean(wdy, axis=1)
        c2 = jnp.mean(wdy * xhat, axis=1)
        dx_ref[...] = ((wdy - c1[:, None] - xhat * c2[:, None])
                       * r[:, None]).astype(dx_ref.dtype)
        dg_ref[...] += jnp.sum(dyb * xhat, axis=0)[None, :]
        db_ref[...] += jnp.sum(dyb, axis=0)[None, :]

    dx, dg, db = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((BN, F), lambda i: (i, 0)),
            pl.BlockSpec((1, F), lambda i: (0, 0)),
            pl.BlockSpec((BN, 1), lambda i: (i, 0)),
            pl.BlockSpec((BN, 1), lambda i: (i, 0)),
            pl.BlockSpec((BN, F), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BN, F), lambda i: (i, 0)),
            pl.BlockSpec((1, F), lambda i: (0, 0)),
            pl.BlockSpec((1, F), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, F), x.dtype),
            jax.ShapeDtypeStruct((1, F), jnp.float32),
            jax.ShapeDtypeStruct((1, F), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(x, g2, mu, rstd, dy)
    return dx, dg.reshape(F), db.reshape(F)
