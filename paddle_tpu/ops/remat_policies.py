"""Shared activation-checkpointing policy names → jax checkpoint policies.

Reference capability: RecomputeOptimizer's checkpoint list
(fluid/optimizer.py:5288) names WHICH activations to keep; jax expresses
the same control as a saveable-predicate policy on ``jax.checkpoint``.
One resolver serves every surface that takes a policy name —
GPTConfig.remat_policy (text/gpt.py), DistributedStrategy
.recompute_configs.policy (distributed/fleet/strategy.py), the generic
PipelineLayer remat, and the on-device A/B tool
(tools/remat_compile_check.py via PADDLE_TPU_REMAT_POLICY).

Accepted names (aliases map to the same policy):
* ``None`` / ``"none"`` / ``"full"`` / ``"nothing_saveable"`` — save
  nothing: full recompute, maximum memory saving;
* ``"dots"`` / ``"dots_saveable"`` — keep matmul outputs, recompute only
  cheap elementwise ops;
* ``"dots_no_batch"`` / ``"dots_with_no_batch_dims_saveable"`` — keep
  only non-batch matmul outputs (weights-stationary contractions);
* ``"everything"`` / ``"everything_saveable"`` — keep all residuals
  (checkpoint becomes a no-op; useful for A/B isolation).
"""
from __future__ import annotations

import jax

_ALIASES = {
    None: None, "none": None, "full": None, "nothing_saveable": None,
    "dots": "dots", "dots_saveable": "dots",
    "dots_no_batch": "dots_no_batch",
    "dots_with_no_batch_dims_saveable": "dots_no_batch",
    "everything": "everything", "everything_saveable": "everything",
}


def canonical(name: str | None) -> str | None:
    """Alias → canonical policy name (None / 'dots' / 'dots_no_batch' /
    'everything').  Estimators must key on THIS, not the raw string, or
    alias spellings silently desynchronize memory models from the
    compiled program."""
    if name not in _ALIASES:
        raise ValueError(
            f"unknown recompute/remat policy {name!r}; choose from "
            f"{sorted(k for k in _ALIASES if isinstance(k, str))} or None")
    return _ALIASES[name]


def resolve(name: str | None):
    """Policy name → jax checkpoint policy (None = save nothing)."""
    canon = canonical(name)
    if canon is None:
        return None
    return {
        "dots": jax.checkpoint_policies.checkpoint_dots,
        "dots_no_batch":
            jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
        "everything": jax.checkpoint_policies.everything_saveable,
    }[canon]
