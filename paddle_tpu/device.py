"""paddle.device — device management facade.

Reference: python/paddle/device.py (set_device/get_device/
is_compiled_with_* over the Place stack, platform/place.h:150).
"""
from .core.place import (  # noqa: F401
    CPUPlace, CUDAPlace, Place, TPUPlace, device_count, get_device,
    is_compiled_with_tpu, set_device)

__all__ = ["set_device", "get_device", "device_count", "Place", "CPUPlace",
           "TPUPlace", "CUDAPlace", "is_compiled_with_tpu",
           "is_compiled_with_cuda", "is_compiled_with_xpu",
           "is_compiled_with_npu", "is_compiled_with_rocm", "XPUPlace",
           "NPUPlace", "CUDAPinnedPlace", "get_cudnn_version"]


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_rocm():
    return False


def XPUPlace(idx: int = 0):
    raise NotImplementedError("TPU build has no XPU backend; use TPUPlace")


def NPUPlace(idx: int = 0):
    raise NotImplementedError("TPU build has no NPU backend; use TPUPlace")


def CUDAPinnedPlace():
    raise NotImplementedError("TPU build has no CUDA pinned memory; "
                              "host staging is PJRT-managed")


def get_cudnn_version():
    return None
