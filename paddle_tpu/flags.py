"""Global flags: ``paddle.set_flags`` / ``get_flags``.

Reference capability: ~35 gflags in platform/flags.cc exposed through
pybind/global_value_getter_setter.cc and settable as FLAGS_* env vars or
``paddle.set_flags``.  TPU-native mapping: flags that correspond to XLA/JAX
config knobs forward there; framework-behavior flags (nan/inf checking, GC,
allocator-strategy equivalents that PJRT owns) live in a plain registry consulted
by the runtime pieces.
"""
from __future__ import annotations

import os
from typing import Any, Iterable, Mapping

_JAX_MAPPED = {
    # reference FLAGS_check_nan_inf (platform/flags.cc:44): XLA-level nan
    # trap on every jitted computation
    "FLAGS_check_nan_inf": "jax_debug_nans",
    # escape hatch: run ops eagerly without compilation
    "FLAGS_disable_jit": "jax_disable_jit",
    # matmul precision on the MXU (bf16 passes vs fp32): 'default'|'high'|'highest'
    "FLAGS_matmul_precision": "jax_default_matmul_precision",
}

_REGISTRY: dict[str, Any] = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_disable_jit": False,
    "FLAGS_matmul_precision": None,
    # host-side step-level nan scan (framework/details/nan_inf_utils role,
    # implemented in framework.debugger for train steps)
    "FLAGS_check_nan_inf_host": False,
    "FLAGS_benchmark": False,
    "FLAGS_allocator_strategy": "pjrt",  # informational: PJRT owns HBM
}

# env seeding, like the reference's FLAGS_* env support — routed through
# set_flags below so JAX-mapped flags actually take effect
_ENV_SEEDED = {}
for _k in list(_REGISTRY):
    if _k in os.environ:
        v = os.environ[_k]
        _ENV_SEEDED[_k] = {"true": True, "false": False, "1": True,
                           "0": False}.get(v.lower(), v)


def set_flags(flags: Mapping[str, Any]):
    import jax

    for k, v in flags.items():
        if k not in _REGISTRY:
            raise ValueError(f"unknown flag {k!r}; known: {sorted(_REGISTRY)}")
        _REGISTRY[k] = v
        if k in _JAX_MAPPED and v is not None:
            jax.config.update(_JAX_MAPPED[k], v)


def get_flags(flags: str | Iterable[str]):
    if isinstance(flags, str):
        flags = [flags]
    return {k: _REGISTRY[k] for k in flags}


def flag(name: str, default=None):
    """Internal accessor used by framework code."""
    return _REGISTRY.get(name, default)


def async_train() -> bool:
    """Sync-free ``Model.fit`` loop (ON by default).

    When on, the fit loop keeps every per-step loss ON DEVICE and only
    drains (host-fetches) it at ``log_freq`` boundaries and epoch end, so
    steady-state train steps issue zero synchronous host<->device round
    trips and JAX async dispatch keeps the device saturated.
    ``PADDLE_TPU_ASYNC_TRAIN=0`` is the escape hatch (per-step float
    losses, the pre-PR-2 behavior).  Read at ``Model.prepare`` /
    ``TrainStep`` construction — like ``PADDLE_TPU_DONATE_DECODE`` it is
    part of the step's construction key (``train_step_key``): flipping it
    mid-process affects new TrainSteps, never a live one."""
    v = os.environ.get("PADDLE_TPU_ASYNC_TRAIN", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def train_grad_accum() -> int:
    """Default microbatch count for in-jit gradient accumulation
    (``TrainStep(grad_accum=...)``); ``PADDLE_TPU_GRAD_ACCUM=N`` sets the
    default for TrainSteps that don't pass it explicitly (1 = off).

    Accumulation is a ``lax.scan`` baked into the compiled step program
    at trace time, so the value is part of ``train_step_key``: flipping
    the env mid-process changes newly built steps (retrace), never a
    compiled one."""
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_GRAD_ACCUM", "1")))
    except ValueError:
        return 1


def fit_prefetch() -> bool:
    """Route ``Model.fit``'s batch stream through ``io.DevicePrefetcher``
    (ON by default): host batch assembly + the host->device transfer run
    in a background thread ``prefetch_factor`` batches ahead, overlapping
    the running step.  ``PADDLE_TPU_FIT_PREFETCH=0`` is the escape hatch
    (synchronous per-step uploads)."""
    v = os.environ.get("PADDLE_TPU_FIT_PREFETCH", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def train_step_key() -> tuple:
    """The trace-time training-flag tuple — the ``_cfg_key`` analog for
    the training hot path.  Everything here is BAKED into a TrainStep at
    construction (accumulation scan shape, async drain mode, prefetch
    routing, the non-finite skip guard, and any in-jit fault injection —
    the last two change the compiled program); today the TrainStep
    INSTANCE is the only cache (each construction re-reads the flags, so
    flipping an env var affects new steps and never a compiled one).
    Any future cross-instance cache of compiled train steps must fold
    this tuple into its key, exactly like the decode cache folds
    ``PADDLE_TPU_DONATE_DECODE``."""
    from . import faults as _faults

    return (train_grad_accum(), async_train(), fit_prefetch(),
            nan_guard(), _faults.spec_string())


def resilience_enabled() -> bool:
    """Resilience layer master switch (ON by default).

    When on, the runtime SURVIVES faults instead of dying on them:
    ``resilience.retry`` engages bounded backoff chains, ``DecodeServer``
    sheds expired requests / runs the OOM retry chain / recovers wedged
    async steps, ``TrainStep`` skips non-finite steps, and the
    ``DevicePrefetcher`` retries transient reader errors.
    ``PADDLE_TPU_RESILIENCE=0`` restores today's fail-fast behavior
    everywhere (retry = one attempt, every degradation chain skipped).
    Host-side scheduling only — never part of a decode jit-cache key;
    the one resilience knob that changes a compiled program
    (:func:`nan_guard`) folds into ``train_step_key`` itself."""
    v = os.environ.get("PADDLE_TPU_RESILIENCE", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def nan_guard() -> bool:
    """In-jit non-finite train-step guard (ON whenever resilience is on).

    When on, ``jit.TrainStep`` compiles a guard around the optimizer
    update: a step whose loss or gradients are non-finite applies NO
    update (params/opt state carried through unchanged) and bumps an
    on-device skip counter, drained by ``Model.fit`` at its existing
    host-fetch boundaries (``train.nonfinite_skips``).  Trace-time: the
    guard is baked into the compiled program, so it is part of
    ``train_step_key``.  ``PADDLE_TPU_NAN_GUARD=0`` disables just the
    guard while keeping the rest of the resilience layer."""
    if not resilience_enabled():
        return False
    v = os.environ.get("PADDLE_TPU_NAN_GUARD", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def nan_restore_k() -> int:
    """``PADDLE_TPU_NAN_RESTORE_K=K``: after K CONSECUTIVE non-finite
    (skipped) train steps, ``Model.fit`` restores the TrainStep from its
    last-good host snapshot (taken at drain boundaries while healthy).
    0 (default) = never restore — skipping alone is usually enough, and
    the snapshot costs a host copy of params+opt state, so it is strictly
    opt-in."""
    try:
        return max(0, int(os.environ.get("PADDLE_TPU_NAN_RESTORE_K", "0")))
    except ValueError:
        return 0


def request_ttl_s() -> float | None:
    """Default per-request serving deadline (``PADDLE_TPU_REQUEST_TTL_S``
    seconds, None = off): a request still QUEUED this long after submit
    is shed with the ``timeout`` status instead of occupying a slot
    (``DecodeServer.submit(ttl_s=...)`` overrides per request).  Host
    scheduling only — never a jit-cache key."""
    v = os.environ.get("PADDLE_TPU_REQUEST_TTL_S", "").strip()
    if not v:
        return None
    try:
        ttl = float(v)
    except ValueError:
        return None
    return ttl if ttl > 0 else None


def step_budget_s() -> float:
    """Wall budget for one async serving step's token fetch
    (``PADDLE_TPU_STEP_BUDGET_S`` seconds, 0 = watchdog off, the
    default): past it the wedge watchdog marks the server wedged
    (``/healthz`` 503), cancels the in-flight dispatch, rolls the slots
    back, and re-decodes — unaffected requests finish with bit-identical
    tokens.  The budget must comfortably exceed a worst-case honest step
    (compile excluded — warm up first)."""
    try:
        return max(0.0, float(
            os.environ.get("PADDLE_TPU_STEP_BUDGET_S", "0")))
    except ValueError:
        return 0.0


def prefetch_retries() -> int:
    """Bounded re-read retries for a ``DevicePrefetcher`` worker whose
    source iterator raises a transient error
    (``PADDLE_TPU_PREFETCH_RETRIES``, default 2; resilience off = 0)."""
    if not resilience_enabled():
        return 0
    try:
        return max(0, int(os.environ.get("PADDLE_TPU_PREFETCH_RETRIES",
                                         "2")))
    except ValueError:
        return 2


def wedge_evidence_ttl_s() -> float:
    """TTL on probe-wedge evidence (``PADDLE_TPU_WEDGE_TTL_S`` seconds,
    default 1800): a failed-probe log entry older than this no longer
    fail-fasts ``bench._probe_backend`` or flips ``probe_health`` to
    wedged — a long-past wedge must not condemn a healthy machine
    forever."""
    try:
        return max(0.0, float(os.environ.get("PADDLE_TPU_WEDGE_TTL_S",
                                             "1800")))
    except ValueError:
        return 1800.0


def donate_decode() -> bool:
    """KV-cache buffer donation on the decode/serving hot path (ON by
    default).

    When on, every jitted decode/prefill/sample step donates its cache
    argument (``donate_argnums``), so XLA aliases the [L, B, T, Hkv, hd]
    K/V buffers in place instead of allocating + copying them per token.
    ``PADDLE_TPU_DONATE_DECODE=0`` is the escape hatch — donation is
    baked into the compiled executable at trace time, so the flag is
    part of the decode jit-cache key (generate._cfg_key): flipping it
    mid-process retraces rather than silently reusing the other
    routing's executable."""
    v = os.environ.get("PADDLE_TPU_DONATE_DECODE", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def flash_decode() -> bool:
    """Split-KV Pallas decode attention on the cached-decode hot path (ON
    by default).

    When on (and the backend is a TPU whose probe passes), every cached
    attention site — single-token decode, batched serving ticks, verify
    chunks, chunked prefill — routes through
    ``ops/decode_attention.decode_attention`` instead of the XLA einsum
    over the full cache; off-TPU the einsum path is used regardless, so
    CPU tests see no change.  ``PADDLE_TPU_FLASH_DECODE=0`` is the escape
    hatch — like donation, the routing is baked into the compiled
    executable at trace time, so the flag is part of the decode jit-cache
    key (``decode_jit_key``): flipping it mid-process retraces."""
    v = os.environ.get("PADDLE_TPU_FLASH_DECODE", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def kv_cache_dtype() -> str:
    """KV-cache STORAGE dtype: '' (default — the model's compute dtype,
    the pre-flag behavior), 'fp32', 'bf16', or 'int8'.

    Selected at ``generate.init_cache`` time; int8 stores per-(position,
    head) scales beside the cache (``decode_attention.quantize_kv``) and
    dequantizes inside the decode kernel — decode HBM reads drop 4x vs
    fp32 (2x vs bf16) and the cache footprint shrinks the same factor.
    Composes with donation: shapes and dtypes are fixed per config, so
    the aliased buffers never change layout.  Part of ``decode_jit_key``
    (trace-time: the storage dtype changes the compiled program)."""
    v = os.environ.get("PADDLE_TPU_KV_DTYPE", "").strip().lower()
    if v in ("", "fp32", "float32"):
        return "" if v == "" else "fp32"
    if v in ("bf16", "bfloat16"):
        return "bf16"
    if v == "int8":
        return "int8"
    raise ValueError(
        f"PADDLE_TPU_KV_DTYPE={v!r}: expected fp32|bf16|int8 (or empty "
        f"for the model compute dtype)")


def kv_layout() -> str:
    """KV-cache LAYOUT for serving: 'contiguous' (default — one
    [L, max_batch, rows, Hkv, hd] slab, every slot provisioned for the
    worst-case context) or 'paged' (``text/kv_pool.py`` — a fixed pool of
    [block_size]-row blocks shared by all slots through per-slot block
    tables, with refcounted prefix reuse and copy-on-write).

    ``PADDLE_TPU_KV_LAYOUT=paged`` flips the ``DecodeServer`` default;
    ``generate.init_cache(layout=...)`` / ``DecodeServer(layout=...)``
    override per call.  Trace-time: the two layouts compile different
    step programs (the cache pytree structure differs), so the flag is
    part of ``decode_jit_key`` — flipping it mid-process retraces
    instead of silently reusing the other layout's executable."""
    v = os.environ.get("PADDLE_TPU_KV_LAYOUT", "").strip().lower()
    if v in ("", "contiguous", "slab"):
        return "contiguous"
    if v == "paged":
        return "paged"
    raise ValueError(
        f"PADDLE_TPU_KV_LAYOUT={v!r}: expected contiguous|paged")


def kv_block_size() -> int:
    """Rows per KV-cache block under the paged layout
    (``PADDLE_TPU_KV_BLOCK``, default 16).  Smaller blocks waste less
    tail memory per request and share finer prefixes; larger blocks cut
    table/grid overhead.  Must be a multiple of 8 (the decode kernel's
    row tile).  Part of ``decode_jit_key`` — the block geometry is baked
    into the compiled paged step."""
    v = os.environ.get("PADDLE_TPU_KV_BLOCK", "16")
    try:
        bs = int(v)
    except ValueError:
        # raise like the sibling flags (kv_layout, kv_cache_dtype): a
        # typo'd geometry must not silently compile a different one
        raise ValueError(
            f"PADDLE_TPU_KV_BLOCK={v!r}: expected an integer multiple "
            f"of 8")
    if bs < 8 or bs % 8:
        raise ValueError(
            f"PADDLE_TPU_KV_BLOCK={bs}: must be a positive multiple of 8")
    return bs


def kv_radix() -> bool:
    """Token-granular radix matching in the paged prefix index (ON by
    default).  When on, a prompt sharing only PART of an indexed block's
    tokens splits that node (the new parent shares the physical block
    under an extra refcount; the adopter's first write copies it through
    the normal COW drain) so admission adopts the longest *token*
    prefix.  ``PADDLE_TPU_KV_RADIX=0`` restores the whole-block
    matching — the A/B baseline ``bench.py --config prefix`` measures
    against.  Host-side index bookkeeping only — adoption depth changes
    which rows prefill recomputes, never the compiled programs, so this
    is NOT part of any jit-cache key."""
    v = os.environ.get("PADDLE_TPU_KV_RADIX", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def kv_spill_mb() -> int:
    """Host-RAM spill tier capacity in MiB for cold prefix-cache blocks
    (``PADDLE_TPU_KV_SPILL_MB``, default 0 = spill off).  When set, the
    OOM chain's evict-cold rung demotes cold block-aligned prefix chains
    to host buffers (one batched ``device_get`` per eviction round)
    instead of dropping them, and admission restores a spilled chain
    with one batched ``device_put`` + table scatter instead of a
    recompute walk.  Host scheduling only — NEVER a jit-cache key: the
    restore scatter rides the existing ``inject_rows`` executable
    buckets, so flipping spill on/off adds zero executable families."""
    try:
        return max(0, int(os.environ.get("PADDLE_TPU_KV_SPILL_MB", "0")))
    except ValueError:
        return 0


def kv_spill_batch() -> int:
    """Max prefix blocks demoted per spill round
    (``PADDLE_TPU_KV_SPILL_BATCH``, default 8) — the batching factor of
    the one ``device_get`` each evict-cold engagement pays.  Candidates
    beyond the batch fall back to a plain drop.  Host scheduling only,
    never a jit-cache key."""
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_KV_SPILL_BATCH",
                                         "8")))
    except ValueError:
        return 8


def kv_spill_rss_mb() -> int:
    """Host-RSS watchdog threshold in MiB
    (``PADDLE_TPU_KV_SPILL_RSS_MB``, default 0 = watchdog off).  When
    the process resident set crosses the threshold, the paged
    allocator's per-tick watchdog (:meth:`PagedAllocator.rss_watchdog`)
    engages one BOUNDED relief round: the oldest host-spilled prefix
    chains are released first (the spill store is the host tier the
    watchdog guards), then cold device-index leaves demote through the
    normal evict-cold LRU rung — at most ``PADDLE_TPU_KV_SPILL_BATCH``
    entries per round, so a hot server sheds pressure over ticks
    instead of stalling one.  Host scheduling only — NEVER a jit-cache
    key."""
    try:
        return max(0, int(os.environ.get("PADDLE_TPU_KV_SPILL_RSS_MB",
                                         "0")))
    except ValueError:
        return 0


def kv_restore() -> bool:
    """Restore policy for spilled prefix chains (ON by default).
    ``PADDLE_TPU_KV_RESTORE=0`` keeps the spill store write-only —
    admission recomputes instead of promoting host rows back, which
    turns the tier into a pure pressure-relief valve (a drill/debug
    posture).  Host scheduling only, never a jit-cache key."""
    v = os.environ.get("PADDLE_TPU_KV_RESTORE", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def fleet_prefill_threshold() -> int:
    """Prompt length (tokens) at which the fleet router hands admission
    prefill to a dedicated prefill worker instead of the decode
    replica's own admission path (``PADDLE_TPU_FLEET_PREFILL_THRESHOLD``,
    default 0 = every prompt when a worker is attached).  Host
    scheduling only — never a jit-cache key; the handoff's injected
    rows are bit-identical to local prefill either way, the threshold
    only picks WHERE the prefill FLOPs run."""
    try:
        return max(0, int(os.environ.get(
            "PADDLE_TPU_FLEET_PREFILL_THRESHOLD", "0")))
    except ValueError:
        return 0


def fleet_tick_block() -> int:
    """Decode steps per replica tick in the fleet router's serve loop
    (``PADDLE_TPU_FLEET_TICK_BLOCK``, default 1): >1 routes each
    replica's tick through ``tick_block(k)`` — fewer host round trips
    per token at block-granular retirement, the bench's serving
    lever.  Host scheduling only."""
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_FLEET_TICK_BLOCK",
                                         "1")))
    except ValueError:
        return 1


def spec_k() -> int:
    """Draft tokens proposed per speculative serving round
    (``PADDLE_TPU_SPEC_K``, default 0 = speculation off).  When a
    ``DecodeServer`` is built without an explicit ``spec_k=`` this is
    the value it resolves; the batched verify executable bakes K into
    its shapes, so the raw env string is part of ``decode_jit_key`` —
    flipping it mid-process retraces instead of silently reusing the
    other K's executable."""
    v = os.environ.get("PADDLE_TPU_SPEC_K", "0")
    try:
        k = int(v)
    except ValueError:
        raise ValueError(f"PADDLE_TPU_SPEC_K={v!r}: expected an integer "
                         f">= 0 (0 disables speculation)")
    if k < 0:
        raise ValueError(f"PADDLE_TPU_SPEC_K={k}: must be >= 0")
    return k


def spec_tree() -> int:
    """Node budget of the tree-speculation round
    (``PADDLE_TPU_SPEC_TREE``, default 0 = tree mode off).  When > 0 a
    ``DecodeServer`` built without an explicit ``spec_tree=`` proposes a
    token TREE of this many node slots per round (node 0 is the feed
    token) and verifies it in one tree-masked pass; mutually exclusive
    with linear ``spec_k``.  The node count is baked into the tree
    verify executable's shapes — the raw env string is part of
    ``decode_jit_key`` — but the tree's TOPOLOGY (ancestor mask +
    depths) is a runtime argument, so per-round shape changes never
    retrace."""
    v = os.environ.get("PADDLE_TPU_SPEC_TREE", "0")
    try:
        n = int(v)
    except ValueError:
        raise ValueError(f"PADDLE_TPU_SPEC_TREE={v!r}: expected an "
                         f"integer >= 0 (0 disables tree speculation)")
    if n < 0 or n == 1:
        raise ValueError(f"PADDLE_TPU_SPEC_TREE={n}: must be 0 (off) or "
                         f">= 2 (node 0 carries the feed token, so a "
                         f"1-node tree proposes nothing)")
    return n


def spec_branch() -> int:
    """Branching factor of tree-speculation proposals
    (``PADDLE_TPU_SPEC_BRANCH``, default 2): how many sibling
    candidates a propose step may fan out per node — top-b from the
    draft model, or distinct n-gram match continuations when
    self-drafting.  Host proposal shaping only — the verify executable
    sees topology as a runtime mask, so this is never a jit-cache
    key."""
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_SPEC_BRANCH", "2")))
    except ValueError:
        return 2


def prefill_budget() -> int:
    """Per-scheduler-round admission prefill token budget
    (``PADDLE_TPU_PREFILL_BUDGET``, default 0 = monolithic admission).
    When > 0, ``DecodeServer`` admission becomes incremental: a
    request's prefill advances at most this many tokens per scheduler
    round, interleaved with decode steps, so a long-prompt admission
    never stalls the decoding slots (Sarathi-style chunked-prefill
    co-scheduling).  The budget is the chunk WIDTH of the admission
    executables — a compiled shape — so the raw env string is part of
    ``decode_jit_key``; flipping it mid-process retraces instead of
    silently reusing the other width's program."""
    v = os.environ.get("PADDLE_TPU_PREFILL_BUDGET", "0")
    try:
        b = int(v)
    except ValueError:
        raise ValueError(
            f"PADDLE_TPU_PREFILL_BUDGET={v!r}: expected an integer >= 0 "
            f"(0 keeps monolithic admission)")
    if b < 0:
        raise ValueError(
            f"PADDLE_TPU_PREFILL_BUDGET={b}: must be >= 0")
    return b


def admission_enabled() -> bool:
    """SLO-driven admission control master switch (ON by default).

    When on, ``DecodeServer`` and ``fleet.Router`` construct an
    :class:`paddle_tpu.text.admission.AdmissionController`: per-tenant
    token-bucket rate limits, bounded per-class queues with
    shed-lowest-class-first overload policy, and the SLO degradation
    ladder (admit cap -> prefill-budget rung -> speculation fallback ->
    shed) driven by the TTFT/TPOT histograms.  ``PADDLE_TPU_ADMISSION=0``
    restores today's greedy FIFO admission EXACTLY (bit-parity: no
    controller is constructed, no request is ever ``rejected``).  Host
    scheduling only — never a jit-cache key; the budget ladder switches
    among PRE-WARMED chunk widths, it never flips the env."""
    v = os.environ.get("PADDLE_TPU_ADMISSION", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def adaptive_budget() -> bool:
    """Adaptive prefill budget (ON by default): the admission
    controller's TPOT objective (the ``serving.decode_gap_ms``
    histogram) drives prefill-budget rung switches on its OWN counter,
    finer than the coarse degradation ladder — one breached window
    shrinks the budget one rung WITHOUT halving the admit cap or
    forcing speculation off; healthy windows grow it back one rung,
    an idle window resets it.  The budget only ever moves between the
    ``ladder_widths`` rungs warmup() pre-compiled, so an adaptive move
    never retraces.  ``PADDLE_TPU_ADAPTIVE_BUDGET=0`` restores the
    ladder-only coupling."""
    v = os.environ.get("PADDLE_TPU_ADAPTIVE_BUDGET", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def _float_or_none(name: str) -> float | None:
    v = os.environ.get(name)
    if v is None or not v.strip():
        return None
    try:
        f = float(v)
    except ValueError:
        raise ValueError(f"{name}={v!r}: expected a number")
    return f if f > 0 else None


def slo_ttft_ms() -> float | None:
    """TTFT SLO in milliseconds (``PADDLE_TPU_SLO_TTFT_MS``; unset/0 =
    no TTFT objective).  The admission controller compares the WINDOWED
    ``serving.ttft_ms`` p99 against this each control tick; a breach
    climbs the degradation ladder."""
    return _float_or_none("PADDLE_TPU_SLO_TTFT_MS")


def slo_tpot_ms() -> float | None:
    """TPOT/decode-gap SLO in milliseconds (``PADDLE_TPU_SLO_TPOT_MS``;
    unset/0 = no TPOT objective).  Compared against the windowed
    ``serving.decode_gap_ms`` p99 — the stall metric budgeted admission
    bounds — each control tick."""
    return _float_or_none("PADDLE_TPU_SLO_TPOT_MS")


def slo_window_s() -> float:
    """SLO evaluation window in seconds (``PADDLE_TPU_SLO_WINDOW_S``,
    default 2.0): the controller re-reads the histograms at most once
    per window, degrades one rung per breached window, and recovers one
    rung per fully healthy window (symmetric by construction)."""
    try:
        return max(0.05, float(os.environ.get("PADDLE_TPU_SLO_WINDOW_S",
                                              "2.0")))
    except ValueError:
        return 2.0


def tenant_rate() -> float | None:
    """Per-tenant token-bucket refill rate, in admitted tokens
    (prompt + max_new) per second (``PADDLE_TPU_TENANT_RATE``; unset/0
    = no per-tenant rate limiting).  A submit whose tenant bucket
    cannot cover its cost is rejected with ``resilience.Overloaded``
    and counted ``admission.tenant_throttles``."""
    return _float_or_none("PADDLE_TPU_TENANT_RATE")


def tenant_burst() -> float | None:
    """Per-tenant token-bucket capacity (``PADDLE_TPU_TENANT_BURST``;
    default 2x the rate): how many tokens a quiet tenant may burst
    before the refill rate binds."""
    return _float_or_none("PADDLE_TPU_TENANT_BURST")


def admission_queue_cap() -> int:
    """Bounded per-class admission queues
    (``PADDLE_TPU_ADMISSION_QUEUE_CAP``, default 0 = unbounded): when
    the total queued work exceeds this cap, the LOWEST priority class
    sheds first (``rejected`` status, ``admission.sheds_class*``
    counters) — overload answers at the door instead of stacking
    queues until TTLs fire."""
    try:
        return max(0, int(os.environ.get(
            "PADDLE_TPU_ADMISSION_QUEUE_CAP", "0")))
    except ValueError:
        return 0


def requeue_max() -> int:
    """Eviction-count aging bound for the OOM-evict requeue path
    (``PADDLE_TPU_EVICT_REQUEUE_MAX``, default 8; 0 = unbounded, the
    pre-bound behavior).  An evicted request re-queues at the FRONT
    with a fresh TTL clock — under sustained pressure that can starve
    the rest of the queue forever, so after this many evictions the
    request fails honestly with the ``error`` status
    (``resilience.evict_requeue_overflows``) instead of cycling."""
    try:
        return max(0, int(os.environ.get("PADDLE_TPU_EVICT_REQUEUE_MAX",
                                         "8")))
    except ValueError:
        return 8


def spec_min_accept() -> float:
    """Rolling per-request acceptance rate below which a speculating
    slot falls back to plain decode (``PADDLE_TPU_SPEC_MIN_ACCEPT``,
    default 0.3).  Below ~1/3 acceptance a K-token verify does more
    target work per emitted token than plain stepping, so the slot
    stops paying for proposals it keeps rejecting.  Host scheduling
    only — never a jit-cache key; acceptance resolution happens on
    fetched logits either way."""
    try:
        return min(1.0, max(0.0, float(os.environ.get(
            "PADDLE_TPU_SPEC_MIN_ACCEPT", "0.3"))))
    except ValueError:
        return 0.3


def fleet_tick_workers() -> int:
    """Upper bound on threads the fleet router fans replica ticks out
    over (``PADDLE_TPU_FLEET_TICK_WORKERS``, default 8; 1 restores the
    sequential loop).  Each replica tick blocks on its own device
    round trip, so with N replicas the sequential loop serializes N
    round trips per router tick; the fan-out overlaps them.  Host
    scheduling only."""
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_FLEET_TICK_WORKERS",
                                         "8")))
    except ValueError:
        return 8


def prefix_route() -> bool:
    """Prefix-aware fleet routing (ON by default).  When on, each
    replica ships a compact prefix summary (root-fanout fingerprints +
    resident-token counts) in ``load_stats()`` and the router scores
    longest-expected-prefix overlap as a leading term beside its load
    triple, so a tenant's traffic lands where its KV already lives.
    ``PADDLE_TPU_PREFIX_ROUTE=0`` restores pure load-order routing.
    Host scheduling only, never a jit-cache key."""
    v = os.environ.get("PADDLE_TPU_PREFIX_ROUTE", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def prefix_route_imbalance() -> int:
    """Load-imbalance cap on prefix affinity: a replica only earns
    affinity credit while its queue depth is within this many requests
    of the least-loaded candidate
    (``PADDLE_TPU_PREFIX_ROUTE_IMBALANCE``, default 2).  The cap is what
    keeps a hot tenant from starving a cold replica — past it the
    router falls back to load order and the cold replica fills.  Host
    scheduling only."""
    try:
        return max(0, int(os.environ.get(
            "PADDLE_TPU_PREFIX_ROUTE_IMBALANCE", "2")))
    except ValueError:
        return 2


def fleet_max_queue() -> int:
    """Queued requests the router will stack on one replica beyond its
    free slots before holding work in the fleet-level queue
    (``PADDLE_TPU_FLEET_MAX_QUEUE``, default 2).  Deeper stacking hides
    admission latency; shallower keeps work re-routable (a request
    still in the FLEET queue can go to any replica when one wedges or
    frees up).  Host scheduling only."""
    try:
        return max(0, int(os.environ.get("PADDLE_TPU_FLEET_MAX_QUEUE",
                                         "2")))
    except ValueError:
        return 2


def stream_chunk_rows() -> int:
    """Prefill rows per streamed handoff chunk
    (``PADDLE_TPU_STREAM_CHUNK_ROWS``, default 256; 0 restores the
    monolithic whole-walk reply).  A prefill worker walks prompts longer
    than this through the offset-aware chunk executables and ships each
    finished chunk's cache rows over the raw transport WHILE computing
    the next one; the decode side injects each chunk through the
    existing pow2 injector buckets between its own ticks — transfer
    overlaps both ends, cutting handoff TTFT.  Host scheduling only,
    never a jit-cache key: the chunk width is rounded to a power of two
    so the executables come from the same bucketed families warmup
    already covers."""
    try:
        return max(0, int(os.environ.get("PADDLE_TPU_STREAM_CHUNK_ROWS",
                                         "256")))
    except ValueError:
        return 256


def fleet_autoscale() -> bool:
    """Telemetry-driven elastic fleet scaling
    (``PADDLE_TPU_FLEET_AUTOSCALE``, default off).  When on, the router
    watches the fleet's worst ``admission_rung`` each tick: sustained
    degradation (>= ``PADDLE_TPU_FLEET_SCALE_RUNG`` for
    ``PADDLE_TPU_FLEET_SCALE_OUT_TICKS`` consecutive ticks) attaches a
    registered spare replica; a sustained fully-idle fleet
    (``PADDLE_TPU_FLEET_SCALE_IN_TICKS`` ticks) drains the youngest
    replica back to the spare pool.  Host scheduling only."""
    v = os.environ.get("PADDLE_TPU_FLEET_AUTOSCALE", "0").strip().lower()
    return v not in ("0", "false", "off", "no", "")


def fleet_scale_rung() -> int:
    """Degradation rung that arms scale-out
    (``PADDLE_TPU_FLEET_SCALE_RUNG``, default 2): the fleet's worst
    replica ``admission_rung`` must sit at or above it.  Host scheduling
    only."""
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_FLEET_SCALE_RUNG",
                                         "2")))
    except ValueError:
        return 2


def fleet_scale_out_ticks() -> int:
    """Consecutive over-rung router ticks before a spare attaches
    (``PADDLE_TPU_FLEET_SCALE_OUT_TICKS``, default 3) — the sustain
    window that keeps one histogram blip from flapping the fleet.  Host
    scheduling only."""
    try:
        return max(1, int(os.environ.get(
            "PADDLE_TPU_FLEET_SCALE_OUT_TICKS", "3")))
    except ValueError:
        return 3


def fleet_scale_in_ticks() -> int:
    """Consecutive fully-idle router ticks before the youngest replica
    drains back to the spare pool
    (``PADDLE_TPU_FLEET_SCALE_IN_TICKS``, default 50).  Scale-in is
    deliberately much slower than scale-out: attaching a spare is
    cheap, re-warming a drained replica's executables is not.  Host
    scheduling only."""
    try:
        return max(1, int(os.environ.get(
            "PADDLE_TPU_FLEET_SCALE_IN_TICKS", "50")))
    except ValueError:
        return 50


def telemetry_enabled() -> bool:
    """Runtime telemetry master switch (ON by default).

    When on, :mod:`paddle_tpu.telemetry` records serving request spans +
    latency histograms, training step timings, and the jit recompile
    watch.  ``PADDLE_TPU_TELEMETRY=0`` is the escape hatch: every record
    call early-outs and the jit-compile instrumentation wrapper is never
    installed (the hot paths run the raw executables).  Unlike the
    trace-time routing flags this is NOT part of any jit-cache key —
    telemetry never changes a compiled program, only host bookkeeping."""
    v = os.environ.get("PADDLE_TPU_TELEMETRY", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def device_feed_enabled() -> bool:
    """Device-truth telemetry feed (ON by default, nested under the
    telemetry master switch).

    When on, every jit-cache miss routed through
    ``telemetry.instrument_compile`` also captures the executable's
    ``cost_analysis``/``memory_analysis`` (per-step FLOPs, HBM bytes
    moved, argument/output/temp sizes) so ``telemetry.snapshot()`` can
    derive live MFU and roofline gauges, and the serving/fit hot paths
    sample PJRT device memory stats at a rate-limited cadence.  The
    capture costs one extra lowering per compiled executable — never per
    step.  The memory-analysis half additionally needs an AOT recompile,
    paid only where cheap/amortized: on CPU, when the persistent compile
    cache is configured (``DecodeServer.warmup`` configures it), or
    under an explicit ``PADDLE_TPU_DEVICE_FEED=full``; otherwise the
    feed carries FLOPs/bytes from the lowering's cost analysis alone.
    ``PADDLE_TPU_DEVICE_FEED=0`` is the escape hatch; like the telemetry
    master it never changes a compiled program, only host bookkeeping."""
    return device_feed_mode() != "off"


def device_feed_mode() -> str:
    """'off' | 'on' | 'full' — the one parse of ``PADDLE_TPU_DEVICE_FEED``
    (telemetry's capture gate and :func:`device_feed_enabled` both read
    it here, so the value set can't diverge between the two sites)."""
    if not telemetry_enabled():
        return "off"
    v = os.environ.get("PADDLE_TPU_DEVICE_FEED", "1").strip().lower()
    if v in ("0", "false", "off", "no"):
        return "off"
    return "full" if v == "full" else "on"


def hbm_sample_interval_s() -> float:
    """Minimum seconds between PJRT ``memory_stats()`` samples on the
    hot paths (``PADDLE_TPU_HBM_SAMPLE_MS``, default 500).  The stats
    call is a host-side PJRT query — not a device sync — but through a
    remote tunnel it is still an RPC, so the hot-path sites rate-limit
    it here."""
    try:
        return max(0.0, float(os.environ.get("PADDLE_TPU_HBM_SAMPLE_MS",
                                             "500"))) / 1e3
    except ValueError:
        return 0.5


def telemetry_log() -> str | None:
    """``PADDLE_TPU_TELEMETRY_LOG=<path>``: append every telemetry span
    as one JSON line (consumed by ``tools/merge_timeline.py`` to build a
    merged Perfetto timeline or a quantile summary).  None = no log."""
    return os.environ.get("PADDLE_TPU_TELEMETRY_LOG") or None


def trace_enabled() -> bool:
    """Fleet distributed-tracing switch (ON by default, nested under
    the telemetry master switch — ``PADDLE_TPU_TELEMETRY=0`` already
    no-ops the whole plane).  ``PADDLE_TPU_TRACE=0`` turns off just the
    trace-context mint at ``Router.submit``: no ``trace`` key rides the
    wire, every span record early-outs on the missing context, and the
    metrics aggregation keeps working.  Host scheduling only — never a
    jit-cache key."""
    v = os.environ.get("PADDLE_TPU_TRACE", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def trace_ring_spans() -> int:
    """Completed fleet-trace spans each entity's ring holds before new
    spans are dropped (and drop-counted) instead of growing host memory
    (``PADDLE_TPU_TRACE_RING``, default 4096).  Host scheduling only —
    never a jit-cache key."""
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_TRACE_RING",
                                         "4096")))
    except ValueError:
        return 4096


def trace_piggyback_cap() -> int:
    """Spans a worker/replica ships per reply or stats collection when
    the router drains its span ring (``PADDLE_TPU_TRACE_PIGGYBACK``,
    default 256) — bounds the header-frame growth of any one transport
    message; the remainder rides the next collection.  Host scheduling
    only."""
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_TRACE_PIGGYBACK",
                                         "256")))
    except ValueError:
        return 256


def fleet_metrics_port() -> int | None:
    """``PADDLE_TPU_FLEET_METRICS_PORT=<port>``: start the Router's
    fleet-aggregated metrics endpoint on this port when the Router is
    constructed without an explicit ``metrics_port=`` (0 = ephemeral).
    None = no endpoint unless asked per-Router.  Host scheduling only."""
    v = os.environ.get("PADDLE_TPU_FLEET_METRICS_PORT")
    if v is None or not v.strip():
        return None
    try:
        return max(0, int(v))
    except ValueError:
        return None


def decode_jit_key() -> tuple:
    """The trace-time decode-routing flag tuple — folded into every
    decode/serving jit-cache key (``generate._cfg_key``), so flipping any
    of these env vars mid-process retraces rather than silently reusing
    an executable that baked in the other routing: W4 kernel gate
    (woq.mm), fused LN (gpt._ln), cache donation, flash-decode kernel
    routing, and the KV-cache storage dtype."""
    return (os.environ.get("PADDLE_TPU_W4_KERNEL", ""),
            os.environ.get("PADDLE_TPU_FUSED_LN", ""),
            os.environ.get("PADDLE_TPU_DONATE_DECODE", ""),
            os.environ.get("PADDLE_TPU_FLASH_DECODE", ""),
            kv_cache_dtype(),
            # paged KV cache (text/kv_pool.py): layout + block geometry
            # change the compiled step (block-table gathers vs slab
            # slices), so both key the cache like the dtype does
            kv_layout(), kv_block_size(),
            # speculative serving: K is baked into the batched verify
            # executable's shapes (tokens [B, K], logits [B, K, V])
            os.environ.get("PADDLE_TPU_SPEC_K", ""),
            # tree speculation: the node budget is the tree verify
            # executable's chunk shape (topology itself is a runtime
            # arg — only the count traces)
            os.environ.get("PADDLE_TPU_SPEC_TREE", ""),
            # budgeted admission: the per-round prefill budget is the
            # chunk width of the admission executables
            os.environ.get("PADDLE_TPU_PREFILL_BUDGET", ""))


if _ENV_SEEDED:
    set_flags(_ENV_SEEDED)
