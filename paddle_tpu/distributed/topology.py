"""Communication topology (reference fleet/base/topology.py:36
CommunicateTopology / :117 HybridCommunicateGroup).

The reference builds NCCL rings per hybrid axis; here a "group" is a named
mesh axis — XLA lowers collectives over exactly those axes.  The classes keep
the reference's rank↔coordinate API so Fleet-style code ports directly, while
``CommGroup.axis`` is what actually drives pjit/shard_map.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass

import numpy as np


@dataclass
class CommGroup:
    """A communicator handle == a mesh axis (+ ranks for introspection)."""

    axis: str | None
    ranks: list
    id: int = 0

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def world_size(self):
        return len(self.ranks)


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(itertools.product(*[range(d) for d in dims]))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[n] for n in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank):
        return self.coordinate[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on axis == index."""
        ax = self._parallel_names.index(axis_name)
        return [r for r, c in enumerate(self.coordinate) if c[ax] == index]

    def get_comm_list(self, axis_name):
        """Groups of ranks that vary only along axis (the reference's ring
        membership lists)."""
        ax = self._parallel_names.index(axis_name)
        others = [self._parallel_names[i] for i in range(len(self._dims)) if i != ax]
        groups = []
        for fixed in itertools.product(*[range(self.get_dim(n)) for n in others]):
            grp = []
            for k in range(self._dims[ax]):
                kw = dict(zip(others, fixed))
                kw[axis_name] = k
                grp.append(self.get_rank(**kw))
            groups.append(grp)
        return groups


class HybridCommunicateGroup:
    """4-D (dp × pp × sharding × mp) topology over the global mesh
    (reference topology.py:117)."""

    AXIS_MAP = {"data": "dp", "pipe": "pp", "sharding": "sharding", "model": "mp"}

    def __init__(self, topology: CommunicateTopology | None = None, rank: int = 0):
        from .env import get_mesh

        if topology is None:
            mesh = get_mesh()
            dims, names = [], []
            for ref_name, ax in self.AXIS_MAP.items():
                names.append(ref_name)
                dims.append(mesh.shape.get(ax, 1))
            topology = CommunicateTopology(names, dims)
        self._topo = topology
        self.global_rank = rank
        self.nranks = topology.world_size()
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._mp_degree = topology.get_dim("model")
        coord = topology.get_coord(rank)
        names = topology.get_hybrid_group_names()
        self._coord = dict(zip(names, coord))

    # degrees
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    # ranks (coordinate along each axis)
    def get_data_parallel_rank(self):
        return self._coord["data"]

    def get_model_parallel_rank(self):
        return self._coord["model"]

    def get_stage_id(self):
        return self._coord["pipe"]

    def get_sharding_parallel_rank(self):
        return self._coord["sharding"]

    # groups — mesh-axis handles
    def get_data_parallel_group(self):
        return CommGroup("dp", self._topo.get_axis_list("data", 0))

    def get_model_parallel_group(self):
        return CommGroup("mp", self._topo.get_axis_list("model", 0))

    def get_pipe_parallel_group(self):
        return CommGroup("pp", self._topo.get_axis_list("pipe", 0))

    def get_sharding_parallel_group(self):
        return CommGroup("sharding", self._topo.get_axis_list("sharding", 0))

    def get_check_parallel_group(self):
        return CommGroup(None, list(range(self.nranks)))

    def get_p2p_next_rank(self):
        stage = (self._coord["pipe"] + 1) % self._pp_degree
        kw = dict(self._coord)
        kw["pipe"] = stage
        return self._topo.get_rank(**kw)

    def get_p2p_prev_rank(self):
        stage = (self._coord["pipe"] - 1) % self._pp_degree
        kw = dict(self._coord)
        kw["pipe"] = stage
        return self._topo.get_rank(**kw)

    def topology(self):
        return self._topo
