"""DataParallel wrapper + grad sync.

Reference: paddle.DataParallel (fluid/dygraph/parallel.py:382) + C++ Reducer
(imperative/reducer.cc — size-bucketed grad allreduce overlapping backward,
unused-parameter graph walk).

TPU-first: under SPMD there is nothing to overlap by hand — when the batch is
sharded on 'dp', XLA inserts (and schedules/overlaps) the gradient
all-reduces itself, bucketing included.  The wrapper therefore:
  * eager multi-device mode: shards input batches over 'dp' on the way in,
    and provides the explicit ``sync_gradients`` used by the eager loop
    (psum of leaf grads over 'dp' — the Reducer's job, one fused call);
  * inside jit/pjit: a no-op passthrough.
``no_sync`` matches the reference API (skip grad sync for gradient
accumulation)."""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from .env import get_mesh, has_mesh


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False):
        super().__init__()
        self._layers = layers
        self._sync_enabled = True
        self._find_unused = find_unused_parameters
        self._comm_buffer_bytes = int(comm_buffer_size * 1024 * 1024)

    def forward(self, *inputs, **kwargs):
        if has_mesh() and get_mesh().shape.get("dp", 1) > 1:
            sharded = []
            sh = NamedSharding(get_mesh(), P("dp"))
            for x in inputs:
                if isinstance(x, Tensor):
                    try:
                        x = Tensor(jax.device_put(x.value, sh),
                                   stop_gradient=x.stop_gradient)
                    except Exception:
                        pass
                sharded.append(x)
            inputs = tuple(sharded)
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        self._sync_enabled = False
        try:
            yield
        finally:
            self._sync_enabled = True

    def scale_loss(self, loss):
        return loss  # SPMD mean-loss semantics already global

    def apply_collective_grads(self):
        self.sync_gradients()

    def sync_gradients(self):
        """The Reducer's job (imperative/reducer.cc): bucketed grad
        allreduce + unused-parameter handling.

        Under single-controller SPMD the allreduce half is subsumed: grads
        of a dp-sharded batch arrive globally reduced (XLA inserted — and
        bucketed/overlapped — the collectives during backward), so no
        explicit communication remains to issue here.  What does remain is
        the unused-parameter walk: params untouched by this backward get
        zero grads so optimizer accumulator updates stay rank-consistent
        (the reference marks them via a graph walk so its allreduce doesn't
        hang; ours would silently skip the optimizer update instead — same
        divergence, same cure)."""
        if not self._sync_enabled:
            return
        if self._find_unused:
            for p in self._layers.parameters():
                if p.grad is None and getattr(p, "trainable", True):
                    p.grad = Tensor(jnp.zeros_like(p.value))

    # delegate everything else
    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__.get("_sub_layers", {}).get("_layers"), name)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, sd, *a, **kw):
        return self._layers.set_state_dict(sd, *a, **kw)
