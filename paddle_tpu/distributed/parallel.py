"""DataParallel wrapper + grad sync.

Reference: paddle.DataParallel (fluid/dygraph/parallel.py:382) + C++ Reducer
(imperative/reducer.cc — size-bucketed grad allreduce overlapping backward,
unused-parameter graph walk).

TPU-first: under SPMD there is nothing to overlap by hand — when the batch is
sharded on 'dp', XLA inserts (and schedules/overlaps) the gradient
all-reduces itself, bucketing included.  The wrapper therefore:
  * eager multi-device mode: shards input batches over 'dp' on the way in,
    and provides the explicit ``sync_gradients`` used by the eager loop
    (psum of leaf grads over 'dp' — the Reducer's job, one fused call);
  * inside jit/pjit: a no-op passthrough.
``no_sync`` matches the reference API (skip grad sync for gradient
accumulation)."""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import autograd as _autograd
from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from .env import get_mesh, has_mesh


def assign_group_by_size(params, group_size_bytes: int,
                         first_group_bytes: int | None = None):
    """Bucket parameters for fused gradient reduction (the reference
    ``AssignGroupBySize``, imperative/reducer.cc:226).

    Parameters are walked in REVERSE registration order (their grads become
    final roughly in that order during backward); the first bucket is
    capped at ``first_group_bytes`` (reference ``last_comm_buffer_size``)
    so the earliest-ready grads flush without waiting to fill a full
    bucket, and buckets never mix dtypes (their grads are concatenated
    into one array).  Returns a list of lists of params."""
    groups: list[list] = []
    cur: list = []
    cur_bytes = 0
    cur_dtype = None
    cap = first_group_bytes if first_group_bytes is not None \
        else group_size_bytes
    for p in reversed(list(params)):
        nbytes = int(np.prod(p.shape or (1,))) * jnp.dtype(p.dtype).itemsize
        if cur and (cur_dtype != p.dtype or cur_bytes + nbytes > cap):
            groups.append(cur)
            cur, cur_bytes = [], 0
            cap = group_size_bytes
        cur.append(p)
        cur_bytes += nbytes
        cur_dtype = p.dtype
    if cur:
        groups.append(cur)
    return groups


class Reducer:
    """Bucketed as-ready gradient reduction (reference imperative/
    reducer.cc): size-ordered buckets over the parameter list, each flushed
    with ONE fused collective the moment its last member's gradient
    becomes final during backward (leaf grad-ready hooks on the tape), so
    the reduction of early buckets overlaps the rest of backward via JAX
    async dispatch.

    Reduction semantics: MEAN over the ``axis`` rank blocks.  Under a
    multi-process (multi-controller) run each process contributes its
    process-local gradients (``jax.make_array_from_process_local_data``
    assembles the stacked global array); under the single controller the
    already-global gradients are tiled into the rank slots, so the mean is
    an exact no-op on the values while still exercising the same fused
    collective — one code path, both worlds."""

    def __init__(self, params, axis: str = "dp",
                 comm_buffer_bytes: int = 25 << 20,
                 first_bucket_bytes: int = 1 << 20,
                 find_unused_parameters: bool = False, on_flush=None):
        import weakref

        from ..compat import shard_map

        self.axis = axis
        self._find_unused = find_unused_parameters
        self._params = [p for p in params
                        if getattr(p, "trainable", True)
                        and not p.stop_gradient]
        self.groups = assign_group_by_size(self._params, comm_buffer_bytes,
                                           first_bucket_bytes)
        self._group_of = {id(p): gi for gi, g in enumerate(self.groups)
                          for p in g}
        self._on_flush = on_flush
        self._enabled = True
        # the reduction communicator, built ONCE (per-flush construction
        # would defeat jax.jit's identity-keyed cache and recompile every
        # bucket every step).  Multi-process: one mesh slot per PROCESS
        # (each contributes its whole local grads regardless of how many
        # devices it owns on the training mesh's dp axis); single
        # controller: the training mesh's dp axis.
        if jax.process_count() > 1:
            per_proc = {}
            for d in jax.devices():
                per_proc.setdefault(d.process_index, d)
            comm_devs = [per_proc[p] for p in sorted(per_proc)]
            self._comm_mesh = jax.sharding.Mesh(np.array(comm_devs),
                                                (axis,))
            self._n_blocks = len(comm_devs)
        else:
            mesh = get_mesh()
            self._comm_mesh = mesh
            self._n_blocks = mesh.shape.get(axis, 1)
        self._reduce_jit = jax.jit(shard_map(
            lambda x: jax.lax.pmean(x[0], axis), mesh=self._comm_mesh,
            in_specs=P(axis), out_specs=P(), check_vma=False))
        self._reset()
        # weakref trampoline: the global hook must not pin this Reducer
        # (and its parameters' grad arrays) for the life of the process
        ref = weakref.ref(self)
        holder = {}

        def hook(t):
            r = ref()
            if r is None:
                holder["remove"]()
                return
            r._ready(t)

        holder["remove"] = _autograd.add_leaf_grad_ready_hook(hook)
        self._remove_hook = holder["remove"]

    def _reset(self):
        self._pending = [len(g) for g in self.groups]
        self._flushed = [False] * len(self.groups)

    def remove(self):
        self._remove_hook()

    def set_enabled(self, flag: bool):
        self._enabled = flag

    def _ready(self, t):
        gi = self._group_of.get(id(t))
        if gi is None or not self._enabled:
            return
        if self._flushed[gi]:
            # a NEW backward re-entering a bucket flushed by a previous one
            # (gradient accumulation without no_sync): re-arm it.  Flushing
            # again is exact — ranks hold reduced(prev) + local(new), and
            # mean(reduced + local) = reduced + mean(local).
            self._flushed[gi] = False
            self._pending[gi] = len(self.groups[gi])
        self._pending[gi] -= 1
        if self._pending[gi] == 0:
            self._flush(gi)

    def _flush(self, gi: int):
        group = self.groups[gi]
        self._flushed[gi] = True
        flat = jnp.concatenate([
            jnp.ravel(p.grad.value if p.grad is not None
                      else jnp.zeros(p.shape, p.dtype)) for p in group])
        n = self._n_blocks
        if n > 1:
            sh = NamedSharding(self._comm_mesh, P(self.axis))
            if jax.process_count() > 1:
                # every process contributes its LOCAL grads as one block
                # of the stacked [n, L] global array
                stacked = jax.make_array_from_process_local_data(
                    sh, np.asarray(flat)[None], (n, flat.shape[0]))
            else:
                stacked = jax.device_put(
                    jnp.broadcast_to(flat, (n,) + flat.shape), sh)
            reduced = self._reduce_jit(stacked)
        else:
            reduced = flat
        off = 0
        for p in group:
            k = int(np.prod(p.shape or (1,)))
            pg = reduced[off:off + k].reshape(p.shape)
            p.grad = Tensor(pg, stop_gradient=True)
            off += k
        if self._on_flush is not None:
            self._on_flush(gi, [p for p in group])

    def finalize(self):
        """End-of-backward sweep (reference Reducer::FinalizeBackward):
        zero-fill unused parameters (find_unused_parameters) and flush any
        bucket whose members were not all reached, then re-arm for the
        next backward."""
        if not self._enabled:
            self._reset()
            return
        for gi, group in enumerate(self.groups):
            if self._flushed[gi]:
                continue
            missing = [p for p in group if p.grad is None]
            if missing and not self._find_unused:
                raise RuntimeError(
                    f"Reducer: {len(missing)} parameter(s) produced no "
                    "gradient this backward (e.g. an untaken branch). "
                    "Construct DataParallel with "
                    "find_unused_parameters=True to zero-fill them "
                    "(reference reducer.cc unused-variable walk)")
            self._flush(gi)
        self._reset()


class DataParallel(Layer):
    """``local_grads`` selects the Reducer mode: None (auto) enables the
    explicit bucketed reduction exactly when gradients are process-local —
    i.e. under a multi-controller run (jax.process_count() > 1).  Under the
    single controller SPMD already returns globally-reduced grads, so the
    Reducer is pure (mean of identical rank blocks) and stays off unless
    forced with ``local_grads=True`` (used by tests and by manual
    shard_map training loops that produce per-rank grads)."""

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 local_grads: bool | None = None):
        super().__init__()
        self._layers = layers
        self._sync_enabled = True
        self._find_unused = find_unused_parameters
        self._comm_buffer_bytes = int(comm_buffer_size * 1024 * 1024)
        if local_grads is None:
            local_grads = jax.process_count() > 1
        self._reducer = None
        if local_grads and has_mesh() \
                and get_mesh().shape.get("dp", 1) > 1:
            self._reducer = Reducer(
                layers.parameters(), axis="dp",
                comm_buffer_bytes=self._comm_buffer_bytes,
                first_bucket_bytes=int(last_comm_buffer_size * 1024 * 1024),
                find_unused_parameters=find_unused_parameters)

    def forward(self, *inputs, **kwargs):
        # multi-controller: every rank computes on its own LOCAL batch (the
        # reference per-rank semantics) and the Reducer merges grads —
        # resharding different per-rank values onto one global array would
        # silently build an inconsistent "global" input
        if jax.process_count() > 1:
            return self._layers(*inputs, **kwargs)
        if has_mesh() and get_mesh().shape.get("dp", 1) > 1:
            sharded = []
            sh = NamedSharding(get_mesh(), P("dp"))
            for x in inputs:
                if isinstance(x, Tensor):
                    try:
                        x = Tensor(jax.device_put(x.value, sh),
                                   stop_gradient=x.stop_gradient)
                    except Exception:
                        pass
                sharded.append(x)
            inputs = tuple(sharded)
        return self._layers(*inputs, **kwargs)

    def overlap_optimizer_update(self, optimizer):
        """Overlap gradient all-reduce with the optimizer update (the
        reference ParallelExecutor's pipelining: bucket k+1's fused
        allreduce runs while bucket k's update kernels execute).

        Wires the Reducer's as-ready bucket flush to
        ``optimizer.step_group``: each bucket's eager update dispatches
        the moment its fused collective does, and JAX async dispatch
        pipelines the next bucket's reduction behind it (the VJP closures
        captured their primals at forward time, so updating parameter
        values mid-backward cannot perturb still-running grad math).  The
        training loop's ``optimizer.step()`` then only closes the round —
        stragglers and unused parameters.  Requires the explicit-Reducer
        mode (``local_grads=True`` or a multi-process run) and no global
        ``grad_clip``."""
        if self._reducer is None:
            raise RuntimeError(
                "overlap_optimizer_update needs the explicit Reducer "
                "(DataParallel(local_grads=True) on a dp>1 mesh, or a "
                "multi-process run); under single-controller SPMD XLA "
                "already schedules/overlaps the collectives")
        if getattr(optimizer, "_grad_clip", None) is not None:
            raise ValueError(
                "global grad_clip needs every gradient before any update; "
                "overlap_optimizer_update is unavailable with grad_clip")
        self._reducer._on_flush = \
            lambda gi, params: optimizer.step_group(params)
        return self

    def close(self):
        """Detach the Reducer's grad-ready hook (safe to call twice; also
        happens automatically when the DataParallel is garbage-collected —
        the hook holds only a weakref)."""
        if self._reducer is not None:
            self._reducer.remove()
            self._reducer = None

    @contextlib.contextmanager
    def no_sync(self):
        self._sync_enabled = False
        if self._reducer is not None:
            self._reducer.set_enabled(False)
        try:
            yield
        finally:
            self._sync_enabled = True
            if self._reducer is not None:
                self._reducer.set_enabled(True)

    def scale_loss(self, loss):
        return loss  # SPMD mean-loss semantics already global

    def apply_collective_grads(self):
        self.sync_gradients()

    def sync_gradients(self):
        """The Reducer's job (imperative/reducer.cc): bucketed grad
        allreduce + unused-parameter handling.

        Under single-controller SPMD the allreduce half is subsumed: grads
        of a dp-sharded batch arrive globally reduced (XLA inserted — and
        bucketed/overlapped — the collectives during backward), so no
        explicit communication remains to issue here.  What does remain is
        the unused-parameter walk: params untouched by this backward get
        zero grads so optimizer accumulator updates stay rank-consistent
        (the reference marks them via a graph walk so its allreduce doesn't
        hang; ours would silently skip the optimizer update instead — same
        divergence, same cure)."""
        if not self._sync_enabled:
            return
        if self._reducer is not None:
            # buckets whose members all fired already flushed DURING
            # backward (as-ready hooks); this sweeps the stragglers +
            # unused params
            self._reducer.finalize()
            return
        if self._find_unused:
            for p in self._layers.parameters():
                if p.grad is None and getattr(p, "trainable", True):
                    p.grad = Tensor(jnp.zeros_like(p.value))

    # delegate everything else
    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__.get("_sub_layers", {}).get("_layers"), name)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, sd, *a, **kw):
        return self._layers.set_state_dict(sd, *a, **kw)
