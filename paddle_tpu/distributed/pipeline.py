"""Pipeline parallelism.

Reference: static PipelineOptimizer + SectionWorker 1F1B schedule
(framework/section_worker.cc:130-183: startup fwds, steady-state 1F1B, drain,
micro-batch scopes) and dygraph PipelineParallel.train_batch
(meta_parallel/pipeline_parallel.py:109, p2p send/recv of activations).

TPU-first: the schedule is DATA — a ``lax.scan`` over M + S - 1 ticks inside
``shard_map`` over the 'pp' mesh axis.  Stage s's input each tick arrives by
``ppermute`` from stage s-1 (an ICI neighbour hop, the send_v2/recv_v2
analog).  Because the whole pipeline is one differentiable program, jax.grad
produces the interleaved backward automatically — activation stashing is
XLA's liveness problem, optionally reduced with jax.checkpoint per stage
(the reference's recompute+pipeline combination).

The model contract is the stacked-block layout of text.gpt: params['blocks']
leaves carry a leading layer axis sharded P('pp'), so each stage physically
holds L/S layers.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..compat import shard_map


def make_pipeline_loss(embed_fn, stage_fn, head_loss_fn, n_micro: int, pp_size: int,
                       pp_axis: str = "pp", remat_stage: bool = True):
    """Loss for one shard_map instance with STATIC pipeline size pp_size."""

    S = pp_size
    perm = [(i, (i + 1) % S) for i in range(S)]

    def loss_fn(params, tokens, key):
        s = jax.lax.axis_index(pp_axis)
        M = n_micro
        B, T = tokens.shape
        mb = tokens.reshape(M, B // M, T)

        stage = stage_fn
        if remat_stage:
            stage = jax.checkpoint(stage_fn)

        ticks = M + S - 1
        keys = jax.random.split(key, ticks)
        x0_probe = embed_fn(params, mb[0])

        def tick(carry, inp):
            x_recv, loss_acc = carry
            t, k_t = inp
            in_idx = jnp.clip(t, 0, M - 1)
            tok_in = jax.lax.dynamic_index_in_dim(mb, in_idx, keepdims=False)
            x_in = jnp.where((s == 0), embed_fn(params, tok_in), x_recv)

            y = stage(params["blocks"], x_in, k_t)

            out_idx = jnp.clip(t - (S - 1), 0, M - 1)
            tok_out = jax.lax.dynamic_index_in_dim(mb, out_idx, keepdims=False)
            active_out = (s == S - 1) & (t >= S - 1)
            l = head_loss_fn(params, y, tok_out)
            loss_acc = loss_acc + jnp.where(active_out, l, 0.0)

            x_send = jax.lax.ppermute(y, pp_axis, perm)
            return (x_send, loss_acc), None

        init = (jnp.zeros_like(x0_probe), jnp.asarray(0.0, jnp.float32))
        (x_last, loss_sum), _ = jax.lax.scan(
            tick, init, (jnp.arange(ticks), keys))
        # only the last stage accumulated loss; make it visible everywhere
        loss = jax.lax.psum(loss_sum, pp_axis) / n_micro
        return loss

    return loss_fn


def build_pipeline_train_step(mesh: Mesh, embed_fn, stage_fn, head_loss_fn,
                              param_specs, optimizer, n_micro: int,
                              dp_axis="dp", pp_axis="pp", remat_stage=True):
    """pjit-compiled full train step with pp (+optional dp/mp) sharding.

    Returns step(params, opt_state, tokens, key, lr, step) -> (params, opt, loss).
    Gradients of pp-replicated params (embeddings) are psum'd across 'pp' by
    shard_map's AD transpose automatically; dp grads by the outer pmean.
    """
    S = mesh.shape[pp_axis]
    loss_inner = make_pipeline_loss(embed_fn, stage_fn, head_loss_fn, n_micro, S,
                                    pp_axis, remat_stage)

    tok_spec = P(dp_axis) if dp_axis in mesh.shape else P()

    def spmd_loss(params, tokens, key):
        l = loss_inner(params, tokens, key)
        if dp_axis in mesh.shape:
            l = jax.lax.pmean(l, dp_axis)
        # replicate across remaining axes for a fully-replicated scalar
        for ax in mesh.axis_names:
            if ax not in (dp_axis, pp_axis):
                l = jax.lax.pmean(l, ax)
        return l

    sharded_loss = shard_map(
        spmd_loss, mesh=mesh,
        in_specs=(param_specs, tok_spec, P()),
        out_specs=P(),
        check_vma=False,
    )

    def step_fn(params, opt_state, tokens, key, lr, step):
        loss, grads = jax.value_and_grad(sharded_loss)(params, tokens, key)
        new_params, new_opt = optimizer.apply_gradients(grads, params, opt_state,
                                                        lr=lr, step=step + 1)
        return new_params, new_opt, loss

    return jax.jit(step_fn, donate_argnums=(0, 1))
