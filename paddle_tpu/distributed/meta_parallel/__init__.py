from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding, mark_sharding,
)
from ..pp_layers import (  # noqa: F401
    LayerDesc, PipelineLayer, SharedLayerDesc,
)
