"""Tensor-parallel (Megatron) layers.

Reference: fleet/meta_parallel/parallel_layers/mp_layers.py —
VocabParallelEmbedding :30, ColumnParallelLinear :97, RowParallelLinear :170,
ParallelCrossEntropy :249 (c_softmax_with_cross_entropy op).

TPU-first: these layers DON'T issue collectives.  They are ordinary layers
whose Parameters carry PartitionSpecs; under pjit, GSPMD inserts the
identical all_gather/all_reduce pattern the reference codes by hand (column:
gather output or keep sharded; row: psum partial sums).  Activation
constraints (`mark_sharding`) pin the intermediate layouts so XLA cannot
de-shard them.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...core.dispatch import dispatch
from ...core.tensor import Tensor
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer_base import Layer
from ..env import get_mesh, has_mesh, normalize_spec


def mark_sharding(x, spec: P):
    """with_sharding_constraint that degrades gracefully outside pjit/mesh."""
    def fn(v):
        if not has_mesh():
            return v
        try:
            return jax.lax.with_sharding_constraint(
                v, jax.sharding.NamedSharding(get_mesh(), normalize_spec(spec)))
        except Exception:
            return v

    if isinstance(x, Tensor):
        return dispatch(fn, x, op_name="shard_constraint")
    return fn(x)


class ColumnParallelLinear(Layer):
    """W [in, out] sharded on out ('mp'); output either kept sharded
    (feeding a RowParallelLinear) or gathered."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr,
                                            default_initializer=I.XavierNormal())
        self.weight._sharding_spec = P(None, "mp")
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias._sharding_spec = P("mp")
        else:
            self.bias = None
            self._parameters["bias"] = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        spec = P(*([None] * (len(y.shape) - 1) + ["mp"]))
        y = mark_sharding(y, spec if not self.gather_output else P())
        return y


class RowParallelLinear(Layer):
    """W [in, out] sharded on in ('mp'); partial products psum'd by XLA."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr,
                                            default_initializer=I.XavierNormal())
        self.weight._sharding_spec = P("mp", None)
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias._sharding_spec = P()
        else:
            self.bias = None
            self._parameters["bias"] = None

    def forward(self, x):
        if self.input_is_parallel:
            spec = P(*([None] * (len(x.shape) - 1) + ["mp"]))
            x = mark_sharding(x, spec)
        y = F.linear(x, self.weight, self.bias)
        return mark_sharding(y, P())


class VocabParallelEmbedding(Layer):
    """Embedding table sharded on vocab ('mp').  GSPMD turns the gather into
    per-shard partial lookups + psum — the reference's masked-lookup +
    allreduce (mp_layers.py:70) emitted by the compiler."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter((num_embeddings, embedding_dim),
                                            attr=weight_attr,
                                            default_initializer=I.Normal(0.0, 0.02))
        self.weight._sharding_spec = P("mp", None)

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Softmax-CE over vocab-sharded logits (reference
    c_softmax_with_cross_entropy_op: sharded max/sum allreduce).  Under pjit
    the fp32 log_softmax reduction is compiled to exactly those collectives
    when the logits' last dim is sharded on 'mp'."""

    def __init__(self, mp_group=None, name=None):
        super().__init__()

    def forward(self, input, label):
        lbl = label.value if isinstance(label, Tensor) else label

        def fn(logits):
            spec = P(*([None] * (logits.ndim - 1) + ["mp"]))
            if has_mesh():
                try:
                    logits = jax.lax.with_sharding_constraint(
                        logits, jax.sharding.NamedSharding(get_mesh(), normalize_spec(spec)))
                except Exception:
                    pass
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            li = lbl
            if li.ndim == logp.ndim:
                li = jnp.squeeze(li, -1)
            picked = jnp.take_along_axis(logp, li[..., None].astype(jnp.int32), axis=-1)
            return -picked

        return dispatch(fn, input, op_name="parallel_cross_entropy")
