"""Collective communication API.

Reference: python/paddle/distributed/collective.py (all_reduce :413,
all_gather :587, scatter :665, barrier :166, alltoall :1455, send/recv
:1526/:1576) lowering to c_* NCCL ops (operators/collective/).

TPU-first, two layers:

1. **Primitives** — used *inside* ``shard_map`` bodies on raw arrays, mapping
   1:1 onto XLA collectives over ICI (psum / all_gather / psum_scatter /
   all_to_all / ppermute).  This is the layer the framework's own parallel
   code (Reducer, pipeline, ring attention) is written in.
2. **Eager API** — Tensor-level functions matching the reference signatures.
   A Tensor is a *global* (possibly sharded) array under single-controller
   SPMD, so e.g. ``all_reduce`` means "psum over the group axis of this
   array's shards" and executes a tiny jitted shard_map.

``use_calc_stream`` / c_sync_* stream ops have no analog: XLA schedules
async collectives itself (SURVEY.md §2.4).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..compat import shard_map as _shard_map

from ..core.tensor import Tensor
from .env import get_mesh
from .topology import CommGroup

__all__ = [
    "ReduceOp", "new_group", "all_reduce", "all_gather", "reduce_scatter",
    "broadcast", "reduce", "scatter", "alltoall", "barrier", "send", "recv",
    "prim",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


_GROUPS: dict[int, CommGroup] = {}
_NEXT_GID = [1]


def new_group(ranks=None, backend=None, axis: str | None = None) -> CommGroup:
    """Create a communicator.  TPU-native: a group IS a mesh axis; ranks lists
    are kept for reference-API introspection only."""
    mesh = get_mesh()
    if axis is None:
        # default: the first (outermost) axis — matches reference global group
        axis = mesh.axis_names[0]
    g = CommGroup(axis, ranks if ranks is not None else list(range(mesh.devices.size)),
                  id=_NEXT_GID[0])
    _GROUPS[g.id] = g
    _NEXT_GID[0] += 1
    return g


def _axis_of(group) -> str:
    if group is None:
        return get_mesh().axis_names[0]
    if isinstance(group, str):
        return group
    return group.axis


# ---------------------------------------------------------------------------
# layer 1: primitives (inside shard_map)
# ---------------------------------------------------------------------------


class prim:
    """XLA collective primitives over a named mesh axis (shard_map scope)."""

    @staticmethod
    def all_reduce(x, op=ReduceOp.SUM, group=None):
        ax = _axis_of(group)
        if op == ReduceOp.SUM:
            return jax.lax.psum(x, ax)
        if op == ReduceOp.MAX:
            return jax.lax.pmax(x, ax)
        if op == ReduceOp.MIN:
            return jax.lax.pmin(x, ax)
        if op == ReduceOp.AVG:
            return jax.lax.pmean(x, ax)
        if op == ReduceOp.PROD:
            return jnp.exp(jax.lax.psum(jnp.log(x), ax))
        raise ValueError(op)

    @staticmethod
    def all_gather(x, group=None, axis=0):
        return jax.lax.all_gather(x, _axis_of(group), axis=axis, tiled=True)

    @staticmethod
    def reduce_scatter(x, group=None, axis=0):
        return jax.lax.psum_scatter(x, _axis_of(group), scatter_dimension=axis, tiled=True)

    @staticmethod
    def all_to_all(x, group=None, split_axis=0, concat_axis=0):
        ax = _axis_of(group)
        return jax.lax.all_to_all(x, ax, split_axis=split_axis, concat_axis=concat_axis,
                                  tiled=True)

    @staticmethod
    def broadcast(x, src=0, group=None):
        ax = _axis_of(group)
        idx = jax.lax.axis_index(ax)
        masked = jnp.where(idx == src, x, jnp.zeros_like(x))
        return jax.lax.psum(masked, ax)

    @staticmethod
    def ppermute(x, perm, group=None):
        return jax.lax.ppermute(x, _axis_of(group), perm)

    @staticmethod
    def send_recv_ring(x, group=None, shift=1):
        """x_i → x_{(i+shift) mod n}: the pipeline/ring-attention edge move."""
        ax = _axis_of(group)
        n = jax.lax.axis_size(ax) if hasattr(jax.lax, "axis_size") else None
        if n is None:
            from .env import axis_size as _as

            n = _as(ax)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return jax.lax.ppermute(x, ax, perm)

    @staticmethod
    def axis_index(group=None):
        return jax.lax.axis_index(_axis_of(group))


# ---------------------------------------------------------------------------
# layer 2: eager Tensor API (single-controller global-array semantics)
# ---------------------------------------------------------------------------


def _run_collective(x: Tensor, body, in_spec, out_spec) -> Tensor:
    mesh = get_mesh()
    fn = _shard_map(body, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec,
                    check_vma=False)
    v = x.value if isinstance(x, Tensor) else x
    # reshard onto the mesh (eager tensors are usually committed to one
    # device; the collective needs the stacked layout distributed)
    v = jax.device_put(v, NamedSharding(mesh, in_spec))
    out = jax.jit(fn)(v)
    return Tensor(out)


def _check_stacked(tensor, ax, opname):
    """Eager collectives use the STACKED-PER-RANK convention: under the
    single controller there is no 'my rank's tensor' — the reference's
    per-rank inputs are represented as ONE global array whose leading dim
    concatenates every rank's contribution (dim0 = group_size * per_rank
    rows).  Anything else is silently wrong, so validate loudly."""
    from .env import axis_size

    n = axis_size(ax)
    v = tensor.value if isinstance(tensor, Tensor) else tensor
    shape = jnp.shape(v)
    if not shape or shape[0] % n:
        raise ValueError(
            f"{opname}: leading dim {shape[0] if shape else '<scalar>'} "
            f"must be a multiple of group size {n} — eager collectives "
            f"take the stacked-per-rank layout (rank i's tensor at rows "
            f"[i*B, (i+1)*B)); a replicated per-rank tensor must be "
            f"stacked/tiled first (see distributed/collective.py docstring)")
    return n


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Stacked-per-rank input [n*B, ...] → in-place result [B, ...]
    replicated: the sum (or max/min/avg/prod) over the n rank blocks —
    reference all_reduce semantics under a single controller."""
    ax = _axis_of(group)
    _check_stacked(tensor, ax, "all_reduce")
    out = _run_collective(
        tensor,
        lambda x: prim.all_reduce(x, op, ax),
        P(ax), P(),
    )
    tensor._value = out.value  # reference all_reduce is in-place
    return tensor


def all_gather(tensor_list, tensor: Tensor, group=None, sync_op=True):
    """Stacked-per-rank input; result (list of per-rank tensors) replicated."""
    ax = _axis_of(group)
    n = _check_stacked(tensor, ax, "all_gather")
    gathered = _run_collective(
        tensor, lambda x: prim.all_gather(x, ax, axis=0), P(ax), P(),
    )
    if tensor_list is not None:
        parts = jnp.split(gathered.value, n, axis=0)
        tensor_list.extend(Tensor(p) for p in parts)
    return gathered


def reduce_scatter(tensor: Tensor, op=ReduceOp.SUM, group=None):
    ax = _axis_of(group)
    _check_stacked(tensor, ax, "reduce_scatter")
    return _run_collective(
        tensor, lambda x: prim.reduce_scatter(x, ax, axis=0), P(ax), P(ax),
    )


def broadcast(tensor: Tensor, src=0, group=None, sync_op=True):
    ax = _axis_of(group)
    _check_stacked(tensor, ax, "broadcast")
    out = _run_collective(
        tensor, lambda x: prim.broadcast(x, src, ax), P(ax), P(),
    )
    tensor._value = out.value
    return tensor


def reduce(tensor: Tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # single-controller: reduce == all_reduce (result visible globally)
    return all_reduce(tensor, op, group)


def scatter(tensor: Tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Global→sharded: slice the source data across the axis.

    ``src`` is accepted for reference-API parity but is meaningless under a
    single controller: there is only one copy of ``tensor_list`` (it IS the
    source rank's data)."""
    ax = _axis_of(group)
    from .env import axis_size

    n = axis_size(ax)
    if tensor_list is not None:
        if len(tensor_list) != n:
            raise ValueError(
                f"scatter: tensor_list has {len(tensor_list)} entries; the "
                f"group size is {n} (one tensor per rank)")
        src_val = jnp.concatenate([t.value if isinstance(t, Tensor) else t
                                   for t in tensor_list], axis=0)
    else:
        _check_stacked(tensor, ax, "scatter")
        src_val = tensor.value
    mesh = get_mesh()
    sharded = jax.device_put(src_val, NamedSharding(mesh, P(ax)))
    tensor._value = sharded
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    ax = _axis_of(group)
    if isinstance(in_tensor_list, (list, tuple)):
        from .env import axis_size

        if len(in_tensor_list) != axis_size(ax):
            raise ValueError(
                f"alltoall: {len(in_tensor_list)} tensors for a group of "
                f"size {axis_size(ax)} (need one per rank)")
        x = Tensor(jnp.concatenate([t.value for t in in_tensor_list], axis=0))
    else:
        _check_stacked(in_tensor_list, ax, "alltoall")
        x = in_tensor_list
    out = _run_collective(
        x, lambda v: prim.all_to_all(v, ax, split_axis=0, concat_axis=0), P(ax), P(ax),
    )
    if out_tensor_list is not None:
        from .env import axis_size

        parts = jnp.split(out.value, axis_size(ax), axis=0)
        out_tensor_list.extend(Tensor(p) for p in parts)
    return out


def barrier(group=None):
    # XLA programs are bulk-synchronous; a psum over a scalar is a true barrier
    ax = _axis_of(group)
    t = Tensor(jnp.zeros((get_mesh().shape.get(ax, 1),), jnp.float32))
    all_reduce(t, ReduceOp.SUM, group)


def send(tensor: Tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv between eager ranks does not exist under "
        "single-controller SPMD; use prim.ppermute inside shard_map (pipeline "
        "edges) — see distributed.pipeline"
    )


recv = send


def get_group(gid: int) -> CommGroup:
    return _GROUPS[gid]
