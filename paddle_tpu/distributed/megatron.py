"""Megatron-style tensor-parallel primitives with MANUAL collectives.

Reference capability: fleet/meta_parallel/parallel_layers/mp_layers.py —
``VocabParallelEmbedding`` (:30), ``ColumnParallelLinear`` (:97),
``RowParallelLinear`` (:170), ``ParallelCrossEntropy`` (:249, backed by the
``c_softmax_with_cross_entropy`` CUDA op in
operators/collective/c_softmax_with_cross_entropy_op.cu).

Two worlds use these:

* Under plain ``pjit``/GSPMD, Megatron TP needs NO manual code — annotate the
  weight PartitionSpecs (text/gpt.py ``param_shardings``) and XLA inserts the
  identical collectives.  That is the default path.
* Inside ``shard_map`` regions (the pipeline-parallel schedule, ring
  attention), collectives are manual — exactly like the reference's c_* ops.
  These functions are that manual layer: each takes the *local shard* of the
  weight and the tensor-parallel ``axis`` name (None ⇒ no TP, degenerate
  single-shard math).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _axis_active(axis) -> bool:
    return axis is not None


def vocab_parallel_embedding(wte_local, tokens, axis: str | None,
                             vocab_per_shard: int | None = None):
    """Embedding lookup with the vocab dim sharded over ``axis``.

    Out-of-shard tokens contribute zeros; a psum over ``axis`` assembles the
    full embedding (reference VocabParallelEmbedding: mask + c_allreduce_sum).
    """
    if not _axis_active(axis):
        return wte_local[tokens]
    vps = vocab_per_shard if vocab_per_shard is not None else wte_local.shape[0]
    rank = lax.axis_index(axis)
    local = tokens - rank * vps
    ok = (local >= 0) & (local < vps)
    emb = wte_local[jnp.clip(local, 0, vps - 1)]
    emb = jnp.where(ok[..., None], emb, jnp.zeros((), emb.dtype))
    return lax.psum(emb, axis)


def column_parallel_linear(x, w_local, b_local=None):
    """y_local = x @ W[:, shard] (+ b[shard]) — no communication; output's
    feature dim is sharded (reference ColumnParallelLinear, gather_output=False)."""
    y = x @ w_local
    if b_local is not None:
        y = y + b_local
    return y


def row_parallel_linear(x_local, w_local, b=None, axis: str | None = None):
    """y = psum_over_axis(x_local @ W[shard, :]) (+ b) — the input's feature
    dim is sharded; one all-reduce restores the full activation (reference
    RowParallelLinear: matmul + c_allreduce_sum)."""
    y = x_local @ w_local
    if _axis_active(axis):
        y = lax.psum(y, axis)
    if b is not None:
        y = y + b
    return y


def vocab_parallel_logits(x, wte_local):
    """LM head against the vocab-sharded (tied) embedding: [., D] @ [Vl, D]^T
    → local logits [., Vl]. Stays sharded; feed to vocab_parallel_softmax_ce."""
    return x @ wte_local.T


def vocab_parallel_softmax_ce(logits_local, targets, axis: str | None,
                              vocab_per_shard: int | None = None):
    """Softmax cross-entropy over a vocab-sharded logits tensor.

    The reference's ``c_softmax_with_cross_entropy`` op: global max (pmax),
    global partition function (psum of exp-sums), target-logit fetch from the
    owning shard (mask + psum).  Per-token loss, fp32.
    """
    lg = logits_local.astype(jnp.float32)
    if not _axis_active(axis):
        m = jnp.max(lg, axis=-1, keepdims=True)
        z = jnp.sum(jnp.exp(lg - m), axis=-1)
        tl = jnp.take_along_axis(lg, targets[..., None], axis=-1)[..., 0]
        return jnp.log(z) + m[..., 0] - tl
    vps = vocab_per_shard if vocab_per_shard is not None else lg.shape[-1]
    # global max for numerical stability only — gradient-free (pmax has no AD
    # rule, so gather the per-shard maxes and reduce locally)
    m_local = lax.stop_gradient(jnp.max(lg, axis=-1))
    m = jnp.max(lax.all_gather(m_local, axis), axis=0)
    z = lax.psum(jnp.sum(jnp.exp(lg - m[..., None]), axis=-1), axis)
    rank = lax.axis_index(axis)
    local = targets - rank * vps
    ok = (local >= 0) & (local < vps)
    tl = jnp.take_along_axis(lg, jnp.clip(local, 0, vps - 1)[..., None], axis=-1)[..., 0]
    tl = lax.psum(jnp.where(ok, tl, 0.0), axis)
    return jnp.log(z) + m - tl
