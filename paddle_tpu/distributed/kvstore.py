"""TCP key-value store: bootstrap + barrier + heartbeat primitive.

Reference capability: the TCP bootstrap plumbing — ncclUniqueId exchange
(platform/gen_comm_id_helper.cc:126 CreateListenSocket / :286
SendBroadCastCommID), the gloo HTTP KV store (fleet/utils/http_server.py),
and the barrier tables.  TPU-native role: JAX's coordination service does the
PJRT-level bootstrap; this store covers the framework-level needs around it —
rendezvous of the coordinator address, elastic membership heartbeats,
cross-host barriers in launch/elastic tooling.  Pure stdlib, thread-per-conn.

Protocol: length-prefixed JSON requests {op, key, value?, ...} → {ok, value?}.
Ops: set, get (blocking-optional), add (atomic counter), barrier, keys, ping.
"""
from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
import time
from typing import Any


def send_frame(sock: socket.socket, payload: bytes):
    """Length-prefixed frame write (shared by the KV store and the PS
    service wire — one framing implementation to fix, not two)."""
    sock.sendall(struct.pack("!I", len(payload)) + payload)


def read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("peer closed")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("!I", read_exact(sock, 4))
    return read_exact(sock, n)


def _send(sock: socket.socket, obj: Any):
    send_frame(sock, json.dumps(obj).encode())


def _recv(sock: socket.socket) -> Any:
    return json.loads(recv_frame(sock).decode())


class KVServer:
    """Threaded TCP KV server; start() returns the bound (host, port)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        store: dict[str, Any] = {}
        cond = threading.Condition()

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        req = _recv(self.request)
                        op = req.get("op")
                        if op == "set":
                            with cond:
                                store[req["key"]] = req["value"]
                                cond.notify_all()
                            _send(self.request, {"ok": True})
                        elif op == "get":
                            timeout = req.get("timeout", 0)
                            deadline = time.time() + timeout
                            with cond:
                                while req["key"] not in store:
                                    left = deadline - time.time()
                                    if timeout == 0 or left <= 0:
                                        break
                                    cond.wait(min(left, 1.0))
                                val = store.get(req["key"])
                            _send(self.request,
                                  {"ok": req["key"] in store, "value": val})
                        elif op == "add":
                            with cond:
                                cur = int(store.get(req["key"], 0)) + int(
                                    req.get("value", 1))
                                store[req["key"]] = cur
                                cond.notify_all()
                            _send(self.request, {"ok": True, "value": cur})
                        elif op == "barrier":
                            key, world = req["key"], int(req["world"])
                            with cond:
                                cur = int(store.get(key, 0)) + 1
                                store[key] = cur
                                cond.notify_all()
                                deadline = time.time() + req.get("timeout", 300)
                                while int(store.get(key, 0)) % world != 0:
                                    left = deadline - time.time()
                                    if left <= 0:
                                        break
                                    cond.wait(min(left, 1.0))
                                done = int(store.get(key, 0)) % world == 0
                            _send(self.request, {"ok": done})
                        elif op == "keys":
                            with cond:
                                ks = [k for k in store
                                      if k.startswith(req.get("prefix", ""))]
                            _send(self.request, {"ok": True, "value": ks})
                        elif op == "stamp":
                            # heartbeat: stamped with the SERVER clock so
                            # liveness never depends on cross-host clock sync
                            with cond:
                                store[req["key"]] = time.time()
                                cond.notify_all()
                            _send(self.request, {"ok": True})
                        elif op == "snapshot":
                            with cond:
                                kv = {k: v for k, v in store.items()
                                      if k.startswith(req.get("prefix", ""))}
                            _send(self.request, {"ok": True, "value": kv,
                                                 "now": time.time()})
                        elif op == "delete":
                            with cond:
                                store.pop(req["key"], None)
                                cond.notify_all()
                            _send(self.request, {"ok": True})
                        elif op == "ping":
                            _send(self.request, {"ok": True})
                        else:
                            _send(self.request, {"ok": False,
                                                 "error": f"bad op {op}"})
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server((host, port), Handler)
        self.host, self.port = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self.host, self.port

    def shutdown(self):
        self._srv.shutdown()
        self._srv.server_close()


class KVClient:
    """Client handle; one persistent connection, thread-safe.

    Connection is retried with backoff until ``connect_timeout`` — peers may
    come up before the rank-0 server (the reference's comm-id exchange
    retries the same way)."""

    def __init__(self, host: str, port: int, timeout: float = 300,
                 connect_timeout: float = 60):
        self._addr = (host, port)
        self._lock = threading.Lock()
        deadline = time.time() + connect_timeout
        delay = 0.05
        while True:
            try:
                self._sock = socket.create_connection(self._addr,
                                                      timeout=timeout)
                break
            except OSError:
                if time.time() >= deadline:
                    raise
                time.sleep(delay)
                delay = min(delay * 2, 1.0)

    def _rpc(self, req: dict, wait: float = 0) -> dict:
        with self._lock:
            # the socket deadline must outlive any server-side blocking wait
            self._sock.settimeout(max(30.0, wait + 30.0))
            _send(self._sock, req)
            return _recv(self._sock)

    def set(self, key: str, value):
        return self._rpc({"op": "set", "key": key, "value": value})["ok"]

    def get(self, key: str, timeout: float = 0):
        r = self._rpc({"op": "get", "key": key, "timeout": timeout},
                      wait=timeout)
        return r["value"] if r["ok"] else None

    def add(self, key: str, value: int = 1) -> int:
        return int(self._rpc({"op": "add", "key": key, "value": value})["value"])

    def barrier(self, key: str, world: int, timeout: float = 300) -> bool:
        return self._rpc({"op": "barrier", "key": key, "world": world,
                          "timeout": timeout}, wait=timeout)["ok"]

    def keys(self, prefix: str = "") -> list:
        return self._rpc({"op": "keys", "prefix": prefix})["value"]

    def stamp(self, key: str):
        """Server-clock heartbeat write."""
        return self._rpc({"op": "stamp", "key": key})["ok"]

    def snapshot(self, prefix: str = ""):
        """Returns ({key: value}, server_now) for clock-skew-free liveness."""
        r = self._rpc({"op": "snapshot", "prefix": prefix})
        return r["value"], float(r["now"])

    def delete(self, key: str):
        return self._rpc({"op": "delete", "key": key})["ok"]

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
