"""DistributedStrategy (reference fleet/base/distributed_strategy.py:104 over
framework/distributed_strategy.proto:158-209 — 30+ toggles, serializable).

TPU-first: a plain serializable dataclass.  Each toggle maps to a composition
rule in the strategy compiler (fleet.base) instead of a Program-rewriting
meta-optimizer:
  amp → bf16 compute dtype;        recompute → jax.checkpoint;
  sharding → ZeRO opt-state specs; pipeline → 'pp' mesh axis + schedule;
  tensor_parallel → 'mp' axis Megatron specs;  dp → 'dp' axis batch shard;
  gradient_merge → k-step grad accumulation inside the jitted step;
  lamb/lars → optimizer swap;      localsgd → periodic param averaging.
"""
from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field


@dataclass
class HybridConfig:
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sp_degree: int = 1  # sequence/context parallel — beyond-reference axis
    # (the zigzag causal load-balancing LAYOUT is a model-level choice, not
    # a mesh degree: see build_gpt_train_step(sp_zigzag=True))


@dataclass
class AMPConfig:
    init_loss_scaling: float = 32768.0
    incr_every_n_steps: int = 1000
    decr_every_n_nan_or_inf: int = 2
    incr_ratio: float = 2.0
    decr_ratio: float = 0.5
    use_dynamic_loss_scaling: bool = True
    custom_white_list: list = field(default_factory=list)
    custom_black_list: list = field(default_factory=list)
    use_pure_bf16: bool = True  # TPU default: bf16, no loss scaling needed


@dataclass
class RecomputeConfig:
    checkpoints: list = field(default_factory=list)
    policy: str = "full"  # full | dots_saveable | nothing_saveable


@dataclass
class ShardingConfig:
    sharding_degree: int = 1
    stage: int = 1  # 1: opt state; 2: +grads; 3: +params  (ZeRO stages)
    offload: bool = False


@dataclass
class PipelineConfig:
    micro_batch_size: int = 1
    accumulate_steps: int = 1
    schedule_mode: str = "1F1B"  # F-then-B | 1F1B (reference section_worker.cc:130)


@dataclass
class GradientMergeConfig:
    k_steps: int = 1
    avg: bool = True


@dataclass
class LocalSGDConfig:
    k_steps: int = 1
    begin_step: int = 1


@dataclass
class DGCConfig:
    rampup_begin_step: int = 0
    sparsity: float = 0.999


@dataclass
class DistributedStrategy:
    # switches (reference proto fields)
    amp: bool = False
    recompute: bool = False
    sharding: bool = False
    pipeline: bool = False
    tensor_parallel: bool = False
    sequence_parallel: bool = False  # beyond-reference (SURVEY §2.3 gap)
    gradient_merge: bool = False
    lamb: bool = False
    lars: bool = False
    localsgd: bool = False
    dgc: bool = False
    fp16_allreduce: bool = False
    find_unused_parameters: bool = False
    fuse_all_reduce_ops: bool = True
    fuse_grad_size_in_MB: int = 32
    nccl_comm_num: int = 1  # kept for API parity; meaningless under XLA
    hierarchical_allreduce: bool = False
    a_sync: bool = False  # parameter-server async mode
    # sub-configs
    hybrid_configs: HybridConfig = field(default_factory=HybridConfig)
    amp_configs: AMPConfig = field(default_factory=AMPConfig)
    recompute_configs: RecomputeConfig = field(default_factory=RecomputeConfig)
    sharding_configs: ShardingConfig = field(default_factory=ShardingConfig)
    pipeline_configs: PipelineConfig = field(default_factory=PipelineConfig)
    gradient_merge_configs: GradientMergeConfig = field(default_factory=GradientMergeConfig)
    localsgd_configs: LocalSGDConfig = field(default_factory=LocalSGDConfig)
    dgc_configs: DGCConfig = field(default_factory=DGCConfig)

    def __setattr__(self, name, value):
        # accept dicts for sub-configs (reference API style:
        # strategy.hybrid_configs = {"dp_degree": 2, ...})
        current = self.__dict__.get(name)
        if isinstance(value, dict) and dataclasses.is_dataclass(current):
            for k, v in value.items():
                if hasattr(current, k):
                    setattr(current, k, v)
            return
        object.__setattr__(self, name, value)

    # serialization (the proto-backed reference is wire-serializable)
    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, s: str) -> "DistributedStrategy":
        data = json.loads(s)
        strat = cls()
        for k, v in data.items():
            setattr(strat, k, v)
        return strat

    def save_to_prototxt(self, path):
        with open(path, "w") as f:
            f.write(self.to_json())

    def load_from_prototxt(self, path):
        with open(path) as f:
            data = json.loads(f.read())
        for k, v in data.items():
            setattr(self, k, v)

    def mesh_shape(self) -> dict:
        h = self.hybrid_configs
        return {"dp": h.dp_degree, "mp": h.mp_degree, "pp": h.pp_degree,
                "sharding": h.sharding_degree, "sp": h.sp_degree}
