"""Fleet facade + strategy compiler.

Reference: fleet/base/fleet_base.py:72 Fleet (init :139,
distributed_optimizer :783, distributed_model :836, minimize :1288) and the
meta-optimizer stack (StrategyCompiler strategy_compiler.py:114 ordering
RawProgram/AMP/Recompute/Sharding/Pipeline program rewrites).

TPU-first: strategies don't rewrite a Program — they parameterize ONE pjit'd
train step:
  - dp        → batch PartitionSpec('dp')       (the RawProgramOptimizer role)
  - tp        → Megatron param specs over 'mp'  (TensorParallelOptimizer)
  - sharding  → ZeRO specs for optimizer state  (ShardingOptimizer)
  - pp        → stacked-layer specs over 'pp' + microbatch schedule
  - recompute → jax.checkpoint                  (RecomputeOptimizer)
  - gradient_merge → lax.scan grad accumulation (GradientMergeOptimizer)
  - amp       → bf16 compute dtype              (AMPOptimizer)
XLA then emits the same collectives the reference's rewrites insert by hand.
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...framework import random as _random
from ..env import get_mesh, init_parallel_env, normalize_spec, set_mesh
from ..topology import HybridCommunicateGroup
from .strategy import DistributedStrategy


class Fleet:
    def __init__(self):
        self._strategy: DistributedStrategy | None = None
        self._hcg: HybridCommunicateGroup | None = None
        self._is_initialized = False
        self._role_maker = None
        self._degraded = False

    def init(self, role_maker=None, is_collective=True, strategy=None,
             allow_degrade=False):
        from ..role_maker import PaddleCloudRoleMaker

        self._role_maker = role_maker or PaddleCloudRoleMaker(is_collective)
        self._strategy = strategy or DistributedStrategy()
        if self._role_maker.is_server():
            # PS-pod server process: no mesh/backend to initialize
            self._is_initialized = True
            return self
        shape = self._strategy.mesh_shape()
        n = len(jax.devices())
        need = int(np.prod(list(shape.values())))
        if need > n:
            # a silently-degraded mesh runs a COMPLETELY different program
            # (e.g. 4-way mp collapses to dp on 1 chip) — only do it when
            # the caller opted in (single-chip dev loop)
            if not allow_degrade:
                raise RuntimeError(
                    f"fleet.init: strategy mesh {shape} needs {need} "
                    f"devices but only {n} are visible; pass "
                    f"allow_degrade=True to collapse to {{'dp': {n}}} for "
                    f"a dev loop, or fix hybrid_configs degrees")
            import warnings

            warnings.warn(
                f"fleet.init: degrading mesh {shape} -> {{'dp': {n}}} "
                f"({need} devices requested, {n} visible); parallelism "
                f"semantics differ from the requested strategy",
                stacklevel=2)
            shape = {"dp": n}
            self._degraded = True
        init_parallel_env(shape)
        self._hcg = HybridCommunicateGroup()
        self._is_initialized = True
        return self

    @property
    def strategy(self):
        return self._strategy

    def get_hybrid_communicate_group(self):
        return self._hcg

    # reference rank/size helpers
    def worker_num(self):
        from ..env import get_world_size

        return get_world_size()

    def worker_index(self):
        from ..env import get_rank

        return get_rank()

    def is_first_worker(self):
        return self.worker_index() == 0

    def is_worker(self):
        return self._role_maker is None or self._role_maker.is_worker()

    def is_server(self):
        return self._role_maker is not None and self._role_maker.is_server()

    def server_num(self):
        return self._role_maker.server_num() if self._role_maker else 0

    def server_index(self):
        return self._role_maker.server_index() if self._role_maker else -1

    def barrier_worker(self):
        from .. import collective

        collective.barrier()

    def distributed_model(self, model):
        """Attach mesh/shardings to a Layer model (reference wraps with
        DataParallel/TensorParallel/PipelineParallel — here the sharding specs
        already on Parameters do the work under pjit)."""
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is not None:
            self._strategy = strategy
        if self._strategy and self._strategy.lamb:
            from ...optimizer import Lamb

            optimizer = Lamb(learning_rate=optimizer.get_lr(),
                             parameters=optimizer._parameter_list)
        if self._strategy and self._strategy.lars:
            from ...optimizer import Lars

            optimizer = Lars(learning_rate=optimizer.get_lr(),
                             parameters=optimizer._parameter_list)
        optimizer._fleet = self
        return optimizer

    def distributed_scaler(self, scaler):
        return scaler

    def build_train_step(self, loss_fn, params, optimizer, param_specs=None,
                         batch_spec=None, donate=True):
        """Compile the strategy-parameterized train step (the minimize
        analog, functional/pytree API).  Validates the toggle plan loudly
        first — unless the caller opted into a degraded mesh, where axis-
        requiring toggles disable with a warning (the reference's
        _disable_strategy behavior)."""
        from .strategy_compiler import compile_strategy

        plan = compile_strategy(
            self._strategy or DistributedStrategy(), dict(get_mesh().shape),
            on_missing_axis="disable" if self._degraded else "raise")
        return ShardedTrainStep(
            loss_fn, params, optimizer, mesh=get_mesh(), param_specs=param_specs,
            batch_spec=batch_spec, strategy=self._strategy, donate=donate,
            plan=plan,
        )

    def build_layer_train_step(self, model, loss_fn, optimizer,
                               example_input=None):
        """Route a Layer model per the compiled strategy plan (the
        distributed_model + minimize dispatch, fleet_base.py:836)."""
        from .strategy_compiler import build_layer_train_step

        return build_layer_train_step(
            model, loss_fn, optimizer,
            self._strategy or DistributedStrategy(),
            mesh=get_mesh(), example_input=example_input,
            on_missing_axis="disable" if self._degraded else "raise")

    def minimize(self, optimizer, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        loss.backward()
        optimizer.step()
        return [], []

    # checkpoint helpers (reference fleet_base.py:732 save_persistables)
    def save_persistables(self, executor_or_model, dirname, **kw):
        from ...framework.io import save

        model = executor_or_model
        save(model.state_dict(), f"{dirname}/model.pdparams")

    def save_inference_model(self, model, dirname, **kw):
        self.save_persistables(model, dirname)


fleet = Fleet()


def _leaf_is_spec(x):
    return isinstance(x, P) or x is None


def zero_shard_spec(spec: P | None, shape, axis_name="sharding", mesh=None):
    """ZeRO: add the sharding axis onto the first unsharded dim divisible by
    its size (reference ShardingOptimizer shards flat param/opt buffers;
    GSPMD shards dims — same memory win, no manual bucketing)."""
    m = mesh or get_mesh()
    size = m.shape.get(axis_name, 1)
    if size <= 1:
        return spec
    parts = list(spec) if spec is not None else []
    parts += [None] * (len(shape) - len(parts))
    used = {a for p in parts if p is not None
            for a in (p if isinstance(p, tuple) else (p,))}
    if axis_name in used:
        return spec
    for i, (p, dim) in enumerate(zip(parts, shape)):
        if dim % size == 0 and dim >= size:
            if p is None:
                parts[i] = axis_name
            elif isinstance(p, tuple):
                parts[i] = (*p, axis_name)
            else:
                parts[i] = (p, axis_name)
            return P(*parts)
    return spec


class ShardedTrainStep:
    """One pjit'd train step over the hybrid mesh (functional/pytree API).

    loss_fn(params, batch, key) -> scalar loss (pure).
    """

    def __init__(self, loss_fn, params, optimizer, mesh=None, param_specs=None,
                 batch_spec=None, strategy=None, donate=True,
                 extra_batch_specs=None, plan=None):
        self.mesh = mesh or get_mesh()
        set_mesh(self.mesh)
        self.optimizer = optimizer
        self.strategy = strategy or DistributedStrategy()
        self._plan = plan  # pre-compiled StrategyPlan (avoids recompiling)
        self._step = 0

        if param_specs is None:
            param_specs = jax.tree_util.tree_map(lambda _: P(), params)
        param_specs = jax.tree_util.tree_map(
            lambda s: normalize_spec(s if s is not None else P(), self.mesh),
            param_specs, is_leaf=_leaf_is_spec,
        )

        # ZeRO stages (reference sharding_optimizer.py:502,635,745 — there a
        # program rewrite staging broadcast/reduce-scatter by hand; here a
        # sharding-spec choice XLA lowers to the same collectives):
        #   1: optimizer state sharded over the zero axis
        #   2: + gradients (reduce-scatter instead of all-reduce; the
        #        grad-accumulation buffer under gradient_merge is sharded)
        #   3: + parameters (stored sharded; XLA all-gathers at use — FSDP)
        # the compiled plan is the single derivation source for strategy-
        # dependent step parameters (zero stage, grad-merge k)
        if self._plan is None:
            from .strategy_compiler import compile_strategy

            self._plan = compile_strategy(self.strategy,
                                          dict(self.mesh.shape),
                                          on_missing_axis="disable")
        plan = self._plan
        zero_stage = plan.zero_stage
        zero_axis = "sharding" if self.mesh.shape.get("sharding", 1) > 1 else "dp"

        def zero_spec_for(spec, v):
            return zero_shard_spec(spec, np.shape(v), zero_axis, self.mesh) or spec

        if zero_stage >= 3:
            param_specs = jax.tree_util.tree_map(
                zero_spec_for, param_specs, params, is_leaf=_leaf_is_spec)
        self.param_specs = param_specs
        p_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), param_specs, is_leaf=_leaf_is_spec
        )
        self.params = jax.tree_util.tree_map(
            lambda v, sh: jax.device_put(jnp.asarray(v), sh), params, p_shardings
        )

        # optimizer state: inherit param specs; ZeRO adds the sharding/dp axis
        def opt_spec_for(spec, v):
            if not zero_stage:
                return spec
            return zero_spec_for(spec, v)

        opt_specs = jax.tree_util.tree_map(
            lambda spec, v: opt_spec_for(spec, v), param_specs, self.params,
            is_leaf=_leaf_is_spec,
        )
        opt_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), opt_specs, is_leaf=_leaf_is_spec
        )
        self.opt_state = jax.jit(
            optimizer.init_state, out_shardings=opt_shardings
        )(self.params)

        if batch_spec is None:
            batch_spec = P("dp") if self.mesh.shape.get("dp", 1) > 1 else P()
        batch_spec = normalize_spec(batch_spec, self.mesh)
        self.batch_sharding = NamedSharding(self.mesh, batch_spec)

        k_steps = plan.k_steps
        remat = plan.has("recompute")
        # the strategy's recompute policy (RecomputeConfig.policy) selects
        # WHICH residuals the checkpoint keeps — 'full' = save nothing
        from ...ops.remat_policies import resolve as _resolve_policy

        remat_policy = _resolve_policy(
            self.strategy.recompute_configs.policy) if remat else None

        # ZeRO-2: gradients live (and accumulate) reduce-scattered over the
        # zero axis; the optimizer update is shard-local and XLA all-gathers
        # the updated params back to their stored sharding.
        grad_shardings = None
        if zero_stage >= 2:
            grad_shardings = jax.tree_util.tree_map(
                lambda spec, v: NamedSharding(self.mesh, zero_spec_for(spec, v)),
                param_specs, self.params, is_leaf=_leaf_is_spec)

        def shard_grads(g):
            if grad_shardings is None:
                return g
            return jax.lax.with_sharding_constraint(g, grad_shardings)

        # bucketed reduce/update overlap applies on the PLAIN dp path
        # only: every ZeRO stage keeps some per-leaf state sharded over
        # the zero axis (stage 1: optimizer m/v; 2/3: also grads/params),
        # and any OTHER live mesh axis (mp/pp/sp/ep) means params
        # themselves are sharded per leaf — in both cases a flat
        # cross-leaf concat would force GSPMD to re-gather exactly what
        # the sharding exists to keep distributed
        _non_dp_axes = [ax for ax, n in self.mesh.shape.items()
                        if ax != "dp" and n > 1]
        dp_bucketed = self.mesh.shape.get("dp", 1) > 1 \
            and not _non_dp_axes \
            and not zero_stage \
            and getattr(optimizer, "_elementwise", False)
        dp_bucket_bytes = int(getattr(self.strategy, "fuse_grad_size_in_MB",
                                      25) or 25) << 20

        def step_fn(params, opt_state, key, lr, step, batch):
            def loss_of(p, b, k):
                return loss_fn(p, b, k)

            if remat:
                loss_of = jax.checkpoint(loss_of, policy=remat_policy)
            grad_fn = jax.value_and_grad(loss_of)

            if k_steps > 1:
                # GradientMerge: split the global batch into k micro-batches
                # and accumulate grads in a scan (reference
                # gradient_merge_optimizer.py; keeps peak memory ∝ micro-batch)
                mb = jax.tree_util.tree_map(
                    lambda b: b.reshape((k_steps, b.shape[0] // k_steps) + b.shape[1:]),
                    batch,
                )
                keys = jax.random.split(key, k_steps)

                def acc_body(carry, xs):
                    g_acc, l_acc = carry
                    b_i, k_i = xs
                    l, g = grad_fn(params, b_i, k_i)
                    g_acc = jax.tree_util.tree_map(jnp.add, g_acc, shard_grads(g))
                    return (shard_grads(g_acc), l_acc + l), None

                g0 = shard_grads(jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params))
                (grads, loss), _ = jax.lax.scan(acc_body, (g0, 0.0), (mb, keys))
                grads = jax.tree_util.tree_map(lambda g: g / k_steps, grads)
                loss = loss / k_steps
            else:
                loss, grads = grad_fn(params, batch, key)
                grads = shard_grads(grads)

            if dp_bucketed:
                # data-parallel meshes: size-bucketed fused update (the
                # ParallelExecutor fused-allreduce role) — each bucket is
                # one flat update chain, so XLA's latency-hiding scheduler
                # overlaps the GSPMD-inserted gradient reduction of bucket
                # k+1 (attached to its concat, the grads' first use) with
                # bucket k's optimizer math.  Bit-identical numerics;
                # non-elementwise optimizers fall back inside.
                new_params, new_opt = optimizer.apply_gradients_bucketed(
                    grads, params, opt_state, lr=lr, step=step + 1,
                    bucket_bytes=dp_bucket_bytes)
            else:
                new_params, new_opt = optimizer.apply_gradients(
                    grads, params, opt_state, lr=lr, step=step + 1)
            return new_params, new_opt, loss

        self._compiled = jax.jit(
            step_fn,
            in_shardings=(p_shardings, opt_shardings, None, None, None,
                          self.batch_sharding),
            out_shardings=(p_shardings, opt_shardings, None),
            donate_argnums=(0, 1) if donate else (),
        )

    def _current_lr(self):
        from ...optimizer.lr import LRScheduler

        if isinstance(self.optimizer._lr, LRScheduler):
            return float(self.optimizer._lr.lr_at(self._step))
        return self.optimizer.get_lr()

    def __call__(self, batch):
        if isinstance(batch, Tensor):
            batch = batch.value
        batch = jax.tree_util.tree_map(
            lambda b: jax.device_put(jnp.asarray(b), self.batch_sharding), batch)
        key = _random.next_key()
        lr = self._current_lr()
        # pass the 0-based step; step_fn's +1 makes Adam's first update t=1
        self.params, self.opt_state, loss = self._compiled(
            self.params, self.opt_state, key, lr, self._step, batch)
        self._step += 1
        return Tensor(loss, stop_gradient=True)
