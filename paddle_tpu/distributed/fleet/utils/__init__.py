"""fleet.utils — filesystem abstraction + helpers.

Reference: python/paddle/distributed/fleet/utils/fs.py (LocalFS/HDFSClient
used by auto-checkpoint and PS snapshot upload) and fleet/utils/__init__.py.
"""
from .fs import FS, HDFSClient, LocalFS  # noqa: F401

__all__ = ["FS", "LocalFS", "HDFSClient"]
