"""Filesystem abstraction (reference fleet/utils/fs.py).

LocalFS is complete; HDFSClient shells out to the ``hadoop`` CLI exactly
like the reference — in hadoop-less environments every call raises a clear
error naming the missing binary instead of failing mid-checkpoint.
"""
from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["FS", "LocalFS", "HDFSClient"]


class FS:
    def ls_dir(self, path):
        raise NotImplementedError

    def is_file(self, path):
        raise NotImplementedError

    def is_dir(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def mv(self, src, dst, overwrite=False):
        raise NotImplementedError

    def touch(self, path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """reference fs.py LocalFS."""

    def ls_dir(self, path):
        if not self.is_exist(path):
            return [], []
        dirs, files = [], []
        for e in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, e))
             else files).append(e)
        return dirs, files

    def is_file(self, path):
        return os.path.isfile(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(src):
            raise FileNotFoundError(src)
        if self.is_exist(dst):
            if not overwrite:
                raise FileExistsError(dst)
            self.delete(dst)
        shutil.move(src, dst)

    def touch(self, path, exist_ok=True):
        if self.is_exist(path) and not exist_ok:
            raise FileExistsError(path)
        open(path, "a").close()

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def list_dirs(self, path):
        return self.ls_dir(path)[0]


class HDFSClient(FS):
    """reference fs.py HDFSClient — ``hadoop fs`` CLI wrapper."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300000,
                 sleep_inter=1000):
        self._hadoop = (os.path.join(hadoop_home, "bin", "hadoop")
                        if hadoop_home else "hadoop")
        self._configs = configs or {}
        self._available = shutil.which(self._hadoop) is not None

    def _run(self, *args):
        if not self._available:
            raise RuntimeError(
                f"hadoop CLI not found ({self._hadoop!r}); HDFSClient needs "
                "a hadoop installation — use LocalFS for local checkpoints")
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        out = subprocess.run(cmd, capture_output=True, text=True)
        return out.returncode, out.stdout

    def ls_dir(self, path):
        rc, out = self._run("-ls", path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, path):
        rc, _ = self._run("-test", "-e", path)
        return rc == 0

    def is_file(self, path):
        rc, _ = self._run("-test", "-f", path)
        return rc == 0

    def is_dir(self, path):
        rc, _ = self._run("-test", "-d", path)
        return rc == 0

    def mkdirs(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path)

    def mv(self, src, dst, overwrite=False):
        if overwrite:
            self.delete(dst)
        self._run("-mv", src, dst)

    def touch(self, path, exist_ok=True):
        self._run("-touchz", path)

    def upload(self, local_path, fs_path):
        self._run("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)
