"""Streaming data-generator protocol for the industrial datasets.

Reference capability: fleet/data_generator/data_generator.py — users
subclass ``DataGenerator``, implement :meth:`generate_sample`, and the
runner turns raw log lines (stdin or memory) into the slot text format
the C++ DataFeed consumes: per slot, ``<n> v1 .. vn`` tokens joined by
spaces, one sample per line.  ``InMemoryDataset``/``QueueDataset``
(fleet/dataset.py) read files written in this format.

TPU-first note: the protocol is pure host-side text processing, so the
implementation is plain Python — the parsed batches reach the chip
through the native feeder (io_runtime) exactly like any other file.
"""
from __future__ import annotations

import sys
from typing import Iterable

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    """Base runner.  Subclasses implement :meth:`generate_sample(line)`
    returning a zero-arg generator of ``[(slot_name, [values...]), ...]``
    samples; optionally :meth:`generate_batch(samples)` for cross-sample
    logic (negative sampling, batching tricks)."""

    def __init__(self):
        self.batch_size_ = 32

    def set_batch(self, batch_size: int):
        self.batch_size_ = int(batch_size)

    # -- user hooks ----------------------------------------------------------
    def generate_sample(self, line):
        raise NotImplementedError(
            "implement generate_sample(line) -> iterator of "
            "[(slot, [values]), ...]")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s

        return local_iter

    # -- runners -------------------------------------------------------------
    def run_from_stdin(self):
        """Read raw lines from stdin, write slot-format lines to stdout
        (the hadoop-streaming shape the reference uses for feature logs)."""
        self._run(sys.stdin, sys.stdout)

    def run_from_memory(self, lines: Iterable[str]) -> list[str]:
        out: list[str] = []

        class _Sink:
            def write(self, s):
                if s.strip():
                    out.append(s.rstrip("\n"))

        self._run(lines, _Sink())
        return out

    def _run(self, lines, sink):
        batch = []
        for line in lines:
            it = self.generate_sample(line)
            for sample in it():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) >= self.batch_size_:
                    self._flush(batch, sink)
                    batch = []
        if batch:
            self._flush(batch, sink)

    def _flush(self, batch, sink):
        for processed in self.generate_batch(batch)():
            sink.write(self._gen_str(processed) + "\n")

    def _gen_str(self, sample) -> str:
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    @staticmethod
    def _check_sample(sample):
        if not isinstance(sample, (list, tuple)) or not sample:
            raise ValueError(
                f"a sample must be a non-empty list/tuple of "
                f"(slot, values) pairs, got {type(sample).__name__}")


class MultiSlotDataGenerator(DataGenerator):
    """Numeric slots: each value rendered via str(); floats keep their
    repr so the DataFeed's float slots parse exactly."""

    def _gen_str(self, sample) -> str:
        self._check_sample(sample)
        parts = []
        for name, values in sample:
            if not isinstance(values, (list, tuple)):
                raise ValueError(f"slot {name!r}: values must be a list")
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts)


class MultiSlotStringDataGenerator(DataGenerator):
    """String slots: values are pre-stringified feasigns, emitted as-is
    (faster: no numeric conversion round-trip)."""

    def _gen_str(self, sample) -> str:
        self._check_sample(sample)
        parts = []
        for name, values in sample:
            if not isinstance(values, (list, tuple)):
                raise ValueError(f"slot {name!r}: values must be a list")
            parts.append(str(len(values)))
            parts.extend(values)
        return " ".join(parts)
