from ..topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401
from .base import Fleet, ShardedTrainStep, fleet, zero_shard_spec  # noqa: F401
from .strategy import DistributedStrategy  # noqa: F401
from .. import meta_parallel  # noqa: F401
from . import comm_opt  # noqa: F401
from . import dataset  # noqa: F401  (InMemoryDataset / QueueDataset)
from .dataset import InMemoryDataset, QueueDataset  # noqa: F401
from . import metrics  # noqa: F401  (distributed AUC/acc/sum/max)
from . import data_generator  # noqa: F401
from .data_generator import (  # noqa: F401
    MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)
from .util import UtilBase  # noqa: F401
from ..role_maker import (  # noqa: F401
    PaddleCloudRoleMaker, Role, UserDefinedRoleMaker,
)
from .strategy_compiler import (  # noqa: F401
    StrategyPlan, compile_strategy,
)


def init(role_maker=None, is_collective=True, strategy=None,
         allow_degrade=False):
    return fleet.init(role_maker, is_collective, strategy,
                      allow_degrade=allow_degrade)


def distributed_optimizer(optimizer, strategy=None):
    return fleet.distributed_optimizer(optimizer, strategy)


def distributed_model(model):
    return fleet.distributed_model(model)


def get_hybrid_communicate_group():
    return fleet.get_hybrid_communicate_group()


def worker_num():
    return fleet.worker_num()


def worker_index():
    return fleet.worker_index()
