"""fleet.util — UtilBase (reference fleet/base/util_factory.py).

Cross-trainer utilities for industrial training scripts: numeric
all_reduce/all_gather over the worker world, a barrier, deterministic
file sharding, and rank-gated printing.  TPU-first: the comm rides the
same XLA-collective layer as everything else (distributed/collective.py)
when a multi-rank world is initialized; with a single-rank world every
op degenerates to the exact identity the reference's gloo path produces
for one trainer.
"""
from __future__ import annotations

import numpy as np

__all__ = ["UtilBase"]


class UtilBase:
    def __init__(self, role_maker=None):
        self._role = role_maker

    # -- world shape ---------------------------------------------------------
    # a "worker" is a TRAINER PROCESS (the reference's trainer), not a
    # device: under single-controller SPMD one process already owns the
    # whole mesh, so the worker world is jax's process world
    def _world(self) -> int:
        if self._role is not None:
            return int(self._role.worker_num())
        import jax

        return jax.process_count()

    def _rank(self) -> int:
        if self._role is not None:
            return int(self._role.worker_index())
        import jax

        return jax.process_index()

    # -- collectives ---------------------------------------------------------
    def all_reduce(self, input, mode: str = "sum", comm_world="worker"):
        """Reduce a host numpy value across the worker processes."""
        arr = np.asarray(input)
        if mode not in ("sum", "max", "min"):
            raise ValueError(f"all_reduce mode must be sum/max/min, "
                             f"got {mode!r}")
        if self._world() <= 1:
            return arr
        g = np.asarray(self._process_allgather(arr))
        return {"sum": g.sum, "max": g.max, "min": g.min}[mode](axis=0)

    def all_gather(self, input, comm_world="worker"):
        """Gather one scalar/array per worker process; returns a list."""
        if self._world() <= 1:
            return [np.asarray(input)]
        g = np.asarray(self._process_allgather(np.asarray(input)))
        return list(g)

    def barrier(self, comm_world="worker"):
        if self._world() <= 1:
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("fleet_util_barrier")

    @staticmethod
    def _process_allgather(arr):
        import jax.numpy as jnp
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(jnp.asarray(arr))

    # -- host-side helpers ---------------------------------------------------
    def get_file_shard(self, files):
        """This rank's slice of ``files`` — contiguous blocks, remainder
        spread over the first ranks (reference get_file_shard contract:
        every file assigned exactly once, sizes differ by at most one)."""
        if not isinstance(files, (list, tuple)):
            raise TypeError("files must be a list of paths")
        n, w, r = len(files), self._world(), self._rank()
        base, rem = divmod(n, w)
        start = r * base + min(r, rem)
        return list(files[start:start + base + (1 if r < rem else 0)])

    def print_on_rank(self, message, rank_id: int = 0):
        if self._rank() == int(rank_id):
            print(message, flush=True)
