"""Communication-reducing optimizers: DGC and LocalSGD (functional).

Reference capability: ``DGCOptimizer`` (fleet/meta_optimizers/
dgc_optimizer.py + dgc_op/dgc_momentum_op + details/
sparse_all_reduce_op_handle.cc — top-k sparse allreduce with momentum
correction and error feedback) and ``LocalSGDOptimizer`` /
``AdaptiveLocalSGDOptimizer`` (localsgd_optimizer.py — local steps +
periodic parameter averaging).

TPU framing: over ICI, dense all-reduce is usually faster than any
compression, so these matter for the **DCN (pod-to-pod) axis** — exchange
only sparse/periodic state across the slow axis while ICI axes stay dense.
Both are pure pytree transforms usable inside any jitted step (pass the
axis to reduce over when running under shard_map).
"""
from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class DGCState(NamedTuple):
    u: Any  # momentum residual
    v: Any  # error-feedback accumulator


def dgc_init(params) -> DGCState:
    z = lambda p: jnp.zeros(p.shape, jnp.float32)
    return DGCState(jax.tree_util.tree_map(z, params),
                    jax.tree_util.tree_map(z, params))


def dgc_compress(grads, state: DGCState, sparsity: float = 0.99,
                 momentum: float = 0.9, axis: str | None = None):
    """One DGC round: momentum correction + error feedback + top-k mask.

    Returns (sparse_grads, new_state).  sparse_grads has ≤ (1-sparsity)
    density per leaf; if ``axis`` is given the sparse grads are all-reduced
    over it (the sparse_all_reduce role — inside shard_map)."""

    def leaf(g, u, v):
        g = g.astype(jnp.float32)
        u2 = momentum * u + g          # local momentum (dgc_momentum op)
        v2 = v + u2                    # error feedback accumulator
        flat = jnp.abs(v2.ravel())
        k = max(1, int(flat.shape[0] * (1.0 - sparsity)))
        thresh = lax.top_k(flat, k)[0][-1]
        mask = jnp.abs(v2) >= thresh
        send = jnp.where(mask, v2, 0.0)
        v3 = jnp.where(mask, 0.0, v2)  # residual stays local
        u3 = jnp.where(mask, 0.0, u2)  # momentum factor masking
        return send, u3, v3

    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_u = treedef.flatten_up_to(state.u)
    flat_v = treedef.flatten_up_to(state.v)
    outs = [leaf(g, u, v) for g, u, v in zip(flat_g, flat_u, flat_v)]
    send = treedef.unflatten([o[0] for o in outs])
    new_u = treedef.unflatten([o[1] for o in outs])
    new_v = treedef.unflatten([o[2] for o in outs])
    if axis is not None:
        send = jax.tree_util.tree_map(lambda s: lax.pmean(s, axis), send)
    return send, DGCState(new_u, new_v)


class LocalSGD:
    """Periodic parameter averaging across a mesh axis.

    Use inside a shard_map'd per-replica train loop: run ``k_steps`` local
    optimizer steps, then ``maybe_average(params, step)`` pmeans parameters
    over ``axis`` every k steps (no-op between syncs, so the slow axis sees
    1/k the traffic)."""

    def __init__(self, k_steps: int = 4, axis: str = "dp"):
        self.k_steps = k_steps
        self.axis = axis

    def maybe_average(self, params, step):
        # the collective must sit under lax.cond so non-sync steps really
        # skip the all-reduce (every device agrees on `step`, so branching
        # is uniform and the collective stays deterministic)
        return lax.cond(
            (step % self.k_steps) == 0,
            lambda p: jax.tree_util.tree_map(
                lambda x: lax.pmean(x, self.axis), p),
            lambda p: p,
            params)
