"""Distributed metrics (reference fleet/metrics/metric.py — sum/max/auc
over all trainers via gloo all_reduce of local numpy stats).

TPU-first: under single-controller SPMD there is ONE process, so metric
stats are usually already global — the reference's per-trainer all_reduce
has no implicit analog.  When the caller DID build per-rank stats (one
block per rank stacked along dim 0), pass ``stacked=world`` to reduce
them; guessing from an ambient mesh would silently misinterpret ordinary
histograms whose length happens to relate to the mesh size."""
from __future__ import annotations

import numpy as np

__all__ = ["sum", "max", "min", "auc", "acc"]


def _reduce(local, op: str, stacked: int | None):
    arr = np.asarray(local)
    if not stacked or stacked <= 1:
        return arr
    n = int(stacked)
    if arr.ndim == 0 or arr.shape[0] % n:
        from ...framework.errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"stacked={n} needs the leading dim to be a multiple of {n}; "
            f"got shape {arr.shape}",
            hint="stack each rank's local stat along dim 0")
    blocks = arr.reshape((n, arr.shape[0] // n) + arr.shape[1:])
    if op == "sum":
        return blocks.sum(0)
    if op == "max":
        return blocks.max(0)
    if op == "min":
        return blocks.min(0)
    raise ValueError(op)


def sum(local, stacked: int | None = None):  # noqa: A001 - reference API name
    return _reduce(local, "sum", stacked)


def max(local, stacked: int | None = None):  # noqa: A001
    return _reduce(local, "max", stacked)


def min(local, stacked: int | None = None):  # noqa: A001
    return _reduce(local, "min", stacked)


def acc(correct, total, stacked: int | None = None):
    """Global accuracy from (correct, total) counts; ``stacked=world``
    when each rank's scalar is stacked along dim 0 (reference
    fleet.metrics.acc all_reduces the two scalars)."""
    c = np.asarray(sum(np.atleast_1d(np.asarray(correct)), stacked),
                   np.float64)
    t = np.asarray(sum(np.atleast_1d(np.asarray(total)), stacked),
                   np.float64)
    return float(c.sum() / np.maximum(t.sum(), 1.0))


def auc(stat_pos, stat_neg, stacked: int | None = None):
    """AUC from positive/negative score histograms (reference
    fleet/metrics/metric.py:auc — trapezoid over merged buckets).

    stat_pos/stat_neg: [num_buckets] global counts, or [world*num_buckets]
    per-rank stacked with ``stacked=world``; bucket i holds scores in
    [i/B, (i+1)/B)."""
    pos = np.asarray(sum(np.asarray(stat_pos, np.float64), stacked))
    neg = np.asarray(sum(np.asarray(stat_neg, np.float64), stacked))
    pos = np.atleast_1d(pos).reshape(-1)
    neg = np.atleast_1d(neg).reshape(-1)
    tot_pos = tot_neg = 0.0
    area = 0.0
    # walk buckets from high score to low (reference order)
    for i in range(len(pos) - 1, -1, -1):
        new_pos = tot_pos + pos[i]
        new_neg = tot_neg + neg[i]
        area += (new_pos + tot_pos) * neg[i] / 2.0
        tot_pos, tot_neg = new_pos, new_neg
    if tot_pos == 0.0 or tot_neg == 0.0:
        return 0.5
    return float(area / (tot_pos * tot_neg))
