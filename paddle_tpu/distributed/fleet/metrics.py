"""Distributed metrics (reference fleet/metrics/metric.py — sum/max/auc
over all trainers via gloo all_reduce of local numpy stats).

TPU-first: under single-controller SPMD a 'per-trainer local stat' is a
stacked-per-rank array (see distributed/collective.py); these helpers
reduce it with the eager collectives when a mesh axis is active and fall
back to plain numpy when running single-process (the common case for
metric aggregation at epoch end).  ``auc`` computes the final value from
the (merged) positive/negative histograms exactly like the reference's
distributed AUC."""
from __future__ import annotations

import numpy as np

__all__ = ["sum", "max", "min", "auc", "acc"]

def _reduce(local, op: str):
    """Stacked-per-rank [n*B, ...] -> reduced [B, ...] when a mesh axis is
    live; identity for single-process."""
    from ..env import get_mesh, has_mesh

    arr = np.asarray(local)
    if not has_mesh():
        return arr
    mesh = get_mesh()
    ax = mesh.axis_names[0]
    n = mesh.shape[ax]
    if n <= 1:
        return arr
    if arr.ndim == 0 or arr.shape[0] % n:
        from ...framework.errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"fleet.metrics with an active {n}-way mesh needs "
            f"stacked-per-rank input (leading dim a multiple of {n}); got "
            f"shape {arr.shape}",
            hint="stack each rank's local stat along dim 0, or aggregate "
                 "before the mesh is initialized")
    blocks = arr.reshape((n, arr.shape[0] // n) + arr.shape[1:])
    if op == "sum":
        return blocks.sum(0)
    if op == "max":
        return blocks.max(0)
    if op == "min":
        return blocks.min(0)
    raise ValueError(op)


def sum(local):  # noqa: A001 - reference API name
    return _reduce(local, "sum")


def max(local):  # noqa: A001
    return _reduce(local, "max")


def min(local):  # noqa: A001
    return _reduce(local, "min")


def acc(correct, total):
    """Global accuracy from per-rank (correct, total) scalars or stacked
    arrays (reference fleet.metrics.acc)."""
    c = np.asarray(sum(np.atleast_1d(np.asarray(correct))), np.float64)
    t = np.asarray(sum(np.atleast_1d(np.asarray(total))), np.float64)
    return float(c.sum() / np.maximum(t.sum(), 1.0))


def auc(stat_pos, stat_neg):
    """AUC from positive/negative score histograms (reference
    fleet/metrics/metric.py:auc — trapezoid over merged buckets).

    stat_pos/stat_neg: [num_buckets] per-rank or stacked [n*num_buckets]
    counts; bucket i holds scores in [i/B, (i+1)/B)."""
    pos = np.asarray(sum(np.asarray(stat_pos, np.float64)), np.float64)
    neg = np.asarray(sum(np.asarray(stat_neg, np.float64)), np.float64)
    pos = np.atleast_1d(pos).reshape(-1)
    neg = np.atleast_1d(neg).reshape(-1)
    tot_pos = tot_neg = 0.0
    area = 0.0
    # walk buckets from high score to low (reference order)
    for i in range(len(pos) - 1, -1, -1):
        new_pos = tot_pos + pos[i]
        new_neg = tot_neg + neg[i]
        area += (new_pos + tot_pos) * neg[i] / 2.0
        tot_pos, tot_neg = new_pos, new_neg
    if tot_pos == 0.0 or tot_neg == 0.0:
        return 0.5
    return float(area / (tot_pos * tot_neg))
