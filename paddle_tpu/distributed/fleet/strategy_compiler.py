"""Strategy compiler: validate + order the strategy toggles into one plan.

Reference capability: ``MetaOptimizerFactory`` (meta_optimizer_factory.py:27)
collects *Optimizer classes and ``StrategyCompiler`` (strategy_compiler.py:
114) orders/validates the meta-optimizer stack — each meta-optimizer
declares ``_can_apply`` and ``_disable_strategy`` and rewrites the Program
in sequence.

TPU-first: strategies don't rewrite programs — they parameterize ONE
compiled train step — so the "stack" becomes a validated, ordered PLAN of
composition rules.  Each rule declares requirements (mesh axes, model
capabilities) and conflicts; :func:`compile_strategy` resolves them and
the fleet facade routes to the right step builder (ShardedTrainStep,
PipelineLayer.build_train_step, gpt_hybrid for the flagship path)."""
from __future__ import annotations

from typing import Any, NamedTuple

from .strategy import DistributedStrategy


class Rule(NamedTuple):
    name: str  # also the DistributedStrategy toggle attribute
    # rules this one cannot compose with (reference _disable_strategy)
    conflicts: tuple = ()
    # mesh axis the rule needs (>1) — None = no axis requirement
    needs_axis: str | None = None
    # ordering priority (lower runs/wraps first — the reference orders
    # graph rewrites; here it documents composition order)
    priority: int = 50


# the rule set mirrors the reference's meta-optimizer list
_RULES = [
    Rule("amp", priority=10),
    Rule("recompute", priority=20),
    Rule("pipeline", needs_axis="pp", priority=30),
    Rule("tensor_parallel", needs_axis="mp", priority=31),
    Rule("sequence_parallel", needs_axis="sp", priority=32),
    Rule("sharding", priority=40),
    Rule("gradient_merge", conflicts=("localsgd",), priority=45),
    Rule("dgc", conflicts=("localsgd", "sharding"), priority=60),
    Rule("localsgd", conflicts=("dgc", "gradient_merge"), priority=61),
    Rule("lamb", conflicts=("lars",), priority=70),
    Rule("lars", conflicts=("lamb",), priority=70),
]


class StrategyPlan(NamedTuple):
    """Ordered applicable rules + resolved facts the builders consume —
    the single derivation source for strategy-dependent step parameters."""
    rules: tuple
    mesh_shape: dict
    zero_stage: int
    n_micro: int
    k_steps: int

    def has(self, name: str) -> bool:
        return name in self.rules


def compile_strategy(strategy: DistributedStrategy,
                     mesh_shape: dict | None = None,
                     on_missing_axis: str = "raise") -> StrategyPlan:
    """Validate toggle compatibility and produce the ordered plan
    (reference StrategyCompiler.generate_optimizer role).

    Conflicting toggles always raise.  A toggle whose required mesh axis
    is missing/1 raises by default (failing loudly is the deliberate
    difference from the reference) — ``on_missing_axis="disable"`` gives
    the reference's ``_disable_strategy`` behavior instead, with a
    warning; that is the right mode after an opted-in mesh degrade."""
    from ...framework.errors import InvalidArgumentError

    shape = dict(mesh_shape or strategy.mesh_shape())
    active = [r for r in _RULES if getattr(strategy, r.name, False)]
    names = {r.name for r in active}
    for r in active:
        for c in r.conflicts:
            if c in names:
                raise InvalidArgumentError(
                    f"strategy toggles {r.name!r} and {c!r} cannot compose",
                    hint="the reference's meta-optimizers disable each "
                         "other here; turn one off")
    kept = []
    for r in active:
        if r.needs_axis is not None and shape.get(r.needs_axis, 1) <= 1:
            if on_missing_axis == "disable":
                import warnings

                warnings.warn(
                    f"strategy {r.name!r} disabled: mesh axis "
                    f"{r.needs_axis!r} is missing/1 (degraded mesh)",
                    stacklevel=2)
                continue
            raise InvalidArgumentError(
                f"strategy {r.name!r} needs mesh axis {r.needs_axis!r} > 1 "
                f"(got {shape.get(r.needs_axis, 1)})",
                hint=f"set hybrid_configs.{r.needs_axis}_degree")
        kept.append(r)
    ordered = tuple(r.name for r in sorted(kept, key=lambda r: r.priority))
    zero_stage = (max(1, int(strategy.sharding_configs.stage))
                  if "sharding" in ordered else 0)
    n_micro = (strategy.pipeline_configs.accumulate_steps
               if "pipeline" in ordered else 1)
    k_steps = (strategy.gradient_merge_configs.k_steps
               if "gradient_merge" in ordered else 1)
    return StrategyPlan(ordered, shape, zero_stage, n_micro, k_steps)


# toggles the Layer-model route cannot honor (they need the functional
# pytree API — ShardedTrainStep via fleet.build_train_step)
def _policy_of(strategy) -> str | None:
    """Canonical recompute policy named by the strategy (None = full)."""
    from ...ops.remat_policies import canonical

    return canonical(strategy.recompute_configs.policy)


_LAYER_ROUTE_UNSUPPORTED = ("sharding", "gradient_merge", "tensor_parallel",
                            "sequence_parallel", "dgc", "localsgd", "amp")


def build_layer_train_step(model, loss_fn, optimizer,
                           strategy: DistributedStrategy, mesh=None,
                           example_input=None,
                           on_missing_axis: str = "raise"):
    """Route a Layer model to the right compiled step per the plan (the
    reference's fleet.distributed_model + minimize dispatch,
    fleet_base.py:836 — TensorParallel/PipelineParallel/ShardingParallel
    wrappers chosen from the strategy).

    * pipeline on → the model must be a PipelineLayer; its pp schedule
      composes dp from the mesh (plus recompute).
    * otherwise → jit.TrainStep with strategy-driven recompute.  Toggles
      this route cannot honor raise UnimplementedError instead of being
      silently dropped — use the functional ``fleet.build_train_step``
      (ShardedTrainStep) for sharding/gradient_merge/amp composition."""
    from ..env import get_mesh
    from ...framework.errors import InvalidArgumentError, UnimplementedError

    mesh = mesh or get_mesh()
    plan = compile_strategy(strategy, dict(mesh.shape),
                            on_missing_axis=on_missing_axis)
    if plan.has("pipeline"):
        from ..pp_layers import PipelineLayer

        if not isinstance(model, PipelineLayer):
            raise InvalidArgumentError(
                "strategy.pipeline needs a PipelineLayer model (wrap the "
                "layer list in distributed.PipelineLayer)",
                hint="reference PipelineOptimizer also requires "
                     "device_guard-annotated programs")
        if example_input is None:
            raise InvalidArgumentError(
                "pipeline routing needs example_input to trace boundary "
                "shapes")
        unsupported = [n for n in _LAYER_ROUTE_UNSUPPORTED if plan.has(n)]
        if unsupported:
            raise UnimplementedError(
                f"strategy toggles {unsupported} do not compose with the "
                f"PipelineLayer route yet",
                hint="use the functional fleet.build_train_step or the "
                     "flagship gpt_hybrid path")
        if plan.has("recompute") and _policy_of(strategy) is not None:
            # PipelineLayer's remat policy is env-selected only
            # (PADDLE_TPU_REMAT_POLICY, see pp_layers.py) — a strategy
            # policy this route cannot honor must be loud, not dropped
            raise UnimplementedError(
                "recompute_configs.policy does not compose with the "
                "PipelineLayer route yet",
                hint="set PADDLE_TPU_REMAT_POLICY or use the functional "
                     "fleet.build_train_step route")
        return model.build_train_step(
            mesh, optimizer, loss_fn, n_micro=max(1, plan.n_micro),
            example_input=example_input, remat=plan.has("recompute"))
    unsupported = [n for n in _LAYER_ROUTE_UNSUPPORTED if plan.has(n)]
    if unsupported:
        raise UnimplementedError(
            f"strategy toggles {unsupported} need the functional pytree "
            f"API; the Layer route supports recompute/pipeline only",
            hint="call fleet.build_train_step(loss_fn, params, optimizer) "
                 "— ShardedTrainStep composes dp/amp/zero/gradient_merge")
    from ...jit import TrainStep

    return TrainStep(model, loss_fn, optimizer, mesh=mesh,
                     remat=plan.has("recompute"),
                     remat_policy=_policy_of(strategy))
