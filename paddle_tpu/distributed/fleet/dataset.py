"""Industrial dataset ingestion: InMemoryDataset / QueueDataset.

Reference capability: python/paddle/distributed/fleet/dataset/dataset.py —
wrappers over the C++ Dataset/DataFeed (framework/data_set.h:43,
data_feed.h:305): multithreaded file readers feeding training directly,
``load_into_memory`` + ``local_shuffle`` for the in-memory variant,
streaming for the queue variant.

TPU-native: both wrap the native C++ shard feeder
(paddle_tpu/_native/io_runtime.cpp).  Records are fixed-length binary
(``set_record_schema`` gives the [seq_len, dtype] layout — the pretraining
shard format); batches surface as numpy arrays ready for jit steps.
"""
from __future__ import annotations

from typing import Iterator, Sequence

import numpy as np


class _DatasetBase:
    def __init__(self):
        self._files: list[str] = []
        self._seq_len = 0
        self._dtype = np.int32
        self._batch = 1
        self._threads = 4
        self._shuffle_window = 0
        self._seed = 0

    # reference config surface
    def set_filelist(self, files: Sequence[str]):
        self._files = list(files)

    def set_batch_size(self, bs: int):
        self._batch = int(bs)

    def set_thread(self, n: int):
        self._threads = int(n)

    def set_record_schema(self, seq_len: int, dtype=np.int32):
        self._seq_len = int(seq_len)
        self._dtype = np.dtype(dtype)

    def set_shuffle_window(self, window: int):
        """Streaming reservoir-shuffle window (0 = no shuffle)."""
        self._shuffle_window = int(window)

    def set_seed(self, seed: int):
        self._seed = int(seed)

    def set_use_var(self, var_list):
        """Bind static data Variables to record columns (reference
        dataset.set_use_var → DataFeed slots).  Each var with trailing dim k
        consumes the next k columns of the flat record, cast to its dtype;
        used by Executor.train_from_dataset to build feeds."""
        self._use_vars = list(var_list)

    def slice_batch(self, batch: np.ndarray) -> dict:
        """Split a [B, seq_len] record batch into a feed dict per use_var."""
        if not getattr(self, "_use_vars", None):
            raise ValueError("set_use_var(...) first")
        feed = {}
        col = 0
        for v in self._use_vars:
            k = 1
            for s in v.shape[1:]:
                if int(s) < 0:
                    raise ValueError(
                        f"use_var {v.name!r} has dynamic trailing dim "
                        f"{list(v.shape)}: record slicing needs static "
                        "widths (only dim 0 may be batch/-1)")
                k *= int(s)
            width = k if len(v.shape) > 1 else 1
            chunk = batch[:, col:col + width]
            col += width
            if len(v.shape) == 1:
                chunk = chunk.reshape(-1)
            else:
                chunk = chunk.reshape((-1,) + tuple(
                    max(1, int(s)) for s in v.shape[1:]))
            feed[v.name] = chunk.astype(v.dtype)
        return feed

    def _reader(self, capacity=8):
        from ...io.native_reader import TokenShardReader

        if not self._files or not self._seq_len:
            raise ValueError("set_filelist + set_record_schema first")
        return TokenShardReader(
            self._files, seq_len=self._seq_len, batch_size=self._batch,
            num_threads=self._threads, dtype=self._dtype, capacity=capacity,
            seed=self._seed, shuffle_window=self._shuffle_window)


class QueueDataset(_DatasetBase):
    """Streaming: batches flow straight from reader threads (no staging).

    The native feeder delivers trailing PARTIAL per-thread batches so no
    record is lost; jitted consumers need static shapes, so QueueDataset
    keeps its documented only-full-batches contract by default
    (``drop_last=True``) and short tails are filtered here.  Call
    ``set_drop_last(False)`` to receive the ragged tails (eager/numpy
    consumers); use InMemoryDataset for epoch-exact full batches."""

    def __init__(self):
        super().__init__()
        self._drop_last = True

    def set_drop_last(self, drop: bool):
        self._drop_last = bool(drop)
        return self

    def __iter__(self) -> Iterator[np.ndarray]:
        for arr in self._reader():
            if self._drop_last and arr.shape[0] < self._batch:
                continue
            yield arr


class InMemoryDataset(_DatasetBase):
    """Stage everything in host RAM, then (re-)shuffle and iterate epochs
    (reference load_into_memory/local_shuffle/global_shuffle)."""

    def __init__(self):
        super().__init__()
        self._records: np.ndarray | None = None

    def load_into_memory(self):
        # stage at record granularity (batch=1) so no ragged per-worker tail
        # is dropped; batching happens at iteration time
        saved = self._batch
        self._batch = 1
        try:
            batches = list(self._reader(capacity=32))
        finally:
            self._batch = saved
        if batches:
            self._records = np.concatenate(batches, axis=0)
        else:
            self._records = np.empty((0, self._seq_len), self._dtype)
        return self

    def local_shuffle(self, seed: int | None = None):
        assert self._records is not None, "load_into_memory first"
        rng = np.random.default_rng(self._seed if seed is None else seed)
        rng.shuffle(self._records)
        return self

    # single-host build: global == local (multi-host would alltoall shards)
    global_shuffle = local_shuffle

    def get_memory_data_size(self) -> int:
        return 0 if self._records is None else len(self._records)

    def __iter__(self) -> Iterator[np.ndarray]:
        assert self._records is not None, "load_into_memory first"
        n = (len(self._records) // self._batch) * self._batch
        for i in range(0, n, self._batch):
            yield self._records[i:i + self._batch]

    def release_memory(self):
        self._records = None
