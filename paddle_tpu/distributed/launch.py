"""Multi-host launch CLI: ``python -m paddle_tpu.distributed.launch``.

Reference capability: fleet/launch.py (get_cluster_from_args :199, per-device
subprocess spawn with PADDLE_TRAINER_* env, watch loop :301) and
launch_utils.py Cluster/Pod/TrainerProc (:59/:173/:443 — abnormal exit of any
local proc kills the pod).

TPU-native shape: ONE process per host (all local chips belong to one
XLA client), not one per device.  The launcher:
  1. rendezvous — rank 0 runs the KV server; every host registers and
     fetches the full host list (the gen_comm_id TCP-exchange role);
  2. exports JAX distributed env (coordinator address, process id/count)
     plus PADDLE_*-shaped variables for reference-style scripts;
  3. spawns the training script, watches it, restarts on failure up to
     --max_restarts (failure detection), tears everything down on success.

Single-host multi-process simulation (the reference's localhost cluster
tests) works with --nproc_per_host N on CPU:
`JAX_PLATFORMS=cpu` + per-proc `XLA_FLAGS=--xla_force_host_platform_device_count=K`.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

from .kvstore import KVClient, KVServer


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch distributed training (one process per host)")
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of hosts (JAX processes) in the job")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--coordinator", default="127.0.0.1:37777",
                   help="host:port of the rank-0 rendezvous/coordination")
    p.add_argument("--nproc_per_host", type=int, default=1,
                   help=">1 simulates a multi-host job on one machine (CPU)")
    p.add_argument("--max_restarts", type=int, default=0)
    p.add_argument("--log_dir", default=None)
    p.add_argument("--elastic_np", default=None,
                   help="MIN:MAX live-host range; watch KV membership and "
                        "relaunch the pod on scale events (reference "
                        "ElasticManager, fleet/elastic.py:90)")
    p.add_argument("--server_num", type=int, default=0,
                   help="parameter-server mode: spawn N table servers "
                        "(reference ParameterServerLauncher)")
    p.add_argument("--worker_num", type=int, default=1,
                   help="parameter-server mode: trainer process count")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _proc_env(rank: int, world: int, coordinator: str, local_sim: bool):
    env = dict(os.environ)
    env.pop("PADDLE_TPU_LIGHT_IMPORT", None)  # trainers need the full package
    env.update({
        # JAX multi-host bring-up (jax.distributed.initialize reads these
        # via our init_parallel_env call or explicit plumbing)
        "PADDLE_TPU_COORDINATOR": coordinator,
        "PADDLE_TPU_NUM_PROCESSES": str(world),
        "PADDLE_TPU_PROCESS_ID": str(rank),
        # reference-shaped env so ported scripts keep working
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_CURRENT_ENDPOINT": coordinator,
    })
    if local_sim:
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=2"
    return env


class TrainerProc:
    def __init__(self, cmd, env, log_path, rank):
        self.cmd, self.env, self.log_path, self.rank = cmd, env, log_path, rank
        self.restarts = 0
        self.proc: subprocess.Popen | None = None
        self._log = None

    def start(self):
        if self._log:  # restart: drop the previous handle first
            self._log.close()
            self._log = None
        if self.log_path:
            self._log = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            self.cmd, env=self.env,
            stdout=self._log or None, stderr=self._log or None)

    def poll(self):
        return self.proc.poll() if self.proc else None

    def terminate(self):
        if self.proc and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()  # reap; old proc must release devices/ports
        if self._log:
            self._log.close()
            self._log = None


def launch_ps(args) -> int:
    """Parameter-server pod: N table servers + M trainer workers
    (reference ParameterServerLauncher, fleet/launch_utils.py:788).
    Servers run paddle_tpu.distributed.ps_service; workers get
    PADDLE_PSERVER_ENDPOINTS / TRAINING_ROLE / PADDLE_TRAINER_ID env."""
    import shutil
    import tempfile

    log_dir = args.log_dir
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix="pt_ps_")
    try:
        return _launch_ps_impl(args, tmp, log_dir)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _launch_ps_impl(args, tmp, log_dir) -> int:
    servers: list[TrainerProc] = []
    for i in range(args.server_num):
        ready = os.path.join(tmp, f"ep{i}.txt")
        cmd = [sys.executable, "-u", "-m", "paddle_tpu.distributed.ps_service",
               "--port", "0", "--server_idx", str(i),
               "--num_servers", str(args.server_num), "--ready_path", ready]
        env = dict(os.environ)
        env["TRAINING_ROLE"] = "PSERVER"
        env["PADDLE_TPU_LIGHT_IMPORT"] = "1"  # servers never need jax
        log = os.path.join(log_dir, f"server.{i}.log") if log_dir else None
        sp = TrainerProc(cmd, env, log, i)
        sp.ready_path = ready
        servers.append(sp)
    for sp in servers:
        sp.start()
    endpoints = []
    deadline = time.time() + 120
    for sp in servers:
        while not (os.path.exists(sp.ready_path)
                   and os.path.getsize(sp.ready_path)):
            if sp.poll() not in (None,):
                for s in servers:
                    s.terminate()
                print(f"[launch] ps server {sp.rank} died during startup",
                      file=sys.stderr)
                return 1
            if time.time() > deadline:
                for s in servers:
                    s.terminate()
                print("[launch] ps servers did not come up", file=sys.stderr)
                return 1
            time.sleep(0.05)
        endpoints.append(open(sp.ready_path).read().strip())

    workers: list[TrainerProc] = []
    for r in range(args.worker_num):
        cmd = [sys.executable, "-u", args.training_script,
               *args.training_script_args]
        env = dict(os.environ)
        env.pop("PADDLE_TPU_LIGHT_IMPORT", None)
        env.update({
            "TRAINING_ROLE": "TRAINER",
            "PADDLE_PSERVER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_TRAINER_ID": str(r),
            "PADDLE_TRAINERS_NUM": str(args.worker_num),
        })
        log = os.path.join(log_dir, f"worker.{r}.log") if log_dir else None
        workers.append(TrainerProc(cmd, env, log, r))
    for w in workers:
        w.start()

    exit_code = 0
    try:
        while True:
            failed = [w for w in workers if w.poll() not in (None, 0)]
            dead_srv = [s for s in servers if s.poll() is not None]
            if failed or dead_srv:
                exit_code = (failed[0].poll() if failed
                             else dead_srv[0].poll()) or 1
                who = (f"worker {failed[0].rank}" if failed
                       else f"server {dead_srv[0].rank}")
                print(f"[launch] {who} exited abnormally; terminating pod",
                      file=sys.stderr)
                break
            if all(w.poll() == 0 for w in workers):
                break  # normal completion
            time.sleep(0.2)
    except KeyboardInterrupt:
        exit_code = exit_code or 1
    finally:
        for p in workers + servers:
            p.terminate()
    return exit_code


def launch(args) -> int:
    if args.server_num > 0:
        return launch_ps(args)
    coord_host, coord_port = args.coordinator.split(":")
    coord_port = int(coord_port)
    local_sim = args.nproc_per_host > 1
    if local_sim and args.nnodes > 1:
        raise SystemExit("--nproc_per_host > 1 is a single-host CPU "
                         "simulation mode; it cannot combine with --nnodes")
    if args.nnodes > 1 and coord_port == 0:
        raise SystemExit("--nnodes > 1 needs a fixed --coordinator port "
                         "(every host must dial the same address)")
    server = None
    if args.node_rank == 0:
        server = KVServer(coord_host if coord_host != "localhost"
                          else "127.0.0.1", coord_port)
        _, coord_port = server.start()  # port 0 → the actually-bound port
    coordinator = f"{coord_host}:{coord_port}"

    world = args.nnodes if not local_sim else args.nproc_per_host

    # rendezvous: register and wait for everyone (gen_comm_id role)
    client = None
    if args.nnodes > 1:
        client = KVClient(coord_host, coord_port)
        client.set(f"host/{args.node_rank}", os.uname().nodename)
        client.barrier("launch/ready", args.nnodes)

    # elastic membership: heartbeat this node, watch the live set, and
    # relaunch the pod on scale events (ElasticManager integration — the
    # reference's elastic.py watch-callback teardown/relaunch)
    elastic = None
    if args.elastic_np:
        from .elastic import ElasticManager

        np_min, np_max = (int(v) for v in args.elastic_np.split(":"))
        if client is None:
            client = KVClient(coord_host, coord_port)
        # TTL must leave slack for scheduler stalls on loaded hosts: a
        # heartbeat thread starved past the TTL reads as a dead peer and
        # triggers a spurious relaunch (env-tunable for tests/CI)
        hb = float(os.environ.get("PADDLE_ELASTIC_HEARTBEAT", "0.2"))
        ttl = float(os.environ.get("PADDLE_ELASTIC_TTL", "2.0"))
        elastic = ElasticManager(client, host_id=f"node{args.node_rank}",
                                 np_range=(np_min, np_max),
                                 heartbeat_interval=hb, ttl=ttl)
        elastic.register()
        if args.nnodes > 1:
            # wait for every expected peer's first heartbeat before
            # baselining, or their arrival reads as a spurious scale event
            elastic.wait_for_np(min(args.nnodes, np_max), timeout=60)
        elastic.resnapshot()

    def spawn_pod(world_n: int, my_rank: int | None = None):
        ps = []
        ranks = range(world_n) if local_sim else [
            my_rank if my_rank is not None else args.node_rank]
        for r in ranks:
            cmd = [sys.executable, "-u", args.training_script,
                   *args.training_script_args]
            env = _proc_env(r, world_n, coordinator, local_sim)
            log = (os.path.join(args.log_dir, f"worker.{r}.log")
                   if args.log_dir else None)
            if args.log_dir:
                os.makedirs(args.log_dir, exist_ok=True)
            ps.append(TrainerProc(cmd, env, log, r))
        for p in ps:
            p.start()
        return ps

    procs = spawn_pod(world)

    # watch loop: abnormal exit of ANY proc stops the whole pod (a multi-
    # process JAX job cannot survive a single dead rank — the reference's
    # launch watch does the same); restarts relaunch the POD, not one rank
    pod_restarts = 0
    exit_code = 0
    try:
        while True:
            alive = any(p.poll() is None for p in procs)
            failed = [p for p in procs if p.poll() not in (None, 0)]
            if elastic is not None and alive:
                status = elastic.check()
                if status == "scale":
                    # re-rank against the capped effective membership: after
                    # a scale-down the surviving hosts' ranks must stay
                    # contiguous (the reference ElasticManager re-ranks)
                    eff = elastic.effective_hosts()
                    new_world = len(eff)
                    me = f"node{args.node_rank}"
                    if not local_sim and me not in eff:
                        print("[launch] elastic: this host fell out of the "
                              "effective membership; exiting", file=sys.stderr)
                        exit_code = 1
                        break
                    new_rank = eff.index(me) if not local_sim else None
                    print(f"[launch] elastic scale event: effective hosts -> "
                          f"{new_world}; relaunching pod", file=sys.stderr)
                    for p in procs:
                        p.terminate()
                    world = new_world if not local_sim else world
                    procs = spawn_pod(world, new_rank)
                    continue
                if status == "exit":
                    print("[launch] elastic: below np_min; terminating",
                          file=sys.stderr)
                    exit_code = 1
                    break
            if failed:
                rc = failed[0].poll()
                for p in procs:
                    p.terminate()
                if pod_restarts < args.max_restarts:
                    pod_restarts += 1
                    print(f"[launch] rank {failed[0].rank} exited {rc}; pod "
                          f"restart {pod_restarts}/{args.max_restarts}",
                          file=sys.stderr)
                    for p in procs:
                        p.start()
                    continue
                print(f"[launch] rank {failed[0].rank} failed (exit {rc}); "
                      "terminating pod", file=sys.stderr)
                exit_code = rc
                break
            if not alive:
                break
            time.sleep(0.2)
    except KeyboardInterrupt:
        exit_code = exit_code or 1
    finally:
        for p in procs:
            p.terminate()
        if elastic is not None:
            elastic.deregister()
        if client:
            client.close()
        if server:
            server.shutdown()
    return exit_code


def main(argv=None):
    sys.exit(launch(parse_args(argv)))


if __name__ == "__main__":
    main()
