"""Elastic membership: heartbeat-based scale-up/down detection + relaunch.

Reference capability: ``ElasticManager`` (fleet/elastic.py:90) — etcd-backed
(:125) host registration, peer watching, teardown+relaunch on scale events,
np range via PADDLE_ELASTIC_NP.  Here membership rides the stdlib KV store
(kvstore.py) instead of etcd: each host heartbeats `elastic/host/<id>` with a
timestamp; the manager watches the live set and reports scale events the
launcher acts on (restart training with the new world size — with JAX this
means re-running jax.distributed.initialize + rebuilding the mesh).
"""
from __future__ import annotations

import threading
import time

from .kvstore import KVClient


class ElasticStatus:
    OK = "ok"
    SCALE = "scale"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, client: KVClient, host_id: str,
                 np_range: tuple[int, int] | None = None,
                 heartbeat_interval: float = 1.0, ttl: float = 5.0):
        self.c = client
        self.host_id = host_id
        self.interval = heartbeat_interval
        self.ttl = ttl
        self.np_min, self.np_max = np_range or (1, 1 << 30)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_live: frozenset = frozenset()

    # -- membership ----------------------------------------------------------
    def register(self):
        self._beat()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        self._last_live = frozenset(self.live_hosts()[: self.np_max])
        return self

    def _beat(self):
        # server-clock stamp: liveness never depends on cross-host clock sync
        self.c.stamp(f"elastic/host/{self.host_id}")

    def _loop(self):
        while not self._stop.is_set():
            self._beat()
            self._stop.wait(self.interval)

    def deregister(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        self.c.delete(f"elastic/host/{self.host_id}")

    def live_hosts(self) -> list:
        kv, now = self.c.snapshot("elastic/host/")  # server clock for both
        return sorted(k.split("/", 2)[2] for k, ts in kv.items()
                      if now - float(ts) < self.ttl)

    def resnapshot(self):
        """Re-baseline the membership snapshot (call once every expected
        peer has registered, so their first heartbeats don't read as a
        scale event)."""
        self._last_live = frozenset(self.live_hosts()[: self.np_max])

    def effective_hosts(self) -> list:
        """The np_max-capped membership the job actually runs with."""
        return self.live_hosts()[: self.np_max]

    # -- watch ---------------------------------------------------------------
    def check(self) -> str:
        """Poll once: OK (effective membership unchanged), SCALE (world
        changed within [np_min, np_max] → relaunch), EXIT (below np_min).
        Hosts beyond np_max are ignored (capped), not a scale event."""
        live = self.live_hosts()
        if len(live) < self.np_min:
            return ElasticStatus.EXIT
        effective = frozenset(live[: self.np_max])
        if effective != self._last_live:
            self._last_live = effective
            return ElasticStatus.SCALE
        return ElasticStatus.OK

    def wait_for_np(self, n: int, timeout: float = 60) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(self.live_hosts()) >= n:
                return True
            time.sleep(self.interval / 2)
        return False
