"""paddle.distributed.spawn analog — run fn in worker subprocesses.

Reference: python/paddle/distributed/spawn.py (:114 _get_subprocess_env_list
builds per-proc env, multiprocessing.spawn start method).  One worker per
"host process"; each worker gets PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM (and
the PADDLE_TPU_* coordination variables when a coordinator is given) before
importing the backend, mirroring launch.py's env contract.
"""
from __future__ import annotations

import multiprocessing as mp
import os
from typing import Callable, Sequence


def _worker(rank: int, world: int, coordinator: str | None, fn, args, force_cpu):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(world)
    if coordinator:
        os.environ["PADDLE_TPU_COORDINATOR"] = coordinator
        os.environ["PADDLE_TPU_NUM_PROCESSES"] = str(world)
        os.environ["PADDLE_TPU_PROCESS_ID"] = str(rank)
    if force_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    fn(*args)


def spawn(func: Callable, args: Sequence = (), nprocs: int = 1,
          coordinator: str | None = None, join: bool = True,
          force_cpu: bool = False):
    """Start ``nprocs`` processes running ``func(*args)`` with rank env set.

    Returns the list of Process objects (joined if join=True; raises if any
    worker exits non-zero — the reference's context.join behavior)."""
    ctx = mp.get_context("spawn")
    procs = []
    for r in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(r, nprocs, coordinator, func, tuple(args),
                              force_cpu))
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        bad = [p.exitcode for p in procs if p.exitcode != 0]
        if bad:
            raise RuntimeError(f"spawned workers failed with exits {bad}")
    return procs


if __name__ == "__main__":  # light-import guard relies on this module name
    raise SystemExit("use paddle_tpu.distributed.spawn.spawn(fn, ...)")
