"""Sparse embedding "parameter server" — sharded tables + sparse updates.

Reference capability (§2.4): the brpc parameter server stack —
``CommonSparseTable`` (distributed/table/common_sparse_table.cc,
shard-hashed embedding rows with per-row adagrad), ``PSClient``
pull_sparse/push_sparse (service/ps_client.h), ``TheOnePSRuntime``
(fleet/runtime/the_one_ps.py), ``distributed_lookup_table`` ops.

TPU-native redesign: there are no separate server processes — the "servers"
are the devices themselves.  A table is a [V, D] jax.Array row-sharded over
a mesh axis (the shard-hash role is the sharding); ``pull`` is a sharded
gather (XLA inserts the comm), ``push`` applies a *sparse* optimizer update
that touches only the referenced rows via scatter ops — no dense [V, D]
gradient ever exists, which is the whole point of a PS for 10^8-row
recommender vocabularies.  Duplicate ids inside a batch are merged exactly
like the reference's push merge (sort + segment-sum), all with static
shapes so the update jits.
"""
from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _merge_duplicate_ids(ids, grads, vocab_size: int):
    """Merge per-occurrence grads of duplicate ids (static shapes).

    Returns (slot_ids [N], merged [N, D]) where only the first occurrence of
    each id keeps its merged gradient and duplicates are redirected to a
    dummy row ``vocab_size`` (the caller's table carries V+1 rows)."""
    order = jnp.argsort(ids)
    s_ids = ids[order]
    s_g = grads[order]
    first = jnp.concatenate([jnp.ones((1,), bool), s_ids[1:] != s_ids[:-1]])
    seg = jnp.cumsum(first) - 1                       # group index per slot
    merged = jax.ops.segment_sum(s_g, seg, num_segments=ids.shape[0])
    # group g's merged grad sits at merged[g]; map back to first-occurrence
    slot_of_group = jax.ops.segment_min(jnp.arange(ids.shape[0]), seg,
                                        num_segments=ids.shape[0])
    out_ids = jnp.where(
        jnp.arange(ids.shape[0]) < seg[-1] + 1,
        s_ids[slot_of_group.clip(0, ids.shape[0] - 1)], vocab_size)
    return out_ids, merged


class SparseTableState(NamedTuple):
    """Functional state of one table (pytree)."""

    rows: Any        # [V+1, D]  (+1 dummy row for duplicate-merge scatter)
    accum: Any       # [V+1] adagrad accumulator (or zeros for sgd)


class SparseEmbeddingTable:
    """Row-sharded embedding table with sparse adagrad/sgd push.

    entry_dim rows sharded P(axis) over the mesh — every device owns a
    contiguous row shard (the reference's shard-hash placement role).
    """

    def __init__(self, vocab_size: int, dim: int, mesh: Mesh | None = None,
                 axis: str | None = "mp", optimizer: str = "adagrad",
                 lr: float = 0.05, initializer_std: float = 0.01,
                 seed: int = 0):
        self.vocab_size = vocab_size
        self.dim = dim
        self.optimizer = optimizer
        self.lr = lr
        self.mesh = mesh
        n_shards = (mesh.shape.get(axis, 1) if mesh is not None else 1)
        # +1 dummy row for the duplicate-merge scatter, padded up so every
        # shard holds the same number of rows
        self._rows_total = -((vocab_size + 1) // -n_shards) * n_shards
        spec = P(axis, None) if (mesh is not None and
                                 mesh.shape.get(axis, 1) > 1) else P()
        self._sharding = (NamedSharding(mesh, spec) if mesh is not None
                          else None)
        acc_spec = P(spec[0]) if spec else P()
        self._acc_sharding = (NamedSharding(mesh, acc_spec)
                              if mesh is not None else None)

        def init(key):
            rows = initializer_std * jax.random.normal(
                key, (self._rows_total, dim), jnp.float32)
            rows = jnp.where(
                (jnp.arange(self._rows_total) < vocab_size)[:, None], rows, 0.0)
            return SparseTableState(rows, jnp.zeros((self._rows_total,),
                                                    jnp.float32))

        if self._sharding is not None:
            self.state = jax.jit(
                init, out_shardings=SparseTableState(
                    self._sharding, self._acc_sharding))(
                jax.random.PRNGKey(seed))
        else:
            self.state = init(jax.random.PRNGKey(seed))

        self._pull = jax.jit(lambda st, ids: st.rows[ids])
        self._push = jax.jit(self._push_impl, donate_argnums=(0,))

    # -- client API (pull_sparse / push_sparse) -----------------------------
    def pull(self, ids):
        """ids [...,] int32 → embeddings [..., D] (the pull_sparse role)."""
        return self._pull(self.state, jnp.asarray(ids))

    def push(self, ids, grads, lr: float | None = None):
        """Apply merged sparse gradients to the touched rows only."""
        ids = jnp.asarray(ids).reshape(-1)
        grads = jnp.asarray(grads).reshape(-1, self.dim)
        self.state = self._push(self.state, ids, grads,
                                jnp.asarray(lr if lr is not None else self.lr,
                                            jnp.float32))
        return self

    def _push_impl(self, st: SparseTableState, ids, grads, lr):
        slot_ids, merged = _merge_duplicate_ids(ids, grads, self.vocab_size)
        if self.optimizer == "adagrad":
            g2 = jnp.sum(merged * merged, axis=-1) / self.dim
            accum = st.accum.at[slot_ids].add(g2)
            denom = jnp.sqrt(accum[slot_ids])[:, None] + 1e-8
            rows = st.rows.at[slot_ids].add(-lr * merged / denom)
            return SparseTableState(rows, accum)
        rows = st.rows.at[slot_ids].add(-lr * merged)  # plain sgd
        return SparseTableState(rows, st.accum)

    # -- embedding-layer style forward with autograd ------------------------
    def lookup_and_grad_fn(self, ids):
        """Returns (embeddings, push_fn) where push_fn(d_embeddings[, lr])
        applies the sparse update — the distributed_lookup_table fwd/bwd
        pair as an explicit functional handshake."""
        emb = self.pull(ids)

        def push_fn(d_emb, lr=None):
            self.push(ids, d_emb, lr)

        return emb, push_fn

    # -- persistence (fleet.save_persistables for tables) -------------------
    def save(self, dirname: str, step: int = 0):
        from ..framework.checkpoint import save_sharded

        save_sharded({"rows": self.state.rows, "accum": self.state.accum},
                     dirname, step)

    def load(self, dirname: str, step: int = 0):
        from ..framework.checkpoint import load_sharded

        out = load_sharded(dirname, step,
                           {"rows": self.state.rows,
                            "accum": self.state.accum})
        self.state = SparseTableState(out["rows"], out["accum"])
        return self


class TheOnePS:
    """Table registry + facade (TheOnePSRuntime / PSClient role)."""

    def __init__(self, mesh: Mesh | None = None):
        self.mesh = mesh
        self._tables: dict[int, SparseEmbeddingTable] = {}

    def create_table(self, table_id: int, vocab_size: int, dim: int, **kw):
        t = SparseEmbeddingTable(vocab_size, dim, mesh=self.mesh, **kw)
        self._tables[table_id] = t
        return t

    def table(self, table_id: int) -> SparseEmbeddingTable:
        return self._tables[table_id]

    def pull_sparse(self, table_id: int, ids):
        return self._tables[table_id].pull(ids)

    def push_sparse(self, table_id: int, ids, grads, lr=None):
        return self._tables[table_id].push(ids, grads, lr)

    def save(self, dirname: str):
        for tid, t in self._tables.items():
            t.save(f"{dirname}/table_{tid}")

    def load(self, dirname: str):
        for tid, t in self._tables.items():
            t.load(f"{dirname}/table_{tid}")


class DistributedGraphTable:
    """Client-side handle on the PS-service graph table (reference
    common_graph_table.cc + graph_brpc_client: graph storage + neighbor
    sampling RPC for GNN recsys models).

    The storage and sampling kernels live server-side
    (_native/ps_table.cpp ``pgt_*``); edges are sharded ``src %
    num_servers`` so each server owns the full out-neighborhood of its
    nodes.  This wrapper binds one table id on a
    :class:`~paddle_tpu.distributed.ps_service.PSClient`."""

    def __init__(self, client, tid: int = 0, seed: int = 0):
        self.client = client
        self.tid = tid
        client.create_graph_table(tid, seed=seed)

    def add_edges(self, src, dst, weights=None):
        self.client.add_edges(self.tid, src, dst, weights)

    def sample_neighbors(self, ids, k: int):
        return self.client.sample_neighbors(self.tid, ids, k)

    def degrees(self, ids):
        return self.client.node_degrees(self.tid, ids)

    def set_node_feat(self, ids, feats):
        self.client.set_node_feat(self.tid, ids, feats)

    def get_node_feat(self, ids):
        """([n..., dim] features, [n...] found mask) — accepts the [n, k]
        output of :meth:`sample_neighbors` directly (padding -1 rows come
        back zero-filled with found=False)."""
        return self.client.get_node_feat(self.tid, ids)

    def random_nodes(self, k: int):
        return self.client.random_sample_nodes(self.tid, k)

    def stat(self):
        return self.client.graph_stat(self.tid)
