"""Role makers: cluster-spec discovery from environment variables.

Reference capability: ``PaddleCloudRoleMaker`` (fleet/base/role_maker.py:530)
parses the PADDLE_* env the launcher exports (trainer id/num/endpoints,
TRAINING_ROLE, pserver endpoints) and answers is_worker/is_server/rank/size;
``UserDefinedRoleMaker`` takes the same facts explicitly.

TPU-native: collective jobs get their topology from the launcher env
(launch.py _proc_env) or from jax.distributed; the PS pod (launch
--server_num) exports TRAINING_ROLE/PADDLE_PSERVER_ENDPOINTS which these
role makers surface to ported recsys scripts."""
from __future__ import annotations

import os

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_num = 1
        self._server_num = 0
        self._worker_endpoints: list[str] = []
        self._server_endpoints: list[str] = []

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def is_first_worker(self) -> bool:
        return self.is_worker() and self._current_id == 0

    def worker_index(self) -> int:
        return self._current_id if self.is_worker() else -1

    def server_index(self) -> int:
        return self._current_id if self.is_server() else -1

    def worker_num(self) -> int:
        return self._worker_num

    def server_num(self) -> int:
        return self._server_num

    def get_trainer_endpoints(self) -> list[str]:
        return list(self._worker_endpoints)

    def get_pserver_endpoints(self) -> list[str]:
        return list(self._server_endpoints)

    def role_id(self) -> int:
        return self._current_id


class PaddleCloudRoleMaker(RoleMakerBase):
    """Parse the launcher-exported env (role_maker.py:530 analog).

    Collective mode (is_collective=True): rank/size from
    PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM (or the paddle_tpu process env).
    PS mode: TRAINING_ROLE selects worker/server and
    PADDLE_PSERVER_ENDPOINTS lists the table servers (launch --server_num
    exports exactly these)."""

    def __init__(self, is_collective: bool = True, **kw):
        super().__init__()
        self._is_collective = is_collective
        env = os.environ
        self._current_id = int(env.get("PADDLE_TRAINER_ID", 0))
        self._worker_num = int(env.get("PADDLE_TRAINERS_NUM",
                                       env.get("PADDLE_TPU_NUM_PROCESSES",
                                               1)))
        eps = env.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = [e for e in eps.split(",") if e]
        ps = env.get("PADDLE_PSERVER_ENDPOINTS", "")
        self._server_endpoints = [e for e in ps.split(",") if e]
        self._server_num = len(self._server_endpoints)
        role = env.get("TRAINING_ROLE", "TRAINER").upper()
        if role in ("PSERVER", "SERVER"):
            self._role = Role.SERVER
            self._current_id = int(env.get("PADDLE_PSERVER_ID",
                                           env.get("POD_INDEX", 0)))
        else:
            self._role = Role.WORKER

    def ps_client(self):
        """Connect a PSClient to the pod's table servers."""
        from .ps_service import PSClient

        if not self._server_endpoints:
            raise RuntimeError("no PADDLE_PSERVER_ENDPOINTS in env — run "
                               "under launch --server_num")
        return PSClient(self._server_endpoints)


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id: int = 0, role: int = Role.WORKER,
                 worker_num: int = 1, server_endpoints=None,
                 worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_num = worker_num
        self._server_endpoints = list(server_endpoints or [])
        self._worker_endpoints = list(worker_endpoints or [])
        self._server_num = len(self._server_endpoints)
