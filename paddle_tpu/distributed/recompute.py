"""Recompute / activation checkpointing.

Reference: fleet/utils/recompute.py:63 RecomputeFunction (PyLayer that stashes
RNG state, drops activations, re-runs forward in backward) and static
RecomputeOptimizer (fluid/optimizer.py:5288).

TPU-first: inside jitted code this is just ``jax.checkpoint`` (XLA remat).
For the eager tape, ``recompute`` wraps the function in a PyLayer whose
backward re-runs the forward under jax.vjp — same memory/compute trade, and
RNG state is restored so dropout masks replay identically (the reference's
preserve_rng_state)."""
from __future__ import annotations

import jax

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..framework import random as _random


def recompute(function, *args, preserve_rng_state=True, **kwargs):
    from ..autograd import PyLayer

    class _Recompute(PyLayer):
        @staticmethod
        def forward(ctx, *inputs):
            ctx.saved_inputs = inputs
            ctx.rng_key = _random._state.key if preserve_rng_state else None
            with no_grad():
                out = function(*inputs, **kwargs)
            return out

        @staticmethod
        def backward(ctx, *grads):
            inputs = ctx.saved_inputs
            vals = [t.value if isinstance(t, Tensor) else t for t in inputs]
            diff_idx = [i for i, t in enumerate(inputs)
                        if isinstance(t, Tensor) and not t.stop_gradient]
            if ctx.rng_key is not None:
                saved_key = _random._state.key
                _random._state.key = ctx.rng_key

            def pure(*diff_vals):
                call = list(vals)
                for i, v in zip(diff_idx, diff_vals):
                    call[i] = v
                ts = [Tensor(v, stop_gradient=True) for v in call]
                with no_grad():
                    out = function(*ts, **kwargs)
                if isinstance(out, (tuple, list)):
                    return tuple(o.value for o in out)
                return out.value

            _, vjp_fn = jax.vjp(pure, *[vals[i] for i in diff_idx])
            if ctx.rng_key is not None:
                _random._state.key = saved_key
            cts = tuple(g.value for g in grads)
            if len(cts) == 1:
                in_grads = vjp_fn(cts[0])
            else:
                in_grads = vjp_fn(cts)
            out, gi = [], 0
            for i, t in enumerate(inputs):
                if not isinstance(t, Tensor):
                    continue
                if i in diff_idx:
                    out.append(Tensor(in_grads[gi]))
                    gi += 1
                else:
                    out.append(None)
            return tuple(out) if len(out) > 1 else out[0]

    return _Recompute.apply(*args)


# pure-function variant for jitted paths
checkpoint = jax.checkpoint
