"""Interleaved pipeline schedule generation (virtual pipeline stages).

Megatron-LM's interleaved 1F1B assigns each pipeline rank ``v`` model
chunks round-robin — virtual stage ``j`` lives on rank ``j % S`` (chunk
``j // S``) — so the pipeline fill is paid in *chunk* units instead of
*stage* units, shrinking the bubble fraction from ``(S-1)/M`` toward
``(S-1)/(v*M)``.  The reference (at its vintage) has only F-then-B and
flat 1F1B (section_worker.cc schedule_mode 0/1); this module goes beyond
it.

TPU-first shape: instead of per-rank imperative send/recv loops, the
schedule is materialized AS DATA — a ``[ticks, S]`` table of slots, each
slot one of fwd/bwd/idle with a (chunk, micro-batch) payload — produced
here by an explicit dependency-driven simulation and consumed by one
``lax.scan`` whose tick executes every rank's slot under ``shard_map``
(pp_layers.PipelineTrainStep).  Simulating instead of hard-coding the
Megatron closed form keeps the generator self-verifying: `validate()`
re-checks every dependency edge, and the tests assert the bubble actually
shrinks with v.

A slot is (kind, chunk, m): kind 0=fwd, 1=bwd, 2=idle.
"""
from __future__ import annotations

from typing import NamedTuple

import numpy as np

F, B, IDLE = 0, 1, 2


class Schedule(NamedTuple):
    table: np.ndarray       # [ticks, S, 3] int32: (kind, chunk, m)
    recv_f: np.ndarray      # [ticks, S, 3] int32: (valid, chunk, mslot) —
    #   forward activation arriving at tick start (sent by rank-1 last tick)
    recv_b: np.ndarray      # [ticks, S, 3] int32: cotangent arriving
    ticks: int
    buf: int                # ring-buffer depth per chunk (max in-flight)
    n_virtual: int
    n_stages: int
    n_micro: int

    @property
    def idle_frac(self) -> float:
        kinds = self.table[:, :, 0]
        return float((kinds == IDLE).sum()) / kinds.size


def _sim(S: int, v: int, M: int):
    """Greedy dependency-driven simulation of interleaved 1F1B.

    Policy per rank per tick: run a READY backward if one exists (drain
    activations as early as possible — the 1F1B memory property), else the
    next READY forward in Megatron chunk-group order, else idle.  Any
    dependency-correct schedule is numerically valid; greedy-bwd-first
    recovers flat 1F1B exactly at v=1 and the Megatron bubble shape for
    v>1 (asserted by tests, not assumed).
    """
    V = S * v
    # fwd_done[j][m] / bwd_done[j][m] = tick when it completed, or -1
    fwd_done = -np.ones((V, M), np.int64)
    bwd_done = -np.ones((V, M), np.int64)

    # per-rank forward work list in Megatron order: micro-batches grouped
    # per chunk in runs of S (finish a group of S micro-batches on chunk c
    # before touching chunk c+1, cycling)
    def fwd_order():
        # identical for every rank: the rank-dependence of interleaved 1F1B
        # lives in WHEN a rank may start (the warmup offset), not in the
        # order it walks its chunks
        order = []
        groups = (M + S - 1) // S
        for g in range(groups):
            ms = range(g * S, min((g + 1) * S, M))
            for c in range(v):
                for m in ms:
                    order.append((c, m))
        return order

    _order = fwd_order()
    fwd_q = {r: list(_order) for r in range(S)}
    bwd_q = {r: [] for r in range(S)}  # filled as forwards complete
    slots = []

    def fwd_ready(r, c, m, t):
        j = c * S + r
        if j == 0:
            return True
        # producer ran on rank (j-1) % S; +1 tick for the ppermute hop
        d = fwd_done[j - 1, m]
        return d >= 0 and d < t

    def bwd_ready(r, c, m, t):
        j = c * S + r
        if fwd_done[j, m] < 0 or fwd_done[j, m] >= t:
            return False
        if j == V - 1:
            return True
        d = bwd_done[j + 1, m]
        return d >= 0 and d < t

    total = 2 * V * M
    done = 0
    t = 0
    while done < total:
        if t > total + 4 * V * M + 16:  # deadlock guard (impossible if
            raise AssertionError(       # the dependency logic is right)
                f"schedule simulation deadlocked: S={S} v={v} M={M}")
        row = []
        decisions = []
        for r in range(S):
            # pick using state as of tick start (fwd_done/bwd_done updated
            # AFTER the loop so ranks can't see same-tick completions)
            pick = None
            for c, m in bwd_q[r]:
                if bwd_ready(r, c, m, t):
                    pick = (B, c, m)
                    break
            if pick is None:
                for c, m in fwd_q[r]:
                    if fwd_ready(r, c, m, t):
                        pick = (F, c, m)
                        break
            row.append(pick if pick else (IDLE, 0, 0))
            decisions.append(pick)
        for r, pick in enumerate(decisions):
            if pick is None:
                continue
            kind, c, m = pick
            j = c * S + r
            if kind == F:
                fwd_q[r].remove((c, m))
                fwd_done[j, m] = t
                bwd_q[r].append((c, m))
            else:
                bwd_q[r].remove((c, m))
                bwd_done[j, m] = t
            done += 1
        slots.append(row)
        t += 1
    return np.asarray(slots, np.int64), fwd_done, bwd_done


def build(S: int, v: int, M: int) -> Schedule:
    table, fwd_done, bwd_done = _sim(S, v, M)
    ticks = table.shape[0]
    V = S * v

    def x_window(j, m):
        """Ticks during which stage j's INPUT for micro-batch m occupies
        its ring slot: stashed when the upstream activation arrives (one
        tick after the producer's fwd; at fwd time for stage 0, whose
        input comes from the batch), freed after bwd(j, m) consumes it."""
        start = fwd_done[j, m] if j == 0 else fwd_done[j - 1, m] + 1
        return start, bwd_done[j, m]

    def d_window(j, m):
        """Cotangent slot: stashed when bwd(j+1, m)'s dx arrives, consumed
        by bwd(j, m).  Empty for the last stage (head-fed)."""
        if j == V - 1:
            return None
        return bwd_done[j + 1, m] + 1, bwd_done[j, m]

    # ring-buffer depth: max simultaneous occupants per (stage, slot kind)
    buf = 1
    for j in range(V):
        for win in (x_window, d_window):
            spans = [win(j, m) for m in range(M)]
            spans = [s for s in spans if s is not None]
            for t in range(ticks):
                alive = sum(1 for a, b in spans if a <= t <= b)
                buf = max(buf, alive)
    buf = min(buf, M)

    # receive tables: what lands on rank r at the START of tick t is what
    # rank (r-1) % S (fwd) / (r+1) % S (bwd) executed at tick t-1
    recv_f = np.zeros((ticks, S, 3), np.int64)
    recv_b = np.zeros((ticks, S, 3), np.int64)
    for t in range(1, ticks):
        for r in range(S):
            kind, c, m = table[t - 1, (r - 1) % S]
            j = c * S + (r - 1) % S
            if kind == F and j + 1 < V:
                # j+1 = c2*S + r: on the wrap hop (sender rank S-1 → rank
                # 0) the chunk advances; otherwise same chunk
                c2 = (j + 1) // S
                assert (j + 1) % S == r
                recv_f[t, r] = (1, c2, m % buf)
            kind, c, m = table[t - 1, (r + 1) % S]
            j = c * S + (r + 1) % S
            if kind == B and j - 1 >= 0:
                c2 = (j - 1) // S
                assert (j - 1) % S == r
                recv_b[t, r] = (1, c2, m % buf)

    sched = Schedule(table.astype(np.int32), recv_f.astype(np.int32),
                     recv_b.astype(np.int32), ticks, int(buf), v, S, M)
    validate(sched)
    return sched


def validate(s: Schedule):
    """Re-derive every dependency edge from the emitted table (the
    consumer trusts this table blindly — a scheduling bug here would show
    up as silently wrong gradients, so fail loudly instead)."""
    S, v, M = s.n_stages, s.n_virtual, s.n_micro
    V = S * v
    fwd_at = {}
    bwd_at = {}
    for t in range(s.ticks):
        for r in range(S):
            kind, c, m = s.table[t, r]
            j = c * S + r
            if kind == F:
                assert (j, m) not in fwd_at, f"dup fwd {(j, m)}"
                if j > 0:
                    assert fwd_at.get((j - 1, m), 10**9) < t, \
                        f"fwd({j},{m})@{t} before producer"
                fwd_at[(j, m)] = t
            elif kind == B:
                assert (j, m) in fwd_at and fwd_at[(j, m)] < t
                if j < V - 1:
                    assert bwd_at.get((j + 1, m), 10**9) < t, \
                        f"bwd({j},{m})@{t} before consumer grad"
                assert (j, m) not in bwd_at
                bwd_at[(j, m)] = t
    assert len(fwd_at) == V * M and len(bwd_at) == V * M, "lost slots"

    # ring-buffer safety on the CONSUMER's actual windows: the x slot for
    # (j, m) is written when the upstream activation ARRIVES (producer
    # fwd + 1 hop tick; at own-fwd time for stage 0) and read by bwd(j,m);
    # the d slot is written at bwd(j+1,m)+1 and read by bwd(j,m).  No
    # other micro-batch sharing the same ring index may write inside a
    # live window.
    def windows(j):
        out = []
        for m in range(M):
            xs = fwd_at[(j, m)] if j == 0 else fwd_at[(j - 1, m)] + 1
            out.append(("x", m, xs, bwd_at[(j, m)]))
            if j < V - 1:
                out.append(("d", m, bwd_at[(j + 1, m)] + 1,
                            bwd_at[(j, m)]))
        return out

    for j in range(V):
        per_kind: dict = {}
        for kind, m, a, b in windows(j):
            per_kind.setdefault((kind, m % s.buf), []).append((a, b, m))
        for (kind, slot), spans in per_kind.items():
            spans.sort()
            for (a1, b1, m1), (a2, b2, m2) in zip(spans, spans[1:]):
                assert a2 > b1, (f"{kind}-slot clobbered: stage {j} "
                                 f"slot {slot}: m={m1}[{a1},{b1}] vs "
                                 f"m={m2}[{a2},{b2}]")
