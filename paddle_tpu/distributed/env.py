"""Distributed environment: device mesh management.

Reference capability: process-per-GPU bring-up — fleet launch env vars
(PADDLE_TRAINER_ID …, launch_utils.py), NCCL-id TCP exchange
(platform/gen_comm_id_helper.cc:286), c_comm_init ops.

TPU-first: one process per *host*, all chips visible to XLA; "rank" is a mesh
coordinate, not an OS process.  Multi-host bootstrap is
``jax.distributed.initialize`` (the coordination service plays the
gen_comm_id role over DCN).  The global Mesh here is the ambient context all
Fleet strategies shard over.
"""
from __future__ import annotations

import os
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_GLOBAL_MESH: Mesh | None = None

# canonical axis order: dp outermost (DCN-friendly), then pp, then mp innermost
# (mp collectives are latency-bound → nearest-neighbour ICI)
AXIS_ORDER = ("dp", "pp", "sharding", "mp", "sp")


def init_parallel_env(mesh_shape: Mapping[str, int] | None = None, devices=None,
                      coordinator_address: str | None = None, num_processes: int | None = None,
                      process_id: int | None = None):
    """Create (and install) the global device mesh.

    mesh_shape e.g. {'dp': 2, 'mp': 4}; missing axes get size 1. With no args,
    all local devices go to 'dp' (classic DataParallel bring-up —
    reference paddle.distributed.init_parallel_env).
    """
    global _GLOBAL_MESH
    if coordinator_address is None:
        # launch.py contract: the launcher exports these for every trainer;
        # the KV store owns the advertised port, JAX coordination takes +1
        env_coord = os.environ.get("PADDLE_TPU_COORDINATOR")
        env_np = int(os.environ.get("PADDLE_TPU_NUM_PROCESSES", "1"))
        if env_coord is not None and env_np > 1:
            host, port = env_coord.rsplit(":", 1)
            coordinator_address = f"{host}:{int(port) + 1}"
            num_processes = env_np
            process_id = int(os.environ.get("PADDLE_TPU_PROCESS_ID", "0"))
    if coordinator_address is not None:
        jax.distributed.initialize(coordinator_address, num_processes, process_id)
    devs = list(devices) if devices is not None else jax.devices()
    if mesh_shape is None:
        mesh_shape = {"dp": len(devs)}
    names = [a for a in AXIS_ORDER if mesh_shape.get(a, 1) > 1]
    sizes = [mesh_shape[a] for a in names]
    if not names:  # degenerate single-device mesh still needs one axis
        names, sizes = ["dp"], [1]
    total = int(np.prod(sizes))
    if total > len(devs):
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {len(devs)}")
    arr = np.array(devs[:total]).reshape(sizes)
    _GLOBAL_MESH = Mesh(arr, tuple(names))
    return _GLOBAL_MESH


def set_mesh(mesh: Mesh):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh
    return mesh


def get_mesh() -> Mesh:
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        init_parallel_env()
    return _GLOBAL_MESH


def has_mesh() -> bool:
    return _GLOBAL_MESH is not None


def get_world_size() -> int:
    if _GLOBAL_MESH is None:
        return jax.device_count()
    return int(np.prod(list(_GLOBAL_MESH.shape.values())))


def get_rank() -> int:
    # single-controller SPMD: the "current rank" concept only exists per-host
    return jax.process_index()


def axis_size(axis: str) -> int:
    m = get_mesh()
    return m.shape.get(axis, 1)


def sharding_for(spec: PartitionSpec | None) -> NamedSharding:
    m = get_mesh()
    return NamedSharding(m, spec if spec is not None else PartitionSpec())


def normalize_spec(spec: PartitionSpec, mesh: Mesh | None = None) -> PartitionSpec:
    """Drop axes not present in the mesh (so one sharding table serves any
    mesh topology — the reference's DistributedStrategy degrade path)."""
    m = mesh or get_mesh()
    parts = []
    for p in spec:
        if p is None:
            parts.append(None)
        elif isinstance(p, (tuple, list)):
            kept = tuple(a for a in p if a in m.shape)
            parts.append(kept if kept else None)
        else:
            parts.append(p if p in m.shape else None)
    while parts and parts[-1] is None:
        parts.pop()
    return PartitionSpec(*parts)
