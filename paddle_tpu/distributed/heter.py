"""Heterogeneous PS training — host-resident sparse tables + device dense.

Reference capability: heterogeneous parameter-server training
(/root/reference/paddle/fluid/framework/fleet/heter_ps/ heter_comm.h,
device_worker.h:367 HeterCpuWorker, trainer.h:180 HeterXpuTrainer): the huge
sparse embedding lives on CPU parameter servers while dense math runs on the
accelerator, with pull/push at every step.

TPU-first shape: the dense half is ONE jitted XLA program whose inputs
include the pulled embedding rows (so embedding grads fall out of the same
value_and_grad), the sparse half is the C++ PS service
(distributed/ps_service.py + _native/ps_table.cpp).  Unique-ids pull,
inverse-gather on device, push of merged row grads — the
pull→compute→push cycle of the reference's HeterCpuWorker::TrainFiles.

Two execution shapes:
* :meth:`HeterTrainer.train_step` — synchronous pull→compute→push;
* :meth:`HeterTrainer.train_stream` — the pull of batch N+1 runs on a
  prefetch thread WHILE the device computes batch N (the reference
  HeterCpuWorker's pipelined data/pull queues), hiding PS round-trip
  latency behind device time.  Rows pulled one step early are one push
  stale — the reference's async-pipeline semantics.

Fault tolerance: transient server loss (crash/restart) is retried — the
client reconnects, re-creates the table on the fresh server, reloads the
last snapshot when ``snapshot_dir`` is set, and repeats the op (the
reference PS-client's retry/reregister path).

Bounded-time degradation (``degrade="stale"`` + ``op_budget``): instead of
blocking in lockstep retries, a pull that exhausts its wall-clock budget is
served from the client-side row cache (zeros for never-seen ids) and a push
that exhausts its budget is DEFERRED — queued locally and drained on later
steps once the server answers again.  This is the reference async
communicator's degradation contract
(fluid/distributed/service/communicator.cc: send queues + stale reads keep
training moving through server hiccups); ``stats`` counts every stale pull
and deferred push so the degradation is observable, never silent.
"""
from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["HeterTrainer"]


class HeterTrainer:
    """Pull-compute-push training over a PSClient + a pure dense step.

    dense_apply(params, embeds, batch) -> (loss, new-like outputs) must be a
    pure function: ``embeds`` is [n_unique, dim] pulled rows; the trainer
    jits loss+grads over (params, embeds) together, pushes the row grads to
    the PS (server-side adagrad), and applies ``optimizer`` to the dense
    params locally.

    ``vocab`` (+ optional ``snapshot_dir``) arms the recovery path: when a
    server dies and comes back empty, the table is re-created (and the
    snapshot reloaded) before the failed op is retried."""

    def __init__(self, client, table_id: int, dim: int,
                 dense_params, dense_apply: Callable, optimizer,
                 sparse_lr: float = 0.05, vocab: int | None = None,
                 snapshot_dir: str | None = None, max_retries: int = 3,
                 retry_interval: float = 0.5, degrade: str = "block",
                 op_budget: float | None = None):
        if degrade not in ("block", "stale"):
            raise ValueError(f"degrade must be 'block' or 'stale', "
                             f"got {degrade!r}")
        self.client = client
        self.tid = table_id
        self.dim = dim
        self.params = dense_params
        self.opt = optimizer
        self.opt_state = optimizer.init_state(dense_params)
        self.sparse_lr = sparse_lr
        self.vocab = vocab
        self.snapshot_dir = snapshot_dir
        self.max_retries = max_retries
        self.retry_interval = retry_interval
        self.degrade = degrade
        self.op_budget = op_budget
        # degradation state: last-known rows for stale reads, queued
        # (shard, ids, grads) for deferred pushes, and observability
        self._row_cache: dict[int, np.ndarray] = {}
        self._deferred: list[tuple[int, np.ndarray, np.ndarray]] = []
        # heuristic server-health flag (benign race under train_stream:
        # feeder writes it, consumer reads it — a stale value only shifts
        # WHICH step pays the drain probe)
        self._last_pull_stale = False
        self.stats = {"stale_pulls": 0, "stale_rows": 0,
                      "deferred_pushes": 0, "drained_pushes": 0}
        self._step = 0

        def _loss(params, embeds, batch):
            return dense_apply(params, embeds, batch)

        self._vg = jax.jit(jax.value_and_grad(_loss, argnums=(0, 1)))
        self._apply = jax.jit(
            lambda g, p, s, lr, step: optimizer.apply_gradients(
                g, p, s, lr=lr, step=step))

    # -- fault tolerance -----------------------------------------------------
    def _recover(self):
        """Reconnect + re-provision restarted (empty) servers.  Snapshots
        are restored ONLY onto shards whose table was just re-created — a
        blanket load would roll healthy shards back to the snapshot while
        the dense params kept their newer state.  A fresh shard with no
        usable snapshot keeps its random re-init (bounded loss on that
        shard's rows; training continues)."""
        self.client.reset_connections()
        if self.vocab is not None:
            fresh = self.client.create_table(self.tid, self.vocab, self.dim)
            if self.snapshot_dir is not None:
                for s, was_fresh in fresh.items():
                    if not was_fresh:
                        continue
                    try:
                        self.client.load_shard(s, self.snapshot_dir)
                    except (RuntimeError, ConnectionError, OSError):
                        pass  # no snapshot yet: keep the fresh init

    def _with_recovery(self, fn, budget: float | None = None):
        """Retry ``fn`` through recovery, bounded by ``budget`` seconds of
        wall clock when given (each attempt still bounded by the client's
        socket timeout).  Exhaustion raises; degradation is the CALLER's
        policy (stale read / deferred push), not this helper's."""
        deadline = None if budget is None else time.monotonic() + budget
        attempt = 0
        while True:
            try:
                return fn()
            except (RuntimeError, ConnectionError, OSError):
                out_of_time = (deadline is not None
                               and time.monotonic() >= deadline)
                if out_of_time or (deadline is None
                                   and attempt >= self.max_retries):
                    raise
                time.sleep(min(self.retry_interval * (attempt + 1),
                               max(0.0, deadline - time.monotonic())
                               if deadline is not None else 60.0))
                attempt += 1
                try:
                    self._recover()
                except (RuntimeError, ConnectionError, OSError):
                    continue  # server still down: next attempt re-tries

    # -- the two step phases -------------------------------------------------
    def _prepare(self, ids: np.ndarray):
        """Host/PS half: unique + pad + pull (safe on a prefetch thread)."""
        ids = np.asarray(ids, np.int64)
        uniq, inv = np.unique(ids.reshape(-1), return_inverse=True)
        # pad unique count to the next power of two so the jitted dense
        # program sees a bounded set of shapes (otherwise every distinct
        # n_unique retraces + recompiles); pad slots repeat row uniq[0] and
        # are never referenced by inv, so their grads are exactly zero
        pad_to = 1 << (len(uniq) - 1).bit_length()
        if pad_to != len(uniq):
            uniq = np.concatenate(
                [uniq, np.full(pad_to - len(uniq), uniq[0], np.int64)])
        try:
            rows = self._with_recovery(
                lambda: self.client.pull_sparse(self.tid, uniq),
                budget=self.op_budget)
            rows = rows.reshape(len(uniq), self.dim)
            if self.degrade == "stale":
                # .copy(): a cached view would pin each pull's whole
                # [pad_to, dim] base array for as long as any row survives
                for j, u in enumerate(uniq):
                    self._row_cache[int(u)] = rows[j].copy()
                self._last_pull_stale = False
        except (RuntimeError, ConnectionError, OSError):
            if self.degrade != "stale":
                raise
            # budget exhausted mid-pull: serve last-known rows (zeros for
            # never-seen ids) so the step completes in bounded time
            rows = np.zeros((len(uniq), self.dim), np.float32)
            miss = 0
            for j, u in enumerate(uniq):
                cached = self._row_cache.get(int(u))
                if cached is not None:
                    rows[j] = cached
                else:
                    miss += 1
            self.stats["stale_pulls"] += 1
            self.stats["stale_rows"] += len(uniq) - miss
            self._last_pull_stale = True
        embeds = jnp.asarray(rows)
        return uniq, inv.reshape(ids.shape), embeds

    def _drain_deferred(self):
        """Re-try queued pushes under the op budget; order within a shard
        is preserved so the server applies grads in step order.  Returns
        the shards that still hold queued deltas — the caller must keep
        routing NEW grads for those shards through the queue, or step
        N+1's update would reach the stateful server-side adagrad before
        step N's."""
        if not self._deferred:
            return set()
        deadline = None if self.op_budget is None \
            else time.monotonic() + self.op_budget
        remaining = []
        blocked: set[int] = set()  # first failure blocks that shard's rest
        timed_out = False
        for item in self._deferred:
            s, i, g = item
            timed_out = timed_out or (deadline is not None
                                      and time.monotonic() >= deadline)
            if timed_out or s in blocked:
                remaining.append(item)
                continue
            try:
                self.client.push_sparse_shard(s, self.tid, i, g,
                                              lr=self.sparse_lr)
                self.stats["drained_pushes"] += 1
            except (RuntimeError, ConnectionError, OSError):
                blocked.add(s)
                remaining.append(item)
        self._deferred = remaining
        return {s for s, _, _ in remaining}

    def _push(self, uniq: np.ndarray, ge: np.ndarray):
        """Per-SHARD pushes, each with its own retry: a whole-fan retry
        would re-apply grads on shards that already succeeded (adagrad is
        not idempotent — double update + inflated accumulator)."""
        backlogged: set[int] = set()
        if self.degrade == "stale":
            # skip the drain while the server is known-down (this step's
            # pull just degraded): probing a dead shard would cost a full
            # socket timeout per step on top of the budgeted push
            if self._last_pull_stale:
                backlogged = {s for s, _, _ in self._deferred}
            else:
                backlogged = self._drain_deferred()
        grads = np.asarray(ge)
        srv = uniq % self.client.S
        local = uniq // self.client.S
        for s in range(self.client.S):
            m = srv == s
            if not m.any():
                continue
            if s in backlogged:
                # older deltas for this shard are still queued: keep step
                # order by queueing the new ones behind them
                self._deferred.append((s, local[m], grads[m]))
                self.stats["deferred_pushes"] += 1
                continue
            try:
                self._with_recovery(
                    lambda s=s, i=local[m], g=grads[m]:
                    self.client.push_sparse_shard(s, self.tid, i, g,
                                                  lr=self.sparse_lr),
                    budget=self.op_budget)
            except (RuntimeError, ConnectionError, OSError):
                if self.degrade != "stale":
                    raise
                # budget exhausted: queue the delta; later steps drain it
                self._deferred.append((s, local[m], grads[m]))
                self.stats["deferred_pushes"] += 1

    def _compute_push_apply(self, prepared, batch) -> float:
        """Device half + push: one fused grad program, then PS push and the
        local dense update."""
        uniq, inv, embeds = prepared
        loss, (gp, ge) = self._vg(self.params, embeds,
                                  dict(batch, _inv=jnp.asarray(inv)))
        self._push(uniq, ge)
        self._step += 1
        self.params, self.opt_state = self._apply(
            gp, self.params, self.opt_state,
            jnp.asarray(self.opt.get_lr(), jnp.float32),
            jnp.asarray(self._step, jnp.int32))
        return float(loss)

    # -- public API ----------------------------------------------------------
    def train_step(self, ids: np.ndarray, batch) -> float:
        """ids: int64 [B, S] sparse feature ids for this batch."""
        return self._compute_push_apply(self._prepare(ids), batch)

    def train_stream(self, batches: Iterable, prefetch: int = 2):
        """Pipelined epoch over ``(ids, batch)`` pairs: a prefetch thread
        pulls batch N+1's rows while the device computes batch N (the
        reference HeterCpuWorker pipeline).  Yields each step's loss."""
        q: _queue.Queue = _queue.Queue(maxsize=max(1, prefetch))
        stop = threading.Event()

        def feeder():
            try:
                for ids, batch in batches:
                    prepared = self._prepare(ids)
                    while not stop.is_set():
                        try:
                            q.put((prepared, batch), timeout=0.2)
                            break
                        except _queue.Full:
                            continue
                    if stop.is_set():
                        return
                q.put(None)
            except BaseException as e:  # surfaced at the consumer
                q.put(e)

        t = threading.Thread(target=feeder, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    return
                if isinstance(item, BaseException):
                    raise item
                prepared, batch = item
                yield self._compute_push_apply(prepared, batch)
        finally:
            stop.set()
            while True:  # unblock a feeder stuck on a full queue
                try:
                    q.get_nowait()
                except _queue.Empty:
                    break
