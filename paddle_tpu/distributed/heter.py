"""Heterogeneous PS training — host-resident sparse tables + device dense.

Reference capability: heterogeneous parameter-server training
(/root/reference/paddle/fluid/framework/fleet/heter_ps/ heter_comm.h,
device_worker.h:367 HeterCpuWorker, trainer.h:180 HeterXpuTrainer): the huge
sparse embedding lives on CPU parameter servers while dense math runs on the
accelerator, with pull/push at every step.

TPU-first shape: the dense half is ONE jitted XLA program whose inputs
include the pulled embedding rows (so embedding grads fall out of the same
value_and_grad), the sparse half is the C++ PS service
(distributed/ps_service.py + _native/ps_table.cpp).  Unique-ids pull,
inverse-gather on device, push of merged row grads — the
pull→compute→push cycle of the reference's HeterCpuWorker::TrainFiles.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["HeterTrainer"]


class HeterTrainer:
    """Pull-compute-push training over a PSClient + a pure dense step.

    dense_apply(params, embeds, batch) -> (loss, new-like outputs) must be a
    pure function: ``embeds`` is [n_unique, dim] pulled rows; the trainer
    jits loss+grads over (params, embeds) together, pushes the row grads to
    the PS (server-side adagrad), and applies ``optimizer`` to the dense
    params locally.
    """

    def __init__(self, client, table_id: int, dim: int,
                 dense_params, dense_apply: Callable, optimizer,
                 sparse_lr: float = 0.05):
        self.client = client
        self.tid = table_id
        self.dim = dim
        self.params = dense_params
        self.opt = optimizer
        self.opt_state = optimizer.init_state(dense_params)
        self.sparse_lr = sparse_lr
        self._step = 0

        def _loss(params, embeds, batch):
            return dense_apply(params, embeds, batch)

        self._vg = jax.jit(jax.value_and_grad(_loss, argnums=(0, 1)))
        self._apply = jax.jit(
            lambda g, p, s, lr, step: optimizer.apply_gradients(
                g, p, s, lr=lr, step=step))

    def train_step(self, ids: np.ndarray, batch) -> float:
        """ids: int64 [B, S] sparse feature ids for this batch."""
        ids = np.asarray(ids, np.int64)
        uniq, inv = np.unique(ids.reshape(-1), return_inverse=True)
        # pad unique count to the next power of two so the jitted dense
        # program sees a bounded set of shapes (otherwise every distinct
        # n_unique retraces + recompiles); pad slots repeat row uniq[0] and
        # are never referenced by inv, so their grads are exactly zero
        pad_to = 1 << (len(uniq) - 1).bit_length()
        if pad_to != len(uniq):
            uniq = np.concatenate(
                [uniq, np.full(pad_to - len(uniq), uniq[0], np.int64)])
        # 1. pull unique rows from the PS shards
        rows = self.client.pull_sparse(self.tid, uniq)
        embeds = jnp.asarray(rows.reshape(len(uniq), self.dim))
        # 2. one fused device program: dense fwd + bwd wrt params AND rows
        inv_dev = jnp.asarray(inv.reshape(ids.shape))
        loss, (gp, ge) = self._vg(self.params, embeds,
                                  dict(batch, _inv=inv_dev))
        # 3. push row grads (server applies its adagrad update)
        self.client.push_sparse(self.tid, uniq, np.asarray(ge),
                                lr=self.sparse_lr)
        # 4. local dense update
        self._step += 1
        self.params, self.opt_state = self._apply(
            gp, self.params, self.opt_state,
            jnp.asarray(self.opt.get_lr(), jnp.float32),
            jnp.asarray(self._step, jnp.int32))
        return float(loss)
