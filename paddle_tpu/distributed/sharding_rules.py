"""Name-pattern → PartitionSpec rules for arbitrary models.

Reference capability: the reference's per-layer manual sharding choices
(mp_layers.py picks row/col sharding per named layer; sharding_optimizer
walks named vars).  TPU-first: users give ordered (regex, PartitionSpec)
rules over parameter path names and get a matching pytree of specs for
pjit/jit in_shardings — the standard JAX-community idiom for sharding
custom models without writing per-layer wrappers.
"""
from __future__ import annotations

import re
from typing import Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["match_sharding_rules", "apply_sharding_rules"]


def _leaf_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names = []
    for path, _ in flat:
        parts = []
        for k in path:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            elif hasattr(k, "name"):
                parts.append(str(k.name))
            else:
                parts.append(str(k))
        names.append("/".join(parts))
    return names, [l for _, l in flat], treedef


def match_sharding_rules(rules: Sequence[Tuple[str, P]], params,
                         default=None, strict=True):
    """Ordered (regex, PartitionSpec) rules → pytree of specs matching
    ``params``.  Scalars are never partitioned.  With ``strict`` a leaf no
    rule matches raises (silently-replicated big weights are the classic
    sharding bug); otherwise it gets ``default`` (replicated when None)."""
    names, leaves, treedef = _leaf_paths(params)
    specs = []
    for name, leaf in zip(names, leaves):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            specs.append(P())
            continue
        for pat, spec in rules:
            if re.search(pat, name):
                specs.append(spec)
                break
        else:
            if strict:
                raise ValueError(
                    f"no sharding rule matches parameter {name!r} "
                    f"(shape {tuple(shape)}); add a rule or pass "
                    "strict=False")
            specs.append(default if default is not None else P())
    return jax.tree_util.tree_unflatten(treedef, specs)


def apply_sharding_rules(rules, params, mesh, default=None, strict=True):
    """Place ``params`` onto ``mesh`` per the matched rules; returns
    (placed params, pytree of NamedShardings for jit in_shardings)."""
    specs = match_sharding_rules(rules, params, default=default,
                                 strict=strict)
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    placed = jax.tree_util.tree_map(jax.device_put, params, shardings)
    return placed, shardings
