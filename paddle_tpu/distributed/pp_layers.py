"""Generic pipeline segmentation: LayerDesc / SharedLayerDesc / PipelineLayer.

Reference capability: fleet/meta_parallel/parallel_layers/pp_layers.py —
``LayerDesc`` (:23) lazily describes one layer, ``SharedLayerDesc`` (:62)
marks weights reused by several stages (tied embeddings), ``PipelineLayer``
(:76) partitions the list into contiguous stage segments and wires p2p
send/recv between per-process stage programs.

TPU-first re-design.  The reference runs one *different* program per stage
process (MPMD); XLA SPMD compiles ONE program for every device, so
heterogeneous stages become per-stage ``lax.switch`` branches and the stage
state becomes data:

* each stage's own params/buffers are flattened into one f32 vector, padded
  to the longest stage, and stacked ``[S, L]`` sharded ``P('pp')`` — rank s
  physically holds only its own stage's weights (the reference's per-process
  partition);
* boundary activations are flattened + padded to one common ``[A]`` buffer
  riding ``lax.ppermute`` over the 'pp' mesh axis (send_v2/recv_v2 analog);
* ``SharedLayerDesc`` weights live in a separate replicated tree; every
  stage that references the key reads the same arrays, and shard_map's AD
  transpose psums their gradients over 'pp' automatically — the reference's
  ``allreduce_shared_weight_gradients`` (pp_layers.py:188) for free.

Three schedules, selectable via ``build_train_step(schedule=)`` — the
first two match the reference SectionWorker's ``schedule_mode``
(section_worker.cc:130-183); the third goes beyond the reference:

* ``"1f1b"`` (default): one scan whose every tick runs ONE forward
  micro-batch step and ONE backward micro-batch step per stage — micro-batch
  m runs forward on stage s at tick ``m + s`` and backward at tick
  ``m + 2(S-1) - s``.  The backward slot re-runs the stage forward under
  ``jax.vjp`` from a ring buffer of the last ``min(M, 2S-1)`` stage *inputs*
  (plus the pre-update buffer vector, so BN recompute sees the same state),
  so activation memory is flat in the micro-batch count M.
* ``"fthenb"``: autodiff over the F-then-B scan (micro-batch m enters at
  tick m, leaves at tick m + S - 1) — simpler, but the scan stores residuals
  for every tick, so activation memory grows with M.
* ``"interleaved"`` (+ ``n_virtual=v``): Megatron-style virtual pipeline
  stages — each rank holds v round-robin model chunks, shrinking the
  pipeline bubble by ~v at the cost of more in-flight activations.  The
  schedule itself is generated and dependency-validated as data in
  pp_schedule.py and executed by :class:`InterleavedPipelineTrainStep`.

The flagship GPT path (text/gpt_hybrid.py) keeps its hand-built
Megatron-aware 1F1B; this module generalizes the same schedule to
*arbitrary Layer lists* (ResNet, BERT, mixed conv/fc models).

Cost model for heterogeneous stages (this module's whole point — and its
price).  XLA SPMD compiles ONE program for every device, so per-stage
differences become padding, not divergence:

* **weights**: each stage's params flatten into one f32 vector padded to
  the LARGEST stage's size ``Lp`` — per-device weight memory is
  ``max_s |params_s|``, not ``|params_s|``.  ``seg_method="parameters"``
  exists to balance exactly this.
* **boundary activations**: every ppermute hop carries the LARGEST
  boundary's flat size ``A = max_s |x_s|`` — a conv stack whose early
  feature maps are 10x its late ones pays the early size on every hop.
* **compute**: a ``lax.switch`` runs only the selected branch — stage
  FLOPs do NOT pad up; per-tick wall-clock is the SLOWEST stage (ordinary
  pipeline balance, same as the reference's per-process stages).

So padding hurts memory/bandwidth, never FLOPs.  When stage sizes are
badly skewed, rebalance with ``seg_method="parameters"`` or hand-place
cuts; ``PipelineTrainStep.padding_report()`` quantifies the current waste
(tests/test_pp_layers.py exercises a 16x-skewed stack against it).
"""
from __future__ import annotations

import functools
import os
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ..compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..framework import random as _random
from ..nn.layer_base import Layer

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    """Lazy layer description (reference pp_layers.py:23)."""

    def __init__(self, layer_class, *args, **kwargs):
        if not issubclass(layer_class, Layer):
            raise TypeError(f"LayerDesc needs a Layer subclass, got "
                            f"{layer_class!r}")
        self.layer_class = layer_class
        self.args = args
        self.kwargs = kwargs

    def build(self) -> Layer:
        return self.layer_class(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """A layer whose weights are shared across every stage that names the
    same ``key`` (reference pp_layers.py:62 — tied embedding/logits).

    ``forward_func(layer, x)`` customizes the reuse (e.g. the logits head
    multiplies by the embedding table's transpose)."""

    def __init__(self, key: str, layer_class, *args,
                 forward_func: Callable | None = None, **kwargs):
        super().__init__(layer_class, *args, **kwargs)
        self.shared_key = key
        self.forward_func = forward_func


class _Item(NamedTuple):
    kind: str            # "layer" | "shared" | "fn"
    layer: Any           # Layer or plain callable
    fwd: Callable | None  # custom forward (shared descs)
    shared_key: str | None


class _PackMeta(NamedTuple):
    """Static recipe for flattening a pytree of arrays into one f32 vector."""
    treedef: Any
    shapes: tuple
    dtypes: tuple
    offsets: tuple
    size: int


def _meta_of(tree) -> _PackMeta:
    """Works on concrete arrays and on eval_shape's ShapeDtypeStructs."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes, dtypes, offsets = [], [], []
    off = 0
    for leaf in leaves:
        shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        dtype = jnp.dtype(getattr(leaf, "dtype", None)
                          or jnp.result_type(leaf))
        shapes.append(shape)
        dtypes.append(dtype)
        offsets.append(off)
        off += int(np.prod(shape)) if shape else 1
    return _PackMeta(treedef, tuple(shapes), tuple(dtypes), tuple(offsets), off)


def _pack(tree, meta: _PackMeta, pad_to: int):
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((pad_to,), jnp.float32)
    vec = jnp.concatenate(
        [jnp.asarray(l).astype(jnp.float32).reshape(-1) for l in leaves])
    return jnp.pad(vec, (0, pad_to - meta.size))


def _unpack(vec, meta: _PackMeta):
    leaves = []
    for shape, dtype, off in zip(meta.shapes, meta.dtypes, meta.offsets):
        n = int(np.prod(shape)) if shape else 1
        leaf = lax.slice_in_dim(vec, off, off + n).reshape(shape).astype(dtype)
        leaves.append(leaf)
    return jax.tree_util.tree_unflatten(meta.treedef, leaves)


def _wrap_tree(x):
    return jax.tree_util.tree_map(
        lambda a: Tensor(a, stop_gradient=True) if not isinstance(a, Tensor)
        else a, x)


def _unwrap_tree(x):
    return jax.tree_util.tree_map(
        lambda t: t.value if isinstance(t, Tensor) else t, x,
        is_leaf=lambda t: isinstance(t, Tensor))


def _current_lr_of(optimizer, step: int) -> float:
    from ..optimizer.lr import LRScheduler

    if isinstance(optimizer._lr, LRScheduler):
        return float(optimizer._lr.lr_at(step))
    return optimizer.get_lr()


def _check_batch_divisible(X, n_micro: int, dp: int):
    for leaf in jax.tree_util.tree_leaves(X):
        B = np.shape(leaf)[0]
        if B % (n_micro * dp):
            raise ValueError(
                f"global batch {B} must divide by n_micro*dp = "
                f"{n_micro * dp}")


def _put_batch(tree, sharding):
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(jnp.asarray(
            a.value if isinstance(a, Tensor) else a), sharding), tree,
        is_leaf=lambda a: isinstance(a, Tensor))


def _apply_item(item: _Item, params, bufs, x, training: bool):
    """Run one list item functionally; returns (y, new_bufs)."""
    from ..jit import _swap_state

    if item.kind == "fn":
        with no_grad():
            y = item.layer(_wrap_tree(x))
        return _unwrap_tree(y), bufs
    layer = item.layer
    layer.training = training
    with _swap_state(layer, params, bufs) as (_, named_b):
        with no_grad():
            if item.fwd is not None:
                y = item.fwd(layer, _wrap_tree(x))
            else:
                args = x if isinstance(x, tuple) else (x,)
                y = layer(*[_wrap_tree(a) for a in args])
        new_bufs = {k: t._value for k, t in named_b.items()}
    return _unwrap_tree(y), new_bufs


class PipelineLayer(Layer):
    """Partition an arbitrary layer list into ``num_stages`` pipeline stages
    (reference pp_layers.py:76).

    ``layers``: list of Layer / LayerDesc / SharedLayerDesc / plain callables
    (pure tensor functions, e.g. reshapes).
    ``seg_method``: "uniform" (equal layer counts) or "parameters" (balance
    parameter numel across stages).

    Eager ``forward`` runs the whole list serially (the single-process
    parity path); :meth:`build_train_step` compiles the pp-parallel step.
    """

    def __init__(self, layers, num_stages: int, seg_method: str = "uniform"):
        super().__init__()
        if num_stages < 1:
            raise ValueError("num_stages must be >= 1")
        self.num_stages = num_stages
        self._shared_layers: dict[str, Layer] = {}
        items: list[_Item] = []
        for i, entry in enumerate(layers):
            if isinstance(entry, SharedLayerDesc):
                if entry.shared_key not in self._shared_layers:
                    self._shared_layers[entry.shared_key] = entry.build()
                layer = self._shared_layers[entry.shared_key]
                items.append(_Item("shared", layer, entry.forward_func,
                                   entry.shared_key))
            elif isinstance(entry, LayerDesc):
                items.append(_Item("layer", entry.build(), None, None))
            elif isinstance(entry, Layer):
                items.append(_Item("layer", entry, None, None))
            elif callable(entry):
                items.append(_Item("fn", entry, None, None))
            else:
                raise TypeError(f"unsupported pipeline entry: {entry!r}")
        if len(items) < num_stages:
            raise ValueError(
                f"cannot split {len(items)} layers into {num_stages} stages")
        self._items = items
        # register sublayers so parameters()/state_dict() see everything once
        for key, l in self._shared_layers.items():
            self.add_sublayer(f"shared_{key}", l)
        for i, it in enumerate(items):
            if it.kind == "layer":
                self.add_sublayer(f"layer_{i}", it.layer)
        self._bounds = self._segment(seg_method)

    # -- segmentation ------------------------------------------------------
    def _segment(self, method: str):
        self._seg_method = method
        return self._segment_bounds(method, self.num_stages)

    def _segment_bounds(self, method: str, S: int):
        n = len(self._items)
        if method == "uniform":
            weights = [1.0] * n
        elif method == "parameters":
            weights = []
            for it in self._items:
                if it.kind == "fn":
                    weights.append(0.0)
                else:
                    weights.append(float(sum(
                        int(np.prod(p.shape)) for p in it.layer.parameters())
                        ) + 1e-3)
        else:
            raise ValueError(f"unknown seg_method {method!r}")
        total = sum(weights)
        bounds = [0]
        acc = 0.0
        for i, w in enumerate(weights):
            acc += w
            remaining_items = n - (i + 1)
            remaining_stages = S - len(bounds)
            if (acc >= total * len(bounds) / S
                    and len(bounds) < S
                    and remaining_items >= remaining_stages):
                bounds.append(i + 1)
        while len(bounds) < S:  # degenerate weights: pad cuts from the tail
            bounds.append(n - (S - len(bounds)))
        bounds.append(n)
        return bounds

    def stage_items(self, s: int) -> list:
        return self._items[self._bounds[s]: self._bounds[s + 1]]

    # -- serial (parity) path ----------------------------------------------
    def forward(self, x):
        for it in self._items:
            if it.kind == "fn":
                x = it.layer(x)
            elif it.fwd is not None:
                x = it.fwd(it.layer, x)
            else:
                x = it.layer(*(x if isinstance(x, tuple) else (x,)))
        return x

    # -- pipeline-parallel compiled step -------------------------------------
    def build_train_step(self, mesh: Mesh, optimizer, loss_fn,
                         n_micro: int, example_input, dp_axis: str = "dp",
                         pp_axis: str = "pp", remat: bool = True,
                         schedule: str = "1f1b", n_virtual: int = 1):
        """Compile the pp(+dp)-parallel train step over ``mesh``.

        ``example_input``: one (global-batch) input array/pytree used to
        trace boundary shapes — its per-micro-batch slice must be valid.
        ``schedule``: "1f1b" (activation memory bounded by the in-flight
        window — reference section_worker.cc schedule_mode 1), "fthenb"
        (autodiff over the forward scan, residuals for every tick —
        schedule_mode 0), or "interleaved" (virtual pipeline stages:
        each rank holds ``n_virtual`` model chunks round-robin, shrinking
        the pipeline bubble by ~n_virtual — beyond the reference, which
        has only modes 0/1; see pp_schedule.py).  With one stage all
        collapse to the same loop.
        ``remat``: rematerialize stage forwards in the backward pass — under
        "fthenb" this is what keeps the scan's residuals to one boundary
        buffer per tick; under "1f1b"/"interleaved" it bounds the
        *within-tick* VJP residuals to the branch inputs (the cross-tick
        window is already flat in M by construction).
        Returns a step object: call ``(X, Y) -> loss``.
        """
        if schedule == "interleaved":
            return InterleavedPipelineTrainStep(
                self, mesh, optimizer, loss_fn, n_micro, example_input,
                dp_axis, pp_axis, remat, n_virtual)
        if n_virtual != 1:
            raise ValueError("n_virtual > 1 requires schedule='interleaved'")
        return PipelineTrainStep(self, mesh, optimizer, loss_fn, n_micro,
                                 example_input, dp_axis, pp_axis, remat,
                                 schedule)


class PipelineTrainStep:
    """Stateful wrapper around the compiled pp train step (the role of the
    reference's PipelineParallel.train_batch, pipeline_parallel.py:109)."""

    def __init__(self, pl: PipelineLayer, mesh: Mesh, optimizer, loss_fn,
                 n_micro: int, example_input, dp_axis: str, pp_axis: str,
                 remat: bool, schedule: str = "1f1b"):
        S = mesh.shape[pp_axis]
        if S != pl.num_stages:
            raise ValueError(f"mesh '{pp_axis}' size {S} != num_stages "
                             f"{pl.num_stages}")
        if schedule not in ("1f1b", "fthenb"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}")
        dp = mesh.shape.get(dp_axis, 1)
        self.pl = pl
        self.mesh = mesh
        self._dp = dp
        self.optimizer = optimizer
        self.n_micro = n_micro
        self._step = 0
        training = pl.training

        # ---- per-stage state packing (params P('pp')-stacked, shared repl.)
        from ..jit import _split_state as _jit_split_state

        stage_ptrees, stage_btrees = [], []
        for s in range(S):
            pt, bt = {}, {}
            for j, it in enumerate(pl.stage_items(s)):
                if it.kind != "layer":
                    continue
                p, b = _jit_split_state(it.layer)
                pt[str(j)] = p
                bt[str(j)] = b
            stage_ptrees.append(pt)
            stage_btrees.append(bt)
        shared_p, shared_b = {}, {}
        for key, l in pl._shared_layers.items():
            shared_p[key], sb = _jit_split_state(l)
            if sb:
                raise NotImplementedError(
                    "SharedLayerDesc layers with buffers are not supported "
                    "(their per-stage updates would diverge)")
        self._pmetas = [_meta_of(t) for t in stage_ptrees]
        self._bmetas = [_meta_of(t) for t in stage_btrees]
        Lp = max(m.size for m in self._pmetas) or 1
        Lb = max((m.size for m in self._bmetas), default=1) or 1
        pvec = jnp.stack([_pack(t, m, Lp)
                          for t, m in zip(stage_ptrees, self._pmetas)])
        bvec = jnp.stack([_pack(t, m, Lb)
                          for t, m in zip(stage_btrees, self._bmetas)])

        # ---- boundary activation metas (trace stage chains with eval_shape)
        def mb_slice(tree):
            return jax.tree_util.tree_map(
                lambda a: jnp.zeros((np.shape(a)[0] // (n_micro * max(dp, 1)),)
                                    + tuple(np.shape(a)[1:]),
                                    jnp.asarray(a).dtype), tree)

        def run_stage_concrete(s, ptree, btree, sp, x):
            new_b = dict(btree)
            for j, it in enumerate(pl.stage_items(s)):
                if it.kind == "layer":
                    x, nb = _apply_item(it, ptree[str(j)], btree[str(j)], x,
                                        training)
                    new_b[str(j)] = nb
                elif it.kind == "shared":
                    x, _ = _apply_item(it, sp[it.shared_key], {}, x, training)
                else:
                    x, _ = _apply_item(it, None, None, x, training)
            return x, new_b

        x_meta = [None] * S  # input boundary meta per stage (s>=1)
        x_abs = mb_slice(example_input)
        for s in range(S):
            if s >= 1:
                x_meta[s] = _meta_of(x_abs)
            x_abs = jax.eval_shape(
                functools.partial(run_stage_concrete, s, stage_ptrees[s],
                                  stage_btrees[s], shared_p), x_abs)[0]
        out_meta = _meta_of(x_abs)  # last stage's output (loss head input)
        A = max([m.size for m in x_meta if m is not None] + [out_meta.size],
                default=1) or 1
        self._x_metas = x_meta
        self._out_meta = out_meta
        self._A = A

        # ---- per-stage switch branches (uniform signature; flags pick the
        # outputs so all three uses share one stage-application body):
        # fthenb ticks need (y, new_bv, loss); 1F1B forward slots own the
        # buffer updates (y, new_bv); 1F1B backward slots VJP the
        # stage+masked-head unit (y, loss)
        def make_branch(s, *, emit_bv: bool, emit_loss: bool):
            pm, bm = self._pmetas[s], self._bmetas[s]

            def branch(pv, bv, sp, x_flat, x0, y_lbl, key):
                ptree = _unpack(pv, pm)
                btree = _unpack(bv, bm)
                x = x0 if s == 0 else _unpack(x_flat, x_meta[s])
                with _random.rng_scope(key):
                    y, new_b = run_stage_concrete(s, ptree, btree, sp, x)
                loss = jnp.zeros((), jnp.float32)
                if s == S - 1:
                    # nothing consumes the last stage's forward output
                    # (fthenb: the head is here; 1f1b: the same-tick
                    # backward recomputes it inside its VJP)
                    y_send = jnp.zeros((A,), jnp.float32)
                    if emit_loss:
                        loss = loss_fn(_wrap_tree(y),
                                       Tensor(y_lbl, stop_gradient=True))
                        loss = (loss.value if isinstance(loss, Tensor)
                                else loss).astype(jnp.float32)
                else:
                    y_send = _pack(y, x_meta[s + 1], A)
                out = (y_send,)
                if emit_bv:
                    out += (lax.stop_gradient(_pack(new_b, bm, Lb)),)
                if emit_loss:
                    out += (loss,)
                return out

            return branch

        branches = [make_branch(s, emit_bv=True, emit_loss=True)
                    for s in range(S)]
        fwd_branches = [make_branch(s, emit_bv=True, emit_loss=False)
                        for s in range(S)]
        full_branches = [make_branch(s, emit_bv=False, emit_loss=True)
                         for s in range(S)]
        perm = [(i, (i + 1) % S) for i in range(S)]
        perm_bwd = [(i, (i - 1) % S) for i in range(S)]
        dp_ax = dp_axis if dp > 1 else None

        def pp_loss(pv_loc, bv_loc, sp, X, Y, key):
            s_idx = lax.axis_index(pp_axis)
            pv = pv_loc[0]
            bv = bv_loc[0]
            M = n_micro
            Xmb = jax.tree_util.tree_map(
                lambda a: a.reshape((M, a.shape[0] // M) + a.shape[1:]), X)
            Ymb = jax.tree_util.tree_map(
                lambda a: a.reshape((M, a.shape[0] // M) + a.shape[1:]), Y)
            ticks = M + S - 1
            keys = jax.random.split(key, ticks)

            step_branch = branches
            if remat:
                step_branch = [jax.checkpoint(b) for b in branches]

            def tick(carry, inp):
                x_flat, bv_c, loss_acc = carry
                t, k_t = inp
                in_idx = jnp.clip(t, 0, M - 1)
                out_idx = jnp.clip(t - (S - 1), 0, M - 1)
                x0 = jax.tree_util.tree_map(
                    lambda a: lax.dynamic_index_in_dim(a, in_idx,
                                                       keepdims=False), Xmb)
                y_lbl = jax.tree_util.tree_map(
                    lambda a: lax.dynamic_index_in_dim(a, out_idx,
                                                       keepdims=False), Ymb)
                k_t = jax.random.fold_in(k_t, s_idx)
                y_flat, bv_n, l = lax.switch(s_idx, step_branch, pv, bv_c, sp,
                                             x_flat, x0, y_lbl, k_t)
                # stage s holds real data only for ticks s..s+M-1 — outside
                # that window the input is fill/drain garbage, which must not
                # contaminate running statistics (BN buffers)
                valid = (t >= s_idx) & (t < s_idx + M)
                bv_n = jnp.where(valid, bv_n, bv_c)
                loss_acc = loss_acc + jnp.where(t >= S - 1, l, 0.0)
                x_send = lax.ppermute(y_flat, pp_axis, perm)
                return (x_send, bv_n, loss_acc), None

            init = (jnp.zeros((A,), jnp.float32), bv,
                    jnp.zeros((), jnp.float32))
            (_, bv_new, loss_sum), _ = lax.scan(tick, init,
                                                (jnp.arange(ticks), keys))
            loss = lax.psum(loss_sum, pp_axis) / M
            if dp_ax:
                loss = lax.pmean(loss, dp_ax)
            for ax in mesh.axis_names:
                if ax not in (dp_axis, pp_axis) and mesh.shape[ax] > 1:
                    loss = lax.pmean(loss, ax)
            return loss, bv_new[None]

        other_axes = tuple(ax for ax in mesh.axis_names
                           if ax not in (dp_axis, pp_axis)
                           and mesh.shape[ax] > 1)

        def pp_1f1b(pv_loc, bv_loc, sp, X, Y, key):
            """Per-rank interleaved schedule: returns (loss, local stage
            grads, shared grads, new buffers) — no outer autodiff needed.
            Micro-batch m: forward on stage s at tick m + s, backward at
            tick m + 2(S-1) - s (the wave reflects off the last stage,
            whose loss-head VJP runs in the same tick as its forward)."""
            s_idx = lax.axis_index(pp_axis)
            pv = pv_loc[0]
            bv = bv_loc[0]
            M = n_micro
            Xmb = jax.tree_util.tree_map(
                lambda a: a.reshape((M, a.shape[0] // M) + a.shape[1:]), X)
            Ymb = jax.tree_util.tree_map(
                lambda a: a.reshape((M, a.shape[0] // M) + a.shape[1:]), Y)
            BUF = min(M, 2 * S - 1)
            ticks = M + 2 * (S - 1)
            g_sp0 = jax.tree_util.tree_map(jnp.zeros_like, sp)

            def tick(carry, t):
                x_fwd, dx_bwd, bv_c, buf_x, buf_bv, g_pv, g_sp, loss_acc = \
                    carry

                # ---- forward slot: micro-batch t - s
                f_m = t - s_idx
                f_valid = (f_m >= 0) & (f_m < M)
                f_idx = jnp.clip(f_m, 0, M - 1)
                x0_f = jax.tree_util.tree_map(
                    lambda a: lax.dynamic_index_in_dim(a, f_idx,
                                                       keepdims=False), Xmb)
                ylbl_f = jax.tree_util.tree_map(
                    lambda a: lax.dynamic_index_in_dim(a, f_idx,
                                                       keepdims=False), Ymb)
                k_f = jax.random.fold_in(jax.random.fold_in(key, f_idx),
                                         s_idx)
                y_f, bv_n = lax.switch(s_idx, fwd_branches, pv, bv_c, sp,
                                       x_fwd, x0_f, ylbl_f, k_f)
                # ring buffer of stage INPUTS (+ the pre-update buffer
                # vector, so the backward recompute sees the same BN state);
                # guard so drain ticks can't clobber an unconsumed slot
                buf_x = jnp.where(
                    f_valid,
                    lax.dynamic_update_index_in_dim(buf_x, x_fwd,
                                                    f_idx % BUF, 0), buf_x)
                buf_bv = jnp.where(
                    f_valid,
                    lax.dynamic_update_index_in_dim(buf_bv, bv_c,
                                                    f_idx % BUF, 0), buf_bv)
                bv_next = jnp.where(f_valid, bv_n, bv_c)
                x_fwd_next = lax.ppermute(y_f, pp_axis, perm)

                # ---- backward slot: micro-batch t - 2(S-1) + s
                b_m = t - 2 * (S - 1) + s_idx
                b_valid = (b_m >= 0) & (b_m < M)
                b_idx = jnp.clip(b_m, 0, M - 1)
                x_saved = lax.dynamic_index_in_dim(buf_x, b_idx % BUF,
                                                   keepdims=False)
                bv_saved = lax.dynamic_index_in_dim(buf_bv, b_idx % BUF,
                                                    keepdims=False)
                x0_b = jax.tree_util.tree_map(
                    lambda a: lax.dynamic_index_in_dim(a, b_idx,
                                                       keepdims=False), Xmb)
                y_lbl = jax.tree_util.tree_map(
                    lambda a: lax.dynamic_index_in_dim(a, b_idx,
                                                       keepdims=False), Ymb)
                k_b = jax.random.fold_in(jax.random.fold_in(key, b_idx),
                                         s_idx)

                def run(pv_, sp_, xf_):
                    return lax.switch(s_idx, full_branches, pv_, bv_saved,
                                      sp_, xf_, x0_b, y_lbl, k_b)

                if remat:
                    # bound the within-tick residuals to the branch inputs;
                    # prevent_cse=False — the scan provides CSE protection
                    # and the default's optimization barriers hang the axon
                    # TPU compile (see text/gpt.py).  Same env overrides as
                    # gpt.py so the on-device variant check covers pp too.
                    from ..ops.remat_policies import resolve as _rp

                    _cse = os.environ.get(
                        "PADDLE_TPU_REMAT_PREVENT_CSE", "") == "1"
                    run = jax.checkpoint(
                        run, prevent_cse=_cse,
                        policy=_rp(os.environ.get(
                            "PADDLE_TPU_REMAT_POLICY") or None))
                (_, loss_mb), vjp_fn = jax.vjp(run, pv, sp, x_saved)
                valid = b_valid.astype(jnp.float32)
                # last stage's cotangent comes from its own head; others
                # receive dL/dy from stage s+1's backward slot
                dy = jnp.where(s_idx == S - 1, jnp.zeros_like(dx_bwd),
                               dx_bwd) * valid
                g_pv_t, g_sp_t, dx = vjp_fn((dy, valid / M))
                g_pv = g_pv + g_pv_t
                g_sp = jax.tree_util.tree_map(jnp.add, g_sp, g_sp_t)
                loss_acc = loss_acc + valid * loss_mb
                dx_next = lax.ppermute(dx, pp_axis, perm_bwd)
                return (x_fwd_next, dx_next, bv_next, buf_x, buf_bv, g_pv,
                        g_sp, loss_acc), None

            init = (jnp.zeros((A,), jnp.float32),
                    jnp.zeros((A,), jnp.float32), bv,
                    jnp.zeros((BUF, A), jnp.float32),
                    jnp.zeros((BUF, Lb), jnp.float32),
                    jnp.zeros_like(pv), g_sp0, jnp.zeros((), jnp.float32))
            (_, _, bv_new, _, _, g_pv, g_sp, loss_sum), _ = lax.scan(
                tick, init, jnp.arange(ticks))

            loss = lax.psum(loss_sum, pp_axis) / M
            # shared weights live replicated across pp — their true grad is
            # the SUM of the per-stage pieces (the reference's
            # allreduce_shared_weight_gradients, pp_layers.py:188)
            g_sp = lax.psum(g_sp, pp_axis)
            mean_axes = (dp_axis,) * (dp > 1) + other_axes
            if mean_axes:
                loss = lax.pmean(loss, mean_axes)
                g_pv = lax.pmean(g_pv, mean_axes)
                g_sp = lax.pmean(g_sp, mean_axes)
            return loss, g_pv[None], g_sp, bv_new[None]

        data_spec = P(dp_axis) if dp > 1 else P()
        in_specs = (P(pp_axis, None), P(pp_axis, None), P(), data_spec,
                    data_spec, P())
        if schedule == "1f1b" and S > 1:
            sharded_1f1b = shard_map(
                pp_1f1b, mesh=mesh, in_specs=in_specs,
                out_specs=(P(), P(pp_axis, None), P(), P(pp_axis, None)),
                check_vma=False)

            def step_fn(ptree, opt_state, bv, X, Y, key, lr, step):
                loss, g_stages, g_shared, bv_new = sharded_1f1b(
                    ptree["stages"], bv, ptree["shared"], X, Y, key)
                grads = {"stages": g_stages, "shared": g_shared}
                new_p, new_o = optimizer.apply_gradients(
                    grads, ptree, opt_state, lr=lr, step=step + 1)
                return new_p, new_o, bv_new, loss
        else:
            sharded = shard_map(
                pp_loss, mesh=mesh, in_specs=in_specs,
                out_specs=(P(), P(pp_axis, None)), check_vma=False)

            def step_fn(ptree, opt_state, bv, X, Y, key, lr, step):
                def loss_of(pt):
                    return sharded(pt["stages"], bv, pt["shared"], X, Y, key)

                (loss, bv_new), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(ptree)
                new_p, new_o = optimizer.apply_gradients(
                    grads, ptree, opt_state, lr=lr, step=step + 1)
                return new_p, new_o, bv_new, loss

        self._params = {"stages": pvec, "shared": shared_p}
        pv_shard = NamedSharding(mesh, P(pp_axis, None))
        repl = NamedSharding(mesh, P())
        shared_shard = jax.tree_util.tree_map(lambda _: repl, shared_p)
        p_shardings = {"stages": pv_shard, "shared": shared_shard}
        self._params = jax.device_put(self._params, p_shardings)
        self._bvec = jax.device_put(bvec, pv_shard)
        # jit propagates the params' shardings onto the moment buffers
        self._opt_state = jax.jit(optimizer.init_state)(self._params)
        self._data_sharding = NamedSharding(mesh, data_spec)
        self._compiled = jax.jit(step_fn, donate_argnums=(0, 1, 2))

    def padding_report(self) -> dict:
        """Quantify the heterogeneous-stage padding cost (see the module
        docstring's cost model): per-stage real parameter/boundary sizes
        vs the padded sizes every device actually pays.

        Returns {"param_sizes", "param_padded", "param_waste_frac",
        "boundary_sizes", "boundary_padded", "boundary_waste_frac"}."""
        p_sizes = [m.size for m in self._pmetas]
        # every real ppermute hop: the inter-stage boundaries AND the last
        # stage's output (it rides the same padded buffer)
        b_sizes = [m.size for m in self._x_metas if m is not None] \
            + [self._out_meta.size]
        Lp = max(p_sizes) or 1
        A = self._A
        n = len(p_sizes)
        p_waste = 1.0 - sum(p_sizes) / (n * Lp)
        b_waste = (1.0 - sum(b_sizes) / (len(b_sizes) * A)) if b_sizes \
            else 0.0
        return {"param_sizes": p_sizes, "param_padded": Lp,
                "param_waste_frac": p_waste,
                "boundary_sizes": b_sizes, "boundary_padded": A,
                "boundary_waste_frac": b_waste}

    def __call__(self, X, Y):
        _check_batch_divisible(X, self.n_micro, self._dp)
        X = _put_batch(X, self._data_sharding)
        Y = _put_batch(Y, self._data_sharding)
        key = _random.next_key()
        lr = _current_lr_of(self.optimizer, self._step)
        # pass the 0-based step; step_fn's +1 makes Adam's first update t=1
        self._params, self._opt_state, self._bvec, loss = self._compiled(
            self._params, self._opt_state, self._bvec, X, Y, key, lr,
            self._step)
        self._step += 1
        return Tensor(loss, stop_gradient=True)

    def sync_to_model(self):
        """Unpack the packed stage vectors back into the Layers' Parameters
        (for eval / state_dict / checkpointing after training)."""
        pl = self.pl
        pvec = np.asarray(self._params["stages"])
        bvec = np.asarray(self._bvec)
        for s in range(pl.num_stages):
            ptree = _unpack(jnp.asarray(pvec[s]), self._pmetas[s])
            btree = _unpack(jnp.asarray(bvec[s]), self._bmetas[s])
            for j, it in enumerate(pl.stage_items(s)):
                if it.kind != "layer":
                    continue
                for k, p in it.layer.named_parameters():
                    p._value = ptree[str(j)][k]
                for k, b in it.layer.named_buffers():
                    b._value = btree[str(j)][k]
        for key, l in pl._shared_layers.items():
            for k, p in l.named_parameters():
                p._value = self._params["shared"][key][k]


class InterleavedPipelineTrainStep:
    """Interleaved-1F1B (virtual pipeline stages) train step.

    Megatron-LM style: the layer list is cut into ``S * v`` virtual stages
    and virtual stage ``j`` lives on rank ``j % S`` (chunk ``j // S``), so
    consecutive stages sit on consecutive ranks and every hop — including
    the chunk-boundary wrap from rank S-1 back to rank 0 — is one
    ``lax.ppermute`` neighbor step on the 'pp' ring.  The pipeline fill is
    paid in chunk units, shrinking the bubble fraction by ~v (the
    reference's SectionWorker has only F-then-B and flat 1F1B).

    SPMD shape: the schedule is data (pp_schedule.build's dependency-
    validated [ticks, S] slot table).  One ``lax.scan`` tick stashes the
    activations/cotangents that arrived over the ring, then runs a 3-way
    ``lax.switch`` — forward slot, backward (VJP) slot, or idle — so each
    rank pays only its scheduled chunk-exec per tick (XLA conditionals
    execute only the taken branch), then both ppermutes run
    unconditionally (collectives must be uniform across ranks).

    Per-rank state: params pvec rank-major ``[S*v, Lp]`` sharded P('pp')
    (local rows = this rank's v chunks), activation ring ``[v, BUF, A]``
    and cotangent ring ``[v, BUF, A]`` with BUF = the schedule's measured
    max in-flight window.  Stages with buffers (BatchNorm) are rejected —
    their update order under interleaving is schedule-dependent; use
    schedule='1f1b' for those models.
    """

    def __init__(self, pl: PipelineLayer, mesh: Mesh, optimizer, loss_fn,
                 n_micro: int, example_input, dp_axis: str, pp_axis: str,
                 remat: bool, n_virtual: int):
        from .pp_schedule import build as _build_schedule

        S = mesh.shape[pp_axis]
        if S != pl.num_stages:
            raise ValueError(f"mesh '{pp_axis}' size {S} != num_stages "
                             f"{pl.num_stages}")
        v = int(n_virtual)
        if v < 1:
            raise ValueError("n_virtual must be >= 1")
        V = S * v
        if len(pl._items) < V:
            raise ValueError(
                f"cannot split {len(pl._items)} layers into {V} virtual "
                f"stages (num_stages={S} x n_virtual={v})")
        dp = mesh.shape.get(dp_axis, 1)
        M = n_micro
        self.pl = pl
        self.mesh = mesh
        self._dp = dp
        self._v = v
        self.optimizer = optimizer
        self.n_micro = M
        self._step = 0
        training = pl.training
        sched = _build_schedule(S, v, M)
        self._sched = sched
        BUF = sched.buf

        bounds = pl._segment_bounds(pl._seg_method, V)
        self._vbounds = bounds

        def vstage_items(j):
            return pl._items[bounds[j]: bounds[j + 1]]

        from ..jit import _split_state as _jit_split_state

        stage_ptrees = []
        for j in range(V):
            pt = {}
            for i, it in enumerate(vstage_items(j)):
                if it.kind != "layer":
                    continue
                p, b = _jit_split_state(it.layer)
                if b:
                    raise NotImplementedError(
                        "interleaved schedule does not support stages with "
                        "buffers (running BatchNorm stats update in "
                        "schedule-dependent order); use schedule='1f1b'")
                pt[str(i)] = p
            stage_ptrees.append(pt)
        shared_p = {}
        for key, l in pl._shared_layers.items():
            shared_p[key], sb = _jit_split_state(l)
            if sb:
                raise NotImplementedError(
                    "SharedLayerDesc layers with buffers are not supported")
        self._pmetas = [_meta_of(t) for t in stage_ptrees]
        Lp = max(m.size for m in self._pmetas) or 1
        # rank-major packing: row r*v + c  =  virtual stage c*S + r, so
        # P('pp') sharding hands each rank exactly its v chunks
        rows = []
        for r in range(S):
            for c in range(v):
                j = c * S + r
                rows.append(_pack(stage_ptrees[j], self._pmetas[j], Lp))
        pvec = jnp.stack(rows)

        # ---- boundary activation metas
        def mb_slice(tree):
            return jax.tree_util.tree_map(
                lambda a: jnp.zeros((np.shape(a)[0] // (M * max(dp, 1)),)
                                    + tuple(np.shape(a)[1:]),
                                    jnp.asarray(a).dtype), tree)

        def run_stage_concrete(j, ptree, sp, x):
            for i, it in enumerate(vstage_items(j)):
                if it.kind == "layer":
                    x, _ = _apply_item(it, ptree[str(i)], {}, x, training)
                elif it.kind == "shared":
                    x, _ = _apply_item(it, sp[it.shared_key], {}, x, training)
                else:
                    x, _ = _apply_item(it, None, None, x, training)
            return x

        x_meta = [None] * V
        x_abs = mb_slice(example_input)
        for j in range(V):
            if j >= 1:
                x_meta[j] = _meta_of(x_abs)
            x_abs = jax.eval_shape(
                functools.partial(run_stage_concrete, j, stage_ptrees[j],
                                  shared_p), x_abs)
        out_meta = _meta_of(x_abs)
        A = max([m.size for m in x_meta if m is not None] + [out_meta.size],
                default=1) or 1
        self._x_metas = x_meta
        self._out_meta = out_meta
        self._A = A

        def make_branch(j, *, emit_loss: bool):
            pm = self._pmetas[j]

            def branch(pv_row, sp, x_flat, x0, y_lbl, key):
                ptree = _unpack(pv_row, pm)
                x = x0 if j == 0 else _unpack(x_flat, x_meta[j])
                with _random.rng_scope(key):
                    y = run_stage_concrete(j, ptree, sp, x)
                loss = jnp.zeros((), jnp.float32)
                if j == V - 1:
                    y_send = jnp.zeros((A,), jnp.float32)
                    if emit_loss:
                        loss = loss_fn(_wrap_tree(y),
                                       Tensor(y_lbl, stop_gradient=True))
                        loss = (loss.value if isinstance(loss, Tensor)
                                else loss).astype(jnp.float32)
                else:
                    y_send = _pack(y, x_meta[j + 1], A)
                return (y_send, loss) if emit_loss else (y_send,)

            return branch

        fwd_branches = [make_branch(j, emit_loss=False) for j in range(V)]
        full_branches = [make_branch(j, emit_loss=True) for j in range(V)]
        perm = [(i, (i + 1) % S) for i in range(S)]
        perm_bwd = [(i, (i - 1) % S) for i in range(S)]
        other_axes = tuple(ax for ax in mesh.axis_names
                           if ax not in (dp_axis, pp_axis)
                           and mesh.shape[ax] > 1)
        TBL = jnp.asarray(sched.table)       # [ticks, S, 3]
        RCF = jnp.asarray(sched.recv_f)
        RCB = jnp.asarray(sched.recv_b)

        def pp_interleaved(pv_loc, sp, X, Y, key):
            s_idx = lax.axis_index(pp_axis)
            M_ = M
            Xmb = jax.tree_util.tree_map(
                lambda a: a.reshape((M_, a.shape[0] // M_) + a.shape[1:]), X)
            Ymb = jax.tree_util.tree_map(
                lambda a: a.reshape((M_, a.shape[0] // M_) + a.shape[1:]), Y)
            g_sp0 = jax.tree_util.tree_map(jnp.zeros_like, sp)

            def tick(carry, trow):
                (x_in, d_in, store_x, store_d, g_pv, g_sp,
                 loss_acc) = carry
                tbl_row, rcf_row, rcb_row = trow

                # ---- stash what arrived over the ring last tick
                fv, fc, fs = (rcf_row[s_idx, 0], rcf_row[s_idx, 1],
                              rcf_row[s_idx, 2])
                upd_x = lax.dynamic_update_slice(
                    store_x, x_in[None, None, :], (fc, fs, 0))
                store_x = jnp.where(fv == 1, upd_x, store_x)
                bv_, bc, bs = (rcb_row[s_idx, 0], rcb_row[s_idx, 1],
                               rcb_row[s_idx, 2])
                upd_d = lax.dynamic_update_slice(
                    store_d, d_in[None, None, :], (bc, bs, 0))
                store_d = jnp.where(bv_ == 1, upd_d, store_d)

                # ---- this tick's slot
                kind = tbl_row[s_idx, 0]
                c = tbl_row[s_idx, 1]
                m = tbl_row[s_idx, 2]
                j = c * S + s_idx
                mslot = m % BUF
                pv_row = lax.dynamic_index_in_dim(pv_loc, c, keepdims=False)
                x0 = jax.tree_util.tree_map(
                    lambda a: lax.dynamic_index_in_dim(a, m, keepdims=False),
                    Xmb)
                y_lbl = jax.tree_util.tree_map(
                    lambda a: lax.dynamic_index_in_dim(a, m, keepdims=False),
                    Ymb)
                x_flat = lax.dynamic_slice(store_x, (c, mslot, 0),
                                           (1, 1, A)).reshape(A)
                dy_in = lax.dynamic_slice(store_d, (c, mslot, 0),
                                          (1, 1, A)).reshape(A)
                # fwd and its bwd recompute must see the SAME rng stream
                k_t = jax.random.fold_in(jax.random.fold_in(key, m), j)

                def fwd_slot(_):
                    (y_send,) = lax.switch(j, fwd_branches, pv_row, sp,
                                           x_flat, x0, y_lbl, k_t)
                    return (y_send, jnp.zeros((A,), jnp.float32), g_pv,
                            g_sp, jnp.zeros((), jnp.float32))

                def bwd_slot(_):
                    def run(pvr, sp_, xf_):
                        return lax.switch(j, full_branches, pvr, sp_, xf_,
                                          x0, y_lbl, k_t)

                    if remat:
                        from ..ops.remat_policies import resolve as _rp

                        _cse = os.environ.get(
                            "PADDLE_TPU_REMAT_PREVENT_CSE", "") == "1"
                        run_ = jax.checkpoint(
                            run, prevent_cse=_cse,
                            policy=_rp(os.environ.get(
                                "PADDLE_TPU_REMAT_POLICY") or None))
                    else:
                        run_ = run
                    (_, loss_mb), vjp_fn = jax.vjp(run_, pv_row, sp, x_flat)
                    dy = jnp.where(j == V - 1, jnp.zeros_like(dy_in), dy_in)
                    g_row, g_sp_t, dx = vjp_fn(
                        (dy, jnp.ones((), jnp.float32) / M_))
                    new_row = lax.dynamic_index_in_dim(
                        g_pv, c, keepdims=False) + g_row
                    g_pv_n = lax.dynamic_update_index_in_dim(
                        g_pv, new_row, c, 0)
                    g_sp_n = jax.tree_util.tree_map(jnp.add, g_sp, g_sp_t)
                    return (jnp.zeros((A,), jnp.float32), dx, g_pv_n,
                            g_sp_n, loss_mb)

                def idle_slot(_):
                    return (jnp.zeros((A,), jnp.float32),
                            jnp.zeros((A,), jnp.float32), g_pv, g_sp,
                            jnp.zeros((), jnp.float32))

                y_send, d_send, g_pv, g_sp, loss_add = lax.switch(
                    kind, [fwd_slot, bwd_slot, idle_slot], 0)
                x_out = lax.ppermute(y_send, pp_axis, perm)
                d_out = lax.ppermute(d_send, pp_axis, perm_bwd)
                return (x_out, d_out, store_x, store_d, g_pv, g_sp,
                        loss_acc + loss_add), None

            init = (jnp.zeros((A,), jnp.float32),
                    jnp.zeros((A,), jnp.float32),
                    jnp.zeros((v, BUF, A), jnp.float32),
                    jnp.zeros((v, BUF, A), jnp.float32),
                    jnp.zeros_like(pv_loc), g_sp0,
                    jnp.zeros((), jnp.float32))
            (_, _, _, _, g_pv, g_sp, loss_sum), _ = lax.scan(
                tick, init, (TBL, RCF, RCB))
            loss = lax.psum(loss_sum, pp_axis) / M_
            g_sp = lax.psum(g_sp, pp_axis)
            mean_axes = (dp_axis,) * (dp > 1) + other_axes
            if mean_axes:
                loss = lax.pmean(loss, mean_axes)
                g_pv = lax.pmean(g_pv, mean_axes)
                g_sp = lax.pmean(g_sp, mean_axes)
            return loss, g_pv, g_sp

        data_spec = P(dp_axis) if dp > 1 else P()
        sharded = shard_map(
            pp_interleaved, mesh=mesh,
            in_specs=(P(pp_axis, None), P(), data_spec, data_spec, P()),
            out_specs=(P(), P(pp_axis, None), P()), check_vma=False)

        def step_fn(ptree, opt_state, X, Y, key, lr, step):
            loss, g_stages, g_shared = sharded(
                ptree["stages"], ptree["shared"], X, Y, key)
            grads = {"stages": g_stages, "shared": g_shared}
            new_p, new_o = optimizer.apply_gradients(
                grads, ptree, opt_state, lr=lr, step=step + 1)
            return new_p, new_o, loss

        self._params = {"stages": pvec, "shared": shared_p}
        pv_shard = NamedSharding(mesh, P(pp_axis, None))
        repl = NamedSharding(mesh, P())
        shared_shard = jax.tree_util.tree_map(lambda _: repl, shared_p)
        self._params = jax.device_put(
            self._params, {"stages": pv_shard, "shared": shared_shard})
        self._opt_state = jax.jit(optimizer.init_state)(self._params)
        self._data_sharding = NamedSharding(mesh, data_spec)
        self._compiled = jax.jit(step_fn, donate_argnums=(0, 1))

    def schedule_report(self) -> dict:
        """Bubble accounting straight from the validated slot table."""
        s = self._sched
        return {"ticks": s.ticks, "n_virtual": s.n_virtual,
                "buf": s.buf, "idle_frac": s.idle_frac,
                "useful_slots": 2 * s.n_stages * s.n_virtual * s.n_micro}

    def __call__(self, X, Y):
        _check_batch_divisible(X, self.n_micro, self._dp)
        X = _put_batch(X, self._data_sharding)
        Y = _put_batch(Y, self._data_sharding)
        key = _random.next_key()
        lr = _current_lr_of(self.optimizer, self._step)
        self._params, self._opt_state, loss = self._compiled(
            self._params, self._opt_state, X, Y, key, lr, self._step)
        self._step += 1
        return Tensor(loss, stop_gradient=True)

    def sync_to_model(self):
        """Unpack rank-major stage vectors back into the Layers."""
        pl = self.pl
        S, v = pl.num_stages, self._v
        pvec = np.asarray(self._params["stages"])
        for r in range(S):
            for c in range(v):
                j = c * S + r
                ptree = _unpack(jnp.asarray(pvec[r * v + c]),
                                self._pmetas[j])
                items = pl._items[self._vbounds[j]: self._vbounds[j + 1]]
                for i, it in enumerate(items):
                    if it.kind != "layer":
                        continue
                    for k, p in it.layer.named_parameters():
                        p._value = ptree[str(i)][k]
        for key, l in pl._shared_layers.items():
            for k, p in l.named_parameters():
                p._value = self._params["shared"][key][k]
