"""Parameter-server SERVICE: server processes + sharded client + async
communicator.

Reference capability (§2.4): the brpc PS stack — ``BrpcPsServer``/
``BrpcPsClient`` (fluid/distributed/service/brpc_ps_*.{h,cc}, protocol
sendrecv.proto), the async ``Communicator`` (service/communicator.cc:
send queues + batched merge push), and TheOnePSRuntime server/worker
bring-up.  This is the capability the in-device tables (distributed/ps.py)
do NOT cover: a CPU-hosted table service that outlives any one worker and
scales recommender vocabularies beyond accelerator memory.

TPU-native split of labor:
* hot loops (pull gather, duplicate-merged adagrad push, snapshot IO) run
  in native code — _native/ps_table.cpp (common_sparse_table.cc role);
* the wire is stdlib TCP with a length-prefixed binary frame carrying
  numpy buffers (the brpc/protobuf role, without the vendored RPC stack);
* sharding is id % num_servers (the reference's shard hash), mapped
  client-side to (server, local_row = id // num_servers).

This module must stay importable WITHOUT jax (server processes are plain
CPU processes; spawn start method re-imports it).
"""
from __future__ import annotations

import ctypes
import io
import os
import queue
import socket
import socketserver
import threading
import time
from typing import Any, Sequence

import numpy as np

# ---------------------------------------------------------------------------
# wire format: !I frame length | !B op | npz-style payload
# ---------------------------------------------------------------------------

OPS = {"create": 1, "pull": 2, "push": 3, "pull_dense": 4, "push_dense": 5,
       "save": 6, "load": 7, "stat": 8, "barrier_add": 9, "shutdown": 10,
       "barrier_get": 11, "err": 12, "push_delta": 13,
       # graph table service (common_graph_table.cc role)
       "g_create": 14, "g_add_edges": 15, "g_sample": 16, "g_degree": 17,
       "g_nodes": 18, "g_add_nodes": 19, "g_stat": 20,
       "g_set_feat": 21, "g_get_feat": 22}
_OP_NAMES = {v: k for k, v in OPS.items()}


def _pack(op: str, meta: dict, arrays: dict[str, np.ndarray]) -> bytes:
    """Frame payload: 1 op byte + npz body (length prefix added by the
    shared kvstore framing on send)."""
    import json

    buf = io.BytesIO()
    np.savez(buf, __meta__=np.frombuffer(
        json.dumps(meta).encode(), np.uint8), **arrays)
    return bytes([OPS[op]]) + buf.getvalue()


def _unpack(frame: bytes):
    import json

    op = _OP_NAMES[frame[0]]
    with np.load(io.BytesIO(frame[1:]), allow_pickle=False) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    return op, meta, arrays


# length-prefixed framing shared with the KV store (kvstore.py)
from .kvstore import recv_frame as _recv_frame  # noqa: E402
from .kvstore import send_frame as _send_frame  # noqa: E402


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class PSServer:
    """One table-shard server process (BrpcPsServer role).

    Owns the rows with ``id % num_servers == server_idx`` of every table,
    stored/updated by the native kernel; handles pull/push/dense/save/load
    over threaded TCP."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 server_idx: int = 0, num_servers: int = 1,
                 ssd_dir: str | None = None):
        from .._native import ps_table

        self._lib = ps_table()
        self.server_idx = server_idx
        self.num_servers = num_servers
        self._ssd_dir = ssd_dir  # enables storage="ssd" tables
        self._tables: dict[int, dict] = {}
        self._tables_lock = threading.Lock()
        self._dense: dict[str, np.ndarray] = {}
        self._dense_lock = threading.Lock()
        self._counters: dict[str, int] = {}
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        frame = _recv_frame(self.request)
                        resp = outer._dispatch(frame)
                        _send_frame(self.request, resp)
                        if frame[0] == OPS["shutdown"]:
                            threading.Thread(
                                target=outer._srv.shutdown,
                                daemon=True).start()
                            return
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server((host, port), Handler)
        self.host, self.port = self._srv.server_address

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def _local_rows(self, vocab: int) -> int:
        # rows this shard owns under id % S == idx
        s, i = self.num_servers, self.server_idx
        return (vocab - i + s - 1) // s if vocab > i else 0

    def _dispatch(self, frame: bytes) -> bytes:
        try:
            op, meta, arrays = _unpack(frame)
        except Exception as e:  # noqa: BLE001 - protocol skew/corrupt frame:
            # the client still deserves an answer, not a dead thread
            return _pack("err", {"ok": False,
                                 "err": f"bad frame: {e!r}"}, {})
        lib = self._lib
        try:
            if op == "create":
                tid = meta["tid"]
                storage = meta.get("storage", "mem")
                fresh = False
                with self._tables_lock:  # concurrent creates must not
                    # race the check-then-insert (handle leak + lost pushes)
                    if tid not in self._tables:
                        fresh = True
                        rows = self._local_rows(meta["vocab"])
                        seed = meta.get("seed", 0) * 1000 + self.server_idx
                        rng = meta.get("init_range", 0.05)
                        if storage == "ssd":
                            # mmap-file-backed shard (SSDSparseTable role)
                            if self._ssd_dir is None:
                                return _pack("create", {
                                    "ok": False,
                                    "err": "server started without "
                                           "ssd_dir"}, {})
                            os.makedirs(self._ssd_dir, exist_ok=True)
                            path = os.path.join(
                                self._ssd_dir,
                                f"table_{tid}.shard{self.server_idx}.mmap")
                            h = lib.pst_create_ssd(rows, meta["dim"], seed,
                                                   rng, path.encode())
                            if not h:
                                return _pack("create", {
                                    "ok": False,
                                    "err": f"mmap create failed: {path}"},
                                    {})
                        else:
                            h = lib.pst_create(rows, meta["dim"], seed, rng)
                        self._tables[tid] = {"h": h, "rows": rows,
                                             "dim": meta["dim"],
                                             "vocab": meta["vocab"],
                                             "storage": storage}
                # fresh=True means THIS server just created (randomly
                # initialized) the shard — recovery paths use it to restore
                # a snapshot onto exactly the restarted servers
                return _pack("create", {"ok": True, "fresh": fresh}, {})
            if op == "pull":
                t = self._tables[meta["tid"]]
                ids = np.ascontiguousarray(arrays["ids"], np.int64)
                out = np.empty((len(ids), t["dim"]), np.float32)
                lib.pst_pull(t["h"],
                             ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                             len(ids),
                             out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
                return _pack("pull", {"ok": True}, {"rows": out})
            if op == "push":
                t = self._tables[meta["tid"]]
                ids = np.ascontiguousarray(arrays["ids"], np.int64)
                g = np.ascontiguousarray(arrays["grads"], np.float32)
                lib.pst_push_adagrad(
                    t["h"],
                    ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    g.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    len(ids), meta.get("lr", 0.05), meta.get("eps", 1e-8))
                return _pack("push", {"ok": True}, {})
            if op == "push_delta":
                t = self._tables[meta["tid"]]
                ids = np.ascontiguousarray(arrays["ids"], np.int64)
                d = np.ascontiguousarray(arrays["deltas"], np.float32)
                lib.pst_push_delta(
                    t["h"],
                    ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    d.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    len(ids))
                return _pack("push_delta", {"ok": True}, {})
            if op == "pull_dense":
                with self._dense_lock:
                    arr = self._dense.get(meta["key"])
                return _pack("pull_dense", {"ok": arr is not None},
                             {"value": arr} if arr is not None else {})
            if op == "push_dense":
                with self._dense_lock:
                    if meta.get("grad", False):
                        if meta["key"] not in self._dense:
                            # applying a grad to nothing would silently store
                            # the gradient AS the parameter
                            return _pack("push_dense", {
                                "ok": False,
                                "err": f"dense key {meta['key']!r} not "
                                       f"initialized; push the value first"},
                                {})
                        self._dense[meta["key"]] = (
                            self._dense[meta["key"]]
                            - meta.get("lr", 0.05) * arrays["value"])
                    else:
                        self._dense[meta["key"]] = arrays["value"]
                return _pack("push_dense", {"ok": True}, {})
            if op == "save":
                os.makedirs(meta["dir"], exist_ok=True)
                with self._tables_lock:  # snapshot: creates may race
                    tables = list(self._tables.items())
                for tid, t in tables:
                    path = os.path.join(
                        meta["dir"],
                        f"table_{tid}.shard{self.server_idx}").encode()
                    if t.get("kind") == "graph":
                        lib.pgt_save(t["h"], path)
                        continue
                    if t.get("storage") == "ssd":
                        lib.pst_sync(t["h"])  # msync the mmap first
                    lib.pst_save(t["h"], path)
                return _pack("save", {"ok": True}, {})
            if op == "load":
                with self._tables_lock:
                    tables = list(self._tables.items())
                for tid, t in tables:
                    path = os.path.join(
                        meta["dir"],
                        f"table_{tid}.shard{self.server_idx}").encode()
                    fn = (lib.pgt_load if t.get("kind") == "graph"
                          else lib.pst_load)
                    rc = fn(t["h"], path)
                    if rc != 0:
                        return _pack("load", {"ok": False, "rc": rc}, {})
                return _pack("load", {"ok": True}, {})
            if op == "g_create":
                tid = meta["tid"]
                with self._tables_lock:
                    if tid not in self._tables:
                        h = lib.pgt_create(
                            meta.get("seed", 0) * 1000 + self.server_idx + 1)
                        self._tables[tid] = {"h": h, "kind": "graph",
                                             "rows": 0, "dim": 0}
                return _pack("g_create", {"ok": True}, {})
            if op == "g_add_edges":
                t = self._tables[meta["tid"]]
                src = np.ascontiguousarray(arrays["src"], np.int64)
                dst = np.ascontiguousarray(arrays["dst"], np.int64)
                w = arrays.get("weights")
                wp = None
                if w is not None:
                    w = np.ascontiguousarray(w, np.float32)
                    wp = w.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                lib.pgt_add_edges(
                    t["h"],
                    src.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    wp, len(src))
                return _pack("g_add_edges", {"ok": True}, {})
            if op == "g_add_nodes":
                t = self._tables[meta["tid"]]
                ids = np.ascontiguousarray(arrays["ids"], np.int64)
                lib.pgt_add_nodes(
                    t["h"],
                    ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    len(ids))
                return _pack("g_add_nodes", {"ok": True}, {})
            if op == "g_sample":
                t = self._tables[meta["tid"]]
                ids = np.ascontiguousarray(arrays["ids"], np.int64)
                k = int(meta["k"])
                out = np.full((len(ids), k), -1, np.int64)
                lib.pgt_sample_neighbors(
                    t["h"],
                    ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    len(ids), k,
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
                return _pack("g_sample", {"ok": True}, {"nbrs": out})
            if op == "g_degree":
                t = self._tables[meta["tid"]]
                ids = np.ascontiguousarray(arrays["ids"], np.int64)
                out = np.zeros(len(ids), np.int64)
                lib.pgt_degrees(
                    t["h"],
                    ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    len(ids),
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
                return _pack("g_degree", {"ok": True}, {"degrees": out})
            if op == "g_set_feat":
                t = self._tables[meta["tid"]]
                ids = np.ascontiguousarray(arrays["ids"], np.int64)
                feats = np.ascontiguousarray(arrays["feats"], np.float32)
                dim = feats.shape[1] if feats.ndim == 2 else int(meta["dim"])
                rc = lib.pgt_set_node_feat(
                    t["h"],
                    ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    feats.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    len(ids), dim)
                if rc != 0:
                    return _pack("err", {"ok": False, "err": (
                        f"set_node_feat: feature dim {dim} conflicts with "
                        f"the table's established dim "
                        f"{int(lib.pgt_feat_dim(t['h']))}")}, {})
                return _pack("g_set_feat", {"ok": True}, {})
            if op == "g_get_feat":
                t = self._tables[meta["tid"]]
                ids = np.ascontiguousarray(arrays["ids"], np.int64)
                dim = int(meta["dim"]) if meta.get("dim") \
                    else int(lib.pgt_feat_dim(t["h"]))
                out = np.zeros((len(ids), max(dim, 1)), np.float32)
                found = np.zeros(len(ids), np.uint8)
                if dim:
                    rc = lib.pgt_get_node_feat(
                        t["h"],
                        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                        len(ids), dim,
                        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                        found.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_uint8)))
                    if rc != 0:
                        return _pack("err", {"ok": False, "err": (
                            f"get_node_feat: dim {dim} != table dim "
                            f"{int(lib.pgt_feat_dim(t['h']))}")}, {})
                return _pack("g_get_feat", {"ok": True, "dim": dim},
                             {"feats": out[:, :dim], "found": found})
            if op == "g_stat":
                # read-only: must not touch the sampling RNG
                t = self._tables[meta["tid"]]
                return _pack("g_stat", {
                    "ok": True,
                    "num_nodes": int(lib.pgt_num_nodes(t["h"])),
                    "num_edges": int(lib.pgt_num_edges(t["h"]))}, {})
            if op == "g_nodes":
                t = self._tables[meta["tid"]]
                k = int(meta["k"])
                out = np.full(k, -1, np.int64)
                lib.pgt_random_sample_nodes(
                    t["h"], k,
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
                return _pack("g_nodes", {
                    "ok": True,
                    "num_nodes": int(lib.pgt_num_nodes(t["h"])),
                    "num_edges": int(lib.pgt_num_edges(t["h"]))},
                    {"nodes": out})
            if op == "barrier_add":
                with self._dense_lock:
                    k = meta["key"]
                    self._counters[k] = self._counters.get(k, 0) + 1
                    return _pack("barrier_add",
                                 {"ok": True, "count": self._counters[k]}, {})
            if op == "barrier_get":
                with self._dense_lock:
                    return _pack("barrier_get", {
                        "ok": True,
                        "count": self._counters.get(meta["key"], 0)}, {})
            if op == "stat":
                with self._tables_lock:
                    tables = list(self._tables.items())
                return _pack("stat", {
                    "ok": True, "server_idx": self.server_idx,
                    "tables": {str(tid): {"rows": t["rows"], "dim": t["dim"]}
                               for tid, t in tables}}, {})
            if op == "shutdown":
                return _pack("shutdown", {"ok": True}, {})
            return _pack(op, {"ok": False, "err": f"bad op {op}"}, {})
        except Exception as e:  # noqa: BLE001 - must answer the client
            return _pack(op, {"ok": False, "err": repr(e)}, {})

    def serve_forever(self):
        self._srv.serve_forever()

    def start_background(self):
        t = threading.Thread(target=self._srv.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self):
        self._srv.shutdown()
        self._srv.server_close()


def run_server(port: int, server_idx: int, num_servers: int,
               ready_path: str | None = None, ssd_dir: str | None = None):
    """Blocking server entry point for a spawned process (the reference's
    server-side main, TheOnePSRuntime._init_server role)."""
    srv = PSServer(port=port, server_idx=server_idx, num_servers=num_servers,
                   ssd_dir=ssd_dir)
    if ready_path:
        with open(ready_path, "w") as f:
            f.write(srv.endpoint)
    srv.serve_forever()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class PSClient:
    """Sharded client (BrpcPsClient role): routes by id % num_servers,
    fans requests to all servers in parallel, reassembles in order."""

    def __init__(self, endpoints: Sequence[str], timeout: float = 60.0):
        from concurrent.futures import ThreadPoolExecutor

        self.endpoints = list(endpoints)
        self.S = len(self.endpoints)
        self._socks: list[socket.socket | None] = [None] * self.S
        self._locks = [threading.Lock() for _ in range(self.S)]
        self._timeout = timeout
        # persistent fan-out pool: pull/push run every training step —
        # per-call thread construction would sit on the hot path
        self._pool = ThreadPoolExecutor(max_workers=self.S,
                                        thread_name_prefix="psclient")

    def _sock(self, s: int) -> socket.socket:
        if self._socks[s] is None:
            host, port = self.endpoints[s].rsplit(":", 1)
            sk = socket.create_connection((host, int(port)),
                                          timeout=self._timeout)
            sk.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks[s] = sk
        return self._socks[s]

    def _rpc(self, s: int, op: str, meta: dict, arrays: dict):
        with self._locks[s]:
            try:
                sk = self._sock(s)
                _send_frame(sk, _pack(op, meta, arrays))
                rop, rmeta, rarr = _unpack(_recv_frame(sk))
            except (ConnectionError, OSError, EOFError):
                # a dead/restarted server leaves the cached socket broken —
                # drop it so the next call dials fresh (heter recovery path)
                try:
                    if self._socks[s] is not None:
                        self._socks[s].close()
                except OSError:
                    pass
                self._socks[s] = None
                raise
        if not rmeta.get("ok", False):
            raise RuntimeError(f"PS {op} on server {s} failed: "
                               f"{rmeta.get('err', rmeta)}")
        return rmeta, rarr

    def reset_connections(self):
        """Drop every cached socket; subsequent RPCs reconnect (used by
        recovery paths after a server restart)."""
        for s in range(self.S):
            with self._locks[s]:
                if self._socks[s] is not None:
                    try:
                        self._socks[s].close()
                    except OSError:
                        pass
                    self._socks[s] = None

    def _fan(self, op: str, metas, arrays_by_s):
        futs = {s: self._pool.submit(self._rpc, s, op, metas[s],
                                     arrays_by_s[s])
                for s in range(self.S)}
        return {s: f.result() for s, f in futs.items()}

    # -- table API ----------------------------------------------------------
    def create_table(self, tid: int, vocab: int, dim: int, seed: int = 0,
                     init_range: float = 0.05, storage: str = "mem"):
        """storage="ssd" puts the shard in an mmap'd file on the server
        (SSDSparseTable role; the server needs ssd_dir).  Returns
        {server -> fresh}: True where the shard was just created (used by
        recovery to reload snapshots onto restarted servers ONLY)."""
        meta = {"tid": tid, "vocab": vocab, "dim": dim, "seed": seed,
                "init_range": init_range, "storage": storage}
        out = self._fan("create", [meta] * self.S, [{}] * self.S)
        return {s: bool(out[s][0].get("fresh", False)) for s in range(self.S)}

    def load_shard(self, s: int, dirname: str):
        """Restore ONE server's tables from a snapshot dir (recovery path —
        a plain load() would roll healthy shards back too)."""
        self._rpc(s, "load", {"dir": dirname}, {})

    def push_sparse_shard(self, s: int, tid: int, local_ids: np.ndarray,
                          grads: np.ndarray, lr: float = 0.05):
        """Push pre-sharded LOCAL row grads to one server.  Retry loops use
        this so a shard that already applied its update is never pushed
        twice (adagrad is not idempotent)."""
        self._rpc(s, "push", {"tid": tid, "lr": lr},
                  {"ids": np.asarray(local_ids, np.int64),
                   "grads": np.asarray(grads, np.float32)})

    def push_sparse_delta(self, tid: int, ids: np.ndarray,
                          deltas: np.ndarray):
        """rows[ids] += deltas (the geo-async merge op)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        deltas = np.asarray(deltas, np.float32).reshape(len(ids), -1)
        srv = ids % self.S
        local = ids // self.S
        metas, arrs = [], []
        for s in range(self.S):
            m = srv == s
            metas.append({"tid": tid})
            arrs.append({"ids": local[m], "deltas": deltas[m]})
        self._fan("push_delta", metas, arrs)

    def pull_sparse(self, tid: int, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        srv = ids % self.S
        local = ids // self.S
        metas, arrs = [], []
        for s in range(self.S):
            metas.append({"tid": tid})
            arrs.append({"ids": local[srv == s]})
        out = self._fan("pull", metas, arrs)
        dim = next(iter(out.values()))[1]["rows"].shape[1]
        res = np.empty((len(ids), dim), np.float32)
        for s in range(self.S):
            res[srv == s] = out[s][1]["rows"]
        return res

    def push_sparse(self, tid: int, ids: np.ndarray, grads: np.ndarray,
                    lr: float = 0.05):
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), -1)
        srv = ids % self.S
        local = ids // self.S
        metas, arrs = [], []
        for s in range(self.S):
            m = srv == s
            metas.append({"tid": tid, "lr": lr})
            arrs.append({"ids": local[m], "grads": grads[m]})
        self._fan("push", metas, arrs)

    # -- graph API (common_graph_table.cc role) ------------------------------
    def create_graph_table(self, tid: int, seed: int = 0):
        """Distributed graph table for GNN sampling: each server owns the
        full out-neighborhood of the nodes with ``src % num_servers ==
        server_idx``."""
        self._fan("g_create", [{"tid": tid, "seed": seed}] * self.S,
                  [{}] * self.S)

    def add_edges(self, tid: int, src, dst, weights=None):
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        if len(src) != len(dst):
            raise ValueError("src/dst length mismatch")
        w = None if weights is None else np.asarray(
            weights, np.float32).reshape(-1)
        srv = src % self.S
        metas, arrs = [], []
        for s in range(self.S):
            m = srv == s
            metas.append({"tid": tid})
            a = {"src": src[m], "dst": dst[m]}
            if w is not None:
                a["weights"] = w[m]
            arrs.append(a)
        self._fan("g_add_edges", metas, arrs)
        # register dst nodes on THEIR owning shards so per-shard node sets
        # partition the graph (random_sample_nodes stays unbiased)
        dsrv = dst % self.S
        self._fan("g_add_nodes", [{"tid": tid}] * self.S,
                  [{"ids": np.unique(dst[dsrv == s])}
                   for s in range(self.S)])

    def sample_neighbors(self, tid: int, ids, k: int) -> np.ndarray:
        """[n, k] int64 of sampled out-neighbors, -1-padded where the
        degree is below k.  Uniform without replacement, or
        weight-proportional when the edges carried weights."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        srv = ids % self.S
        metas, arrs = [], []
        for s in range(self.S):
            metas.append({"tid": tid, "k": int(k)})
            arrs.append({"ids": ids[srv == s]})
        out = self._fan("g_sample", metas, arrs)
        res = np.full((len(ids), int(k)), -1, np.int64)
        for s in range(self.S):
            res[srv == s] = out[s][1]["nbrs"]
        return res

    def node_degrees(self, tid: int, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        srv = ids % self.S
        metas, arrs = [], []
        for s in range(self.S):
            metas.append({"tid": tid})
            arrs.append({"ids": ids[srv == s]})
        out = self._fan("g_degree", metas, arrs)
        res = np.zeros(len(ids), np.int64)
        for s in range(self.S):
            res[srv == s] = out[s][1]["degrees"]
        return res

    def set_node_feat(self, tid: int, ids, feats):
        """Store per-node float feature vectors on the owning shards
        (reference common_graph_table.h:121 set_node_feat).  ``feats`` is
        [n, dim]; the dim is fixed by the first call table-wide."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        feats = np.asarray(feats, np.float32)
        if feats.ndim != 2 or len(feats) != len(ids) or feats.shape[1] == 0:
            raise ValueError(f"feats must be [{len(ids)}, dim>=1], got "
                             f"{feats.shape}")
        if (ids < 0).any():
            # get_node_feat treats negative ids as sample padding — a
            # stored-but-unreadable feature would be a silent write loss
            raise ValueError("negative node ids cannot carry features")
        srv = ids % self.S
        metas, arrs = [], []
        for s in range(self.S):
            m = srv == s
            metas.append({"tid": tid, "dim": int(feats.shape[1])})
            arrs.append({"ids": ids[m], "feats": feats[m]})
        self._fan("g_set_feat", metas, arrs)

    def get_node_feat(self, tid: int, ids):
        """[n, dim] float32 features for ``ids`` plus an [n] bool found
        mask; unknown nodes (including -1 sample padding) zero-fill with
        found=False, so sampled neighborhoods feed the model directly."""
        ids = np.asarray(ids, np.int64)
        shape = ids.shape
        flat = ids.reshape(-1)
        srv = flat % self.S
        # -1 padding from sample_neighbors: never ask a shard for it
        srv = np.where(flat < 0, -1, srv)
        metas, arrs = [], []
        for s in range(self.S):
            metas.append({"tid": tid, "dim": 0})
            arrs.append({"ids": flat[srv == s]})
        out = self._fan("g_get_feat", metas, arrs)
        dims = [out[s][0]["dim"] for s in range(self.S)]
        nonzero = sorted({d for d in dims if d})
        if len(nonzero) > 1:
            # a shard restored from a different-dim snapshot must be LOUD,
            # not silently zero-filled training data
            raise RuntimeError(
                f"graph table {tid}: shards disagree on feature dim "
                f"(per-shard dims {dims}); reload matching snapshots")
        dim = nonzero[0] if nonzero else 0
        res = np.zeros((len(flat), dim), np.float32)
        found = np.zeros(len(flat), bool)
        for s in range(self.S):
            m = srv == s
            fe = out[s][1]["feats"]
            if fe.shape[1] == dim:  # dim-0 shard = no features stored there
                res[m] = fe
                found[m] = out[s][1]["found"].astype(bool)
        return (res.reshape(shape + (dim,)),
                found.reshape(shape))

    def random_sample_nodes(self, tid: int, k: int) -> np.ndarray:
        """k nodes drawn ~uniformly across the whole distributed graph:
        each shard returns k uniform draws from its own node set plus its
        node count; the client keeps a count-weighted mix."""
        out = self._fan("g_nodes", [{"tid": tid, "k": int(k)}] * self.S,
                        [{}] * self.S)
        counts = np.array([out[s][0]["num_nodes"] for s in range(self.S)],
                          np.float64)
        if counts.sum() == 0:
            return np.full(int(k), -1, np.int64)
        take = np.random.multinomial(int(k), counts / counts.sum())
        picks = [out[s][1]["nodes"][:t] for s, t in enumerate(take)]
        res = np.concatenate(picks) if picks else np.empty(0, np.int64)
        # a shard with fewer unique draws than requested never under-fills:
        # the server samples with replacement, so take<=k always satisfiable
        return res

    def graph_stat(self, tid: int) -> dict:
        out = self._fan("g_stat", [{"tid": tid}] * self.S, [{}] * self.S)
        return {"num_nodes": sum(out[s][0]["num_nodes"]
                                 for s in range(self.S)),
                "num_edges": sum(out[s][0]["num_edges"]
                                 for s in range(self.S))}

    # -- dense API (key-sharded by hash) -------------------------------------
    def _dense_server(self, key: str) -> int:
        import zlib

        # stable across processes (python's hash() is per-process salted —
        # workers would route the same key to different servers)
        return zlib.crc32(key.encode()) % self.S

    def push_dense(self, key: str, value: np.ndarray, grad: bool = False,
                   lr: float = 0.05):
        s = self._dense_server(key)
        self._rpc(s, "push_dense", {"key": key, "grad": grad, "lr": lr},
                  {"value": np.asarray(value, np.float32)})

    def pull_dense(self, key: str) -> np.ndarray:
        s = self._dense_server(key)
        _, arr = self._rpc(s, "pull_dense", {"key": key}, {})
        return arr["value"]

    # -- control -------------------------------------------------------------
    def save(self, dirname: str):
        self._fan("save", [{"dir": dirname}] * self.S, [{}] * self.S)

    def load(self, dirname: str):
        self._fan("load", [{"dir": dirname}] * self.S, [{}] * self.S)

    def stat(self):
        return [self._rpc(s, "stat", {}, {})[0] for s in range(self.S)]

    def barrier(self, key: str, world: int, timeout: float = 60.0):
        """All-worker barrier through server 0's counter table (the
        reference BarrierTable role).  Generation-based so the same key is
        reusable across epochs: my arrival number fixes my generation, and
        I wait until that whole generation has arrived — the counter only
        ever grows, no reset race."""
        m, _ = self._rpc(0, "barrier_add", {"key": key}, {})
        gen_target = ((m["count"] - 1) // world + 1) * world
        t0 = time.time()
        while time.time() - t0 < timeout:
            c, _ = self._rpc(0, "barrier_get", {"key": key}, {})
            if c["count"] >= gen_target:
                return True
            time.sleep(0.05)
        raise TimeoutError(f"PS barrier {key!r}")

    def shutdown_servers(self):
        for s in range(self.S):
            try:
                self._rpc(s, "shutdown", {}, {})
            except Exception:  # noqa: BLE001 - best effort on teardown
                pass

    def close(self):
        self._pool.shutdown(wait=False)
        for sk in self._socks:
            if sk is not None:
                try:
                    sk.close()
                except OSError:
                    pass


# ---------------------------------------------------------------------------
# async communicator (a_sync mode)
# ---------------------------------------------------------------------------

class AsyncCommunicator:
    """Client-side async push batching (reference service/communicator.cc:
    per-table send queues, merged batched push, bounded staleness).

    ``push_sparse`` enqueues; a background thread concatenates pending
    (ids, grads) per table — duplicate merge happens server-side — and
    pushes every ``flush_interval`` seconds or ``max_pending`` batches."""

    def __init__(self, client: PSClient, flush_interval: float = 0.01,
                 max_pending: int = 16):
        self.client = client
        self.flush_interval = flush_interval
        self.max_pending = max_pending
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        # race-free flush accounting: every enqueued push increments
        # _pushed; only after its batch is ACKed by the server does
        # _applied catch up (no event-flag lost-wakeup window)
        self._cv = threading.Condition()
        self._pushed = 0
        self._applied = 0
        self._err: Exception | None = None
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def push_sparse(self, tid: int, ids, grads, lr: float = 0.05):
        if self._err is not None:
            raise self._err
        with self._cv:
            self._pushed += 1
        self._q.put((tid, np.asarray(ids, np.int64).reshape(-1),
                     np.asarray(grads, np.float32), float(lr)))

    def _drain(self):
        pending: dict[tuple, list] = {}
        n = 0
        while n < self.max_pending:
            try:
                tid, ids, g, lr = self._q.get_nowait()
            except queue.Empty:
                break
            pending.setdefault((tid, lr), []).append(
                (ids, g.reshape(len(ids), -1)))
            n += 1
        for (tid, lr), items in pending.items():
            ids = np.concatenate([i for i, _ in items])
            grads = np.concatenate([g for _, g in items])
            self.client.push_sparse(tid, ids, grads, lr=lr)
        if n:
            with self._cv:
                self._applied += n
                self._cv.notify_all()
        return n

    def _loop(self):
        while not self._stop.is_set():
            try:
                if self._drain() == 0:
                    self._stop.wait(self.flush_interval)
            except Exception as e:  # noqa: BLE001 - surfaced on next push/flush
                with self._cv:
                    self._err = e
                    self._cv.notify_all()
                return

    def flush(self, timeout: float = 30.0):
        deadline = time.time() + timeout
        with self._cv:
            while self._applied < self._pushed and self._err is None:
                remaining = deadline - time.time()
                if remaining <= 0 or not self._cv.wait(timeout=remaining):
                    raise TimeoutError("AsyncCommunicator flush")
            if self._err is not None:
                raise self._err

    def stop(self):
        self.flush()
        self._stop.set()
        self._t.join(timeout=5)


class GeoCommunicator:
    """Geo-async sparse training (reference SparseGeoTable +
    GeoCommunicator, service/communicator.cc geo mode): the trainer
    trains against a LOCAL row cache (zero-latency pull/push) and every
    ``k_steps`` pushes only the accumulated per-row DELTA to the server
    and refreshes its cache with the globally merged rows — bounded
    staleness instead of per-step round trips."""

    def __init__(self, client: PSClient, tid: int, k_steps: int = 10):
        self.client = client
        self.tid = tid
        self.k_steps = k_steps
        self._cache: dict[int, np.ndarray] = {}  # id -> local row
        self._base: dict[int, np.ndarray] = {}   # id -> row at last sync
        self._dirty: set[int] = set()
        self._step = 0

    def pull(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        missing = [int(i) for i in ids if int(i) not in self._cache]
        if missing:
            rows = self.client.pull_sparse(self.tid, np.asarray(missing))
            for i, r in zip(missing, rows):
                self._cache[i] = r.copy()
                self._base[i] = r.copy()
        return np.stack([self._cache[int(i)] for i in ids])

    def push(self, ids: np.ndarray, grads: np.ndarray, lr: float = 0.05):
        """Local SGD on the cache; server sync every k_steps.  Ids never
        pulled are fetched lazily first (push-before-pull is legal)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), -1)
        self.pull(ids)  # ensure every id is cached (no-op when warm)
        for i, g in zip(ids, grads):
            i = int(i)
            self._cache[i] = self._cache[i] - lr * g
            self._dirty.add(i)
        self._step += 1
        if self._step % self.k_steps == 0:
            self.sync()

    def sync(self):
        """Push accumulated deltas, refresh the cache with merged rows."""
        if not self._dirty:
            return
        ids = np.asarray(sorted(self._dirty), np.int64)
        deltas = np.stack([self._cache[int(i)] - self._base[int(i)]
                           for i in ids])
        self.client.push_sparse_delta(self.tid, ids, deltas)
        merged = self.client.pull_sparse(self.tid, ids)
        for i, r in zip(ids, merged):
            self._cache[int(i)] = r.copy()
            self._base[int(i)] = r.copy()
        self._dirty.clear()


def main(argv=None):
    """Server-process CLI: python -m paddle_tpu.distributed.ps_service
    --port P --server_idx I --num_servers N [--ready_path F]"""
    import argparse

    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.ps_service")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--server_idx", type=int, required=True)
    p.add_argument("--num_servers", type=int, required=True)
    p.add_argument("--ready_path", default=None)
    p.add_argument("--ssd_dir", default=None,
                   help="enable storage='ssd' tables (mmap files here)")
    a = p.parse_args(argv)
    run_server(a.port, a.server_idx, a.num_servers, a.ready_path, a.ssd_dir)


if __name__ == "__main__":
    main()
