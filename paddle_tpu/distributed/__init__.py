"""paddle_tpu.distributed — SPMD parallelism over a TPU device mesh.

Reference: python/paddle/distributed (collective.py API, fleet/, launch).
See module docstrings for the NCCL→XLA-collective mapping (SURVEY.md §2.4).

Under light import (launcher/spawn processes — see paddle_tpu/__init__.py)
only the backend-free tooling modules load: kvstore, elastic, launch.
"""
import paddle_tpu as _root

from . import elastic, kvstore  # noqa: F401  (backend-free, always safe)

if not _root._LIGHT_IMPORT:
    from . import fleet  # noqa: F401
    from .collective import (  # noqa: F401
        ReduceOp, all_gather, all_reduce, alltoall, barrier, broadcast,
        new_group, prim, recv, reduce, reduce_scatter, scatter, send,
    )
    from .env import (  # noqa: F401
        get_mesh, get_rank, get_world_size, has_mesh, init_parallel_env,
        set_mesh,
    )
    from .parallel import DataParallel  # noqa: F401
    from .recompute import recompute  # noqa: F401
    from . import megatron, pipeline, pp_layers, ps, role_maker  # noqa: F401
    from .role_maker import (  # noqa: F401
        PaddleCloudRoleMaker, UserDefinedRoleMaker,
    )
    from .pp_layers import (  # noqa: F401
        LayerDesc, PipelineLayer, SharedLayerDesc,
    )
    from .topology import (  # noqa: F401
        CommunicateTopology, HybridCommunicateGroup,
    )

    from . import heter, sharding_rules, spawn  # noqa: F401
    from .sharding_rules import (  # noqa: F401
        apply_sharding_rules, match_sharding_rules)
    from .fleet.dataset import InMemoryDataset, QueueDataset  # noqa: F401

    class ParallelEnv:
        """reference fluid/dygraph/parallel.py ParallelEnv: per-process rank
        view (populated by the launcher's env contract)."""

        def __init__(self):
            from .env import get_rank, get_world_size

            self.rank = get_rank()
            self.world_size = get_world_size()
            self.local_rank = int(__import__("os").environ.get(
                "PADDLE_LOCAL_RANK", self.rank))
            self.nranks = self.world_size
            self.dev_id = self.local_rank

        @property
        def current_endpoint(self):
            import os

            return os.environ.get("PADDLE_CURRENT_ENDPOINT", "")

        @property
        def trainer_endpoints(self):
            import os

            eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
            return eps.split(",") if eps else []

    def wait(tensor, group=None, use_calc_stream=True):
        """reference collective.wait — XLA orders collectives; block for
        parity semantics."""
        import jax

        if hasattr(tensor, "value"):
            jax.block_until_ready(tensor.value)
        return tensor

    class CountFilterEntry:
        """Sparse-table admission policy (reference entry configs for PS
        tables): admit a feature after `count` occurrences."""

        def __init__(self, count=1):
            self.count = int(count)

    class ProbabilityEntry:
        def __init__(self, probability=1.0):
            self.probability = float(probability)

    def get_group(gid=0):
        from .collective import get_group as _g

        return _g(gid)

    def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
              weight_attr=None, bias_attr=None, name=None):
        """reference collective.py:1282 paddle.distributed.split —
        megatron-style sharded fc/embedding via meta_parallel layers."""
        from .meta_parallel import (ColumnParallelLinear, RowParallelLinear,
                                    VocabParallelEmbedding)

        if operation == "linear":
            cls = ColumnParallelLinear if axis == 1 else RowParallelLinear
            layer = cls(size[0], size[1], weight_attr=weight_attr,
                        has_bias=bias_attr is not False)
            return layer(x)
        if operation == "embedding":
            layer = VocabParallelEmbedding(size[0], size[1],
                                           weight_attr=weight_attr)
            return layer(x)
        raise ValueError(operation)
