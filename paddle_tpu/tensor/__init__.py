"""``paddle.tensor`` module surface (reference python/paddle/tensor/).

The reference defines tensor functions in grouped submodules
(math/creation/...) and hoists them to ``paddle.*``; this framework
defines them once in ``tensor_api`` and hoists the same way, so this
package is the inverse mapping — the module-path surface users import
from (``from paddle.tensor.math import add``).  Every public
``tensor_api`` callable is re-exported here, and the grouped submodules
delegate to the same definitions (one source of truth, no per-group
copies to drift).
"""
from __future__ import annotations

from .. import tensor_api as _api

__all__ = list(_api.__all__)

for _n in __all__:
    globals()[_n] = getattr(_api, _n)

from . import (attribute, creation, linalg, logic, manipulation, math,  # noqa: E402,F401
               random, search, stat)

del _n
