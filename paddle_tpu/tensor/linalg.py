"""paddle.tensor.linalg — delegates to the single tensor_api definition set
(reference python/paddle/tensor/linalg.py defines these; here they live once
in tensor_api and this module serves the grouped import path)."""
from __future__ import annotations


def __getattr__(name):
    from .. import tensor_api

    try:
        return getattr(tensor_api, name)
    except AttributeError:
        raise AttributeError(
            f"module 'paddle_tpu.tensor.linalg' has no attribute {name!r}")
