"""Python 2/3 compatibility helpers (reference python/paddle/compat.py).

Kept for API parity: v2.1-era user code imports these for text/bytes
normalization and py2-style arithmetic.  Implementations are py3-native.
"""
from __future__ import annotations

import math as _math

__all__ = []

int_type = int
long_type = int


def _convert(obj, conv, inplace):
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_convert(o, conv, False) for o in obj]
            return obj
        return [_convert(o, conv, False) for o in obj]
    if isinstance(obj, set):
        vals = {_convert(o, conv, False) for o in obj}
        if inplace:
            obj.clear()
            obj.update(vals)
            return obj
        return vals
    return conv(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    """bytes (or containers of bytes) → str; str passes through."""
    def conv(o):
        return o.decode(encoding) if isinstance(o, bytes) else str(o)

    return _convert(obj, conv, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """str (or containers of str) → bytes; bytes passes through."""
    def conv(o):
        return o.encode(encoding) if isinstance(o, str) else bytes(o)

    return _convert(obj, conv, inplace)


def round(x, d=0):  # noqa: A001 - parity name
    """Py2-style half-away-from-zero rounding (py3 rounds half-to-even)."""
    p = 10 ** d
    if x > 0:
        return float(_math.floor((x * p) + 0.5)) / p
    if x < 0:
        return float(_math.ceil((x * p) - 0.5)) / p
    return 0.0


def floor_division(x, y):
    """Py2 ``/`` on ints == py3 ``//``."""
    return x // y


def get_exception_message(exc):
    """The message string of an exception object."""
    return str(exc)
