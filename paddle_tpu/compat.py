"""Python 2/3 compatibility helpers (reference python/paddle/compat.py).

Kept for API parity: v2.1-era user code imports these for text/bytes
normalization and py2-style arithmetic.  Implementations are py3-native.
"""
from __future__ import annotations

import math as _math

__all__ = []

int_type = int
long_type = int


def _convert(obj, conv, inplace):
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_convert(o, conv, False) for o in obj]
            return obj
        return [_convert(o, conv, False) for o in obj]
    if isinstance(obj, set):
        vals = {_convert(o, conv, False) for o in obj}
        if inplace:
            obj.clear()
            obj.update(vals)
            return obj
        return vals
    return conv(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    """bytes (or containers of bytes) → str; str passes through."""
    def conv(o):
        return o.decode(encoding) if isinstance(o, bytes) else str(o)

    return _convert(obj, conv, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """str (or containers of str) → bytes; bytes passes through."""
    def conv(o):
        return o.encode(encoding) if isinstance(o, str) else bytes(o)

    return _convert(obj, conv, inplace)


def round(x, d=0):  # noqa: A001 - parity name
    """Py2-style half-away-from-zero rounding (py3 rounds half-to-even)."""
    p = 10 ** d
    if x > 0:
        return float(_math.floor((x * p) + 0.5)) / p
    if x < 0:
        return float(_math.ceil((x * p) - 0.5)) / p
    return 0.0


def floor_division(x, y):
    """Py2 ``/`` on ints == py3 ``//``."""
    return x // y


def get_exception_message(exc):
    """The message string of an exception object."""
    return str(exc)


# ---------------------------------------------------------------------------
# pinned-toolchain compat (jax): one import site for APIs that moved
# between the jax versions this framework supports
# ---------------------------------------------------------------------------

try:  # jax >= 0.6 promoted shard_map to the public namespace
    from jax import shard_map as _sm
    _LEGACY_SHARD_MAP = False
except ImportError:  # pinned 0.4.x: the experimental module
    from jax.experimental import shard_map as _sm
    _LEGACY_SHARD_MAP = True

# either import may resolve to the module rather than the function
_shard_map_impl = getattr(_sm, "shard_map", _sm)
del _sm

# the replication-check kwarg was renamed check_rep -> check_vma when
# shard_map went public; the repo is written against the new name, so
# translate (both directions) to whatever this jax's signature takes
import functools as _functools
import inspect as _inspect

_SM_PARAMS = frozenset(_inspect.signature(_shard_map_impl).parameters)


@_functools.wraps(_shard_map_impl)
def shard_map(*args, **kwargs):
    if "check_vma" in kwargs and "check_vma" not in _SM_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _SM_PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map_impl(*args, **kwargs)


def axis_size(axis):
    """Concrete size of a named mesh axis from inside a mapped region.

    ``jax.lax.axis_size`` only exists on newer jax; on the pinned 0.4.x
    the equivalent is ``psum(1, axis)``, which constant-folds to a
    Python int for non-tracer inputs — concrete, so callers may use it
    in Python control flow (ring step counts, ppermute tables)."""
    import jax.lax as _lax

    if hasattr(_lax, "axis_size"):
        return _lax.axis_size(axis)
    return _lax.psum(1, axis)


def _patch_legacy_shard_map_transpose():
    """Backport the upstream fix for shard_map's transpose rule on the
    pinned 0.4.x jax.

    Under jit-of-grad with ``check_rep=False``, scalar residuals are
    promoted to shape (1,) (``_promote_scalar_residuals``) so their
    ``{0: axes}`` out-names are valid — but the TRANSPOSE re-runs
    partial eval on the staged jaxpr, which strips the promoted
    singleton, so a nonzero residual cotangent comes out scalar while
    its position's names still claim dim 0, and ``_check_names`` raises
    ``_SpecError`` (the pipeline/MoE grad paths all hit this).  Fixed
    upstream when shard_map left experimental; here the rule is
    re-registered with the one-line repair: re-promote any nonzero
    scalar cotangent whose position carries axis names.  Registration
    failure leaves the stock rule in place (no new breakage on a jax
    whose internals moved)."""
    import math

    import numpy as _np

    import jax
    import jax.experimental.shard_map as _smx
    from jax.tree_util import tree_flatten, tree_unflatten
    from jax._src import core as _core
    from jax._src import dtypes as _dtypes
    from jax._src import linear_util as _lu
    from jax._src.api_util import flatten_fun_nokwargs
    from jax._src.interpreters import ad as _ad
    from jax._src.interpreters import partial_eval as _pe
    from jax._src.util import partition_list

    # resolve every private helper the rule needs NOW: if this jax's
    # shard_map internals use other names, the AttributeError lands here
    # — inside the caller's try, keeping the stock rule — instead of at
    # grad time inside every shard_map transpose
    _unmentioned2 = _smx._unmentioned2
    _shard_aval = _smx._shard_aval
    _unshard_aval = _smx._unshard_aval
    _shard_map_p = _smx.shard_map_p

    def _transpose(out_cts, *args, jaxpr, mesh, in_names, out_names,
                   check_rep, rewrite, auto):
        mb_div = lambda x, y: x / y if y != 1 else x
        out_cts = [
            _ad.Zero(_shard_aval(mesh, ns, x.aval))
            if type(x) is _ad.Zero
            else x if rewrite or _dtypes.dtype(x) == _dtypes.float0
            else mb_div(x, math.prod(map(
                mesh.shape.get, _unmentioned2(mesh, ns, auto))))
            for ns, x in zip(out_names, out_cts)]
        args = [x if type(x) is not _ad.UndefinedPrimal else
                _ad.UndefinedPrimal(_shard_aval(mesh, ns, x.aval))
                for ns, x in zip(in_names, args)]
        all_args, in_tree = tree_flatten((out_cts, args))

        @_lu.wrap_init
        def fun_trans(out_cts, args):
            res, undefs = partition_list(
                list(map(_ad.is_undefined_primal, args)), args)
            jaxpr_known, jaxpr_unknown, _, _ = _pe.partial_eval_jaxpr_nounits(
                _pe.close_jaxpr(jaxpr),
                list(map(_ad.is_undefined_primal, args)), False)
            res_reshaped = _core.jaxpr_as_fun(jaxpr_known)(*res)
            out = _ad.backward_pass(
                jaxpr_unknown.jaxpr, False, (), (*res_reshaped, *undefs),
                out_cts)
            out = [
                _ad.Zero(_unshard_aval(mesh, ns, x.aval))
                if type(x) is _ad.Zero
                else x if rewrite
                else jax.lax.psum(x, tuple(
                    _unmentioned2(mesh, ns, auto)))
                for ns, x in zip(in_names, out)]
            # THE FIX: the re-partial-eval above strips the promoted
            # residual singleton, so a nonzero residual ct can be scalar
            # while its names claim dim 0 — re-promote it (a genuinely
            # scalar input can never carry names, so this is exact)
            out = [jax.lax.broadcast(x, (1,))
                   if (type(x) is not _ad.Zero and ns
                       and _np.ndim(x) == 0) else x
                   for ns, x in zip(in_names, out)]
            return out

        fun_trans, nz_arg_cts = _ad.nonzero_outputs(fun_trans)
        fun_trans_flat, out_tree = flatten_fun_nokwargs(fun_trans, in_tree)
        new_in_names = \
            [n for n, x in zip(out_names, out_cts)
             if type(x) is not _ad.Zero] + \
            [n for n, x in zip(in_names, args)
             if type(x) is not _ad.UndefinedPrimal]

        def new_out_names_thunk():
            return tuple(names for names, nz in zip(in_names, nz_arg_cts())
                         if nz)

        out_flat = _shard_map_p.bind(
            fun_trans_flat, *all_args, mesh=mesh,
            in_names=tuple(new_in_names),
            out_names_thunk=new_out_names_thunk, check_rep=check_rep,
            rewrite=rewrite, auto=auto)
        return tree_unflatten(out_tree(), out_flat)

    _ad.primitive_transposes[_shard_map_p] = _transpose


if _LEGACY_SHARD_MAP:
    try:
        _patch_legacy_shard_map_transpose()
    except Exception:  # noqa: BLE001 - internals moved: keep stock rule
        pass
