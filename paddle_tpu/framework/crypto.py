"""Encrypted model save/load (reference framework/io/crypto/ — AES-CBC via
cryptopp, pybind/crypto.cc, used to ship encrypted inference models).

TPU-native build vendors no crypto library, so the cipher is a documented
stdlib construction: SHA256-CTR keystream XOR (encrypt) with
HMAC-SHA256 encrypt-then-MAC integrity, random 16-byte nonce per file.
This provides the same *capability* (models unreadable without the key,
tamper detection); swap `_keystream` for AES when a vetted library is
available in the deployment image.

File layout: magic(8) | nonce(16) | ciphertext | hmac(32).
"""
from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import struct

_MAGIC = b"PTENC\x00\x01\x00"


def _keystream(key: bytes, nonce: bytes, nbytes: int) -> bytes:
    n_blocks = (nbytes + 31) // 32
    prefix = key + nonce
    return b"".join(
        hashlib.sha256(prefix + struct.pack("<Q", c)).digest()
        for c in range(n_blocks))[:nbytes]


def _xor(data: bytes, ks: bytes) -> bytes:
    import numpy as np

    # vectorized: a 500MB model must not take minutes of per-byte Python
    a = np.frombuffer(data, np.uint8)
    b = np.frombuffer(ks, np.uint8)
    return np.bitwise_xor(a, b).tobytes()


def _norm_key(key: bytes | str) -> bytes:
    if isinstance(key, str):
        key = key.encode()
    return hashlib.sha256(b"paddle_tpu-enc" + key).digest()


def encrypt_bytes(data: bytes, key: bytes | str) -> bytes:
    k = _norm_key(key)
    nonce = os.urandom(16)
    ct = _xor(data, _keystream(k, nonce, len(data)))
    mac = hmac.new(k, _MAGIC + nonce + ct, hashlib.sha256).digest()
    return _MAGIC + nonce + ct + mac


def decrypt_bytes(blob: bytes, key: bytes | str) -> bytes:
    k = _norm_key(key)
    if len(blob) < len(_MAGIC) + 16 + 32 or not blob.startswith(_MAGIC):
        raise ValueError("not a paddle_tpu encrypted blob")
    nonce = blob[len(_MAGIC):len(_MAGIC) + 16]
    ct = blob[len(_MAGIC) + 16:-32]
    mac = blob[-32:]
    want = hmac.new(k, _MAGIC + nonce + ct, hashlib.sha256).digest()
    if not hmac.compare_digest(mac, want):
        raise ValueError("wrong key or tampered file (HMAC mismatch)")
    return _xor(ct, _keystream(k, nonce, len(ct)))


def save_encrypted(obj, path: str, key: bytes | str, protocol: int = 4):
    """paddle.save + encryption (reference paddle.save with cipher).
    Plaintext never touches disk: pickling happens in memory."""
    from .io import _to_numpy_tree

    blob = encrypt_bytes(
        pickle.dumps(_to_numpy_tree(obj), protocol=protocol), key)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(blob)
    return path


def load_encrypted(path: str, key: bytes | str):
    """Decrypt + paddle.load (reference encrypted-model load path);
    decryption and unpickling stay in memory."""
    with open(path, "rb") as f:
        data = decrypt_bytes(f.read(), key)
    return pickle.loads(data)
