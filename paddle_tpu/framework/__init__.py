from . import crypto, errors, monitor, random
from .random import get_rng_state_tracker, seed
