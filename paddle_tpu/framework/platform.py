"""Platform pinning helpers.

The container may pre-register an accelerator PJRT plugin (e.g. the axon TPU
tunnel) in every interpreter, in which case ``JAX_PLATFORMS`` env alone is
ignored once jax resolves backends — the live jax config must be updated
*before the first backend init*.  One shared recipe (used by tests/conftest,
__graft_entry__ and bench) so fixes land in one place.
"""
from __future__ import annotations

import os
import re

_COUNT_FLAG = re.compile(r"--xla_force_host_platform_device_count=(\d+)")

# Per-chip peaks keyed on a ``device_kind`` substring (lowercased match):
# (dense-MXU bf16 peak FLOPs/s, HBM bandwidth bytes/s).  The single source
# of truth for every MFU / roofline computation — bench.py and
# telemetry's device feed both read it, so a headline MFU and the live
# gauge can never disagree about what "peak" means.  There is
# deliberately NO catch-all TPU entry: a chip kind not listed here gets
# (None, None) and MFU reports as null — an honest "unknown" beats a
# fabricated percentage (the old 459e12-for-anything-TPU fallback made
# CPU-fallback numbers look like plausible MFUs).
DEVICE_PEAKS: dict = {
    "v4": (275e12, 1.23e12),
    "v5p": (459e12, 2.77e12),
    "v5 lite": (197e12, 0.82e12),
    "v5e": (197e12, 0.82e12),
    "v6 lite": (918e12, 1.64e12),
    "v6e": (918e12, 1.64e12),
    "v6": (918e12, 1.64e12),
    "trillium": (918e12, 1.64e12),
}


def device_peaks(device_kind: str | None = None,
                 platform: str | None = None) -> tuple:
    """(peak_flops, peak_hbm_bytes_per_s) for a chip kind, resolved by
    substring against :data:`DEVICE_PEAKS`; the ``PALLAS_AXON_TPU_GEN``
    env var stands in when the kind string is empty/unrecognized (the
    tunnel sometimes reports an opaque kind).  Unknown -> (None, None):
    callers must treat MFU as unknowable, not guess.

    ``platform`` (the jax device's ``.platform`` — pass it when you have
    the device) hard-gates the env hint: a non-TPU platform never picks
    up TPU peaks, so a CPU-fallback run with ``PALLAS_AXON_TPU_GEN``
    still exported (the normal tunnel environment) cannot fabricate a
    TPU-peak MFU.  The kind-substring guard below covers callers that
    only have the kind string."""
    plat = (platform or "").lower()
    if plat and plat not in ("tpu", "axon"):
        return (None, None)
    kind = (device_kind or "").lower()
    for k, peaks in DEVICE_PEAKS.items():
        if k in kind:
            return peaks
    if "cpu" in kind or "gpu" in kind:
        return (None, None)
    env_gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    if env_gen:
        for k, peaks in DEVICE_PEAKS.items():
            if k in env_gen:
                return peaks
    return (None, None)


def peak_flops(device_kind: str | None = None,
               platform: str | None = None):
    """bf16 peak FLOPs/s for a chip kind, or None when unknown."""
    return device_peaks(device_kind, platform)[0]

_cache_inited: str | None = None


def init_compile_cache(path: str | None = None) -> str | None:
    """Enable jax's persistent (on-disk) compilation cache — idempotent.

    Serving re-launches recompile the same decode executables from
    scratch; the persistent cache makes re-launch compiles a disk read,
    so warm-start latency and bench numbers stop paying the XLA
    compile.  Resolution order: explicit ``path`` arg >
    ``PADDLE_TPU_COMPILE_CACHE`` env > an already-configured
    ``jax_compilation_cache_dir`` (e.g. JAX_COMPILATION_CACHE_DIR, left
    untouched) > ``~/.cache/paddle_tpu/xla``.  Set
    ``PADDLE_TPU_COMPILE_CACHE=off`` (or 0/none) to disable.  Returns
    the active cache dir, or None when disabled/unavailable — failures
    are never fatal (a read-only HOME must not take down serving)."""
    global _cache_inited
    if _cache_inited is not None and path is None:
        return _cache_inited
    path = path or os.environ.get("PADDLE_TPU_COMPILE_CACHE")
    if path is not None and path.strip().lower() in ("", "0", "off",
                                                     "none", "false"):
        return None
    try:
        import jax

        if path is None:
            configured = jax.config.jax_compilation_cache_dir
            if configured:  # an operator already chose a dir: respect it
                _cache_inited = configured
                return configured
            path = os.path.join(os.path.expanduser("~"), ".cache",
                                "paddle_tpu", "xla")
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # serve small decode-step executables from the cache too — the
        # defaults skip sub-second compiles, which is exactly what a
        # tiny per-bucket prefill looks like
        for knob, v in (("jax_persistent_cache_min_entry_size_bytes", 0),
                        ("jax_persistent_cache_min_compile_time_secs", 0)):
            try:
                jax.config.update(knob, v)
            except Exception:  # noqa: BLE001 - knob absent on this jax
                pass
        _cache_inited = path
        return path
    except Exception:  # noqa: BLE001 - cache is an optimization, never
        # a serving outage
        return None


def force_cpu(n_devices: int = 1):
    """Pin the CPU platform with >= ``n_devices`` virtual devices.

    Must run BEFORE any jax backend initializes; raises if a non-CPU backend
    already won or the virtual-device flag landed too late.  Returns the
    first ``n_devices`` devices.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = _COUNT_FLAG.search(flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}")
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = _COUNT_FLAG.sub(
            f"--xla_force_host_platform_device_count={n_devices}", flags)
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    if devs[0].platform != "cpu":
        raise RuntimeError(
            f"need the CPU platform but got {devs[0].platform!r}; a non-CPU "
            f"backend was already initialized before force_cpu() was called")
    if len(devs) < n_devices:
        raise RuntimeError(
            f"need {n_devices} virtual CPU devices, have {len(devs)}; "
            f"XLA_FLAGS was set too late (backend already initialized). Set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices} "
            f"before importing jax")
    return devs[:n_devices]
