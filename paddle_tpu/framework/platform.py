"""Platform pinning helpers.

The container may pre-register an accelerator PJRT plugin (e.g. the axon TPU
tunnel) in every interpreter, in which case ``JAX_PLATFORMS`` env alone is
ignored once jax resolves backends — the live jax config must be updated
*before the first backend init*.  One shared recipe (used by tests/conftest,
__graft_entry__ and bench) so fixes land in one place.
"""
from __future__ import annotations

import os
import re

_COUNT_FLAG = re.compile(r"--xla_force_host_platform_device_count=(\d+)")


def force_cpu(n_devices: int = 1):
    """Pin the CPU platform with >= ``n_devices`` virtual devices.

    Must run BEFORE any jax backend initializes; raises if a non-CPU backend
    already won or the virtual-device flag landed too late.  Returns the
    first ``n_devices`` devices.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = _COUNT_FLAG.search(flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}")
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = _COUNT_FLAG.sub(
            f"--xla_force_host_platform_device_count={n_devices}", flags)
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    if devs[0].platform != "cpu":
        raise RuntimeError(
            f"need the CPU platform but got {devs[0].platform!r}; a non-CPU "
            f"backend was already initialized before force_cpu() was called")
    if len(devs) < n_devices:
        raise RuntimeError(
            f"need {n_devices} virtual CPU devices, have {len(devs)}; "
            f"XLA_FLAGS was set too late (backend already initialized). Set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices} "
            f"before importing jax")
    return devs[:n_devices]
