"""Checkpoint save/load.

Reference: python/paddle/framework/io.py:565 paddle.save / :781 paddle.load
(pickle-based nested state_dict).  Same wire format here (pickled dict of
numpy arrays) so checkpoints are host-portable; sharded/distributed
checkpoint of pjit arrays lives in distributed.fleet.checkpoint (per-host
shard files, reference auto_checkpoint analog).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def _to_numpy_tree(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj.value)
    if isinstance(obj, dict):
        return {k: _to_numpy_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy_tree(v) for v in obj)
    if hasattr(obj, "dtype") and hasattr(obj, "shape"):  # jax array
        return np.asarray(obj)
    return obj


def save(obj, path, protocol=4):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_numpy_tree(obj), f, protocol=protocol)


def load(path, **kwargs):
    with open(path, "rb") as f:
        return pickle.load(f)
