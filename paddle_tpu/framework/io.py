"""Checkpoint save/load.

Reference: python/paddle/framework/io.py:565 paddle.save / :781 paddle.load
(pickle-based nested state_dict).  Same wire format here (pickled dict of
numpy arrays) so checkpoints are host-portable; sharded/distributed
checkpoint of pjit arrays lives in distributed.fleet.checkpoint (per-host
shard files, reference auto_checkpoint analog).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def _to_numpy_tree(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj.value)
    if isinstance(obj, dict):
        return {k: _to_numpy_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy_tree(v) for v in obj)
    if hasattr(obj, "dtype") and hasattr(obj, "shape"):  # jax array
        return np.asarray(obj)
    return obj


def save(obj, path, protocol=4):
    """Atomic checkpoint write: the tree is pickled to a sibling temp
    file, fsync'd, and os.replace'd over ``path`` — a crash (or full
    disk) mid-save can never corrupt the last good checkpoint, because
    ``path`` only ever transitions between complete states.  One retry
    on a transient I/O error (resilience layer; fail-fast with
    ``PADDLE_TPU_RESILIENCE=0``)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tree = _to_numpy_tree(obj)

    def _write():
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(tree, f, protocol=protocol)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            # never leave a torn temp file beside the checkpoint
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    from .. import resilience as _resilience

    _resilience.retry(_write, name="checkpoint.save", attempts=2,
                      base=0.1, jitter=0.0, retry_on=OSError)


def load(path, **kwargs):
    with open(path, "rb") as f:
        return pickle.load(f)
