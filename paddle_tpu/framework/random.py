"""Global RNG state.

Reference capability: paddle.seed / Generator
(/root/reference/python/paddle/framework/random.py, fluid/framework.py default
program random_seed) plus per-mp-rank seed control
(distributed/fleet/meta_parallel/parallel_layers/random.py).

TPU-first: JAX threads explicit PRNG keys.  Eagerly we keep a global splitting
key (dygraph convenience); jitted code paths install a *traced* key via
``rng_scope`` so random ops inside jit stay functional.  ``RNGStatesTracker``
provides named streams whose seeds are offset per model-parallel rank so
dropout masks are identical-or-independent across TP shards as required.
"""
from __future__ import annotations

import contextlib
import threading

import jax
from jax.core import Tracer


class _RNGState(threading.local):
    """Global key is LAZY: ``import paddle_tpu`` must never initialize a jax
    backend (creating a PRNGKey at import time forces platform selection
    before the caller can pin it — see tests/conftest.py)."""

    def __init__(self):
        self.key = None  # materialized on first use
        self.override = None  # traced key stack for jitted paths
        self.trace_calls = 0  # distinct-key counter under foreign traces

    def get_key(self):
        if self.key is None:
            self.key = jax.random.PRNGKey(0)
        return self.key


_state = _RNGState()


def seed(s: int):
    _state.key = jax.random.PRNGKey(int(s))
    return _state.key


def next_key(n: int = 1):
    """Split a fresh key off the active stream (override-aware)."""
    if _state.override is not None:
        tracker = _state.override
        return tracker.next(n)
    key = _state.get_key()
    new_key, *sub = jax.random.split(key, n + 1)
    if isinstance(new_key, Tracer):
        # Under a FOREIGN trace (ONNX export / make_jaxpr over a
        # StaticFunction — jitted paddle paths install ``rng_scope``
        # instead and never reach here): storing the traced key would let
        # the tracer escape and poison every later RNG use, but NOT
        # advancing at all would hand every call site the same key,
        # silently correlating e.g. all dropout masks.  A Python-side
        # counter folds a distinct stream per call site into the frozen
        # key; the concrete global stream stays untouched.
        _state.trace_calls += 1
        sub = list(jax.random.split(
            jax.random.fold_in(key, _state.trace_calls), n))
    else:
        _state.key = new_key
    return sub[0] if n == 1 else list(sub)


class _TracedKeyStream:
    """Deterministic stream of keys derived from one traced root key."""

    def __init__(self, root_key):
        self.key = root_key

    def next(self, n: int = 1):
        self.key, *sub = jax.random.split(self.key, n + 1)
        return sub[0] if n == 1 else list(sub)


@contextlib.contextmanager
def rng_scope(key):
    """Route next_key() to a traced key — used by jitted train steps so that
    dropout etc. remain pure functions of an input key."""
    prev = _state.override
    _state.override = _TracedKeyStream(key)
    try:
        yield
    finally:
        _state.override = prev


class RNGStatesTracker:
    """Named RNG streams (reference parallel_layers/random.py RNGStatesTracker):
    'global' stream shared across TP ranks, 'local' stream offset by mp rank so
    per-shard dropout is independent."""

    def __init__(self):
        self.states = {}

    def add(self, name: str, s: int):
        self.states[name] = jax.random.PRNGKey(int(s))

    @contextlib.contextmanager
    def rng_state(self, name: str):
        if name not in self.states:
            raise ValueError(f"RNG state {name!r} not registered")
        prev_key = _state.key
        _state.key = self.states[name]
        try:
            yield
        finally:
            self.states[name] = _state.key
            _state.key = prev_key


_MODEL_PARALLEL_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _MODEL_PARALLEL_TRACKER


def model_parallel_random_seed(base_seed: int, mp_rank: int = 0):
    """Reference meta_parallel random.py: global seed same across mp ranks,
    local seed offset per rank."""
    seed(base_seed)
    _MODEL_PARALLEL_TRACKER.states.clear()
    _MODEL_PARALLEL_TRACKER.add("global_seed", base_seed)
    _MODEL_PARALLEL_TRACKER.add("local_seed", base_seed + 1024 + mp_rank)
