"""Typed error system (reference platform/enforce.h:427 PADDLE_ENFORCE* +
error_codes.proto — LEGACY/INVALID_ARGUMENT/NOT_FOUND/OUT_OF_RANGE/
ALREADY_EXISTS/.../UNAVAILABLE typed exceptions with enriched messages).

TPU-first: plain Python exception classes carrying an error code, plus
``enforce``/``enforce_eq``/``enforce_shape`` helpers that build the
reference-style message (expected vs actual, caller hint) without the C++
stack machinery — the Python traceback IS the stack."""
from __future__ import annotations

from typing import Any

__all__ = ["EnforceNotMet", "InvalidArgumentError", "NotFoundError",
           "OutOfRangeError", "AlreadyExistsError", "PermissionDeniedError",
           "UnimplementedError", "UnavailableError", "ResourceExhaustedError",
           "PreconditionNotMetError", "ExecutionTimeoutError", "FatalError",
           "enforce", "enforce_eq", "enforce_gt", "enforce_shape"]


class EnforceNotMet(RuntimeError):
    """Base of all typed framework errors (enforce.h EnforceNotMet)."""

    code = "LEGACY"

    def __init__(self, msg: str, hint: str = ""):
        self.hint = hint
        full = f"[{self.code}] {msg}"
        if hint:
            full += f"\n  [Hint: {hint}]"
        super().__init__(full)


class InvalidArgumentError(EnforceNotMet):
    code = "INVALID_ARGUMENT"


class NotFoundError(EnforceNotMet):
    code = "NOT_FOUND"


class OutOfRangeError(EnforceNotMet):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(EnforceNotMet):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(EnforceNotMet):
    code = "RESOURCE_EXHAUSTED"


class PreconditionNotMetError(EnforceNotMet):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(EnforceNotMet):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(EnforceNotMet):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(EnforceNotMet):
    code = "UNIMPLEMENTED"


class UnavailableError(EnforceNotMet):
    code = "UNAVAILABLE"


class FatalError(EnforceNotMet):
    code = "FATAL"


def enforce(cond: Any, msg: str, exc: type = InvalidArgumentError,
            hint: str = ""):
    """PADDLE_ENFORCE analog: raise ``exc`` with an enriched message when
    ``cond`` is falsy."""
    if not cond:
        raise exc(msg, hint)


def enforce_eq(a, b, what: str = "value", exc: type = InvalidArgumentError):
    """PADDLE_ENFORCE_EQ analog with expected-vs-actual in the message."""
    if a != b:
        raise exc(f"{what} mismatch: expected {b!r}, got {a!r}")


def enforce_gt(a, b, what: str = "value", exc: type = InvalidArgumentError):
    if not a > b:
        raise exc(f"{what} must be > {b!r}, got {a!r}")


def enforce_shape(x, shape, what: str = "tensor",
                  exc: type = InvalidArgumentError):
    """Shape check tolerating None wildcards in ``shape``."""
    import numpy as np

    actual = tuple(np.shape(x))
    if len(actual) != len(shape) or any(
            s is not None and s != a for s, a in zip(shape, actual)):
        raise exc(f"{what} shape mismatch: expected "
                  f"{tuple(shape)!r}, got {actual!r}")
