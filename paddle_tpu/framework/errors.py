"""Typed error system (reference platform/enforce.h:427 PADDLE_ENFORCE* +
error_codes.proto — LEGACY/INVALID_ARGUMENT/NOT_FOUND/OUT_OF_RANGE/
ALREADY_EXISTS/.../UNAVAILABLE typed exceptions with enriched messages).

TPU-first: plain Python exception classes carrying an error code, plus
``enforce``/``enforce_eq``/``enforce_shape`` helpers that build the
reference-style message (expected vs actual, caller hint) without the C++
stack machinery — the Python traceback IS the stack."""
from __future__ import annotations

from typing import Any

__all__ = ["EnforceNotMet", "InvalidArgumentError", "NotFoundError", "check_shape",
           "OutOfRangeError", "AlreadyExistsError", "PermissionDeniedError",
           "UnimplementedError", "UnavailableError", "ResourceExhaustedError",
           "PreconditionNotMetError", "ExecutionTimeoutError", "FatalError",
           "enforce", "enforce_eq", "enforce_gt", "enforce_shape"]


class EnforceNotMet(RuntimeError):
    """Base of all typed framework errors (enforce.h EnforceNotMet)."""

    code = "LEGACY"

    def __init__(self, msg: str, hint: str = ""):
        self.hint = hint
        full = f"[{self.code}] {msg}"
        if hint:
            full += f"\n  [Hint: {hint}]"
        super().__init__(full)


class InvalidArgumentError(EnforceNotMet):
    code = "INVALID_ARGUMENT"


class NotFoundError(EnforceNotMet):
    code = "NOT_FOUND"


class OutOfRangeError(EnforceNotMet):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(EnforceNotMet):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(EnforceNotMet):
    code = "RESOURCE_EXHAUSTED"


class PreconditionNotMetError(EnforceNotMet):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(EnforceNotMet):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(EnforceNotMet):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(EnforceNotMet):
    code = "UNIMPLEMENTED"


class UnavailableError(EnforceNotMet):
    code = "UNAVAILABLE"


class FatalError(EnforceNotMet):
    code = "FATAL"


def enforce(cond: Any, msg: str, exc: type = InvalidArgumentError,
            hint: str = ""):
    """PADDLE_ENFORCE analog: raise ``exc`` with an enriched message when
    ``cond`` is falsy."""
    if not cond:
        raise exc(msg, hint)


def enforce_eq(a, b, what: str = "value", exc: type = InvalidArgumentError):
    """PADDLE_ENFORCE_EQ analog with expected-vs-actual in the message."""
    if a != b:
        raise exc(f"{what} mismatch: expected {b!r}, got {a!r}")


def enforce_gt(a, b, what: str = "value", exc: type = InvalidArgumentError):
    if not a > b:
        raise exc(f"{what} must be > {b!r}, got {a!r}")


def enforce_shape(x, shape, what: str = "tensor",
                  exc: type = InvalidArgumentError):
    """Shape check tolerating None wildcards in ``shape``."""
    import numpy as np

    actual = tuple(np.shape(x))
    if len(actual) != len(shape) or any(
            s is not None and s != a for s, a in zip(shape, actual)):
        raise exc(f"{what} shape mismatch: expected "
                  f"{tuple(shape)!r}, got {actual!r}")


def check_shape(shape, op_name: str = "op"):
    """Validate a shape ARGUMENT before an op consumes it (reference
    data_feeder.py:142 check_shape, exported as paddle.check_shape): a
    list/tuple of python ints (or int arrays/Tensors for runtime dims),
    or a 1-D integer Tensor.  Raises TypeError with the op name."""
    from ..core.tensor import Tensor

    def _is_int_tensor(v):
        import numpy as np

        arr = v.value if isinstance(v, Tensor) else v
        # read the dtype attribute directly: np.asarray would materialize
        # the value (device->host copy, and a crash on jax tracers — the
        # reference skips this check under tracing for the same reason)
        return hasattr(arr, "dtype") and np.issubdtype(arr.dtype,
                                                       np.integer)

    if isinstance(shape, Tensor) or hasattr(shape, "dtype"):
        if not _is_int_tensor(shape):
            raise TypeError(
                f"The data type of 'shape' in {op_name} must be int32 or "
                f"int64 when shape is a Tensor")
        return
    if not isinstance(shape, (list, tuple)):
        raise TypeError(
            f"The type of 'shape' in {op_name} must be list, tuple or "
            f"Tensor, but received {type(shape).__name__}")
    for item in shape:
        if isinstance(item, bool) or not (
                isinstance(item, int) or _is_int_tensor(item)):
            raise TypeError(
                f"The type of element of 'shape' in {op_name} must be int "
                f"or integer Tensor, but received {type(item).__name__}")
