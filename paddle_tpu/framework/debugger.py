"""Numerical debugging: nan/inf detection over pytrees and train steps.

Reference capability: FLAGS_check_nan_inf (platform/flags.cc:44) →
``CheckVarHasNanOrInf`` scanning every kernel output
(framework/details/nan_inf_utils_detail.cc:299 + .cu kernel).

TPU-native: two tiers —
  * compile-time trap: ``paddle.set_flags({'FLAGS_check_nan_inf': True})``
    flips XLA's jax_debug_nans (every jitted computation re-runs un-jitted on
    a nan and raises at the offending primitive — the per-kernel scan role);
  * host-side step scan: ``find_nan_inf(tree)`` / ``assert_finite(tree)``
    check materialized outputs (loss/grads/params) with named leaf paths for
    actionable errors, used by train loops when FLAGS_check_nan_inf_host.
"""
from __future__ import annotations

from typing import Any

import numpy as np


def find_nan_inf(tree: Any) -> list:
    """Returns [(leaf_path, n_nan, n_inf), ...] for non-finite leaves."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    bad = []
    for path, leaf in flat:
        if leaf is None or not hasattr(leaf, "dtype"):
            continue
        if not np.issubdtype(np.asarray(leaf).dtype, np.floating):
            continue
        a = np.asarray(leaf)
        n_nan = int(np.isnan(a).sum())
        n_inf = int(np.isinf(a).sum())
        if n_nan or n_inf:
            bad.append((jax.tree_util.keystr(path), n_nan, n_inf))
    return bad


def assert_finite(tree: Any, msg: str = "tensor"):
    bad = find_nan_inf(tree)
    if bad:
        detail = ", ".join(f"{p} (nan={n}, inf={i})" for p, n, i in bad[:8])
        more = f" … and {len(bad) - 8} more" if len(bad) > 8 else ""
        raise FloatingPointError(
            f"nan/inf detected in {msg}: {detail}{more}")


def check_numerics_enabled() -> bool:
    from .. import flags

    return bool(flags.flag("FLAGS_check_nan_inf_host"))
