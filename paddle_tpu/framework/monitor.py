"""Global runtime counters (reference platform/monitor.h:44 —
``StatValue``/``StatRegistry``, the GPU mem/usage counters surfaced by
``paddle.fluid.core.get_mem_usage`` style getters).

TPU-first: a thread-safe process-local registry; device-side numbers come
from PJRT (``jax.local_devices()[i].memory_stats()``) and are snapshotted
into the same registry so one ``stats()`` call observes both.  The
telemetry layer (:mod:`paddle_tpu.telemetry`) routes its counters and
histogram count/sum mirrors through this registry too — float stats
(``as_float=True``) carry latency sums; existing counters keep the
reference's int64 semantics."""
from __future__ import annotations

import threading
import time
from typing import Iterator

__all__ = ["StatValue", "StatRegistry", "get_stat", "stats", "reset_all",
           "snapshot_device_stats"]


class StatValue:
    """One named monotonic-ish counter.  Int64 semantics by default like
    the reference's StatValue (add/sub/reset/get truncate to int);
    ``as_float=True`` makes it a float accumulator (latency sums) — the
    cast is fixed at creation, so existing int counters are unchanged."""

    def __init__(self, name: str, as_float: bool = False):
        self.name = name
        self.is_float = bool(as_float)
        self._cast = float if as_float else int
        self._v = self._cast(0)
        self._lock = threading.Lock()

    def add(self, n=1):
        with self._lock:
            self._v += self._cast(n)
            return self._v

    def sub(self, n=1):
        return self.add(-n)

    def set(self, n) -> None:
        with self._lock:
            self._v = self._cast(n)

    def get(self):
        with self._lock:
            return self._v

    def reset(self) -> None:
        self.set(0)


class StatRegistry:
    """Singleton name→StatValue table (monitor.h StatRegistry)."""

    _inst: "StatRegistry | None" = None
    _inst_lock = threading.Lock()

    def __init__(self):
        self._stats: dict[str, StatValue] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "StatRegistry":
        with cls._inst_lock:
            if cls._inst is None:
                cls._inst = cls()
            return cls._inst

    def get(self, name: str, as_float: bool = False) -> StatValue:
        with self._lock:
            if name not in self._stats:
                self._stats[name] = StatValue(name, as_float=as_float)
            return self._stats[name]

    def __iter__(self) -> Iterator[StatValue]:
        with self._lock:
            return iter(list(self._stats.values()))

    def dict(self) -> dict:
        return {s.name: s.get() for s in self}

    def reset_all(self) -> None:
        for s in self:
            s.reset()


def get_stat(name: str, as_float: bool = False, **labels) -> StatValue:
    """Registry accessor; ``labels`` build a Prometheus-style namespaced
    name — ``get_stat("serving.ttft_ms", slot=3)`` →
    ``serving.ttft_ms{slot="3"}`` — so per-entity series live beside the
    bare aggregate without a separate label store.  The first ``get``
    fixes a stat's int/float semantics."""
    if labels:
        def esc(v):  # Prometheus exposition escaping for label values
            return str(v).replace("\\", r"\\").replace('"', r'\"') \
                .replace("\n", r"\n")

        name = name + "{" + ",".join(
            f'{k}="{esc(labels[k])}"' for k in sorted(labels)) + "}"
    return StatRegistry.instance().get(name, as_float=as_float)


def stats() -> dict:
    return StatRegistry.instance().dict()


def reset_all() -> None:
    StatRegistry.instance().reset_all()


def snapshot_device_stats(devices=None) -> dict[str, int]:
    """Fold PJRT per-device memory stats into the registry
    (STAT_gpuN_mem analog: stat 'device{i}_bytes_in_use' etc.).

    ``devices`` overrides the sampled device list (anything with a
    ``memory_stats()`` method — tests inject fakes; None = every local
    jax device).  Backends without memory stats (CPU) contribute
    nothing — the return is {} and no stat is written."""
    if devices is None:
        import jax

        devices = jax.local_devices()
    out = {}
    for i, d in enumerate(devices):
        ms = d.memory_stats() or {}
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if k in ms:
                name = f"device{i}_{k}"
                get_stat(name).set(ms[k])
                out[name] = ms[k]
    get_stat("device_stats_snapshot_time_ns").set(time.time_ns())
    return out
