"""Sharded (distributed) checkpoint save/resume for pjit train states.

Reference capability: sharding-aware persistence — fleet save_persistables
(fleet_base.py:732), dist_sharding_save.py test, and the transparent
epoch-granular **auto-checkpoint** (fluid/incubate/checkpoint/
auto_checkpoint.py — AutoCheckpointChecker :71, env-driven job dir).

TPU-native format: every leaf of the train-state pytree is a (possibly
sharded) jax.Array.  Each host writes only the shards it owns (replica 0 of
each chunk) as .npy chunk files + a JSON manifest holding the tree structure,
global shapes and chunk index.  Loading rebuilds arrays with
``jax.make_array_from_callback`` against ANY target sharding/mesh — chunks
are read via numpy mmap so resharding (e.g. resuming 8-way ZeRO on 4 chips)
only touches the bytes each device needs.

Layout:  <dir>/step_<N>/manifest.json
         <dir>/step_<N>/<leaf-path>.c<chunk>.npy
"""
from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Callable

import numpy as np


def _flatten(tree):
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names = [re.sub(r"[^A-Za-z0-9_.-]+", "_", jax.tree_util.keystr(p)).strip("_")
             for p, _ in flat]
    return names, [v for _, v in flat], treedef


def _chunk_id(index, shape) -> str:
    starts = [(s.start or 0) for s in index] if index else []
    return "-".join(str(s) for s in starts) or "0"


def save_sharded(tree: Any, ckpt_dir: str, step: int):
    """Write one checkpoint; atomic via tmp-dir rename.  Multi-host: every
    process writes its own chunks; call on all hosts."""
    import jax

    names, leaves, _ = _flatten(tree)
    final = os.path.join(ckpt_dir, f"step_{step}")
    pid = jax.process_index()
    tmp = final + f".tmp{pid}" if jax.process_count() > 1 else final + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    manifest = {"step": step, "leaves": {}}
    for name, leaf in zip(names, leaves):
        arr = leaf
        if not hasattr(arr, "addressable_shards"):
            arr = np.asarray(arr)
            np.save(os.path.join(tmp, f"{name}.c0.npy"), arr)
            manifest["leaves"][name] = {
                "shape": list(np.shape(arr)),
                "dtype": np.asarray(arr).dtype.name,
                "chunks": {"0": {"starts": [0] * np.ndim(arr),
                                 "shape": list(np.shape(arr))}},
            }
            continue
        meta = {"shape": list(arr.shape), "dtype": np.dtype(arr.dtype).name,
                "chunks": {}}
        for sh in arr.addressable_shards:
            if sh.replica_id != 0:
                continue
            idx = sh.index
            starts = [s.start or 0 for s in idx] if idx else []
            cid = _chunk_id(idx, arr.shape)
            data = np.asarray(sh.data)
            np.save(os.path.join(tmp, f"{name}.c{cid}.npy"), data)
            meta["chunks"][cid] = {"starts": starts or [0] * data.ndim,
                                   "shape": list(data.shape)}
        manifest["leaves"][name] = meta
    with open(os.path.join(tmp, f"manifest.{pid}.json"), "w") as f:
        json.dump(manifest, f)
    if jax.process_count() == 1:
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
    else:  # multi-host: merge under coordination (process 0 finalizes)
        # every process wrote to its own tmp dir; process 0 merges after a
        # barrier provided by the caller (fleet/kvstore) — here best-effort
        os.makedirs(final, exist_ok=True)
        for fn in os.listdir(tmp):
            os.replace(os.path.join(tmp, fn), os.path.join(final, fn))
        os.rmdir(tmp)
        if jax.process_index() == 0:
            with open(os.path.join(final, "manifest.json"), "w") as f:
                json.dump(manifest, f)
    return final


_STEP_DIR = re.compile(r"step_(\d+)")


def available_steps(ckpt_dir: str) -> list[int]:
    """All checkpoint steps with a finalized manifest, ascending.  A step
    listed here may still be TORN (a peer crashed between writing its
    chunks and the finalizer's manifest — the multi-host save is
    best-effort): loaders that must survive crashes walk this list newest
    → oldest (AutoCheckpoint.resume)."""
    if not os.path.isdir(ckpt_dir):
        return []
    # strict match: transient multi-host 'step_N.tmpP' dirs must not parse
    return sorted(int(m.group(1)) for d in os.listdir(ckpt_dir)
                  if (m := _STEP_DIR.fullmatch(d))
                  and os.path.exists(os.path.join(ckpt_dir, d,
                                                  "manifest.json")))


def latest_step(ckpt_dir: str) -> int | None:
    steps = available_steps(ckpt_dir)
    return steps[-1] if steps else None


def _merged_manifest(d: str) -> dict:
    with open(os.path.join(d, "manifest.json")) as f:
        manifest = json.load(f)
    for fn in os.listdir(d):
        if fn.startswith("manifest.") and fn != "manifest.json":
            with open(os.path.join(d, fn)) as f:
                part = json.load(f)
            for name, meta in part["leaves"].items():
                manifest["leaves"].setdefault(name, meta)
                manifest["leaves"][name]["chunks"].update(meta["chunks"])
    return manifest


def _verified_manifest(d: str):
    """Merged manifest if the step directory is globally complete, else
    None (see verify_step)."""
    try:
        manifest = _merged_manifest(d)
    except (OSError, json.JSONDecodeError):
        return None
    for name, meta in manifest["leaves"].items():
        total = int(np.prod(meta["shape"])) if meta["shape"] else 1
        got = 0
        for cid, cm in meta["chunks"].items():
            if not os.path.exists(os.path.join(d, f"{name}.c{cid}.npy")):
                return None
            got += int(np.prod(cm["shape"])) if cm["shape"] else 1
        if got != total:
            return None
    return manifest


def verify_step(ckpt_dir: str, step: int) -> bool:
    """GLOBAL completeness check of one checkpoint, independent of this
    host's shardings — every host computes the same verdict from the same
    files, so multi-host resume agrees on the step (per-host hole checks
    would let ranks resume from DIFFERENT steps after a torn save).

    Sound for this module's save format: chunks are the disjoint
    replica-0 shard blocks, so full coverage == every listed chunk file
    present and the element counts summing to the leaf's size."""
    return _verified_manifest(
        os.path.join(ckpt_dir, f"step_{step}")) is not None


def load_sharded(ckpt_dir: str, step: int, target: Any, manifest=None):
    """Rebuild the checkpoint into ``target``'s tree structure + shardings.

    target: pytree of jax.Arrays (a freshly-initialized state) OR of
    (ShapeDtypeStruct-with-sharding); each leaf's sharding decides which
    bytes this host reads.  ``manifest``: a pre-merged manifest (callers
    that just verified the step pass it to avoid re-parsing)."""
    import jax

    d = os.path.join(ckpt_dir, f"step_{step}")
    if manifest is None:
        # multi-host saves: union every per-process manifest's chunk lists
        # so a loader sees ALL shards, not just the finalizing process's own
        manifest = _merged_manifest(d)
    names, leaves, treedef = _flatten(target)
    out = []
    for name, leaf in zip(names, leaves):
        meta = manifest["leaves"][name]
        shape = tuple(meta["shape"])
        dtype = np.dtype(meta["dtype"])
        chunks = []
        for cid, cm in meta["chunks"].items():
            path = os.path.join(d, f"{name}.c{cid}.npy")
            chunks.append((tuple(cm["starts"]), tuple(cm["shape"]), path))

        def read_slice(index, *, _chunks=chunks, _shape=shape, _dtype=dtype,
                       _name=name):
            # requested global slice -> assemble from overlapping chunks
            req_start = [(s.start or 0) for s in index] if index else []
            req_stop = [s.stop if s.stop is not None else dim
                        for s, dim in zip(index, _shape)] if index else []
            if not req_start:
                req_start, req_stop = [0] * len(_shape), list(_shape)
            req_size = 1
            for a, b in zip(req_start, req_stop):
                req_size *= b - a
            out_arr = np.empty([b - a for a, b in zip(req_start, req_stop)],
                               _dtype)
            covered = np.zeros(out_arr.shape, bool) if req_size else None
            for cstart, cshape, path in _chunks:
                cstop = [a + b for a, b in zip(cstart, cshape)]
                inter_a = [max(a, ca) for a, ca in zip(req_start, cstart)]
                inter_b = [min(b, cb) for b, cb in zip(req_stop, cstop)]
                if any(a >= b for a, b in zip(inter_a, inter_b)):
                    continue
                try:
                    src = np.load(path, mmap_mode="r")
                except OSError:
                    continue  # listed but unreadable -> counts as a hole
                src_sl = tuple(slice(a - ca, b - ca)
                               for a, b, ca in zip(inter_a, inter_b, cstart))
                dst_sl = tuple(slice(a - ra, b - ra)
                               for a, b, ra in zip(inter_a, inter_b, req_start))
                out_arr[dst_sl] = src[src_sl]
                covered[dst_sl] = True
            if covered is not None and not covered.all():
                # a hole means an incomplete/unbarriered save — corrupt
                # resume silently would be worse than failing here
                missing = int(req_size - covered.sum())
                raise ValueError(
                    f"checkpoint leaf {_name!r}: chunks cover only "
                    f"{req_size - missing}/{req_size} elements of the "
                    f"requested slice (incomplete multi-host save or missing "
                    f"chunk files in {d!r})")
            return out_arr

        sharding = getattr(leaf, "sharding", None)
        if sharding is None:
            out.append(read_slice(tuple(slice(0, s) for s in shape)))
        else:
            out.append(jax.make_array_from_callback(shape, sharding,
                                                    read_slice))
    return treedef.unflatten(out)


class AutoCheckpoint:
    """Transparent periodic checkpoint + resume (auto_checkpoint.py analog).

    Env-driven like the reference (job dir via PADDLE_TPU_CKPT_DIR), keeps
    the newest ``keep_max`` checkpoints, resumes from the latest on start.
    """

    def __init__(self, ckpt_dir: str | None = None, every_steps: int = 100,
                 keep_max: int = 2):
        self.dir = ckpt_dir or os.environ.get("PADDLE_TPU_CKPT_DIR", ".ckpt")
        self.every = every_steps
        self.keep_max = keep_max

    def resume(self, target):
        """Returns (state, step): the newest LOADABLE checkpoint restored
        into target's shardings, or (target, 0) if none exists.  Torn
        checkpoints — a rank crashed after the manifest was finalized but
        before its own chunks landed — are skipped in favor of the next
        older one (the reference auto_checkpoint's crash-resume
        guarantee)."""
        import json as _json
        import warnings

        for s in reversed(available_steps(self.dir)):
            # GLOBAL completeness first: every host reads the same files
            # and skips the same torn steps, so multi-host resume agrees
            # on the step — a per-host hole check would let ranks resume
            # from different steps and deadlock the first collective
            manifest = _verified_manifest(
                os.path.join(self.dir, f"step_{s}"))
            if manifest is None:
                warnings.warn(
                    f"checkpoint step_{s} in {self.dir} is torn "
                    f"(missing chunks); falling back to an older one")
                continue
            try:
                return load_sharded(self.dir, s, target,
                                    manifest=manifest), s
            except (OSError, _json.JSONDecodeError) as e:
                torn = e  # raced away under our feet mid-read
            except ValueError as e:
                if "chunks cover only" not in str(e):
                    raise  # structural/shape mismatch: a real error, not a
                    # torn snapshot — silently discarding checkpoints here
                    # would lose data
                torn = e
            warnings.warn(
                f"checkpoint step_{s} in {self.dir} is torn "
                f"({torn!r}); falling back to an older one")
        return target, 0

    def maybe_save(self, state, step: int):
        if step % self.every:
            return False
        save_sharded(state, self.dir, step)
        self._gc()
        return True

    def _gc(self):
        steps = sorted(
            int(m.group(1)) for d in os.listdir(self.dir)
            if (m := _STEP_DIR.fullmatch(d)))
        for s in steps[: -self.keep_max]:
            shutil.rmtree(os.path.join(self.dir, f"step_{s}"),
                          ignore_errors=True)
