"""Functional tensor API + Tensor method attachment.

Reference capability: python/paddle/tensor/{math,manipulation,creation,linalg,
logic,random,search,stat}.py (each op there has a dygraph fast path through
generated ``core.ops.*`` bindings — pybind/op_function_generator.cc:518 — and
a static ``append_op`` path).  TPU-first: ONE implementation per op — a pure
jax function dispatched through the tape (core/dispatch.py).  The same code
both executes eagerly and traces under jit, which is the whole
dygraph/to_static duality collapsed into a single path.

Every public function is also attached as a Tensor method at import time.
"""
from __future__ import annotations

import builtins
import functools

import jax
import jax.numpy as jnp
import numpy as np

from .core.dispatch import dispatch
from .core.dtype import convert_dtype, get_default_dtype
from .core.place import current_jax_device
from .core.static_mode import static_aware
from .core.tensor import Tensor, to_tensor
from .framework import random as _random

__all__: list = []


_NEVER_RECORD = {"is_tensor", "to_tensor"}  # python-level predicates


def _public(fn):
    __all__.append(fn.__name__)
    if fn.__name__ in _NEVER_RECORD:
        return fn
    # static-graph duality: while a Program records (paddle.static), calls
    # with Variable args append to the program instead of executing
    return static_aware(fn)


def _v(x):
    return x.value if isinstance(x, Tensor) else x


def _axes(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------


def _place_new(arr):
    return Tensor(jax.device_put(arr, current_jax_device()))


@_public
def zeros(shape, dtype=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return _place_new(jnp.zeros(_shape_list(shape), d))


@_public
def ones(shape, dtype=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return _place_new(jnp.ones(_shape_list(shape), d))


@_public
def full(shape, fill_value, dtype=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return _place_new(jnp.full(_shape_list(shape), fill_value, d))


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.tolist())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


@_public
def zeros_like(x, dtype=None):
    d = convert_dtype(dtype)
    return Tensor(jnp.zeros_like(_v(x), dtype=d))


@_public
def ones_like(x, dtype=None):
    d = convert_dtype(dtype)
    return Tensor(jnp.ones_like(_v(x), dtype=d))


@_public
def full_like(x, fill_value, dtype=None):
    d = convert_dtype(dtype)
    return Tensor(jnp.full_like(_v(x), fill_value, dtype=d))


@_public
def empty(shape, dtype=None):
    return zeros(shape, dtype)


@_public
def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


@_public
def arange(start=0, end=None, step=1, dtype=None):
    d = convert_dtype(dtype)
    if end is None:
        start, end = 0, start
    start, end, step = _v(start), _v(end), _v(step)
    if d is None:
        # NB: plain all() here would hit this module's tensor `all` op
        is_int = builtins.all(isinstance(a, (int, np.integer))
                              for a in (start, end, step))
        d = jnp.int64 if is_int else get_default_dtype()
    return _place_new(jnp.arange(start, end, step, dtype=d))


@_public
def linspace(start, stop, num, dtype=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return _place_new(jnp.linspace(_v(start), _v(stop), int(num), dtype=d))


@_public
def eye(num_rows, num_columns=None, dtype=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return _place_new(jnp.eye(num_rows, num_columns, dtype=d))


@_public
def assign(x, output=None):
    t = to_tensor(x) if not isinstance(x, Tensor) else x.clone()
    if output is not None:
        output._value = t._value
        output._node = t._node
        output._out_index = t._out_index
        return output
    return t


@_public
def numel(x):
    return Tensor(jnp.asarray(np.prod(_v(x).shape, dtype=np.int64)))


@_public
def clone(x):
    return x.clone()


@_public
def diag(x, offset=0):
    return dispatch(lambda a: jnp.diag(a, k=offset), x, op_name="diag")


@_public
def meshgrid(*args):
    arrs = [_v(a) for a in args]
    outs = jnp.meshgrid(*arrs, indexing="ij")
    return [Tensor(o) for o in outs]


# ---------------------------------------------------------------------------
# random
# ---------------------------------------------------------------------------


@_public
def rand(shape, dtype=None):
    d = convert_dtype(dtype) or get_default_dtype()
    k = _random.next_key()
    return Tensor(jax.random.uniform(k, _shape_list(shape), dtype=d))


@_public
def randn(shape, dtype=None):
    d = convert_dtype(dtype) or get_default_dtype()
    k = _random.next_key()
    return Tensor(jax.random.normal(k, _shape_list(shape), dtype=d))


@_public
def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    d = convert_dtype(dtype)
    k = _random.next_key()
    return Tensor(jax.random.randint(k, _shape_list(shape), low, high, dtype=d))


@_public
def uniform(shape, dtype=None, min=-1.0, max=1.0):
    d = convert_dtype(dtype) or get_default_dtype()
    k = _random.next_key()
    return Tensor(jax.random.uniform(k, _shape_list(shape), dtype=d, minval=min, maxval=max))


@_public
def normal(mean=0.0, std=1.0, shape=None):
    if shape is None:
        shape = ()
    k = _random.next_key()
    d = get_default_dtype()
    return Tensor(mean + std * jax.random.normal(k, _shape_list(shape), dtype=d))


@_public
def randperm(n, dtype="int64"):
    k = _random.next_key()
    return Tensor(jax.random.permutation(k, n).astype(convert_dtype(dtype)))


@_public
def bernoulli(x):
    k = _random.next_key()
    return dispatch(
        lambda p: jax.random.bernoulli(k, p).astype(p.dtype), x, op_name="bernoulli"
    )


@_public
def multinomial(x, num_samples=1, replacement=False):
    k = _random.next_key()
    v = _v(x)
    logits = jnp.log(jnp.maximum(v, 1e-30))
    out = jax.random.categorical(k, logits, axis=-1, shape=(*v.shape[:-1], num_samples))
    return Tensor(out.astype(jnp.int64))


# ---------------------------------------------------------------------------
# elementwise math  (reference operators/elementwise + activation ops)
# ---------------------------------------------------------------------------


def _binary(name, fn):
    def op(x, y, name_arg=None):
        if isinstance(x, Tensor) and isinstance(y, Tensor):
            return dispatch(fn, x, y, op_name=name)
        if isinstance(x, Tensor):
            yy = _v(y)
            return dispatch(lambda a: fn(a, yy), x, op_name=name)
        xx = _v(x)
        return dispatch(lambda b: fn(xx, b), y, op_name=name)

    op.__name__ = name
    __all__.append(name)
    return static_aware(op)


add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.true_divide)
floor_divide = _binary("floor_divide", jnp.floor_divide)
remainder = _binary("remainder", jnp.remainder)
mod = remainder
__all__.append("mod")
pow = _binary("pow", jnp.power)  # noqa: A001
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)


def _unary(name, fn):
    def op(x, name_arg=None):
        return dispatch(fn, x, op_name=name)

    op.__name__ = name
    __all__.append(name)
    return static_aware(op)


abs = _unary("abs", jnp.abs)  # noqa: A001
neg = _unary("neg", jnp.negative)
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
square = _unary("square", jnp.square)
reciprocal = _unary("reciprocal", jnp.reciprocal)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)  # noqa: A001
sign = _unary("sign", jnp.sign)
erf = _unary("erf", jax.scipy.special.erf)
digamma = _unary("digamma", jax.scipy.special.digamma)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
trunc = _unary("trunc", jnp.trunc)


@_public
def clip(x, min=None, max=None):
    return dispatch(lambda a: jnp.clip(a, min, max), x, op_name="clip")


@_public
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    if bias_after_scale:
        out = dispatch(lambda a: a * scale + bias, x, op_name="scale")
    else:
        out = dispatch(lambda a: (a + bias) * scale, x, op_name="scale")
    return out


@_public
def lerp(x, y, weight):
    if isinstance(weight, Tensor):
        # weight must flow through dispatch or its gradient is lost
        return dispatch(lambda a, b, w: a + w * (b - a), x, y, weight,
                        op_name="lerp")
    return dispatch(lambda a, b: a + weight * (b - a), x, y, op_name="lerp")


@_public
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return dispatch(lambda a: scale_b * jnp.tanh(scale_a * a), x, op_name="stanh")


# ---------------------------------------------------------------------------
# reductions (reference operators/reduce_ops)
# ---------------------------------------------------------------------------


@_public
def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    d = convert_dtype(dtype)
    return dispatch(
        lambda a: jnp.sum(a, axis=_axes(axis), dtype=d, keepdims=keepdim), x, op_name="sum"
    )


@_public
def mean(x, axis=None, keepdim=False):
    return dispatch(lambda a: jnp.mean(a, axis=_axes(axis), keepdims=keepdim), x, op_name="mean")


@_public
def max(x, axis=None, keepdim=False):  # noqa: A001
    return dispatch(lambda a: jnp.max(a, axis=_axes(axis), keepdims=keepdim), x, op_name="max")


@_public
def min(x, axis=None, keepdim=False):  # noqa: A001
    return dispatch(lambda a: jnp.min(a, axis=_axes(axis), keepdims=keepdim), x, op_name="min")


@_public
def prod(x, axis=None, keepdim=False, dtype=None):
    d = convert_dtype(dtype)
    return dispatch(
        lambda a: jnp.prod(a, axis=_axes(axis), keepdims=keepdim, dtype=d), x, op_name="prod"
    )


@_public
def std(x, axis=None, unbiased=True, keepdim=False):
    return dispatch(
        lambda a: jnp.std(a, axis=_axes(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
        op_name="std",
    )


@_public
def var(x, axis=None, unbiased=True, keepdim=False):
    return dispatch(
        lambda a: jnp.var(a, axis=_axes(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
        op_name="var",
    )


@_public
def logsumexp(x, axis=None, keepdim=False):
    return dispatch(
        lambda a: jax.scipy.special.logsumexp(a, axis=_axes(axis), keepdims=keepdim),
        x,
        op_name="logsumexp",
    )


@_public
def all(x, axis=None, keepdim=False):  # noqa: A001
    return Tensor(jnp.all(_v(x), axis=_axes(axis), keepdims=keepdim))


@_public
def any(x, axis=None, keepdim=False):  # noqa: A001
    return Tensor(jnp.any(_v(x), axis=_axes(axis), keepdims=keepdim))


@_public
def cumsum(x, axis=None, dtype=None):
    d = convert_dtype(dtype)
    if axis is None:
        return dispatch(lambda a: jnp.cumsum(a.reshape(-1), dtype=d), x, op_name="cumsum")
    return dispatch(lambda a: jnp.cumsum(a, axis=int(axis), dtype=d), x, op_name="cumsum")


@_public
def cumprod(x, dim=None, dtype=None):
    d = convert_dtype(dtype)
    return dispatch(lambda a: jnp.cumprod(a, axis=dim, dtype=d), x, op_name="cumprod")


@_public
def median(x, axis=None, keepdim=False):
    return dispatch(lambda a: jnp.median(a, axis=_axes(axis), keepdims=keepdim), x, op_name="median")


@_public
def nanmean(x, axis=None, keepdim=False):
    return dispatch(lambda a: jnp.nanmean(a, axis=_axes(axis), keepdims=keepdim), x, op_name="nanmean")


@_public
def amax(x, axis=None, keepdim=False):
    return max(x, axis, keepdim)


@_public
def amin(x, axis=None, keepdim=False):
    return min(x, axis, keepdim)


# ---------------------------------------------------------------------------
# linalg (reference operators/matmul_v2, math/blas)
# ---------------------------------------------------------------------------


@_public
def matmul(x, y, transpose_x=False, transpose_y=False):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return dispatch(fn, x, y, op_name="matmul")


mm = matmul
__all__.append("mm")


@_public
def bmm(x, y):
    return dispatch(jnp.matmul, x, y, op_name="bmm")


@_public
def dot(x, y):
    return dispatch(
        lambda a, b: jnp.sum(a * b, axis=-1), x, y, op_name="dot"
    )


@_public
def t(x):
    return dispatch(lambda a: a.T, x, op_name="t")


@_public
def transpose(x, perm):
    return dispatch(lambda a: jnp.transpose(a, axes=tuple(perm)), x, op_name="transpose")


@_public
def norm(x, p="fro", axis=None, keepdim=False):
    def fn(a):
        if p == "fro" or p is None:
            return jnp.sqrt(jnp.sum(a * a, axis=_axes(axis), keepdims=keepdim))
        if p == np.inf or p == "inf":
            return jnp.max(jnp.abs(a), axis=_axes(axis), keepdims=keepdim)
        if p == -np.inf:
            return jnp.min(jnp.abs(a), axis=_axes(axis), keepdims=keepdim)
        pv = float(p)
        return jnp.sum(jnp.abs(a) ** pv, axis=_axes(axis), keepdims=keepdim) ** (1.0 / pv)

    return dispatch(fn, x, op_name="norm")


@_public
def dist(x, y, p=2):
    return norm(subtract(x, y), p=float(p) if p not in ("fro",) else p)


@_public
def cross(x, y, axis=None):
    ax = -1 if axis is None else int(axis)
    return dispatch(lambda a, b: jnp.cross(a, b, axis=ax), x, y, op_name="cross")


@_public
def outer(x, y):
    return dispatch(lambda a, b: jnp.outer(a, b), x, y, op_name="outer")


@_public
def inner(x, y):
    return dispatch(lambda a, b: jnp.inner(a, b), x, y, op_name="inner")


@_public
def trace(x, offset=0, axis1=0, axis2=1):
    return dispatch(
        lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x, op_name="trace"
    )


@_public
def kron(x, y):
    return dispatch(jnp.kron, x, y, op_name="kron")


@_public
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return dispatch(
        lambda i, a, b: beta * i + alpha * (a @ b), input, x, y, op_name="addmm"
    )


@_public
def multiplex(inputs, index):
    idx = _v(index).reshape(-1)
    stacked = jnp.stack([_v(i) for i in inputs], axis=0)
    rows = jnp.arange(stacked.shape[1])
    return Tensor(stacked[idx, rows])


# ---------------------------------------------------------------------------
# manipulation (reference operators reshape/transpose/concat/split/…)
# ---------------------------------------------------------------------------


@_public
def reshape(x, shape):
    shp = _shape_list(shape) if not isinstance(shape, (list, tuple)) else tuple(
        int(_v(s)) if isinstance(s, Tensor) else int(s) for s in shape
    )
    return dispatch(lambda a: jnp.reshape(a, shp), x, op_name="reshape")


@_public
def flatten(x, start_axis=0, stop_axis=-1):
    def fn(a):
        nd = a.ndim
        s, e = start_axis % nd, stop_axis % nd
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1 :]
        return jnp.reshape(a, new_shape)

    return dispatch(fn, x, op_name="flatten")


@_public
def squeeze(x, axis=None):
    return dispatch(lambda a: jnp.squeeze(a, axis=_axes(axis)), x, op_name="squeeze")


@_public
def unsqueeze(x, axis):
    return dispatch(lambda a: jnp.expand_dims(a, _axes(axis)), x, op_name="unsqueeze")


@_public
def concat(x, axis=0):
    tensors = list(x)
    ax = int(_v(axis)) if isinstance(axis, Tensor) else int(axis)
    return dispatch(lambda *vs: jnp.concatenate(vs, axis=ax), *tensors, op_name="concat")


@_public
def stack(x, axis=0):
    tensors = list(x)
    return dispatch(lambda *vs: jnp.stack(vs, axis=axis), *tensors, op_name="stack")


@_public
def split(x, num_or_sections, axis=0):
    ax = int(_v(axis)) if isinstance(axis, Tensor) else int(axis)
    v = _v(x)
    dim = v.shape[ax]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: axis {ax} size {dim} is not divisible by {num_or_sections}"
            )
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        neg = [i for i, s in enumerate(sizes) if s < 0]
        if neg:
            known = builtins.sum(s for s in sizes if s >= 0)
            sizes[neg[0]] = dim - known
    offsets = np.cumsum([0] + sizes)[:-1]

    def fn(a):
        return tuple(
            jax.lax.dynamic_slice_in_dim(a, int(o), int(s), axis=ax)
            for o, s in zip(offsets, sizes)
        )

    return list(dispatch(fn, x, op_name="split"))


@_public
def chunk(x, chunks, axis=0):
    return split(x, chunks, axis)


@_public
def unbind(x, axis=0):
    v = _v(x)
    n = v.shape[axis]

    def fn(a):
        return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(a, n, axis=axis))

    return list(dispatch(fn, x, op_name="unbind"))


unstack = unbind
__all__.append("unstack")


@_public
def tile(x, repeat_times):
    reps = tuple(int(_v(r)) if isinstance(r, Tensor) else int(r) for r in repeat_times)
    return dispatch(lambda a: jnp.tile(a, reps), x, op_name="tile")


@_public
def expand(x, shape):
    shp = _shape_list(shape)
    def fn(a):
        tgt = tuple(
            a.shape[i - (len(shp) - a.ndim)] if s == -1 else s for i, s in enumerate(shp)
        )
        return jnp.broadcast_to(a, tgt)
    return dispatch(fn, x, op_name="expand")


@_public
def expand_as(x, y):
    shp = tuple(_v(y).shape)
    return dispatch(lambda a: jnp.broadcast_to(a, shp), x, op_name="expand_as")


@_public
def broadcast_to(x, shape):
    return expand(x, shape)


@_public
def broadcast_tensors(inputs):
    vs = jnp.broadcast_arrays(*[_v(i) for i in inputs])
    return [Tensor(v) for v in vs]


@_public
def flip(x, axis):
    return dispatch(lambda a: jnp.flip(a, axis=_axes(axis)), x, op_name="flip")


@_public
def roll(x, shifts, axis=None):
    return dispatch(lambda a: jnp.roll(a, shifts, axis=_axes(axis)), x, op_name="roll")


@_public
def tril(x, diagonal=0):
    return dispatch(lambda a: jnp.tril(a, k=diagonal), x, op_name="tril")


@_public
def triu(x, diagonal=0):
    return dispatch(lambda a: jnp.triu(a, k=diagonal), x, op_name="triu")


@_public
def rot90(x, k=1, axes=(0, 1)):
    return dispatch(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x, op_name="rot90")


@_public
def repeat_interleave(x, repeats, axis=None):
    r = _v(repeats) if isinstance(repeats, Tensor) else repeats
    return dispatch(lambda a: jnp.repeat(a, r, axis=axis), x, op_name="repeat_interleave")


@_public
def gather(x, index, axis=0):
    idx = _v(index)
    if idx.ndim > 1:
        idx = idx.reshape(-1)
    ax = int(_v(axis)) if isinstance(axis, Tensor) else int(axis)
    return dispatch(lambda a: jnp.take(a, idx, axis=ax), x, op_name="gather")


@_public
def gather_nd(x, index):
    idx = _v(index)

    def fn(a):
        return a[tuple(jnp.moveaxis(idx, -1, 0))]

    return dispatch(fn, x, op_name="gather_nd")


@_public
def scatter(x, index, updates, overwrite=True):
    idx = _v(index).reshape(-1)

    def fn(a, u):
        if overwrite:
            return a.at[idx].set(u)
        base = a.at[idx].set(jnp.zeros_like(u))
        return base.at[idx].add(u)

    return dispatch(fn, x, updates, op_name="scatter")


@_public
def scatter_nd_add(x, index, updates):
    idx = _v(index)

    def fn(a, u):
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)

    return dispatch(fn, x, updates, op_name="scatter_nd_add")


@_public
def scatter_nd(index, updates, shape):
    z = zeros(shape, dtype=np.dtype(_v(updates).dtype).name)
    return scatter_nd_add(z, index, updates)


@_public
def take_along_axis(x, indices, axis):
    idx = _v(indices)
    return dispatch(
        lambda a: jnp.take_along_axis(a, idx, axis=axis), x, op_name="take_along_axis"
    )


@_public
def put_along_axis(x, indices, values, axis):
    idx = _v(indices)

    def fn(a, v):
        vv = jnp.broadcast_to(v, idx.shape) if jnp.ndim(v) == 0 else v
        return jnp.put_along_axis(a, idx, vv, axis=axis, inplace=False)

    return dispatch(fn, x, values, op_name="put_along_axis")


@_public
def index_select(x, index, axis=0):
    idx = _v(index)
    return dispatch(lambda a: jnp.take(a, idx, axis=axis), x, op_name="index_select")


@_public
def index_sample(x, index):
    idx = _v(index)
    return dispatch(
        lambda a: jnp.take_along_axis(a, idx, axis=1), x, op_name="index_sample"
    )


@_public
def masked_select(x, mask):
    m = np.asarray(_v(mask)).reshape(-1)
    return dispatch(lambda a: a.reshape(-1)[np.nonzero(m)[0]], x, op_name="masked_select")


@_public
def masked_fill(x, mask, value):
    m = _v(mask)
    val = _v(value)
    return dispatch(lambda a: jnp.where(m, jnp.asarray(val, a.dtype), a), x, op_name="masked_fill")


@_public
def where(condition, x=None, y=None):
    c = _v(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return dispatch(lambda a, b: jnp.where(c, a, b), x, y, op_name="where")


@_public
def nonzero(x, as_tuple=False):
    arr = np.asarray(_v(x))
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(n)) for n in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1)))


@_public
def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    arr = np.asarray(_v(x))
    res = np.unique(
        arr, return_index=return_index, return_inverse=return_inverse,
        return_counts=return_counts, axis=axis,
    )
    if isinstance(res, tuple):
        return tuple(Tensor(jnp.asarray(r)) for r in res)
    return Tensor(jnp.asarray(res))


@_public
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    pads = [int(_v(p)) if isinstance(p, Tensor) else int(p) for p in pad]
    v = _v(x)
    nd = v.ndim
    if len(pads) == 2 * nd:
        # full-form: [d0_lo, d0_hi, d1_lo, d1_hi, ...] in paddle order (per dim)
        width = [(pads[2 * i], pads[2 * i + 1]) for i in range(nd)]
    else:
        # partial spec applies to trailing spatial dims (reference pad3d/pad2d):
        # paddle lists them as (last_dim_lo, last_dim_hi, second_last_lo, ...)
        width = [(0, 0)] * nd
        n = len(pads) // 2
        for i in range(n):
            dim = nd - 1 - i
            width[dim] = (pads[2 * i], pads[2 * i + 1])
    mode_map = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}

    def fn(a):
        if mode == "constant":
            return jnp.pad(a, width, mode="constant", constant_values=value)
        return jnp.pad(a, width, mode=mode_map[mode])

    return dispatch(fn, x, op_name="pad")


@_public
def cast(x, dtype):
    return x.astype(dtype) if isinstance(x, Tensor) else to_tensor(x, dtype=dtype)


@_public
def slice(x, axes, starts, ends):  # noqa: A001
    def fn(a):
        out = a
        for ax, s, e in zip(axes, starts, ends):
            sl = [builtins.slice(None)] * a.ndim
            sl[ax] = builtins.slice(int(_v(s)), int(_v(e)))
            out = out[tuple(sl)]
        return out

    return dispatch(fn, x, op_name="slice")


@_public
def strided_slice(x, axes, starts, ends, strides):
    def fn(a):
        sl = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            sl[ax] = builtins.slice(int(_v(s)), int(_v(e)), int(_v(st)))
        return a[tuple(sl)]

    return dispatch(fn, x, op_name="strided_slice")


@_public
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def fn(a):
        shard_size = (index_num + nshards - 1) // nshards
        in_shard = (a // shard_size) == shard_id
        return jnp.where(in_shard, a % shard_size, ignore_value)

    return Tensor(fn(_v(input)))


@_public
def moveaxis(x, source, destination):
    return dispatch(lambda a: jnp.moveaxis(a, source, destination), x, op_name="moveaxis")


@_public
def swapaxes(x, axis0, axis1):
    return dispatch(lambda a: jnp.swapaxes(a, axis0, axis1), x, op_name="swapaxes")


@_public
def as_real(x):
    return dispatch(lambda a: jnp.stack([a.real, a.imag], axis=-1), x, op_name="as_real")


@_public
def as_complex(x):
    return dispatch(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x, op_name="as_complex")


# ---------------------------------------------------------------------------
# search / sort
# ---------------------------------------------------------------------------


@_public
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    v = jnp.argmax(_v(x), axis=axis, keepdims=keepdim).astype(convert_dtype(dtype))
    return Tensor(v)


@_public
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    v = jnp.argmin(_v(x), axis=axis, keepdims=keepdim).astype(convert_dtype(dtype))
    return Tensor(v)


@_public
def argsort(x, axis=-1, descending=False):
    v = _v(x)
    out = jnp.argsort(-v if descending else v, axis=axis)
    return Tensor(out.astype(jnp.int64))


@_public
def sort(x, axis=-1, descending=False):
    def fn(a):
        s = jnp.sort(a, axis=axis)
        return jnp.flip(s, axis=axis) if descending else s

    return dispatch(fn, x, op_name="sort")


@_public
def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    kk = int(_v(k)) if isinstance(k, Tensor) else int(k)

    def fn(a):
        ax = axis % a.ndim
        a_m = jnp.moveaxis(a, ax, -1)
        src = a_m if largest else -a_m
        vals, idx = jax.lax.top_k(src, kk)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax)

    return dispatch(fn, x, op_name="topk")


@_public
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(_v(sorted_sequence), _v(values), side=side)
    return Tensor(out.astype(jnp.int32 if out_int32 else jnp.int64))


@_public
def histogram(input, bins=100, min=0, max=0):  # noqa: A002
    v = np.asarray(_v(input))
    if min == 0 and max == 0:
        min, max = float(v.min()), float(v.max())
    hist, _ = np.histogram(v, bins=bins, range=(min, max))
    return Tensor(jnp.asarray(hist))


@_public
def bincount(x, weights=None, minlength=0):
    w = _v(weights) if weights is not None else None
    return Tensor(jnp.bincount(_v(x).reshape(-1), weights=w, minlength=minlength))


@_public
def mode(x, axis=-1, keepdim=False):
    arr = np.asarray(_v(x))
    from scipy import stats as _stats  # type: ignore

    m = _stats.mode(arr, axis=axis, keepdims=keepdim)
    return Tensor(jnp.asarray(m.mode)), Tensor(jnp.asarray(m.count))


# ---------------------------------------------------------------------------
# logic / comparison
# ---------------------------------------------------------------------------


def _cmp(name, fn):
    def op(x, y):
        return Tensor(fn(_v(x), _v(y)))

    op.__name__ = name
    __all__.append(name)
    return static_aware(op)


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)


@_public
def logical_not(x):
    return Tensor(jnp.logical_not(_v(x)))


@_public
def bitwise_not(x):
    return Tensor(jnp.bitwise_not(_v(x)))


@_public
def isnan(x):
    return Tensor(jnp.isnan(_v(x)))


@_public
def isinf(x):
    return Tensor(jnp.isinf(_v(x)))


@_public
def isfinite(x):
    return Tensor(jnp.isfinite(_v(x)))


@_public
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return Tensor(jnp.allclose(_v(x), _v(y), rtol=rtol, atol=atol, equal_nan=equal_nan))


@_public
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return Tensor(jnp.isclose(_v(x), _v(y), rtol=rtol, atol=atol, equal_nan=equal_nan))


@_public
def equal_all(x, y):
    return Tensor(jnp.array_equal(_v(x), _v(y)))


@_public
def is_empty(x):
    return Tensor(jnp.asarray(_v(x).size == 0))


@_public
def is_tensor(x):
    return isinstance(x, Tensor)


# ---------------------------------------------------------------------------
# linalg / misc completions (reference python/paddle/tensor/linalg.py,
# math.py, manipulation.py, creation.py)
# ---------------------------------------------------------------------------


@_public
def add_n(inputs):
    xs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    return dispatch(lambda *vs: functools.reduce(jnp.add, vs), *xs,
                    op_name="add_n")


@_public
def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@_public
def cholesky(x, upper=False):
    def fn(a):
        c = jnp.linalg.cholesky(a)
        return jnp.swapaxes(c, -1, -2) if upper else c

    return dispatch(fn, x, op_name="cholesky")


@_public
def inverse(x):
    return dispatch(jnp.linalg.inv, x, op_name="inverse")


@_public
def matrix_power(x, n):
    return dispatch(lambda a: jnp.linalg.matrix_power(a, n), x,
                    op_name="matrix_power")


@_public
def mv(x, vec):
    return dispatch(lambda a, b: a @ b, x, vec, op_name="mv")


@_public
def conj(x):
    return dispatch(jnp.conj, x, op_name="conj")


@_public
def real(x):
    return dispatch(jnp.real, x, op_name="real")


@_public
def imag(x):
    return dispatch(jnp.imag, x, op_name="imag")


@_public
def diagonal(x, offset=0, axis1=0, axis2=1):
    return dispatch(lambda a: jnp.diagonal(a, offset, axis1, axis2), x,
                    op_name="diagonal")


@_public
def diagflat(x, offset=0):
    return dispatch(lambda a: jnp.diagflat(a, offset), x, op_name="diagflat")


@_public
def rank(x):
    return Tensor(jnp.asarray(_v(x).ndim))


@_public
def shape(x):
    return Tensor(jnp.asarray(_v(x).shape, jnp.int32))


@_public
def increment(x, value=1.0):
    """In-place increment (reference increment op): mutates eager tensors."""
    out = _v(x) + value
    if isinstance(x, Tensor):
        x._value = out
        return x
    return Tensor(out)


@_public
def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


@_public
def tolist(x):
    return np.asarray(_v(x)).tolist()


@_public
def floor_mod(x, y):
    return remainder(x, y)


@_public
def crop_tensor(x, shape=None, offsets=None):
    v = _v(x)
    offsets = [0] * v.ndim if offsets is None else [int(o) for o in offsets]
    shape = list(v.shape) if shape is None else [
        int(s) if int(s) != -1 else v.shape[i] - offsets[i]
        for i, s in enumerate(shape)]
    sl = tuple(builtins.slice(o, o + s) for o, s in zip(offsets, shape))
    return dispatch(lambda a: a[sl], x, op_name="crop_tensor")


@_public
def crop(x, shape=None, offsets=None):
    """Alias (reference exports crop_tensor as paddle.crop)."""
    return crop_tensor(x, shape=shape, offsets=offsets)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     linewidth=None, sci_mode=None):
    """reference paddle.set_printoptions → numpy printoptions here."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


__all__.append("set_printoptions")


# -- eager in-place variants (reference *_ ops mutate the VarBase buffer) ----

def _inplace(name, fn):
    def op(x, *args, **kwargs):
        from .core import autograd as _ag

        if (isinstance(x, Tensor) and not x.stop_gradient
                and x._node is None and _ag.is_grad_enabled()):
            # same restriction as the reference/torch: mutating a leaf that
            # requires grad would silently detach it from its .grad
            raise RuntimeError(
                f"{name}: a leaf Tensor that requires grad cannot be used "
                "in an in-place operation; call it under no_grad() or on "
                "the op's out-of-place variant")
        # run the op against a SNAPSHOT carrying the original producer node,
        # so the recorded tape edge points upstream (x._node = new node would
        # otherwise make x its own producer — a self-edge that starves
        # backward of every upstream gradient)
        snap = Tensor(x._value, stop_gradient=x.stop_gradient)
        snap._node = x._node
        snap._out_index = x._out_index
        out = fn(snap, *args, **kwargs)
        x._value = out.value if isinstance(out, Tensor) else out
        x._node = getattr(out, "_node", None)
        x._out_index = getattr(out, "_out_index", 0)
        x.stop_gradient = getattr(out, "stop_gradient", x.stop_gradient)
        return x

    op.__name__ = name
    __all__.append(name)
    return op


@_public
def reverse(x, axis):
    return flip(x, axis)


# -- LoD tensor-array ops (reference lod_tensor_array + array ops): a plain
# python list plays the TensorArray role; inside jit use lax.scan instead ----

@_public
def create_array(dtype="float32", initialized_list=None):
    return list(initialized_list or [])


@_public
def array_write(x, i, array=None):
    if array is None:
        array = []
    i = int(_v(i)) if not isinstance(i, int) else i
    while len(array) <= i:
        array.append(None)
    array[i] = x if isinstance(x, Tensor) else Tensor(_v(x))
    return array


@_public
def array_read(array, i):
    return array[int(_v(i)) if not isinstance(i, int) else i]


@_public
def array_length(array):
    return Tensor(jnp.asarray(len(array), jnp.int64))


reshape_ = _inplace("reshape_", lambda x, *a, **k: reshape(x, *a, **k))
scatter_ = _inplace("scatter_", lambda x, *a, **k: scatter(x, *a, **k))
squeeze_ = _inplace("squeeze_", lambda x, *a, **k: squeeze(x, *a, **k))
unsqueeze_ = _inplace("unsqueeze_", lambda x, *a, **k: unsqueeze(x, *a, **k))
tanh_ = _inplace("tanh_", lambda x: tanh(x))
clip_ = _inplace("clip_", lambda x, *a, **k: clip(x, *a, **k))
exp_ = _inplace("exp_", lambda x: exp(x))
sqrt_ = _inplace("sqrt_", lambda x: sqrt(x))
rsqrt_ = _inplace("rsqrt_", lambda x: rsqrt(x))
reciprocal_ = _inplace("reciprocal_", lambda x: reciprocal(x))
round_ = _inplace("round_", lambda x: round(x))
ceil_ = _inplace("ceil_", lambda x: ceil(x))
floor_ = _inplace("floor_", lambda x: floor(x))
scale_ = _inplace("scale_", lambda x, *a, **k: scale(x, *a, **k))
subtract_ = _inplace("subtract_", lambda x, y: subtract(x, y))
flatten_ = _inplace("flatten_", lambda x, *a, **k: flatten(x, *a, **k))
add_ = _inplace("add_", lambda x, y: add(x, y))


# ---------------------------------------------------------------------------
# Tensor method / dunder attachment
# ---------------------------------------------------------------------------

_METHODS = {}
for _name in list(__all__):
    _fn = globals()[_name]
    if callable(_fn) and _name not in ("to_tensor", "is_tensor", "meshgrid", "broadcast_tensors", "scatter_nd"):
        _METHODS[_name] = _fn

for _name, _fn in _METHODS.items():
    if not hasattr(Tensor, _name):
        setattr(Tensor, _name, _fn)

# `pow` name clash: method exists
Tensor.pow = pow


def _swap(fn):
    return lambda x, y: fn(y, x)


_DUNDERS = {
    "__add__": add,
    "__radd__": add,
    "__sub__": subtract,
    "__rsub__": _swap(subtract),
    "__mul__": multiply,
    "__rmul__": multiply,
    "__truediv__": divide,
    "__rtruediv__": _swap(divide),
    "__floordiv__": floor_divide,
    "__rfloordiv__": _swap(floor_divide),
    "__mod__": remainder,
    "__pow__": pow,
    "__rpow__": _swap(pow),
    "__matmul__": matmul,
    "__rmatmul__": _swap(matmul),
    "__neg__": neg,
    "__abs__": abs,
    "__eq__": equal,
    "__ne__": not_equal,
    "__lt__": less_than,
    "__le__": less_equal,
    "__gt__": greater_than,
    "__ge__": greater_equal,
    "__and__": logical_and,
    "__or__": logical_or,
    "__xor__": logical_xor,
    "__invert__": logical_not,
}
for _d, _fn in _DUNDERS.items():
    setattr(Tensor, _d, _fn)

__all__ += ["to_tensor"]
