"""paddle_tpu — a TPU-native deep-learning framework.

Capability surface modeled on PaddlePaddle v2.1 (/root/reference), re-designed
from scratch for TPU: JAX/XLA is the compiler+runtime, Pallas provides hot
kernels, pjit/shard_map over a device Mesh provides every parallelism the
reference's Fleet implements with NCCL/brpc.
"""
from __future__ import annotations

import os as _os
import sys as _sys

__version__ = "0.1.0"

# Tooling entry points (launch CLI, spawn helpers) must not initialize the
# accelerator backend in their own process — the reference launcher never
# touches CUDA either (fleet/launch.py only builds env + subprocesses).
# `python -m paddle_tpu.distributed.launch` imports this package before the
# module runs, so the light-import switch is decided here.
def _is_light_entry() -> bool:
    if _os.environ.get("PADDLE_TPU_LIGHT_IMPORT") == "1":
        return True
    # only a `-m <launcher>` among the INTERPRETER options counts — the scan
    # stops at the first script/command argument, so a training command that
    # merely mentions the launcher (even as its own -m flag value) must not
    # get the stripped-down package
    argv = list(getattr(_sys, "orig_argv", []))
    i = 1
    while i < len(argv):
        a = argv[i]
        if a == "-m":
            return i + 1 < len(argv) and argv[i + 1] in (
                "paddle_tpu.distributed.launch",
                "paddle_tpu.distributed.spawn")
        if a == "-c" or a == "-" or not a.startswith("-"):
            return False  # command string / stdin / script path reached
        if a in ("-W", "-X", "--check-hash-based-pycs"):
            i += 2  # interpreter option with a separate value argument
        else:
            i += 1
    return False


_LIGHT_IMPORT = _is_light_entry()

if not _LIGHT_IMPORT:
    # dtypes
    from .core.dtype import (  # noqa: F401
        bfloat16,
        bool_ as bool,  # noqa: A001
        complex64,
        complex128,
        float16,
        float32,
        float64,
        get_default_dtype,
        int8,
        int16,
        int32,
        int64,
        set_default_dtype,
        uint8,
    )

    # device / place
    from .core.place import (  # noqa: F401
        CPUPlace,
        CUDAPlace,
        Place,
        TPUPlace,
        device_count,
        get_device,
        is_compiled_with_tpu,
        set_device,
    )

    # tensor + autograd
    from .core.tensor import Parameter, Tensor, to_tensor  # noqa: F401
    from .core.autograd import (  # noqa: F401
        enable_grad, grad, is_grad_enabled, no_grad, set_grad_enabled,
    )
    from .framework.random import seed  # noqa: F401

    # the full tensor-op surface (also attaches Tensor methods)
    from .tensor_api import *  # noqa: F401,F403
    from . import tensor_api as _tensor_api

    from . import core, framework  # noqa: F401
    from . import autograd  # noqa: F401
    from . import nn  # noqa: F401
    from . import optimizer  # noqa: F401
    from . import amp  # noqa: F401
    from . import jit  # noqa: F401
    from . import io  # noqa: F401
    from . import metric  # noqa: F401
    from . import vision  # noqa: F401
    from . import text  # noqa: F401
    from . import inference  # noqa: F401
    from . import compat  # noqa: F401
    from . import dataset  # noqa: F401
    from . import reader  # noqa: F401
    from . import tensor  # noqa: F401
    from . import quantization  # noqa: F401
    from . import sparsity  # noqa: F401
    from . import hapi  # noqa: F401
    from .hapi import Model, summary  # noqa: F401
    from . import profiler  # noqa: F401
    from . import telemetry  # noqa: F401
    from . import faults  # noqa: F401
    from . import resilience  # noqa: F401
    from .flags import get_flags, set_flags  # noqa: F401
    from .framework import checkpoint, debugger  # noqa: F401
    from .framework.io import load, save  # noqa: F401
    from .nn.clip import (  # noqa: F401
        ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
    )

    from . import static  # noqa: F401
    from . import onnx  # noqa: F401
    from . import incubate  # noqa: F401
    from . import callbacks  # noqa: F401
    from . import device  # noqa: F401
    from . import distribution  # noqa: F401
    from . import hub  # noqa: F401
    from . import regularizer  # noqa: F401
    from . import sysconfig  # noqa: F401
    from . import version  # noqa: F401
    from .version import full_version  # noqa: F401
    from .framework.errors import check_shape  # noqa: F401

    def disable_static():
        """Leave Program-recording mode (back to dygraph)."""
        from .static.program import disable_static_recording

        disable_static_recording()

    def enable_static():
        """Route public API calls on static Variables into the default main
        Program (reference paddle.enable_static); run with static.Executor."""
        from .static.program import enable_static_recording

        enable_static_recording()

    def in_dynamic_mode():
        from .core import static_mode

        return static_mode.CURRENT is None

    from .device import (  # noqa: F401  (single definition in device.py)
        CUDAPinnedPlace, NPUPlace, XPUPlace, get_cudnn_version,
        is_compiled_with_cuda, is_compiled_with_npu, is_compiled_with_rocm,
        is_compiled_with_xpu)

    def ones_like(x, dtype=None):  # re-export convenience
        return _tensor_api.ones_like(x, dtype)

    # dygraph-era aliases (reference fluid/framework.py)
    VarBase = Tensor
    import numpy as _np

    dtype = _np.dtype  # paddle.dtype('float32') etc.
    from .nn.layer_base import ParamAttr  # noqa: F401
    from .hapi.model import flops  # noqa: F401
    from .static.program import create_parameter  # noqa: F401

    def enable_dygraph(place=None):
        disable_static()

    def disable_dygraph():
        enable_static()

    def in_dygraph_mode():
        return in_dynamic_mode()

    def batch(reader, batch_size, drop_last=False):
        """reference paddle.batch: wrap a sample reader into a batch reader."""
        def batch_reader():
            buf = []
            for sample in reader():
                buf.append(sample)
                if len(buf) == batch_size:
                    yield buf
                    buf = []
            if buf and not drop_last:
                yield buf

        return batch_reader

    def get_cuda_rng_state():  # no CUDA generator on TPU builds
        return []

    def set_cuda_rng_state(state):
        return None

    def monkey_patch_math_varbase():  # method attachment happens at import
        return None

    def monkey_patch_variable():
        return None


# distributed is imported lazily to keep plain single-chip import light (and
# it is the only namespace available under light import)
def __getattr__(name):
    if name == "distributed":
        import importlib

        mod = importlib.import_module(".distributed", __name__)
        globals()["distributed"] = mod
        return mod
    if name == "commit":  # lazy: resolving it shells out to git once
        from . import version as _version

        globals()["commit"] = _version.commit
        return globals()["commit"]
    if not _LIGHT_IMPORT and name == "DataParallel":
        from .distributed.parallel import DataParallel

        return DataParallel
    extra = " (light import: launcher process)" if _LIGHT_IMPORT else ""
    raise AttributeError(
        f"module 'paddle_tpu' has no attribute {name!r}{extra}")
